"""CI smoke for the index-server read path: build a tiny index, start the
REAL HTTP search server (`index serve`), run clip-, uuid- and text-queries
over the wire, assert IVF recall >= 0.95 vs exact cosine top-k, then fold
pending fragments with a CONCURRENT `index compact` while hammering the
server — every response must be generation-consistent and results for
already-indexed content must not change. Exercised by
scripts/run_ci_checks.sh (skip with CI_SKIP=search)."""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request
from pathlib import Path

import numpy as np

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

DIM = 16  # matches clip-text-tiny-test's projection_dim (text-query path)
K = 6


def post(port: int, payload: dict, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/search",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def wait_healthy(port: int, proc: subprocess.Popen, timeout: float = 60.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"server exited rc={proc.returncode}")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/health", timeout=2
            ) as resp:
                if json.loads(resp.read()).get("status") == "ok":
                    return
        except OSError:
            time.sleep(0.2)
    raise RuntimeError("server never became healthy")


def main() -> int:
    rng = np.random.default_rng(7)
    centers = rng.standard_normal((K, DIM)).astype(np.float32)
    centers /= np.linalg.norm(centers, axis=1, keepdims=True)
    corpus = np.concatenate(
        [c + 0.05 * rng.standard_normal((40, DIM)) for c in centers]
    ).astype(np.float32)
    ids = [f"c{i}" for i in range(len(corpus))]

    from cosmos_curate_tpu.dedup.corpus_index import CorpusIndex
    from cosmos_curate_tpu.dedup.index_store import IndexStore, normalize_rows

    tmp = Path(tempfile.mkdtemp(prefix="search_smoke_"))
    root = str(tmp / "idx")
    CorpusIndex.build(root, ids, corpus, model="m", k=K)

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        # no CLIP checkpoint in CI: the text path runs the random-init tiny
        # tower (provenance gate explicitly opted out; production refuses)
        "CURATE_INDEX_ALLOW_RANDOM": "1",
    }
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "cosmos_curate_tpu.cli.main", "index", "serve",
            "--index-path", root, "--port", str(port),
            "--text-model", "clip-text-tiny-test",
        ],
        cwd=str(REPO), env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        wait_healthy(port, proc)

        # -- clip-to-clip recall over the wire
        queries = (corpus[::4] + 0.01 * rng.standard_normal((len(corpus[::4]), DIM))).astype(np.float32)
        qn, cn = normalize_rows(queries), normalize_rows(corpus)
        exact = np.argsort(-(qn @ cn.T), axis=1)[:, :5]
        hits = [
            post(port, {"embedding": [float(v) for v in q], "top_k": 5, "nprobe": 3})
            for q in queries
        ]
        recall = sum(
            len({r["clip_uuid"] for r in hits[i]["results"]} & {ids[j] for j in exact[i]}) / 5
            for i in range(len(queries))
        ) / len(queries)
        assert recall >= 0.95, f"IVF recall over HTTP {recall} < 0.95"
        gens = {h["generation"] for h in hits}
        assert gens == {0}, gens

        # -- uuid + text modes
        by_uuid = post(port, {"clip_uuid": "c5", "top_k": 3})
        assert by_uuid["results"][0]["clip_uuid"] == "c5", by_uuid
        by_text = post(port, {"text": "a red car driving at night", "top_k": 4})
        assert by_text["mode"] == "text" and len(by_text["results"]) == 4, by_text

        # -- concurrent compaction changes no results
        baseline = [
            post(port, {"embedding": [float(v) for v in q], "top_k": 5})["results"]
            for q in queries[:8]
        ]
        new = (rng.standard_normal((12, DIM)) * 3).astype(np.float32)
        IndexStore(root).write_pending_fragment(
            "smoke", [f"n{i}" for i in range(12)], new,
            model="m", provenance="checkpoint:smoke",
        )
        stop = threading.Event()
        observed: list[tuple[int, int, list[str]]] = []
        errors: list[BaseException] = []

        def hammer() -> None:
            i = 0
            while not stop.is_set():
                qi = i % 8
                try:
                    r = post(port, {"embedding": [float(v) for v in queries[qi]], "top_k": 5})
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)
                    return
                observed.append((qi, r["generation"], [x["clip_uuid"] for x in r["results"]]))
                i += 1

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        for t in threads:
            t.start()
        compact = subprocess.run(
            [sys.executable, "-m", "cosmos_curate_tpu.cli.main", "index", "compact",
             "--index-path", root, "--no-mesh"],
            cwd=str(REPO), env=env, capture_output=True, text=True, timeout=300,
        )
        assert compact.returncode == 0, compact.stderr[-2000:]
        report = json.loads(compact.stdout)
        assert report["published"] and report["folded"] == 12, report
        # keep hammering until the server adopts (adopt interval 1 s)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if post(port, {"clip_uuid": "c0", "top_k": 1})["generation"] == report["generation"]:
                break
            time.sleep(0.2)
        stop.set()
        for t in threads:
            t.join()
        assert not errors, errors[:1]
        gens = {g for _qi, g, _r in observed}
        assert gens <= {0, report["generation"]}, gens
        for qi, _gen, result_ids in observed:
            want = [x["clip_uuid"] for x in baseline[qi]]
            assert result_ids == want, (qi, result_ids, want)
        # the folded vectors are servable post-adoption
        folded = post(port, {"embedding": [float(v) for v in new[0]], "top_k": 1})
        assert folded["results"][0]["clip_uuid"] == "n0", folded
        assert folded["generation"] == report["generation"], folded

        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/v1/search/stats", timeout=5
        ) as resp:
            stats = json.loads(resp.read())
        assert stats["generation"] == report["generation"], stats
        assert stats["cache"]["hit_bytes"] > 0, stats
        print(
            f"search smoke ok: recall@5 {recall:.3f} over HTTP, "
            f"{len(observed)} queries concurrent with compaction "
            f"(generations {sorted(gens)}), folded 12 vectors into "
            f"generation {report['generation']}, cache hit bytes "
            f"{stats['cache']['hit_bytes']}"
        )
        return 0
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


if __name__ == "__main__":
    sys.exit(main())
