#!/usr/bin/env bash
# The one CI entry point (.github/workflows/ci.yml): every PR must hold
# the line on (1) the tier-1 CPU suite, (2) a bench smoke, (3) the
# 8-device multichip dry-run, and (4) the static-analysis gate
# (curate-lint + shardcheck + tracing/caption smokes), plus (5) the
# corpus-index build/add/query smoke, plus (6) the durable-service gate
# (crash-safe queue + kill -9 resume soak), plus (7) the node-loss gate
# (failure detector + lineage reconstruction units; the agent-killing e2e
# + soak run nightly), plus (8) the search-serving gate (index server over
# HTTP: recall + generation-consistent results under concurrent
# compaction), plus (9) the bench trend gate (>20% warm clips/s regression
# between committed BENCH rounds fails), plus (10) the concurrency gate
# (whole-repo lock-order/blocking-under-lock verifier must stay clean, and
# its seeded-fixture + runtime-sanitizer suites must pass), plus (11) the
# schema gate (protocol frames + durable JSON formats must match the
# analysis/schemas/ goldens — drift needs a version bump, breaking durable
# drift a migration shim; the skew-fuzz suites must pass). Individual
# gates can be skipped via
# CI_SKIP=tier1,bench,trend,multichip,index,service,nodeloss,search,static,concurrency,schema
# for local use.
set -uo pipefail

cd "$(dirname "$0")/.."

SKIP=",${CI_SKIP:-},"
skip() { [[ "$SKIP" == *",$1,"* ]]; }
failures=()

if ! skip tier1; then
  echo "== tier-1 CPU suite =="
  # the ROADMAP's canonical tier-1 command (870 s cap, DOTS count logged)
  set -o pipefail
  rm -f /tmp/_t1.log
  timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
  rc=${PIPESTATUS[0]}
  echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
  # rc 124 = the suite hit the wall-clock cap on a small box; failures
  # inside the window still fail the gate (grep for F/E markers)
  if [[ $rc -ne 0 && $rc -ne 124 ]]; then
    failures+=("tier-1 suite (rc=$rc)")
  elif grep -aqE "^(FAILED|ERROR) " /tmp/_t1.log; then
    failures+=("tier-1 suite (test failures)")
  fi
fi

if ! skip bench; then
  echo "== bench smoke (2 videos, tiny caption) =="
  if ! BENCH_NUM_VIDEOS=2 BENCH_CAPTION_REQUESTS=2 JAX_PLATFORMS=cpu \
      timeout -k 10 1800 python bench.py > /tmp/_bench.json; then
    failures+=("bench smoke")
  else
    python - <<'PY' || failures+=("bench smoke (malformed record)")
import json
rec = json.loads(open("/tmp/_bench.json").read().strip().splitlines()[-1])
assert rec["metric"] == "clips_per_sec_split_annotate" and rec["value"] > 0, rec
print(f"bench smoke: {rec['value']} clips/s (backend={rec.get('backend', 'tpu')})")
PY
  fi
fi

if ! skip trend; then
  echo "== bench trend gate (>20% warm clips/s regression fails) =="
  # round-vs-round over the committed BENCH_r*.json trajectory; when the
  # bench smoke above produced a fresh row it is NOT used here (smoke runs
  # at 2 videos — not comparable to full rounds)
  if ! python scripts/bench_trend.py; then
    failures+=("bench trend")
  fi
fi

if ! skip multichip; then
  echo "== dryrun_multichip(8) =="
  if ! JAX_PLATFORMS=cpu timeout -k 10 1500 python -c \
      "import __graft_entry__ as g; g.dryrun_multichip(8)"; then
    failures+=("dryrun_multichip(8)")
  fi
fi

if ! skip index; then
  echo "== corpus-index smoke (build/add/query/stats CLI + IVF recall) =="
  if ! JAX_PLATFORMS=cpu timeout -k 10 600 python scripts/index_smoke.py; then
    failures+=("corpus-index smoke")
  fi
fi

if ! skip service; then
  echo "== durable-service checks (crash-safe queue, kill -9 resume soak) =="
  if ! bash scripts/run_service_checks.sh; then
    failures+=("service checks")
  fi
fi

if ! skip search; then
  echo "== search smoke (index server over HTTP: recall + concurrent compaction) =="
  if ! JAX_PLATFORMS=cpu timeout -k 10 600 python scripts/search_smoke.py; then
    failures+=("search smoke")
  fi
fi

if ! skip nodeloss; then
  echo "== node-loss checks (failure detector + lineage reconstruction units) =="
  # the fast half of scripts/run_nodeloss_checks.sh; the agent-killing
  # e2e suite + loopback soak run on the nightly schedule
  if ! JAX_PLATFORMS=cpu timeout -k 10 600 python -m pytest \
      tests/engine/test_node_loss.py -q -p no:randomly -m 'not slow'; then
    failures+=("node-loss units")
  fi
fi

if ! skip static; then
  echo "== static checks (lint + shardcheck + smokes) =="
  if ! bash scripts/run_static_checks.sh; then
    failures+=("static checks")
  fi
fi

if ! skip concurrency; then
  echo "== concurrency gate (lock-order graph clean + verifier/sanitizer suites) =="
  # the whole-repo pass on its own (static gate bundles it too, but this
  # keeps CI_SKIP=static from silently dropping deadlock coverage), then
  # the seeded-fixture and runtime-sanitizer suites
  if ! JAX_PLATFORMS=cpu timeout -k 10 300 python -m cosmos_curate_tpu.cli.main \
      lint --concurrency cosmos_curate_tpu; then
    failures+=("concurrency lint")
  fi
  if ! JAX_PLATFORMS=cpu timeout -k 10 600 python -m pytest \
      tests/analysis/test_concurrency_check.py tests/analysis/test_lock_runtime.py \
      -q -p no:randomly; then
    failures+=("concurrency suites")
  fi
fi

if ! skip schema; then
  echo "== schema gate (wire/durable contract surfaces vs checked-in goldens) =="
  # drift without a bump (or a breaking durable bump without a migration
  # shim) fails; fix is a version bump + `lint --schema --update` + commit
  if ! JAX_PLATFORMS=cpu timeout -k 10 300 python -m cosmos_curate_tpu.cli.main \
      lint --schema cosmos_curate_tpu; then
    failures+=("schema lint")
  fi
  if ! JAX_PLATFORMS=cpu timeout -k 10 600 python -m pytest \
      tests/analysis/test_schema_check.py tests/engine/test_protocol_skew.py \
      tests/service/test_schema_versioning.py -q -p no:randomly; then
    failures+=("schema suites (seeded drift + skew fuzz)")
  fi
fi

if ((${#failures[@]})); then
  printf 'CI FAILED: %s\n' "${failures[@]}"
  exit 1
fi
echo "CI checks passed"
