#!/usr/bin/env bash
# Cross-host scheduling gate: a local driver plus a loopback node agent run
# a real split pipeline end to end — CPU stages placed on the agent node by
# the per-node planner, the embed stage in-process on the driver — and the
# run must produce ONE connected trace plus object-plane evidence that
# push-ahead prefetch overlapped compute (prefetch wait < transfer time,
# pipeline_object_plane_bytes_total > 0). See docs/PERFORMANCE.md
# ("Cross-host scheduling") for the model this validates.
set -euo pipefail

cd "$(dirname "$0")/.."

echo "== fast units: per-node planner + router =="
JAX_PLATFORMS=cpu python -m pytest \
  tests/engine/test_node_planner.py \
  tests/engine/test_autoscaler.py \
  -q -p no:randomly

echo "== two-agent e2e: routing + prefetch (spawns real agents) =="
JAX_PLATFORMS=cpu python -m pytest \
  tests/engine/test_cross_host_routing.py \
  -q -p no:randomly -m ''

echo "== loopback soak: split pipeline across driver + 1 agent =="
# a real script file, not a heredoc: the driver's local workers are
# spawned processes that re-import __main__, and '<stdin>' has no path
JAX_PLATFORMS=cpu python scripts/crosshost_soak.py

echo "cross-host checks passed"
