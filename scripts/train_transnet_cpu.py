#!/usr/bin/env python
"""Train the committed ``weights/transnetv2-tpu`` checkpoint on CPU.

The reference ships pretrained TransNetV2 weights
(cosmos_curate/models/transnetv2.py:530); this image has no egress, so the
committed checkpoint comes from the synthetic-cut trainer
(models/transnet_train.py). A single CPU core makes full training
expensive (tens of seconds per step at the default batch 4 x the
inference WINDOW — training at any other window is REJECTED, see
transnet_train.train), so this script adds EVAL-BASED EARLY STOPPING:
every ``--eval-every`` steps it scores the golden-test criteria
(tests/models/test_transnet_golden.py — cut peak within ±2 frames, prob >
threshold, separation over scene interiors, no false cuts in continuous
clips) through the PRODUCTION windowed-inference path on a fixed held-out
eval set, and stops as soon as every criterion passes with margin.
Progress checkpoints land in a per-run /tmp staging dir (crash-resume);
``--out-dir`` (the committed ``weights/`` tree) is written ONLY on a full
eval pass — the goldens un-skip the moment the file exists.

Run (low priority, background):
    PYTHONPATH=/root/repo JAX_PLATFORMS=cpu nice -n 19 \
        python scripts/train_transnet_cpu.py --out-dir weights
"""

from __future__ import annotations

import argparse
import tempfile
import time

import numpy as np


def _two_scene_eval_clip(seed: int, t_per_scene: int = 60):
    """Held-out clip in the golden test's family (solid background + moving
    rectangle, hard cut at t_per_scene) with per-seed colors."""
    rng = np.random.default_rng(seed)
    h, w = 27, 48
    scenes = []
    for _ in range(2):
        base = rng.integers(20, 236, 3).astype(np.float32)
        fg = rng.integers(0, 256, 3).astype(np.float32)
        frames = np.empty((t_per_scene, h, w, 3), np.uint8)
        for i in range(t_per_scene):
            frame = np.full((h, w, 3), base, np.float32)
            x = (i * 2) % (w - 12)
            frame[8:20, x : x + 12] = fg
            frames[i] = np.clip(frame + rng.normal(0, 2, frame.shape), 0, 255)
        scenes.append(frames)
    return np.concatenate(scenes), t_per_scene


def _continuous_eval_clip(seed: int, t: int = 120):
    rng = np.random.default_rng(seed)
    h, w = 27, 48
    base = rng.integers(20, 236, 3).astype(np.float32)
    fg = rng.integers(0, 256, 3).astype(np.float32)
    frames = np.empty((t, h, w, 3), np.uint8)
    for i in range(t):
        frame = np.full((h, w, 3), base, np.float32)
        x = i % (w - 10)
        frame[10:18, x : x + 10] = fg
        frames[i] = np.clip(frame + rng.normal(0, 2, frame.shape), 0, 255)
    return frames


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out-dir", default="weights")
    ap.add_argument("--max-steps", type=int, default=1200)
    ap.add_argument("--batch", type=int, default=4)
    # must equal transnetv2.WINDOW (enforced below)
    ap.add_argument("--window", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--eval-every", type=int, default=25)
    # fraction of batch rows that are a SINGLE scene (all-zero labels);
    # see models/transnet_train.synthesize_batch
    ap.add_argument("--single-scene-frac", type=float, default=0.35)
    # margins over the golden test's thresholds (0.5 peak, 5x separation,
    # 0.5 false-cut ceiling) so a pass here implies a pass there
    ap.add_argument("--peak-prob", type=float, default=0.65)
    ap.add_argument("--separation", type=float, default=7.0)
    ap.add_argument("--false-cut", type=float, default=0.35)
    a = ap.parse_args()

    import os

    # evals run TransNetV2TPU through the registry against a PER-RUN
    # staging dir (the production loading + windowed-inference path the
    # golden tests use; unique per run so concurrent sweeps cannot score
    # each other's checkpoints)
    staging = tempfile.mkdtemp(prefix="transnet-staging-")
    os.environ["CURATE_MODEL_WEIGHTS_DIR"] = staging
    print(f"staging dir: {staging}", flush=True)

    import jax
    import jax.numpy as jnp
    import optax

    from cosmos_curate_tpu.models import registry
    from cosmos_curate_tpu.models.transnet_train import synthesize_batch
    from cosmos_curate_tpu.models.transnetv2 import (
        INPUT_H,
        INPUT_W,
        WINDOW,
        TransNet,
        TransNetConfig,
    )

    if a.window != WINDOW:
        raise SystemExit(
            f"--window {a.window} != inference WINDOW {WINDOW} "
            "(transnetv2.py): the dilated convs' edge signatures make "
            "train/inference window mismatch produce positional, "
            "content-free predictions — train at the inference window"
        )

    cfg = TransNetConfig()
    model = TransNet(cfg)
    rng = np.random.default_rng(a.seed)
    params = model.init(
        jax.random.PRNGKey(a.seed),
        jnp.zeros((1, a.window, INPUT_H, INPUT_W, 3), jnp.uint8),
    )
    # clipping keeps the higher escape-the-constant-basin LR stable
    opt = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(a.lr))
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, frames, labels):
        def loss_fn(p):
            logits = model.apply(p, frames)
            per = optax.sigmoid_binary_cross_entropy(logits, labels)
            return (per * (1.0 + 7.0 * labels)).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    two_scene = [_two_scene_eval_clip(100 + i) for i in range(4)]
    continuous = [_continuous_eval_clip(200 + i) for i in range(2)]

    # ONE inference wrapper for all evals (its jitted apply compiles once);
    # each eval swaps the live params in and ALSO stages them for
    # crash-resume. The final publish re-verifies through a fresh
    # registry-loaded model, so the production load path is still proven.
    from cosmos_curate_tpu.models.transnetv2 import TransNetV2TPU

    eval_model = TransNetV2TPU()
    eval_model.setup()  # random init now; params swapped per eval

    def evaluate(params, m=None) -> tuple[bool, str]:
        registry.save_params("transnetv2-tpu", params, root=staging)
        if m is None:
            m = eval_model
            m._params = params
        oks = []
        peaks = []
        for frames, cut in two_scene:
            probs = m.predict_transitions(frames)
            peak = int(np.argmax(probs))
            interior = np.concatenate([probs[5 : cut - 5], probs[cut + 5 : -5]])
            ok = (
                abs(peak - cut) <= 2
                and probs[peak] > a.peak_prob
                and probs[peak] > a.separation * interior.max()
            )
            oks.append(ok)
            peaks.append(float(probs[peak]))
        false_max = 0.0
        for frames in continuous:
            probs = m.predict_transitions(frames)
            false_max = max(false_max, float(probs[4:-4].max()))
        oks.append(false_max < a.false_cut)
        msg = (
            f"two-scene ok {sum(oks[:-1])}/{len(two_scene)} "
            f"peaks {['%.2f' % p for p in peaks]} false-max {false_max:.3f}"
        )
        return all(oks), msg

    t0 = time.time()
    for i in range(1, a.max_steps + 1):
        frames, labels = synthesize_batch(
            rng, a.batch, a.window, single_scene_frac=a.single_scene_frac
        )
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(frames), jnp.asarray(labels)
        )
        if i % a.eval_every == 0:
            # evaluate() stages into /tmp/transnet_staging itself; weights/
            # is only published on a full eval pass — a committed tree must
            # never hold a half-trained checkpoint (the golden tests
            # un-skip the moment weights/transnetv2-tpu exists)
            passed, msg = evaluate(params)
            print(
                f"step {i}/{a.max_steps} loss {float(loss):.4f} "
                f"[{(time.time() - t0) / 60:.1f} min] {msg}"
                + (" -> PASS, stopping" if passed else ""),
                flush=True,
            )
            if passed:
                # re-verify through a FRESH registry-loaded model (the
                # exact production path) before touching the committed tree
                fresh = TransNetV2TPU()
                fresh.setup()
                passed2, msg2 = evaluate(params, m=fresh)
                if not passed2:
                    print(f"registry-loaded re-check FAILED ({msg2}); continuing")
                    continue
                ckpt = registry.save_params("transnetv2-tpu", params, root=a.out_dir)
                print(f"published {ckpt}")
                return 0
    print(f"max steps reached without a full eval pass; last kept in {staging} only")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
