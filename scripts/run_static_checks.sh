#!/usr/bin/env bash
# Static-analysis gate: AST lint over the package + the analysis test suite.
# CI and pre-merge hooks call this; it exits nonzero on any finding or test
# failure. See docs/STATIC_ANALYSIS.md for the rule catalogue.
set -euo pipefail

cd "$(dirname "$0")/.."

echo "== curate-lint: AST rules + shardcheck over cosmos_curate_tpu/ =="
# `cosmos-curate-tpu lint` when the console script is installed; module
# invocation otherwise (dev checkouts without `pip install -e .`).
# --shard-check is device-free (jax.eval_shape over an AbstractMesh), so
# it runs on the CPU-only CI image with zero device allocation.
if command -v cosmos-curate-tpu >/dev/null 2>&1; then
  JAX_PLATFORMS=cpu cosmos-curate-tpu lint --shard-check cosmos_curate_tpu
else
  JAX_PLATFORMS=cpu python -m cosmos_curate_tpu.cli.main lint --shard-check cosmos_curate_tpu
fi

echo "== analysis test suite =="
JAX_PLATFORMS=cpu python -m pytest tests/analysis -q

echo "static checks passed"
