#!/usr/bin/env bash
# Static-analysis gate: AST lint over the package + the analysis test suite.
# CI and pre-merge hooks call this; it exits nonzero on any finding or test
# failure. See docs/STATIC_ANALYSIS.md for the rule catalogue.
set -euo pipefail

cd "$(dirname "$0")/.."

echo "== curate-lint: AST rules + shardcheck + concurrency + schema over cosmos_curate_tpu/ =="
# `cosmos-curate-tpu lint` when the console script is installed; module
# invocation otherwise (dev checkouts without `pip install -e .`).
# --shard-check is device-free (jax.eval_shape over an AbstractMesh), so
# it runs on the CPU-only CI image with zero device allocation.
# --concurrency adds the whole-repo lock-order graph / blocking-under-lock
# / guarded-by pass (analysis/concurrency_check.py) — the repo must stay
# concurrency-clean.
# --schema diffs the wire/durable contract surfaces against the
# analysis/schemas/ goldens (analysis/schema_check.py) — drift without a
# version bump, or a breaking durable bump without a migration shim, fails.
if command -v cosmos-curate-tpu >/dev/null 2>&1; then
  JAX_PLATFORMS=cpu cosmos-curate-tpu lint --shard-check --concurrency --schema cosmos_curate_tpu
else
  JAX_PLATFORMS=cpu python -m cosmos_curate_tpu.cli.main lint --shard-check --concurrency --schema cosmos_curate_tpu
fi

echo "== analysis test suite =="
JAX_PLATFORMS=cpu python -m pytest tests/analysis -q

echo "== tracing smoke: 2-stage traced run -> one connected trace + run report =="
# The programmatic equivalent of a `--tracing` run: two trivial stages
# through the pipelined runner (thread-pool hop included), then the flight
# recorder must see exactly ONE trace id and write a well-formed
# report/run_report.json that the report CLI can render.
JAX_PLATFORMS=cpu python - <<'PY'
import json, tempfile
from pathlib import Path

from cosmos_curate_tpu.core.pipeline import run_pipeline
from cosmos_curate_tpu.core.pipelined_runner import PipelinedRunner
from cosmos_curate_tpu.core.stage import Stage
from cosmos_curate_tpu.core.tasks import PipelineTask
from cosmos_curate_tpu.observability import tracing
from cosmos_curate_tpu.observability.flight_recorder import render_report, write_run_report


class Tok(PipelineTask):
    def __init__(self, v):
        self.v = v


class Inc(Stage):
    thread_safe = True

    def process_data(self, tasks):
        return [Tok(t.v + 1) for t in tasks]


class Dbl(Stage):
    thread_safe = True

    def process_data(self, tasks):
        return [Tok(t.v * 2) for t in tasks]


out = tempfile.mkdtemp(prefix="trace_smoke_")
tracing.enable_tracing(f"{out}/profile/traces/driver.ndjson")
runner = PipelinedRunner()
res = run_pipeline([Tok(i) for i in range(8)], [Inc(), Dbl()], runner=runner)
tracing.disable_tracing()
assert sorted(t.v for t in res) == [(i + 1) * 2 for i in range(8)]

report = write_run_report(out, runner=runner)
assert report["connected"] and len(report["trace_ids"]) == 1, (
    f"trace fragments: {report['trace_ids']}"
)
data = json.loads(Path(out, "report", "run_report.json").read_text())
assert data["span_count"] >= 4 and data["critical_path"], data
assert data["critical_path"][0]["name"] == "pipeline.run"
assert "stage_times" in data and "dead_lettered" in data
render_report(data)  # must not raise
print(f"tracing smoke ok: {data['span_count']} spans, one connected trace")
PY

echo "== caption-bench smoke: tiny engine, 2 requests -> efficiency + paged prefix sharing =="
# Tiny end-to-end caption serving check: the benchmark must compute
# pipeline efficiency, the shared-prefix cache must actually fire (every
# request after the warmup's first shares the instruction prefix), and the
# paged KV pool must serve those prefixes COPY-FREE: block references > 0,
# ZERO whole-prefix device-copy dispatches (the deleted insert_prefix
# path), per-request KV reservation strictly below the slot-row worst
# case, and two concurrent owners interleaving decode steps. Under
# paged_attention=kernel the paged programs must actually have run
# (paged_kernel_steps > 0 is the structural no-gathered-working-set proof).
JAX_PLATFORMS=cpu python - <<'PY'
import json, subprocess, sys

proc = subprocess.run(
    [sys.executable, "-m", "benchmarks.caption_benchmark",
     "--config", "tiny", "--requests", "2", "--max-new", "8",
     "--batch", "2", "--frames", "2", "--uniform",
     "--paged-attention", "kernel"],
    capture_output=True, text=True, timeout=1200,
)
assert proc.returncode == 0, proc.stderr[-2000:]
rec = json.loads(proc.stdout.strip().splitlines()[-1])
assert "caption_pipeline_efficiency" in rec, rec
assert rec["caption_pipeline_efficiency"] > 0, rec
assert rec["prefix_cache_hits"] > 0, rec
assert rec["prefill_tokens"] > 0 and rec["prefix_tokens_saved"] > 0, rec
assert "caption_phases" in rec and rec["caption_phases"]["decode_s"] > 0, rec
assert rec["prefix_block_refs"] > 0, rec
assert rec["prefix_copy_dispatches"] == 0, rec
assert rec["kv_bytes_per_request"] < rec["kv_bytes_per_request_worst_case"], rec
assert rec["paged_attention"] == "kernel", rec
assert rec["paged_kernel_steps"] > 0, rec
assert rec["kv_gather_bytes_avoided"] > 0, rec
assert rec["kv_block_size_requested"] == rec["kv_block_size"], rec
cj = rec["cross_job"]
assert cj["interleaved_steps"] > 0, cj
assert all(v > 0 for v in cj["owner_decode_tokens"].values()), cj
print(
    f"caption smoke ok: efficiency {rec['caption_pipeline_efficiency']}, "
    f"{rec['prefix_block_refs']} prefix block refs (0 prefix copies), "
    f"kv {rec['kv_bytes_per_request']:.0f}B/req vs "
    f"{rec['kv_bytes_per_request_worst_case']:.0f}B worst-case, "
    f"{cj['interleaved_steps']} interleaved cross-job steps, "
    f"{rec['paged_kernel_steps']} paged decode steps "
    f"({rec['kv_gather_bytes_avoided']}B gathered-view copies avoided)"
)
PY

echo "== paged-attention parity smoke: kernel vs gather, same prompts =="
# The paged programs (attention reads the KV pool through the block table)
# and the legacy gather-view programs must caption IDENTICALLY on the same
# prompts — greedy byte parity is the contract that lets auto-mode flip
# between them per platform.
JAX_PLATFORMS=cpu python - <<'PY'
from cosmos_curate_tpu.models.vlm import (
    CaptionEngine, CaptionRequest, SamplingConfig, VLM_TINY_TEST,
)

def drive(mode, params=None):
    eng = CaptionEngine(
        VLM_TINY_TEST, max_batch=2, kv_lanes=((64, 1), (128, 1)),
        prefill_chunk=16, paged_attention=mode,
    )
    eng.setup()
    if params is not None:
        eng.params = params
    tok = eng.tokenizer
    for i, text in enumerate(("a quiet street at dusk", "close-up of rain " * 6)):
        eng.add_request(CaptionRequest(
            request_id=f"r{i}", prompt_ids=tok.encode(text),
            sampling=SamplingConfig(max_new_tokens=12),
        ))
    out = {r.request_id: r.text for r in eng.run_until_complete()}
    return out, eng

kernel_out, kernel_eng = drive("kernel")
gather_out, gather_eng = drive("gather", kernel_eng.params)
assert kernel_out == gather_out, (kernel_out, gather_out)
assert kernel_eng.paged_kernel_steps > 0 and gather_eng.paged_kernel_steps == 0
print(f"paged parity smoke ok: {len(kernel_out)} prompts bit-equal across paths")
PY

echo "static checks passed"
