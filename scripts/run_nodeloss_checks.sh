#!/usr/bin/env bash
# Node-loss fault-tolerance gate: a mid-run node death must cost only
# recomputation. Fast units cover the failure detector (heartbeat
# deadlines, rejoin dedup), the lineage tracker, and the runner's
# reconstruction machinery; the e2e suite kills/partitions real loopback
# agents; the soak runs a real split pipeline twice and asserts the
# faulted run's clip set EQUALS the unfaulted baseline's with
# objects_reconstructed > 0, zero dead-letters and ONE connected trace.
# See docs/FAULT_TOLERANCE.md ("Node-loss fault tolerance").
set -euo pipefail

cd "$(dirname "$0")/.."

echo "== fast units: detector + lineage + reconstruction =="
JAX_PLATFORMS=cpu python -m pytest \
  tests/engine/test_node_loss.py \
  -q -p no:randomly -m 'not slow'

echo "== e2e: kill + partition one of the loopback agents (spawns real agents) =="
JAX_PLATFORMS=cpu python -m pytest \
  tests/engine/test_node_loss.py \
  -q -p no:randomly -m slow

echo "== loopback soak: split pipeline, one of two agents SIGKILLed mid-run =="
# a real script file, not a heredoc: the driver's local workers are
# spawned processes that re-import __main__, and '<stdin>' has no path.
# CURATE_LOCKCHECK=1 arms the runtime lock sanitizer in the driver and
# every agent; the soak itself asserts the reports are inversion-free
# (_lockcheck_verdict in scripts/nodeloss_soak.py).
CURATE_LOCKCHECK=1 JAX_PLATFORMS=cpu python scripts/nodeloss_soak.py

echo "node-loss checks passed"
