#!/usr/bin/env bash
# Durable-service gate: the fast tests/service suite (journal, admission,
# dispatch, drain, killpg — also part of tier-1) plus the end-to-end soak:
# boot the real service, submit 2-tenant mixed-priority split jobs, prove
# quota shedding (429 + Retry-After), kill -9 the service mid-run, restart
# against the same work_root, and assert every job reaches `done` with
# resume (no recompute) and no duplicate clip outputs. See docs/SERVICE.md.
set -euo pipefail

cd "$(dirname "$0")/.."

echo "== service unit + integration suites (fast; tier-1 subset) =="
JAX_PLATFORMS=cpu python -m pytest \
  tests/service \
  -q -p no:randomly

echo "== service crash/resume soak (boots the real service, kill -9, restart) =="
JAX_PLATFORMS=cpu timeout -k 10 900 python scripts/service_soak.py

echo "service checks passed"
