"""Loopback node-loss soak (driven by scripts/run_nodeloss_checks.sh).

Two loopback node agents run a real split pipeline twice against the same
corpus: an UNFAULTED baseline, then a faulted run where one agent SIGKILLs
itself (chaos ``agent.kill``) right after relaying its first result — the
instant its outputs are referenced downstream but about to die with it.
The faulted run must prove mid-run node death costs only recomputation:

- the run completes, and its clip output set EQUALS the baseline's
  (fixed-stride clips have deterministic uuid5 ids);
- ``pipeline_objects_reconstructed_total`` > 0 (lineage re-execution ran);
- ZERO dead-lettered batches;
- ONE connected trace (reconstruction re-runs stay in the run's trace).

A real file (not a heredoc) because the driver's local workers are spawned
processes that re-import ``__main__``.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_agent(port: int, node_id: str, extra_env: dict | None = None):
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "CURATE_TRACING": "1",
        "PYTHONPATH": str(REPO),
        **(extra_env or {}),
    }
    return subprocess.Popen(
        [
            sys.executable, "-m", "cosmos_curate_tpu.engine.remote_agent",
            "--driver", f"127.0.0.1:{port}",
            "--node-id", node_id, "--num-cpus", "4",
        ],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, text=True,
    )


def _clip_set(out: Path) -> set[str]:
    kept = {p.stem for p in (out / "metas" / "v0").glob("*.json")}
    filtered = {p.stem for p in (out / "metas" / "filtered").glob("*.json")}
    return kept | filtered


def _run_split(out: Path, vids: Path, port: int, agents: list) -> tuple[dict, object]:
    from cosmos_curate_tpu.core.pipeline import PipelineConfig
    from cosmos_curate_tpu.engine.runner import StreamingRunner
    from cosmos_curate_tpu.pipelines.video.split import SplitPipelineArgs, run_split

    os.environ["CURATE_ENGINE_DRIVER_PORT"] = str(port)
    args = SplitPipelineArgs(
        input_path=str(vids),
        output_path=str(out),
        splitting_algorithm="fixed-stride",
        fixed_stride_len_s=1.0,
        min_clip_len_s=0.5,
        motion_filter="disable",
        extract_fps=(8.0,),
        extract_resize_hw=(224, 224),
        embedding_model="video",
        tracing=True,
    )
    runner = StreamingRunner(poll_interval_s=0.01)
    t0 = time.monotonic()
    summary = run_split(
        args, runner=runner,
        # ~half a core locally: CPU stages place on the agents, so the
        # killed agent provably owned live intermediates
        config=PipelineConfig(num_cpus=0.5),
    )
    print(
        f"soak: {summary['num_clips']} clips in {time.monotonic() - t0:.1f}s "
        f"-> {out}", flush=True,
    )
    return summary, runner


def _lockcheck_verdict(tmp: Path) -> str:
    """With CURATE_LOCKCHECK=1: the driver's in-process recorder plus every
    agent report dumped into the lockcheck dir must be inversion-free —
    the dynamic counterpart of the `lint --concurrency` gate, exercised
    under real node death."""
    from cosmos_curate_tpu.analysis import lock_runtime

    rec = lock_runtime.active()
    if rec is None:
        return "lockcheck: off"
    reports = [rec.report()]
    # agents dump lockcheck-<pid>.json at exit; the SIGKILLed agent
    # never gets the chance — best-effort by design
    for p in sorted((tmp / "lockcheck").glob("lockcheck-*.json")):
        reports.append(json.loads(p.read_text()))
    inversions = [i for r in reports for i in r["inversions"]]
    assert not inversions, f"lock-order inversions under node loss: {inversions}"
    locks = sum(len(r["locks"]) for r in reports)
    return f"lockcheck ok: {len(reports)} report(s), {locks} lock site(s), 0 inversions"


def main() -> int:
    tmp = Path(tempfile.mkdtemp(prefix="nodeloss_soak_"))
    os.environ.update(
        {
            "CURATE_ENGINE_TOKEN": "nodeloss-soak-secret",
            "CURATE_ENGINE_WAIT_NODES": "2",
            "CURATE_ENGINE_WAIT_S": "90",
            "CURATE_PREWARM": "0",
            "CURATE_AGENT_HEARTBEAT_S": "0.5",
            "CURATE_AGENT_HEARTBEAT_MISSES": "3",
            "CURATE_DLQ_DIR": str(tmp / "dlq"),
        }
    )
    if os.environ.get("CURATE_LOCKCHECK"):
        # spawned agents inherit the flag; give every process one report
        # dir so the sweep in _lockcheck_verdict sees them all
        (tmp / "lockcheck").mkdir()
        os.environ["CURATE_LOCKCHECK_REPORT"] = str(tmp / "lockcheck")

    import bench  # corpus generator (deterministic; small override here)

    bench.NUM_VIDEOS = 3
    vids = bench.make_corpus(tmp)
    print(f"soak: corpus of 3 videos at {vids}", flush=True)

    from cosmos_curate_tpu import chaos

    kill_plan = chaos.FaultPlan(
        rules=(
            chaos.FaultRule(
                site=chaos.SITE_AGENT_KILL, kind="crash", count=1,
                worker_re="^doomed-agent$",
            ),
        ),
        seed=13,
    ).to_json()

    # -- pass 1: unfaulted baseline ------------------------------------
    port = _free_port()
    out1 = tmp / "baseline"
    agents = [_spawn_agent(port, "agent-a"), _spawn_agent(port, "agent-b")]
    try:
        summary1, runner1 = _run_split(out1, vids, port, agents)
        assert summary1["num_clips"] > 0, summary1
        baseline = _clip_set(out1)
        assert baseline, "baseline produced no clip metas"
    finally:
        for a in agents:
            a.terminate()
        for a in agents:
            try:
                a.wait(timeout=10)
            except subprocess.TimeoutExpired:
                a.kill()

    # -- pass 2: kill one of two agents mid-run ------------------------
    port = _free_port()
    out2 = tmp / "faulted"
    agents = [
        _spawn_agent(port, "agent-a"),
        _spawn_agent(
            port, "doomed",
            {"CURATE_CHAOS": kill_plan, "CURATE_WORKER_ID": "doomed-agent"},
        ),
    ]
    try:
        summary2, runner2 = _run_split(out2, vids, port, agents)
        assert agents[1].poll() is not None, "chaos agent.kill never fired"

        # 1. same clip output set as the unfaulted run (uuid5 ids are
        # deterministic per video+span: node loss dropped NOTHING)
        faulted = _clip_set(out2)
        assert faulted == baseline, (
            f"clip sets diverged: missing={sorted(baseline - faulted)[:5]} "
            f"extra={sorted(faulted - baseline)[:5]}"
        )

        # 2. the death was declared and lineage reconstruction ran
        assert any(e["node"] == "doomed" for e in runner2.node_events), (
            runner2.node_events
        )
        assert runner2.objects_reconstructed > 0, (
            "node died but nothing was reconstructed"
        )

        # 3. zero dead-letters: recomputation, not data loss
        dead = sum(c["dead_lettered"] for c in runner2.stage_counts.values())
        assert dead == 0, f"dead-lettered batches: {runner2.stage_counts}"

        # 4. ONE connected trace, with node_events in the run report
        report = json.loads((out2 / "report" / "run_report.json").read_text())
        assert report["connected"] and len(report["trace_ids"]) == 1, (
            f"trace fragments: {report['trace_ids']}"
        )
        events = report.get("node_events") or {}
        assert events.get("objects_reconstructed", 0) > 0, events
        print(
            f"soak ok: {len(faulted)} clips match baseline, "
            f"{runner2.objects_reconstructed} object(s) reconstructed in "
            f"{runner2.reconstruction_seconds:.2f}s, 0 dead-letters, "
            f"1 connected trace; report: {out2 / 'report' / 'run_report.json'}",
            flush=True,
        )
        print(f"soak {_lockcheck_verdict(tmp)}", flush=True)
    finally:
        for a in agents:
            a.terminate()
        for a in agents:
            try:
                a.wait(timeout=10)
            except subprocess.TimeoutExpired:
                a.kill()
    return 0


if __name__ == "__main__":
    sys.exit(main())
