"""Durable-service soak (driven by scripts/run_service_checks.sh).

The acceptance round trip from ISSUE 12, against the *real* service
process and the *real* split pipeline:

1. boot the service (`cosmos-curate-tpu serve`) on a scratch work_root,
2. submit mixed-priority jobs from two tenants (+ prove quota shedding:
   an over-quota burst gets 429 + Retry-After, not an unbounded queue),
3. ``kill -9`` the service mid-run — one running job's process group is
   killed with it, another is left orphaned (the restart must reap it),
4. restart against the same work_root,
5. assert every job reaches ``done``, the interrupted job *resumed*
   (records that existed at kill time were not rewritten; strictly fewer
   videos reprocessed than total), and there are no duplicate clip
   outputs (clip files == sum of per-video record clip counts),
6. SIGTERM the service and assert a clean graceful-drain exit.

A real file (not a heredoc) so the service subprocess and its pipeline
workers re-import cleanly.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

POLL_S = 0.5


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _req(port: int, method: str, path: str, body: dict | None = None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _wait_http(port: int, timeout: float = 60.0) -> None:
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        try:
            status, _, _ = _req(port, "GET", "/health")
            if status == 200:
                return
        except (urllib.error.URLError, ConnectionError, TimeoutError):
            pass
        time.sleep(POLL_S)
    raise RuntimeError("service did not come up")


def _start_service(port: int, work_root: Path) -> subprocess.Popen:
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "cosmos_curate_tpu.cli.main", "serve",
            "--host", "127.0.0.1", "--port", str(port),
            "--work-root", str(work_root),
            "--max-concurrent", "2",
            "--cpus-per-job", "0",  # deterministic concurrency on a 1-core CI box
            "--max-queued-per-tenant", "2",
            "--drain-s", "30",
            # live-ops acceptance: a sub-millisecond queue-wait target means
            # every dispatch breaches — /v1/slo must show it per tenant
            "--slo-queue-wait-s", "0.0001",
        ],
        cwd=str(REPO),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        start_new_session=True,
    )
    _wait_http(port)
    return proc


def _make_videos(d: Path, n: int) -> None:
    from tests.fixtures.media import make_scene_video

    d.mkdir(parents=True)
    for i in range(n):
        make_scene_video(d / f"v{i}.mp4", scene_len_frames=24, num_scenes=2)


def _submit_split(port: int, tenant: str, priority: str, inp: Path, out: Path) -> str:
    status, doc, _ = _req(
        port, "POST", "/v1/invoke",
        {
            "pipeline": "split",
            "tenant": tenant,
            "priority": priority,
            "args": {
                "input_path": str(inp),
                "output_path": str(out),
                "fixed_stride_len_s": 1.0,
                "min_clip_len_s": 0.5,
            },
        },
    )
    assert status == 200, (status, doc)
    return doc["job_id"]


def _records(out: Path) -> dict[str, float]:
    """vid -> newest record mtime under <out>/processed_videos."""
    root = out / "processed_videos"
    if not root.is_dir():
        return {}
    return {
        d.name: max(f.stat().st_mtime for f in d.glob("*.json"))
        for d in root.iterdir()
        if d.is_dir() and any(d.glob("*.json"))
    }


def _clip_accounting(out: Path) -> tuple[int, int]:
    """(clip files on disk, clips promised by per-video records)."""
    n_files = len(list((out / "clips").glob("*.mp4"))) if (out / "clips").is_dir() else 0
    promised = 0
    root = out / "processed_videos"
    if root.is_dir():
        for d in root.iterdir():
            recs = sorted(d.glob("*.json"))
            if recs:
                promised += int(json.loads(recs[0].read_text()).get("num_clips_total", 0))
    return n_files, promised


def main() -> int:
    tmp = Path(tempfile.mkdtemp(prefix="service_soak_"))
    work_root = tmp / "svc"
    in_a, out_a = tmp / "in_a", tmp / "out_a"
    in_b, out_b = tmp / "in_b", tmp / "out_b"
    n_a, n_b = 6, 3
    _make_videos(in_a, n_a)
    _make_videos(in_b, n_b)
    port = _free_port()

    print(f"== boot service on :{port} (work_root={work_root})")
    svc = _start_service(port, work_root)
    job_a = job_b = None
    try:
        print("== submit: tenant-a interactive (6 videos), tenant-b batch (3 videos)")
        job_a = _submit_split(port, "tenant-a", "interactive", in_a, out_a)
        job_b = _submit_split(port, "tenant-b", "batch", in_b, out_b)

        print("== quota shed: 3rd queued job from one tenant must get 429")
        empty_in = tmp / "empty"
        empty_in.mkdir()
        shed_ids = [
            _submit_split(port, "tenant-c", "batch", empty_in, tmp / f"out_c{i}")
            for i in range(2)  # fills tenant-c's --max-queued-per-tenant 2
        ]
        status, doc, headers = _req(
            port, "POST", "/v1/invoke",
            {"pipeline": "split", "tenant": "tenant-c",
             "args": {"input_path": str(empty_in), "output_path": str(tmp / "out_c2")}},
        )
        assert status == 429, f"expected shed, got {status}: {doc}"
        assert "Retry-After" in headers, headers
        assert doc["reason"] in ("tenant_queue_full", "queue_full"), doc
        print(f"   shed ok: 429 reason={doc['reason']} Retry-After={headers['Retry-After']}")
        for sid in shed_ids:  # keep the run about tenants a+b
            _req(port, "POST", f"/v1/terminate/{sid}")

        print("== live ops: /v1/jobs/<id>/status serves an in-flight snapshot")
        # the real split job child publishes <out>/report/live/status.json;
        # the service serves it live — well-formed, state=running, with
        # nonzero per-stage queue/busy/in-flight data
        live_proved = False
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            status, doc, _ = _req(port, "GET", f"/v1/jobs/{job_a}/status")
            assert status == 200, (status, doc)
            snap = doc.get("snapshot")
            if doc.get("live") and snap and snap.get("state") == "running":
                stages = snap.get("stages") or {}
                if stages and any(
                    s.get("queue_depth", 0) > 0
                    or s.get("inflight")
                    or s.get("busy_frac", 0) > 0
                    for s in stages.values()
                ):
                    live_proved = True
                    busy = {
                        n: (s.get("queue_depth", 0), len(s.get("inflight") or []))
                        for n, s in stages.items()
                    }
                    print(
                        f"   live snapshot ok: seq={snap.get('seq')} "
                        f"{len(stages)} stages, queue/inflight={busy}"
                    )
                    break
            if _records(out_a):
                break  # job already finishing; don't spin forever
            time.sleep(0.2)
        assert live_proved, "no live snapshot with per-stage data was ever served"

        print("== live ops: readiness payload + per-tenant SLO standing")
        _, health, _ = _req(port, "GET", "/health")
        assert health["ready"] is True, health
        assert health["dispatcher_running"] and health["journal_writable"], health
        _, slo_doc, _ = _req(port, "GET", "/v1/slo")
        assert slo_doc["enabled"] is True, slo_doc
        a_slo = slo_doc["tenants"].get("tenant-a")
        assert a_slo and a_slo["queue_wait"]["breaches"] >= 1, (
            f"tenant-a never breached the 0.1 ms queue-wait target: {slo_doc}"
        )
        print(
            f"   slo ok: tenant-a queue_wait breaches="
            f"{a_slo['queue_wait']['breaches']} "
            f"(mean {a_slo['queue_wait']['mean_s']}s)"
        )

        print("== wait for partial progress on tenant-a, then kill -9 the service")
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            recs = _records(out_a)
            if 1 <= len(recs) < n_a:
                break
            if len(recs) >= n_a:
                raise RuntimeError("job finished before the kill; add videos")
            time.sleep(0.2)
        else:
            raise RuntimeError("no progress before kill deadline")
        pre_kill = _records(out_a)
        print(f"   {len(pre_kill)}/{n_a} videos done at kill time")

        # kill job A's process group WITH the service (job B, if running,
        # is left orphaned: the restart must reap + resume it too)
        status, doc, _ = _req(port, "GET", "/v1/jobs?state=running")
        running_pids = [j["pid"] for j in doc["jobs"] if j["pid"]]
        a_pid = next(
            (j["pid"] for j in doc["jobs"] if j["job_id"] == job_a and j["pid"]), None
        )
        os.killpg(svc.pid, signal.SIGKILL)
        svc.wait(timeout=10)
        if a_pid is not None:
            try:
                os.killpg(a_pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        print(f"   killed service (running job pids at crash: {running_pids})")

        print("== restart service against the same work_root")
        svc = _start_service(port, work_root)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            _, doc, _ = _req(port, "GET", "/v1/jobs")
            states = {j["job_id"]: j["state"] for j in doc["jobs"]}
            if states.get(job_a) == "done" and states.get(job_b) == "done":
                break
            bad = {j: s for j, s in states.items() if s in ("failed", "dead_lettered")}
            assert not (set(bad) & {job_a, job_b}), f"job failed after restart: {bad}"
            time.sleep(1.0)
        else:
            raise RuntimeError(f"jobs not done after restart: {states}")
        print("   both tenants' jobs reached done")

        print("== assert resume (no recompute of pre-kill videos, no duplicate clips)")
        post = _records(out_a)
        assert len(post) == n_a, f"{len(post)}/{n_a} videos processed"
        rewritten = [
            vid for vid, mt in pre_kill.items() if post.get(vid, 0) > mt + 1e-6
        ]
        assert not rewritten, f"resume recomputed already-done videos: {rewritten}"
        assert len(pre_kill) >= 1, "nothing was done pre-kill; kill timing broken"
        print(
            f"   resumed: {len(pre_kill)} pre-kill videos untouched, "
            f"{n_a - len(pre_kill)} processed after restart (< {n_a} total)"
        )
        for out, n in ((out_a, n_a), (out_b, n_b)):
            files, promised = _clip_accounting(out)
            assert files == promised, (
                f"{out}: {files} clip files vs {promised} promised — duplicates!"
            )
        # terminal-state invariant: nothing stuck pending/interrupted
        _, doc, _ = _req(port, "GET", "/v1/jobs")
        stuck = [
            j for j in doc["jobs"]
            if j["state"] not in ("done", "failed", "dead_lettered", "terminated")
        ]
        assert not stuck, f"non-terminal jobs after drain+restart: {stuck}"

        print("== per-job receipt: progress carries summary (+ report when traced)")
        _, doc, _ = _req(port, "GET", f"/v1/progress/{job_a}")
        # the resumed run discovered only the videos the dead run had NOT
        # finished — the summary itself is resume evidence
        assert doc["summary"]["num_videos"] == n_a - len(pre_kill), doc

        print("== graceful drain: SIGTERM exits clean")
        os.kill(svc.pid, signal.SIGTERM)
        rc = svc.wait(timeout=60)
        assert rc == 0, f"drain exit code {rc}"
        print("service soak passed")
        return 0
    finally:
        try:
            os.killpg(svc.pid, signal.SIGKILL)
        except (ProcessLookupError, OSError):
            pass


if __name__ == "__main__":
    sys.exit(main())
