#!/usr/bin/env python3
"""Bench trajectory gate: fail CI on a warm clips/s regression.

The repo accumulates one ``BENCH_r<NN>.json`` per round (a driver-captured
record whose ``tail`` holds bench.py's stdout, including the final NDJSON
metric row), but nothing ever ENFORCED the trajectory — a PR could halve
warm throughput and every gate would stay green. This script compares the
newest round's ``clips_per_sec_split_annotate`` (the warm-pass headline
since PR 4) against the previous round and exits nonzero when it dropped
by more than the threshold (default 20%, ``--threshold`` /
``BENCH_TREND_THRESHOLD``).

Guard rails, because round records are messy field data:

- fewer than two parseable rows → pass with a notice (nothing to compare);
- backend changes (cpu ↔ tpu) are never compared — a TPU row against a CPU
  row is a hardware delta, not a regression;
- ``--json <file>`` compares a freshly produced bench NDJSON row (e.g.
  CI's /tmp/_bench.json) against the newest committed round instead of
  round-vs-round.

Usage::

    python scripts/bench_trend.py                 # newest vs previous round
    python scripts/bench_trend.py --json /tmp/_bench.json
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from pathlib import Path

METRIC = "clips_per_sec_split_annotate"
ROUND_RE = re.compile(r"^BENCH_r(\d+)\.json$")


def extract_row(path: Path) -> dict | None:
    """The final metric row from one BENCH round record (or a raw bench
    NDJSON file). Unparseable files return None — the gate skips them."""
    try:
        text = path.read_text(encoding="utf-8", errors="replace")
    except OSError:
        return None
    rows: list[dict] = []
    try:
        doc = json.loads(text)
        if isinstance(doc, dict) and "tail" in doc:
            text = doc["tail"]
        elif isinstance(doc, dict) and doc.get("metric") == METRIC:
            return doc
    except ValueError:
        pass  # raw NDJSON (bench.py stdout): scan the lines below
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith('{"metric"'):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("metric") == METRIC and isinstance(rec.get("value"), (int, float)):
            rows.append(rec)
    return rows[-1] if rows else None


def round_rows(repo: Path) -> list[tuple[int, Path, dict]]:
    """(round, path, row) for every parseable committed round, ascending."""
    out = []
    for p in repo.glob("BENCH_r*.json"):
        m = ROUND_RE.match(p.name)
        if not m:
            continue
        row = extract_row(p)
        if row is not None:
            out.append((int(m.group(1)), p, row))
    return sorted(out)


def compare(prev: dict, new: dict, threshold: float) -> tuple[bool, str]:
    """(ok, message). ok=True also covers the skip cases."""
    pb, nb = prev.get("backend", "tpu"), new.get("backend", "tpu")
    if pb != nb:
        return True, f"skip: backend changed {pb} -> {nb} (not comparable)"
    pv, nv = float(prev["value"]), float(new["value"])
    if pv <= 0:
        return True, f"skip: previous value {pv} not positive"
    delta = (nv - pv) / pv
    msg = (
        f"{METRIC}: {pv:.3f} -> {nv:.3f} clips/s "
        f"({delta:+.1%}, threshold -{threshold:.0%}, backend={nb})"
    )
    return delta >= -threshold, msg


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--repo", default=str(Path(__file__).resolve().parents[1]),
        help="repo root holding BENCH_r*.json",
    )
    ap.add_argument(
        "--json", default="",
        help="fresh bench NDJSON to compare against the newest round "
        "(instead of round-vs-round)",
    )
    ap.add_argument(
        "--threshold",
        type=float,
        default=float(os.environ.get("BENCH_TREND_THRESHOLD", "0.2")),
        help="max tolerated fractional drop (0.2 = 20%%)",
    )
    args = ap.parse_args(argv)
    repo = Path(args.repo)
    rounds = round_rows(repo)
    if args.json:
        new = extract_row(Path(args.json))
        if new is None:
            print(f"bench-trend FAIL: no {METRIC} row in {args.json}")
            return 1
        if not rounds:
            print("bench-trend: no committed rounds to compare against; pass")
            return 0
        prev = rounds[-1][2]
        label = f"{rounds[-1][1].name} vs {args.json}"
    else:
        if len(rounds) < 2:
            print(
                f"bench-trend: {len(rounds)} parseable round(s); nothing to "
                "compare, pass"
            )
            return 0
        prev, new = rounds[-2][2], rounds[-1][2]
        label = f"{rounds[-2][1].name} vs {rounds[-1][1].name}"
    ok, msg = compare(prev, new, args.threshold)
    print(f"bench-trend [{label}] {msg}")
    if not ok:
        print("bench-trend FAIL: warm throughput regressed past the threshold")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
