#!/usr/bin/env python3
"""Bench trajectory gate: fail CI on a warm clips/s regression.

The repo accumulates one ``BENCH_r<NN>.json`` per round (a driver-captured
record whose ``tail`` holds bench.py's stdout, including the final NDJSON
metric row), but nothing ever ENFORCED the trajectory — a PR could halve
warm throughput and every gate would stay green. This script compares the
newest round's ``clips_per_sec_split_annotate`` (the warm-pass headline
since PR 4) against the previous round and exits nonzero when it dropped
by more than the threshold (default 20%, ``--threshold`` /
``BENCH_TREND_THRESHOLD``).

Guard rails, because round records are messy field data:

- fewer than two parseable rows → pass with a notice (nothing to compare);
- backend changes (cpu ↔ tpu) are never compared — a TPU row against a CPU
  row is a hardware delta, not a regression;
- ``--json <file>`` compares a freshly produced bench NDJSON row (e.g.
  CI's /tmp/_bench.json) against the newest committed round instead of
  round-vs-round.

Rows are also validated against the checked-in BENCH golden schema
(``cosmos_curate_tpu/analysis/schemas/bench-row.json`` — the same snapshot
``lint --schema`` diffs bench.py against). Fresh ``--json`` rows validate
STRICTLY (every required field present, concrete types match): a fresh row
that drifted from the golden means bench.py and the golden disagree and
the trend data would rot. Committed rounds validate leniently — only the
fields this gate consumes (``metric``/``value``/``backend``) — because old
rounds legitimately predate schema versioning.

Usage::

    python scripts/bench_trend.py                 # newest vs previous round
    python scripts/bench_trend.py --json /tmp/_bench.json
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from pathlib import Path

METRIC = "clips_per_sec_split_annotate"
ROUND_RE = re.compile(r"^BENCH_r(\d+)\.json$")
GOLDEN_REL = Path("cosmos_curate_tpu/analysis/schemas/bench-row.json")

# golden type name -> Python types a JSON value may decode to (bool is an
# int subclass, so int/float must exclude it explicitly)
_TYPE_OK = {
    "str": lambda v: isinstance(v, str),
    "int": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "float": lambda v: isinstance(v, (int, float)) and not isinstance(v, bool),
    "bool": lambda v: isinstance(v, bool),
    "list": lambda v: isinstance(v, list),
    "tuple": lambda v: isinstance(v, list),
    "dict": lambda v: isinstance(v, dict),
}


def load_golden_fields(repo: Path) -> dict | None:
    """The BENCH row's golden field table, or None when the golden is
    missing/unreadable (bootstrap repos: validation skips with a notice)."""
    try:
        doc = json.loads((repo / GOLDEN_REL).read_text())
        return doc["schemas"]["row"]["fields"]
    except (OSError, ValueError, KeyError):
        return None


def validate_row(row: dict, fields: dict, *, strict: bool) -> list[str]:
    """Problems with ``row`` against the golden field table. Strict mode
    (fresh rows) checks required-field presence, concrete types, and —
    unless the golden declares a ``<dynamic>`` key — unknown fields.
    Lenient mode (historical committed rounds) checks only the fields the
    trend gate consumes."""
    consumed = ("metric", "value", "backend")
    problems: list[str] = []
    for name, spec in sorted(fields.items()):
        if name == "<dynamic>":
            continue
        if not strict and name not in consumed:
            continue
        if name not in row:
            if strict and spec.get("required"):
                problems.append(f"missing required field {name!r}")
            continue
        check = _TYPE_OK.get(spec.get("type", "any"))
        if check is not None and not check(row[name]):
            problems.append(
                f"field {name!r} is {type(row[name]).__name__}, "
                f"golden says {spec['type']}"
            )
    if strict and "<dynamic>" not in fields:
        for name in sorted(set(row) - set(fields)):
            problems.append(f"unknown field {name!r} (not in the golden)")
    return problems


def extract_row(path: Path) -> dict | None:
    """The final metric row from one BENCH round record (or a raw bench
    NDJSON file). Unparseable files return None — the gate skips them."""
    try:
        text = path.read_text(encoding="utf-8", errors="replace")
    except OSError:
        return None
    rows: list[dict] = []
    try:
        doc = json.loads(text)
        if isinstance(doc, dict) and "tail" in doc:
            text = doc["tail"]
        elif isinstance(doc, dict) and doc.get("metric") == METRIC:
            return doc
    except ValueError:
        pass  # raw NDJSON (bench.py stdout): scan the lines below
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith('{"metric"'):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("metric") == METRIC and isinstance(rec.get("value"), (int, float)):
            rows.append(rec)
    return rows[-1] if rows else None


def round_rows(repo: Path) -> list[tuple[int, Path, dict]]:
    """(round, path, row) for every parseable committed round, ascending."""
    out = []
    for p in repo.glob("BENCH_r*.json"):
        m = ROUND_RE.match(p.name)
        if not m:
            continue
        row = extract_row(p)
        if row is not None:
            out.append((int(m.group(1)), p, row))
    return sorted(out)


def compare(prev: dict, new: dict, threshold: float) -> tuple[bool, str]:
    """(ok, message). ok=True also covers the skip cases."""
    pb, nb = prev.get("backend", "tpu"), new.get("backend", "tpu")
    if pb != nb:
        return True, f"skip: backend changed {pb} -> {nb} (not comparable)"
    pv, nv = float(prev["value"]), float(new["value"])
    if pv <= 0:
        return True, f"skip: previous value {pv} not positive"
    delta = (nv - pv) / pv
    msg = (
        f"{METRIC}: {pv:.3f} -> {nv:.3f} clips/s "
        f"({delta:+.1%}, threshold -{threshold:.0%}, backend={nb})"
    )
    return delta >= -threshold, msg


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--repo", default=str(Path(__file__).resolve().parents[1]),
        help="repo root holding BENCH_r*.json",
    )
    ap.add_argument(
        "--json", default="",
        help="fresh bench NDJSON to compare against the newest round "
        "(instead of round-vs-round)",
    )
    ap.add_argument(
        "--threshold",
        type=float,
        default=float(os.environ.get("BENCH_TREND_THRESHOLD", "0.2")),
        help="max tolerated fractional drop (0.2 = 20%%)",
    )
    args = ap.parse_args(argv)
    repo = Path(args.repo)
    rounds = round_rows(repo)
    golden = load_golden_fields(repo)
    if golden is None:
        print(f"bench-trend: no golden at {GOLDEN_REL}; schema check skipped")
    else:
        for _, p, row in rounds:
            for prob in validate_row(row, golden, strict=False):
                print(f"bench-trend warning [{p.name}]: {prob}")
    if args.json:
        new = extract_row(Path(args.json))
        if new is None:
            print(f"bench-trend FAIL: no {METRIC} row in {args.json}")
            return 1
        if golden is not None:
            problems = validate_row(new, golden, strict=True)
            if problems:
                for prob in problems:
                    print(f"bench-trend FAIL [{args.json}]: {prob}")
                print(
                    "bench-trend FAIL: fresh row drifted from the BENCH "
                    "golden schema (bench.py and "
                    f"{GOLDEN_REL.name} disagree — run "
                    "`cosmos-curate-tpu lint --schema`)"
                )
                return 1
        if not rounds:
            print("bench-trend: no committed rounds to compare against; pass")
            return 0
        prev = rounds[-1][2]
        label = f"{rounds[-1][1].name} vs {args.json}"
    else:
        if len(rounds) < 2:
            print(
                f"bench-trend: {len(rounds)} parseable round(s); nothing to "
                "compare, pass"
            )
            return 0
        prev, new = rounds[-2][2], rounds[-1][2]
        label = f"{rounds[-2][1].name} vs {rounds[-1][1].name}"
    ok, msg = compare(prev, new, args.threshold)
    print(f"bench-trend [{label}] {msg}")
    if not ok:
        print("bench-trend FAIL: warm throughput regressed past the threshold")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
