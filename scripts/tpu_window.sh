#!/bin/bash
# TPU-window watcher: the moment the relay recovers, train all
# self-trainable weights, commit them, run the weights-gated goldens,
# validate the Pallas kernels on chip, then re-run the bench.
#
# Background: the axon TPU relay on this box wedges for hours at a time
# (docs in ROUND3_NOTES.md). Run this under nohup at session start so any
# live window is used automatically:
#   nohup bash scripts/tpu_window.sh >> /tmp/train_when_tpu.log 2>&1 &
cd /root/repo
export CURATE_JAX_CACHE_DIR=/tmp/curate_jax_cache
log() { echo "[$(date +%H:%M:%S)] $*"; }
for i in $(seq 1 700); do
  if timeout 90 python -c "import jax; assert jax.devices()[0].platform=='tpu'" 2>/dev/null; then
    log "TPU alive at attempt $i"
    ok=1
    if [ ! -f weights/transnetv2-tpu/params.msgpack ]; then
      log "training transnet"
      timeout 3000 python -m cosmos_curate_tpu.models.transnet_train --steps 600 --out-dir /root/repo/weights && log TRANSNET_OK || { log "transnet failed rc=$?"; ok=0; }
    fi
    if [ $ok = 1 ] && [ ! -f weights/ocr-detector-tpu/params.msgpack ]; then
      log "training ocr"
      timeout 3600 python -m cosmos_curate_tpu.models.ocr_train --out-dir /root/repo/weights && log OCR_OK || { log "ocr failed rc=$?"; ok=0; }
    fi
    if [ $ok = 1 ] && [ ! -f weights/super-resolution-tpu/params.msgpack ]; then
      log "training sr"
      timeout 3000 python -m cosmos_curate_tpu.models.sr_train --out-dir /root/repo/weights && log SR_OK || { log "sr failed rc=$?"; ok=0; }
    fi
    if [ $ok = 1 ] && [ ! -f weights/tracker-siamese-tpu/params.msgpack ]; then
      log "training tracker"
      timeout 3000 python -m cosmos_curate_tpu.models.tracker_train --out-dir /root/repo/weights && log TRACKER_OK || { log "tracker failed rc=$?"; ok=0; }
    fi
    if [ $ok = 0 ]; then sleep 60; continue; fi
    log "ALL_TRAINED — committing weights"
    git add weights/ && git -c user.name=distsys-graft -c user.email=graft@local \
      commit -m "Stage trained weights for transnet/OCR/SR/tracker" --no-verify || true
    log "running goldens"
    PYTHONPATH= JAX_PLATFORMS=cpu timeout 1800 python -m pytest tests/models -q 2>&1 | tail -3
    log "validating Pallas kernels on chip"
    timeout 1200 python -m benchmarks.kernel_validation > /tmp/kernel_validation.json 2>/dev/null && log KERNELS_OK || log "kernel validation FAILED (see /tmp/kernel_validation.json)"
    cat /tmp/kernel_validation.json 2>/dev/null
    if [ ! -f /tmp/bench_r03_done ]; then
      log "running bench"
      timeout 3600 python bench.py > /tmp/bench_r03.out 2>&1 && touch /tmp/bench_r03_done
      tail -2 /tmp/bench_r03.out
    fi
    log "watcher complete"
    exit 0
  fi
  sleep 60
done
log "TPU never recovered"
exit 1
