#!/bin/bash
# TPU-window watcher: the moment the relay recovers, convert the window
# into committed artifacts INCREMENTALLY, smallest model first, so even a
# 30-minute window yields a committed transnet and a chip-backed bench:
#
#   transnet (600 steps) -> commit -> bench on chip -> commit BENCH json
#   -> OCR -> commit -> SR -> commit -> tracker -> commit
#   -> diffusion-SR -> commit -> goldens -> kernel validation
#   -> final bench refresh
#
# Background: the axon TPU relay on this box wedges for hours at a time
# (docs in ROUND3_NOTES.md). Run this under nohup at session start so any
# live window is used automatically:
#   nohup bash scripts/tpu_window.sh >> /tmp/train_when_tpu.log 2>&1 &
cd /root/repo
export CURATE_JAX_CACHE_DIR=/tmp/curate_jax_cache
log() { echo "[$(date +%H:%M:%S)] $*"; }

commit_weights() { # $1 = model name; stages only that model's dir
  git add "weights/$1" && git -c user.name=distsys-graft -c user.email=graft@local \
    commit -m "Stage trained $1 weights from TPU window" --no-verify || true
}

run_bench() { # $1 = tag for the log/commit message
  log "running bench ($1)"
  timeout 3600 python bench.py > /tmp/bench_tpu_$1.out 2>&1
  rc=$?
  tail -2 /tmp/bench_tpu_$1.out
  # Commit the bench output as evidence only if it actually ran on chip.
  # bench.py emits a "backend" key ONLY on a non-TPU fallback, so the chip
  # check is rc=0 and no backend key in the final JSON line.
  if [ $rc = 0 ] && tail -1 /tmp/bench_tpu_$1.out | python -c '
import json, sys
rec = json.loads(sys.stdin.readline())
chip = rec.get("backend") in (None, "tpu") and "caption_backend" not in rec
sys.exit(0 if chip else 1)
' 2>/dev/null; then
    tail -1 /tmp/bench_tpu_$1.out > BENCH_TPU.json
    cp BENCH_TPU.json BENCH_r05.json
    git add BENCH_TPU.json BENCH_r05.json \
      && git -c user.name=distsys-graft -c user.email=graft@local \
        commit -m "Chip-backed bench result ($1)" --no-verify || true
    return 0
  fi
  return 1
}

train_one() { # $1 = weights dir name, $2 = module, $3 = timeout, extra args...
  name=$1; module=$2; tmo=$3; shift 3
  if [ -f "weights/$name/params.msgpack" ]; then
    # Guard against a truncated checkpoint from a pre-atomic-write run:
    # only skip retraining if the msgpack actually parses.
    if PYTHONPATH= python -c "
import sys, flax.serialization as s
s.msgpack_restore(open('weights/$name/params.msgpack','rb').read())
" 2>/dev/null; then
      # May exist from an earlier interrupted watcher run without having
      # been committed — commit_weights no-ops when clean.
      if [ -n "$(git status --porcelain "weights/$name")" ]; then commit_weights "$name"; fi
      return 0
    fi
    log "$name checkpoint corrupt — retraining"
    rm -f "weights/$name/params.msgpack"
  fi
  log "training $name"
  timeout "$tmo" python -m "$module" --out-dir /root/repo/weights "$@"
  rc=$?
  if [ $rc = 0 ]; then
    log "${name}_OK"
    commit_weights "$name"
    return 0
  fi
  log "$name failed rc=$rc"
  return 1
}

benched=0
for i in $(seq 1 700); do
  if ! timeout 90 python -c "import jax; assert jax.devices()[0].platform=='tpu'" 2>/dev/null; then
    sleep 60
    continue
  fi
  log "TPU alive at attempt $i"
  # Smallest first; each trainer commits its own weights on success.
  # TransNet goes through the EVAL-GATED script (publishes into weights/
  # only when the golden-margin criteria pass — a raw train_and_stage run
  # would commit an unverified checkpoint and un-skip the goldens red).
  if [ ! -f weights/transnetv2-tpu/params.msgpack ]; then
    timeout 3000 python scripts/train_transnet_cpu.py --out-dir weights \
      && commit_weights transnetv2-tpu || { sleep 60; continue; }
  fi
  # First chip bench as soon as the canonical transnet config can activate.
  if [ $benched = 0 ] && run_bench after-transnet; then benched=1; fi
  train_one ocr-detector-tpu cosmos_curate_tpu.models.ocr_train 3600 || { sleep 60; continue; }
  train_one super-resolution-tpu cosmos_curate_tpu.models.sr_train 3000 || { sleep 60; continue; }
  train_one tracker-siamese-tpu cosmos_curate_tpu.models.tracker_train 3000 || { sleep 60; continue; }
  train_one diffusion-sr-tpu cosmos_curate_tpu.models.diffusion_sr_train 3600 || { sleep 60; continue; }
  log "ALL_TRAINED — running goldens"
  PYTHONPATH= JAX_PLATFORMS=cpu timeout 1800 python -m pytest tests/models -q 2>&1 | tail -3
  log "validating Pallas kernels on chip"
  timeout 1200 python -m benchmarks.kernel_validation > /tmp/kernel_validation.json 2>/dev/null \
    && log KERNELS_OK || log "kernel validation FAILED (see /tmp/kernel_validation.json)"
  cat /tmp/kernel_validation.json 2>/dev/null
  run_bench final || true
  log "watcher complete"
  exit 0
done
log "TPU never recovered"
exit 1
