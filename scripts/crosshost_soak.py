"""Loopback cross-host soak (driven by scripts/run_crosshost_checks.sh).

One driver + one loopback node agent run a real split pipeline: the
per-node planner must put the CPU stages on the agent and keep the
TPU-declared embed stage in-process on the driver; the run must yield ONE
connected trace and object-plane evidence that push-ahead prefetch
overlapped compute. A real file (not a heredoc) because the driver's local
workers are spawned processes that re-import ``__main__``.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))


def main() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    tmp = Path(tempfile.mkdtemp(prefix="crosshost_soak_"))
    out = tmp / "out"
    trace_dir = out / "profile" / "traces"
    trace_dir.mkdir(parents=True)

    os.environ.update(
        {
            "CURATE_ENGINE_TOKEN": "crosshost-soak-secret",
            "CURATE_ENGINE_DRIVER_PORT": str(port),
            "CURATE_ENGINE_WAIT_NODES": "1",
            "CURATE_ENGINE_WAIT_S": "90",
            "CURATE_PREWARM": "0",
            "CURATE_TRACE_DIR": str(trace_dir),
        }
    )

    import bench  # corpus generator (deterministic; small override here)

    bench.NUM_VIDEOS = 3
    vids = bench.make_corpus(tmp)
    print(f"soak: corpus of 3 videos at {vids}", flush=True)

    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "CURATE_TRACING": "1",  # the agent joins the driver's trace
        "PYTHONPATH": str(REPO),
    }
    agent = subprocess.Popen(
        [
            sys.executable, "-m", "cosmos_curate_tpu.engine.remote_agent",
            "--driver", f"127.0.0.1:{port}",
            "--node-id", "loopback-agent", "--num-cpus", "4",
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        from cosmos_curate_tpu.core.pipeline import PipelineConfig
        from cosmos_curate_tpu.engine.runner import StreamingRunner
        from cosmos_curate_tpu.pipelines.video.split import (
            SplitPipelineArgs,
            run_split,
        )

        args = SplitPipelineArgs(
            input_path=str(vids),
            output_path=str(out),
            splitting_algorithm="fixed-stride",
            fixed_stride_len_s=1.0,
            min_clip_len_s=0.5,
            motion_filter="disable",
            extract_fps=(8.0,),
            extract_resize_hw=(224, 224),
            embedding_model="video",
            tracing=True,
        )
        runner = StreamingRunner(poll_interval_s=0.01)
        t0 = time.monotonic()
        summary = run_split(
            args,
            runner=runner,
            # ~half a core locally: the planner must put the CPU stages on
            # the agent while the TPU-declared embed stage stays
            # driver-in-process
            config=PipelineConfig(num_cpus=0.5),
        )
        wall = time.monotonic() - t0
        assert summary["num_clips"] > 0, summary
        print(
            f"soak: {summary['num_clips']} clips "
            f"({summary['num_with_embeddings']} embedded) in {wall:.1f}s",
            flush=True,
        )

        # 1. the per-node plan split the pipeline as prescribed
        plan = runner.node_plan
        assert plan, "no per-node plan was emitted"
        embed = plan.get("ClipEmbeddingStage", {})
        assert set(embed) == {""}, f"embed stage left the driver: {embed}"
        agent_cpu_stages = [
            name
            for name, counts in plan.items()
            if counts.get("loopback-agent", 0) > 0
        ]
        assert agent_cpu_stages, f"no CPU stage placed on the agent: {plan}"
        print(f"soak: agent-placed stages: {agent_cpu_stages}", flush=True)

        # 2. ONE connected trace across driver + agent + workers
        report_file = out / "report" / "run_report.json"
        report = json.loads(report_file.read_text())
        assert report["connected"] and len(report["trace_ids"]) == 1, (
            f"trace fragments: {report['trace_ids']}"
        )

        # 3. object-plane prefetch overlapped compute
        plane = report.get("object_plane") or {}
        moved = sum(
            a.get("fetch_bytes", 0) + a.get("prefetch_bytes", 0)
            for a in plane.values()
        )
        assert moved > 0, f"pipeline_object_plane_bytes_total == 0: {plane}"
        hits = sum(a.get("prefetch_hits", 0) for a in plane.values())
        hit_wait = sum(a.get("prefetch_hit_wait_s", 0.0) for a in plane.values())
        transfer = sum(a.get("prefetch_transfer_s", 0.0) for a in plane.values())
        assert hits > 0, f"prefetch never hit: {plane}"
        assert hit_wait < transfer, (
            f"prefetch wait {hit_wait:.3f}s >= transfer {transfer:.3f}s: "
            "transfers did not overlap compute"
        )
        print(
            f"soak ok: {moved / 1e6:.1f}MB over the object plane, "
            f"{hits} prefetch hits, wait {hit_wait:.3f}s < transfer "
            f"{transfer:.3f}s; report: {report_file}",
            flush=True,
        )
    finally:
        agent.terminate()
        try:
            agent.wait(timeout=10)
        except subprocess.TimeoutExpired:
            agent.kill()
    return 0


if __name__ == "__main__":
    sys.exit(main())
