#!/usr/bin/env bash
# Chaos / fault-tolerance gate: the fast chaos unit suites (also part of
# tier-1) plus the slow end-to-end fault-injection tests that spawn real
# worker pools (crash→requeue, hang→deadline-kill, exhausted→DLQ→requeue,
# and the mixed-fault soak). See docs/FAULT_TOLERANCE.md.
set -euo pipefail

cd "$(dirname "$0")/.."

echo "== chaos unit suites (fast; tier-1 subset) =="
JAX_PLATFORMS=cpu python -m pytest \
  tests/chaos \
  tests/storage/test_retry.py \
  tests/engine/test_dead_letter.py \
  tests/analysis/test_ad_hoc_backoff.py \
  -q -p no:randomly

echo "== service chaos suites (journal outage, job-crash retry, kill -9 replay) =="
# the service.job.crash / service.journal.write sites plus the durable
# queue's crash-recovery paths (tests/service, all fast)
JAX_PLATFORMS=cpu python -m pytest \
  tests/service/test_job_queue.py \
  tests/service/test_admission.py \
  tests/service/test_durable_service.py \
  -q -p no:randomly

echo "== pipelined-runner chaos + smoke (in-process, fast) =="
# crash-site coverage, retry/drop->DLQ, and the 2-stage CPU smoke for the
# stage-overlapped runner (core/pipelined_runner.py)
JAX_PLATFORMS=cpu python -m pytest \
  tests/core/test_pipelined_runner.py \
  -q -p no:randomly

echo "== chaos end-to-end + soak (spawns real worker pools) =="
# -m '' overrides the default marker filter so the @slow suites run here
JAX_PLATFORMS=cpu python -m pytest \
  tests/engine/test_chaos_faults.py -q -p no:randomly -m ''

echo "== live-ops closed loop (hang -> stuck_batch anomaly BEFORE the deadline kill) =="
# the anomaly detector watching a chaos worker.batch.hang must emit
# stuck_batch while the batch is still hung — proving detection beats the
# batch_timeout_s kill (fast detector units ride tier-1 in
# tests/observability/test_anomaly.py)
JAX_PLATFORMS=cpu python -m pytest \
  tests/observability/test_anomaly_chaos.py -q -p no:randomly -m ''

echo "chaos checks passed"
