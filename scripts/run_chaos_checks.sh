#!/usr/bin/env bash
# Chaos / fault-tolerance gate: the fast chaos unit suites (also part of
# tier-1) plus the slow end-to-end fault-injection tests that spawn real
# worker pools (crash→requeue, hang→deadline-kill, exhausted→DLQ→requeue,
# and the mixed-fault soak). See docs/FAULT_TOLERANCE.md.
set -euo pipefail

cd "$(dirname "$0")/.."

echo "== chaos unit suites (fast; tier-1 subset) =="
JAX_PLATFORMS=cpu python -m pytest \
  tests/chaos \
  tests/storage/test_retry.py \
  tests/engine/test_dead_letter.py \
  tests/analysis/test_ad_hoc_backoff.py \
  -q -p no:randomly

echo "== service chaos suites (journal outage, job-crash retry, kill -9 replay) =="
# the service.job.crash / service.journal.write sites plus the durable
# queue's crash-recovery paths (tests/service, all fast)
JAX_PLATFORMS=cpu python -m pytest \
  tests/service/test_job_queue.py \
  tests/service/test_admission.py \
  tests/service/test_durable_service.py \
  -q -p no:randomly

echo "== pipelined-runner chaos + smoke (in-process, fast) =="
# crash-site coverage, retry/drop->DLQ, and the 2-stage CPU smoke for the
# stage-overlapped runner (core/pipelined_runner.py)
JAX_PLATFORMS=cpu python -m pytest \
  tests/core/test_pipelined_runner.py \
  -q -p no:randomly

echo "== chaos end-to-end + soak (spawns real worker pools) =="
# -m '' overrides the default marker filter so the @slow suites run here.
# CURATE_LOCKCHECK=1 arms the runtime lock sanitizer (the dynamic twin of
# `lint --concurrency`): every repo-created Lock/RLock is proxied, and the
# driver + every spawned worker dumps a lockcheck-<pid>.json into the
# report dir at exit. The sweep below fails the gate on any observed
# lock-order inversion.
LOCKCHECK_DIR="$(mktemp -d /tmp/chaos_lockcheck.XXXXXX)"
CURATE_LOCKCHECK=1 CURATE_LOCKCHECK_REPORT="$LOCKCHECK_DIR" \
  JAX_PLATFORMS=cpu python -m pytest \
  tests/engine/test_chaos_faults.py -q -p no:randomly -m ''

echo "== lockcheck sweep: soak must be inversion-free =="
LOCKCHECK_DIR="$LOCKCHECK_DIR" JAX_PLATFORMS=cpu python - <<'PY'
import json, os
from pathlib import Path

reports = sorted(Path(os.environ["LOCKCHECK_DIR"]).glob("lockcheck-*.json"))
assert reports, "sanitizer-enabled soak produced no lockcheck reports"
inversions = []
for p in reports:
    data = json.loads(p.read_text())
    inversions.extend(data["inversions"])
assert not inversions, f"lock-order inversions under chaos: {inversions}"
locks = sum(len(json.loads(p.read_text())["locks"]) for p in reports)
print(f"lockcheck ok: {len(reports)} report(s), {locks} lock site(s), 0 inversions")
PY
rm -rf "$LOCKCHECK_DIR"

echo "== live-ops closed loop (hang -> stuck_batch anomaly BEFORE the deadline kill) =="
# the anomaly detector watching a chaos worker.batch.hang must emit
# stuck_batch while the batch is still hung — proving detection beats the
# batch_timeout_s kill (fast detector units ride tier-1 in
# tests/observability/test_anomaly.py)
JAX_PLATFORMS=cpu python -m pytest \
  tests/observability/test_anomaly_chaos.py -q -p no:randomly -m ''

echo "chaos checks passed"
