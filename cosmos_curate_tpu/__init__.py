"""cosmos-curate-tpu: a TPU-native video curation framework.

A brand-new JAX/XLA/Pallas implementation of the capabilities of
nvidia-cosmos/cosmos-curate (reference at /root/reference): a streaming,
auto-scaled, multi-stage pipeline system that ingests raw video, shot-detects
and splits it into clips, transcodes on CPU, filters, embeds, captions with
vision-language models, semantically deduplicates, and shards webdatasets.

Design stance (see SURVEY.md §7): the pipeline *shape* (streaming stages,
worker pools, object-store refs) is device-agnostic and kept; every
CUDA-touching leaf is replaced with a JAX/TPU equivalent. Model parallelism is
pjit/shard_map over a `jax.sharding.Mesh` (ICI within a slice, DCN across
slices) instead of NCCL; video decode/encode stays CPU-side.
"""

__version__ = "0.1.0"

# Opt-in runtime lock sanitizer (the dynamic twin of `lint --concurrency`):
# CURATE_LOCKCHECK=1 proxies every repo-created threading.Lock/RLock to
# record acquisition order, inversions, and blocking-under-lock, dumping
# lockcheck_report.json at exit. No-op (and zero overhead) otherwise.
import os as _os

if _os.environ.get("CURATE_LOCKCHECK", "") in ("1", "true", "yes"):
    from cosmos_curate_tpu.analysis import lock_runtime as _lock_runtime

    _lock_runtime.maybe_install_from_env()
