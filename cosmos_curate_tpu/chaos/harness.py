"""Fault-plan model and the per-process injection runtime.

Design constraints (ISSUE 2):

- **Guaranteed no-op when disabled.** ``fire(site)`` is the only call on
  production hot paths; while no plan is installed it is a single falsy
  check on a module global. Env parsing happens once, at install time,
  never per call.
- **Deterministic.** Each armed rule owns a ``random.Random`` seeded from
  ``(plan.seed, site)``, so a given plan produces the same fire/skip
  sequence every run — chaos tests are reproducible, not flaky.
- **Cross-process.** Plans serialize to JSON and ride the ``CURATE_CHAOS``
  env var into spawned workers (engine/pool.py forwards it); each process
  arms its own counters, so ``count`` bounds firings *per process*.

Fault kinds:

- ``crash``   — ``os._exit(exit_code)``: a worker death with no exception,
  no cleanup (the reaper path, not the retry path).
- ``hang``    — ``time.sleep(delay_s)``: a deadlocked decoder / stuck
  socket stand-in. Pair with ``StageSpec.batch_timeout_s``.
- ``error``   — raise :class:`InjectedFault` (a ``ConnectionError``
  subclass, so connection-drop and storage-timeout handling paths treat
  it exactly like the real thing).
- ``delay``   — ``time.sleep(delay_s)`` then continue: injected latency
  without failure (slow-network soak tests).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from dataclasses import dataclass, field

CHAOS_ENV = "CURATE_CHAOS"

# Named injection sites. Adding a site = embedding one fire() call and
# listing the name here (tests assert the catalogue matches the docs).
SITE_WORKER_CRASH = "worker.batch.crash"
SITE_WORKER_HANG = "worker.batch.hang"
SITE_OBJECT_CHANNEL_FETCH = "object_channel.fetch"
SITE_OBJECT_CHANNEL_SERVE = "object_channel.serve"
SITE_REMOTE_PLANE_SEND = "remote_plane.send"
SITE_REMOTE_PLANE_RECV = "remote_plane.recv"
SITE_STORAGE_REQUEST = "storage.request"
# Service-layer sites (service/app.py + service/job_queue.py): a job
# subprocess dying at startup (crash kind rides CURATE_CHAOS into the
# child; pair with FaultRule.worker_re against the stamped
# CURATE_WORKER_ID=job-<id>-a<attempt> to fault only the first attempt),
# and the durable journal's append path failing mid-write.
SITE_SERVICE_JOB_CRASH = "service.job.crash"
SITE_SERVICE_JOURNAL_WRITE = "service.journal.write"
# Node-loss sites (engine/remote_agent.py): agent.kill fires in the recv
# loop AND right after a successful result relay (kind=crash: os._exit, a
# whole-node SIGKILL — the post-result site dies at the most hostile
# instant, with outputs the driver already references); agent.partition
# fires on every frame in both directions (kind=hang with delay_s: frames
# stall, heartbeats miss, the driver's failure detector declares the node
# dead; when the sleep ends the agent's next send fails against the
# quarantined socket and it reconnects as a fresh node). Pin to one agent
# of a fleet via FaultRule.worker_re against CURATE_WORKER_ID stamped into
# that agent's environment.
SITE_AGENT_KILL = "agent.kill"
SITE_AGENT_PARTITION = "agent.partition"

ALL_SITES = (
    SITE_WORKER_CRASH,
    SITE_WORKER_HANG,
    SITE_OBJECT_CHANNEL_FETCH,
    SITE_OBJECT_CHANNEL_SERVE,
    SITE_REMOTE_PLANE_SEND,
    SITE_REMOTE_PLANE_RECV,
    SITE_STORAGE_REQUEST,
    SITE_SERVICE_JOB_CRASH,
    SITE_SERVICE_JOURNAL_WRITE,
    SITE_AGENT_KILL,
    SITE_AGENT_PARTITION,
)

_KINDS = ("crash", "hang", "error", "delay")


class InjectedFault(ConnectionError):
    """Raised by ``error``-kind rules.

    Subclasses ``ConnectionError`` deliberately: the object channel, the
    remote plane and the storage retry loops already handle connection
    failures, and an injected fault must flow through those *production*
    handlers, not a parallel test-only path.
    """

    def __init__(self, site: str) -> None:
        super().__init__(f"chaos: injected fault at {site}")
        self.site = site


@dataclass(frozen=True)
class FaultRule:
    """Arm one site: fire with ``probability`` up to ``count`` times."""

    site: str
    kind: str = "error"
    probability: float = 1.0
    count: int | None = None  # max firings in this process; None = unlimited
    delay_s: float = 0.0  # hang/delay duration
    exit_code: int = 17  # crash exit code (distinguishable from real deaths)
    # only fire in workers whose CURATE_WORKER_ID matches this regex ('' =
    # all processes). Worker ids are deterministic (s<stage>-<name>-p<n>),
    # so e.g. "-p0$" faults the FIRST worker and lets its replacement
    # survive — the crash-then-recover shape most chaos tests want.
    worker_re: str = ""

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; one of {_KINDS}")
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(f"probability must be in [0, 1], got {self.probability}")
        if self.count is not None and self.count < 0:
            raise ValueError(f"count must be >= 0, got {self.count}")


@dataclass(frozen=True)
class FaultPlan:
    """A set of armed rules plus the seed that makes them deterministic."""

    rules: tuple[FaultRule, ...] = ()
    seed: int = 0

    def to_json(self) -> str:
        return json.dumps(
            {
                "seed": self.seed,
                "rules": [
                    {
                        "site": r.site,
                        "kind": r.kind,
                        "probability": r.probability,
                        "count": r.count,
                        "delay_s": r.delay_s,
                        "exit_code": r.exit_code,
                        "worker_re": r.worker_re,
                    }
                    for r in self.rules
                ],
            }
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        doc = json.loads(text)
        return cls(
            seed=int(doc.get("seed", 0)),
            rules=tuple(FaultRule(**r) for r in doc.get("rules", ())),
        )


class _ArmedRule:
    """Per-process mutable state for one rule (RNG + remaining budget)."""

    def __init__(self, rule: FaultRule, seed: int) -> None:
        import re

        self.rule = rule
        self.rng = random.Random(f"{seed}:{rule.site}")
        self.remaining = rule.count  # None = unlimited
        self.fired = 0
        self.lock = threading.Lock()
        self.worker_pat = re.compile(rule.worker_re) if rule.worker_re else None

    def should_fire(self) -> bool:
        if self.worker_pat is not None and not self.worker_pat.search(
            os.environ.get("CURATE_WORKER_ID", "")
        ):
            return False
        with self.lock:
            if self.remaining is not None and self.remaining <= 0:
                return False
            if self.rule.probability < 1.0 and self.rng.random() >= self.rule.probability:
                return False
            if self.remaining is not None:
                self.remaining -= 1
            self.fired += 1
            return True


class _ActivePlan:
    def __init__(self, plan: FaultPlan) -> None:
        self.plan = plan
        self.by_site: dict[str, _ArmedRule] = {
            r.site: _ArmedRule(r, plan.seed) for r in plan.rules
        }

    def fire(self, site: str) -> None:
        armed = self.by_site.get(site)
        if armed is None or not armed.should_fire():
            return
        rule = armed.rule
        if rule.kind == "crash":
            os._exit(rule.exit_code)
            return  # only reachable when tests stub os._exit
        if rule.kind in ("hang", "delay"):
            time.sleep(rule.delay_s)
            return
        raise InjectedFault(site)


# THE hot-path global: None while chaos is disabled. fire() below is the
# only thing production code calls, and its disabled cost is one falsy
# check — install()/uninstall() do all the work.
_active: _ActivePlan | None = None


def fire(site: str) -> None:
    """Injection-site entry point; a no-op unless a plan arms ``site``."""
    active = _active
    if active is None:
        return
    active.fire(site)


def enabled() -> bool:
    return _active is not None


def fire_count(site: str) -> int:
    """How many times ``site`` has fired in this process (tests/metrics)."""
    active = _active
    if active is None:
        return 0
    armed = active.by_site.get(site)
    return armed.fired if armed is not None else 0


def install(plan: FaultPlan, *, export_env: bool = False) -> None:
    """Arm ``plan`` in this process. ``export_env=True`` additionally
    writes it to ``CURATE_CHAOS`` so worker processes spawned *after* this
    call inherit and arm the same plan."""
    global _active
    unknown = [r.site for r in plan.rules if r.site not in ALL_SITES]
    if unknown:
        raise ValueError(f"unknown chaos site(s): {unknown}; known: {list(ALL_SITES)}")
    sites = [r.site for r in plan.rules]
    dupes = sorted({s for s in sites if sites.count(s) > 1})
    if dupes:
        # one armed rule per site: silently keeping only the last rule
        # would make a chaos test exercise less than it claims
        raise ValueError(f"duplicate rule(s) for site(s): {dupes}")
    _active = _ActivePlan(plan)
    if export_env:
        os.environ[CHAOS_ENV] = plan.to_json()


def uninstall() -> None:
    """Disarm; also clears ``CURATE_CHAOS`` from this environment."""
    global _active
    _active = None
    os.environ.pop(CHAOS_ENV, None)


def install_from_env() -> bool:
    """Arm from ``CURATE_CHAOS`` if present; True when a plan was armed.

    Called once at process bring-up (worker_main, agent main) — NOT on any
    per-batch path."""
    text = os.environ.get(CHAOS_ENV, "")
    if not text:
        return False
    install(FaultPlan.from_json(text))
    return True
