"""Chaos fault-injection harness for the streaming engine.

The engine's crash paths (dead-worker reaping, retry budgets, the DLQ)
only earn trust if they can be exercised on demand. This package gives
every interesting failure mode a *named injection site* — a single call
embedded in production code — and a :class:`FaultPlan` that arms a subset
of those sites with deterministic, seeded faults.

Disabled is the default and costs one falsy module-attribute check per
site (no env reads, no dict lookups, no IO on the hot path): ``fire()``
returns immediately while no plan is installed. Plans are installed
programmatically (:func:`install`) or from the ``CURATE_CHAOS`` env var
(:func:`install_from_env`), which worker processes inherit so faults
fire inside spawned workers too.

See docs/FAULT_TOLERANCE.md for the site catalogue and how to write a
chaos test.
"""

from cosmos_curate_tpu.chaos.harness import (
    CHAOS_ENV,
    SITE_AGENT_KILL,
    SITE_AGENT_PARTITION,
    SITE_OBJECT_CHANNEL_FETCH,
    SITE_OBJECT_CHANNEL_SERVE,
    SITE_REMOTE_PLANE_RECV,
    SITE_REMOTE_PLANE_SEND,
    SITE_SERVICE_JOB_CRASH,
    SITE_SERVICE_JOURNAL_WRITE,
    SITE_STORAGE_REQUEST,
    SITE_WORKER_CRASH,
    SITE_WORKER_HANG,
    ALL_SITES,
    FaultPlan,
    FaultRule,
    InjectedFault,
    enabled,
    fire,
    fire_count,
    install,
    install_from_env,
    uninstall,
)

__all__ = [
    "CHAOS_ENV",
    "SITE_AGENT_KILL",
    "SITE_AGENT_PARTITION",
    "SITE_OBJECT_CHANNEL_FETCH",
    "SITE_OBJECT_CHANNEL_SERVE",
    "SITE_REMOTE_PLANE_RECV",
    "SITE_REMOTE_PLANE_SEND",
    "SITE_SERVICE_JOB_CRASH",
    "SITE_SERVICE_JOURNAL_WRITE",
    "SITE_STORAGE_REQUEST",
    "SITE_WORKER_CRASH",
    "SITE_WORKER_HANG",
    "ALL_SITES",
    "FaultPlan",
    "FaultRule",
    "InjectedFault",
    "enabled",
    "fire",
    "fire_count",
    "install",
    "install_from_env",
    "uninstall",
]
