"""Shared schema-version stamping for every durable JSON format.

Long-lived deployments replay journals, list DLQ entries and open index
manifests written by OLDER builds (rolling upgrades, crash-resume across a
deploy). Before this module each durable writer invented its own version
story — ``run_report.json`` carried a lone ``"version": 1``, the job
journal, DLQ metadata and index manifests carried nothing — so a reader
could not even *tell* it was looking at an old record, let alone migrate
it. This module is the one place that knows:

- the **published version** of every durable surface
  (:data:`SCHEMA_VERSIONS` — bumping a number here is what the
  ``lint --schema`` drift gate means by "a version bump");
- how to **stamp** a document at write time (:func:`stamp` — every
  report/snapshot/journal/manifest writer routes through it);
- how to **upgrade** an old document at read time (:func:`upgrade` — the
  registered :data:`MIGRATIONS` shims carry version-N−1 records forward,
  one step at a time, so replay/recover paths accept what the previous
  build wrote).

The static half of the contract lives in ``analysis/schema_check.py``
(``lint --schema``): it extracts each surface's field schema from the
code, diffs it against the checked-in golden under ``analysis/schemas/``
and fails the gate when the shape drifted without a bump here — or when a
breaking drift bumped the version but forgot to register a shim.

The wire-protocol counterpart (``PROTOCOL_VERSION``) lives in
``engine/remote_plane.py``: control-plane frames are never persisted, so
skew there is rejected at the Hello/HelloAck handshake instead of being
migrated.
"""

from __future__ import annotations

from typing import Any, Callable

STAMP_KEY = "schema_version"

# surface -> published version. A version is "published" once records with
# it exist outside one process: bumping requires regenerating the goldens
# (`lint --schema --update`) and, for breaking changes, a MIGRATIONS shim
# from the previous version. Version 1 is the historical, unstamped format
# of each surface (no STAMP_KEY on disk).
SCHEMA_VERSIONS: dict[str, int] = {
    # service/job_queue.py journal envelope + JobRecord snapshot
    "job-journal": 2,
    # engine/dead_letter.py meta.json
    "dlq-meta": 2,
    # dedup/index_store.py manifests/gen-N.json + MANIFEST.json pointer
    "index-manifest": 2,
    # observability/flight_recorder.py report/run_report.json
    "run-report": 1,
    # observability/flight_recorder.py report/node-stats-<rank>.json
    "node-stats": 1,
    # observability/live_status.py report/live/status.json
    "live-status": 1,
    # bench.py final NDJSON metric row (BENCH_r*.json tails). v2: adds the
    # caption_attention micro-section (paged kernel vs gather decode-step
    # times) and the paged-attention counters
    "bench-row": 2,
}


class SchemaVersionError(ValueError):
    """A document's version cannot be reconciled with this build: newer
    than published, or older with no registered migration shim."""


def stamp(doc: dict, surface: str) -> dict:
    """Stamp ``doc`` (in place) with the surface's published version and
    return it. Unknown surfaces raise — a writer inventing a surface name
    must register it here (and in the schema_check registry) first."""
    if surface not in SCHEMA_VERSIONS:
        raise KeyError(
            f"unknown durable surface {surface!r}; register it in "
            "utils/schema_stamp.SCHEMA_VERSIONS and analysis/schema_check.py"
        )
    doc[STAMP_KEY] = SCHEMA_VERSIONS[surface]
    return doc


# -- migration shims --------------------------------------------------------
#
# (surface, from_version) -> shim taking a from_version document and
# returning the (from_version + 1) document. Shims run at READ time
# (replay, list, open); they must be total — never raise on any document
# the old writer could have produced — and must not mutate their input.


def _journal_v1_to_v2(doc: dict) -> dict:
    """v1 journal lines predate stamping: the envelope was
    ``{ts, event, record}`` with no schema_version and no field renames
    since — carrying it forward is filling in the stamp."""
    out = dict(doc)
    out[STAMP_KEY] = 2
    return out


def _dlq_meta_v1_to_v2(doc: dict) -> dict:
    """v1 DLQ meta.json predates stamping; field set is unchanged."""
    out = dict(doc)
    out[STAMP_KEY] = 2
    return out


def _manifest_v1_to_v2(doc: dict) -> dict:
    """v1 manifests (and MANIFEST.json pointers) predate stamping; field
    set is unchanged."""
    out = dict(doc)
    out[STAMP_KEY] = 2
    return out


def _bench_row_v1_to_v2(doc: dict) -> dict:
    """v2 added the caption_attention micro-section and paged-attention
    counters — purely additive; v1 rows carry forward without them (trend
    tooling treats the keys as absent, not zero)."""
    out = dict(doc)
    out[STAMP_KEY] = 2
    return out


MIGRATIONS: dict[tuple[str, int], Callable[[dict], dict]] = {
    ("job-journal", 1): _journal_v1_to_v2,
    ("dlq-meta", 1): _dlq_meta_v1_to_v2,
    ("index-manifest", 1): _manifest_v1_to_v2,
    ("bench-row", 1): _bench_row_v1_to_v2,
}


def doc_version(doc: dict) -> int:
    """The version a document claims; unstamped documents are the
    historical version 1 by definition."""
    v = doc.get(STAMP_KEY, 1)
    try:
        return int(v)
    except (TypeError, ValueError):
        return 1


def has_migration(surface: str, from_version: int) -> bool:
    return (surface, from_version) in MIGRATIONS


def upgrade(doc: dict, surface: str, *, strict: bool = True) -> dict:
    """Carry ``doc`` forward to the surface's published version through the
    shim chain; same-version documents return unchanged (not copied).

    A document NEWER than this build (rolling upgrade read the new build's
    output) raises :class:`SchemaVersionError` when ``strict``; with
    ``strict=False`` it is returned as-is — callers whose parsers already
    ignore unknown fields (e.g. ``JobRecord.from_dict``) can read
    best-effort rather than wedge. A missing shim always raises: silently
    misreading an old record is the failure mode this module exists to
    kill."""
    current = SCHEMA_VERSIONS[surface]
    v = doc_version(doc)
    if v == current:
        return doc
    if v > current:
        if strict:
            raise SchemaVersionError(
                f"{surface} document is schema v{v} but this build publishes "
                f"v{current}; upgrade this process before reading it"
            )
        return doc
    while v < current:
        shim = MIGRATIONS.get((surface, v))
        if shim is None:
            raise SchemaVersionError(
                f"{surface} document is schema v{v} and no migration shim "
                f"({surface}, {v})->v{v + 1} is registered in "
                "utils/schema_stamp.MIGRATIONS"
            )
        doc = shim(doc)
        v = doc_version(doc)
    return doc


def describe() -> dict[str, Any]:
    """Machine-readable summary (``lint --schema --json`` and tests)."""
    return {
        "versions": dict(SCHEMA_VERSIONS),
        "migrations": sorted(f"{s}:v{v}->v{v + 1}" for s, v in MIGRATIONS),
    }
