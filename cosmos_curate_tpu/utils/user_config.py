"""User configuration file: credentials and defaults.

Equivalent capability of the reference's config system
(cosmos_curate/core/utils/config/config.py:81 — ``ConfigFileData`` from
``~/.config/cosmos_curate/config.yaml`` holding API/storage credentials;
deployment context via env vars, environment.py:15-63).

File: ``~/.config/cosmos_curate_tpu/config.yaml`` (override with
``CURATE_CONFIG_PATH``). Recognized sections::

    s3:        {access_key_id, secret_access_key, region, endpoint_url}
    gcs:       {project, credentials_file}
    huggingface: {token}
    weights:   {prefix}     # remote weight cache (MODEL_WEIGHTS_PREFIX equiv)
"""

from __future__ import annotations

import functools
import os
from pathlib import Path
from typing import Any

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

DEFAULT_PATH = "~/.config/cosmos_curate_tpu/config.yaml"


@functools.lru_cache(maxsize=1)
def load_user_config() -> dict[str, Any]:
    path = Path(os.environ.get("CURATE_CONFIG_PATH", DEFAULT_PATH)).expanduser()
    if not path.exists():
        return {}
    import yaml

    try:
        data = yaml.safe_load(path.read_text()) or {}
        if not isinstance(data, dict):
            raise ValueError("config root must be a mapping")
        return data
    except Exception as e:
        logger.warning("unreadable user config %s: %s", path, e)
        return {}


def get_section(name: str) -> dict[str, Any]:
    section = load_user_config().get(name, {})
    return section if isinstance(section, dict) else {}


def s3_session_kwargs() -> dict[str, Any]:
    """boto3 session/client kwargs from the config (env vars still win —
    boto3's own chain applies when this is empty)."""
    s3 = get_section("s3")
    out: dict[str, Any] = {}
    if s3.get("access_key_id"):
        out["aws_access_key_id"] = s3["access_key_id"]
    if s3.get("secret_access_key"):
        out["aws_secret_access_key"] = s3["secret_access_key"]
    if s3.get("region"):
        out["region_name"] = s3["region"]
    if s3.get("endpoint_url"):
        out["endpoint_url"] = s3["endpoint_url"]
    return out
