"""Minimal pure-Python PostgreSQL wire-protocol (v3) client.

The reference's AV state layer runs on Postgres through psycopg
(cosmos_curate/core/utils/db/ ``PostgresDB``); no driver ships in this
image, so this module speaks the public frontend/backend protocol directly
over a socket: StartupMessage, password authentication (cleartext, MD5,
and SCRAM-SHA-256 per RFC 5802/7677), the simple-query cycle
(Query → RowDescription/DataRow/CommandComplete → ReadyForQuery), and
error surfacing. Enough for the state DB's needs (DDL, INSERT/UPDATE,
SELECT with text results); not a general driver.

Tested against an in-process fake server speaking the same protocol
(tests/pipelines/test_pg_client.py) — including the SCRAM exchange.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import socket
import struct
from dataclasses import dataclass


class PgError(RuntimeError):
    def __init__(self, fields: dict[str, str]) -> None:
        self.fields = fields
        super().__init__(
            f"{fields.get('S', 'ERROR')} {fields.get('C', '')}: {fields.get('M', '')}"
        )


def quote_literal(value) -> str:
    """Escape a Python value as a SQL literal (simple-query protocol has no
    bind parameters)."""
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "TRUE" if value else "FALSE"
    if isinstance(value, (int, float)):
        return repr(value)
    s = str(value).replace("'", "''")
    if "\\" in s:
        return "E'" + s.replace("\\", "\\\\") + "'"
    return f"'{s}'"


@dataclass
class QueryResult:
    columns: list[str]
    rows: list[tuple]
    command: str


def parse_dsn(dsn: str) -> dict:
    """postgres:// DSN -> PgConnection kwargs (single source of truth for
    host/port/user/password/database defaults)."""
    import urllib.parse

    u = urllib.parse.urlparse(dsn)
    return dict(
        host=u.hostname or "127.0.0.1",
        port=u.port or 5432,
        user=urllib.parse.unquote(u.username or "postgres"),
        password=urllib.parse.unquote(u.password or ""),
        database=(u.path or "/postgres").lstrip("/") or "postgres",
    )


class PgConnection:
    def __init__(
        self,
        *,
        host: str = "127.0.0.1",
        port: int = 5432,
        user: str = "postgres",
        password: str = "",
        database: str = "postgres",
        timeout_s: float = 30.0,
        query_timeout_s: float = 600.0,
    ) -> None:
        """``timeout_s`` bounds connect+auth; ``query_timeout_s`` bounds each
        statement — generous by default so a lock wait (a normal, transient
        condition) is not misread as a dead connection by retry layers."""
        self._sock = socket.create_connection((host, port), timeout=timeout_s)
        self._query_timeout_s = query_timeout_s
        self._buf = b""
        self.user = user
        self.password = password
        self._startup(user, database)

    # -- wire primitives ---------------------------------------------------

    def _send(self, type_byte: bytes, payload: bytes) -> None:
        self._sock.sendall(type_byte + struct.pack("!I", len(payload) + 4) + payload)

    def _recv_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise ConnectionError("postgres server closed the connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _recv_message(self) -> tuple[bytes, bytes]:
        head = self._recv_exact(5)
        type_byte = head[:1]
        (length,) = struct.unpack("!I", head[1:])
        return type_byte, self._recv_exact(length - 4)

    @staticmethod
    def _cstr(payload: bytes, pos: int) -> tuple[str, int]:
        end = payload.index(b"\x00", pos)
        return payload[pos:end].decode(), end + 1

    @staticmethod
    def _error_fields(payload: bytes) -> dict[str, str]:
        fields: dict[str, str] = {}
        pos = 0
        while pos < len(payload) and payload[pos] != 0:
            code = chr(payload[pos])
            val, pos = PgConnection._cstr(payload, pos + 1)
            fields[code] = val
        return fields

    # -- startup & auth ----------------------------------------------------

    def _startup(self, user: str, database: str) -> None:
        params = f"user\x00{user}\x00database\x00{database}\x00\x00".encode()
        payload = struct.pack("!I", 196608) + params  # protocol 3.0
        self._sock.sendall(struct.pack("!I", len(payload) + 4) + payload)
        while True:
            t, body = self._recv_message()
            if t == b"E":
                raise PgError(self._error_fields(body))
            if t == b"R":
                (code,) = struct.unpack("!I", body[:4])
                if code == 0:  # AuthenticationOk
                    continue
                if code == 3:  # cleartext password
                    self._send(b"p", self.password.encode() + b"\x00")
                elif code == 5:  # MD5
                    salt = body[4:8]
                    inner = hashlib.md5(
                        (self.password + self.user).encode()
                    ).hexdigest()
                    digest = hashlib.md5(inner.encode() + salt).hexdigest()
                    self._send(b"p", f"md5{digest}".encode() + b"\x00")
                elif code == 10:  # SASL: pick SCRAM-SHA-256
                    self._scram(body[4:])
                else:
                    raise PgError({"M": f"unsupported auth method {code}"})
            elif t == b"Z":  # ReadyForQuery
                return
            # S (ParameterStatus), K (BackendKeyData), N (Notice): ignore

    def _scram(self, mechanisms: bytes) -> None:
        names = [m for m in mechanisms.split(b"\x00") if m]
        if b"SCRAM-SHA-256" not in names:
            raise PgError({"M": f"no supported SASL mechanism in {names}"})
        nonce = base64.b64encode(os.urandom(18)).decode()
        first_bare = f"n={self.user},r={nonce}"
        client_first = "n,," + first_bare
        init = b"SCRAM-SHA-256\x00" + struct.pack("!I", len(client_first)) + client_first.encode()
        self._send(b"p", init)

        t, body = self._recv_message()
        if t == b"E":
            raise PgError(self._error_fields(body))
        (code,) = struct.unpack("!I", body[:4])
        assert code == 11, f"expected SASLContinue, got {code}"
        server_first = body[4:].decode()
        parts = dict(kv.split("=", 1) for kv in server_first.split(","))
        server_nonce, salt_b64, iterations = parts["r"], parts["s"], int(parts["i"])
        if not server_nonce.startswith(nonce):
            raise PgError({"M": "SCRAM server nonce does not extend client nonce"})

        salted = hashlib.pbkdf2_hmac(
            "sha256", self.password.encode(), base64.b64decode(salt_b64), iterations
        )
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored_key = hashlib.sha256(client_key).digest()
        without_proof = f"c={base64.b64encode(b'n,,').decode()},r={server_nonce}"
        auth_message = f"{first_bare},{server_first},{without_proof}".encode()
        client_sig = hmac.new(stored_key, auth_message, hashlib.sha256).digest()
        proof = bytes(a ^ b for a, b in zip(client_key, client_sig))
        final = f"{without_proof},p={base64.b64encode(proof).decode()}"
        self._send(b"p", final.encode())

        t, body = self._recv_message()
        if t == b"E":
            raise PgError(self._error_fields(body))
        (code,) = struct.unpack("!I", body[:4])
        assert code == 12, f"expected SASLFinal, got {code}"
        server_final = dict(kv.split("=", 1) for kv in body[4:].decode().split(","))
        server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        expected = hmac.new(server_key, auth_message, hashlib.sha256).digest()
        if base64.b64decode(server_final.get("v", "")) != expected:
            raise PgError({"M": "SCRAM server signature verification failed"})

    # -- queries -----------------------------------------------------------

    def execute(self, sql: str, params: tuple = ()) -> QueryResult:
        """Simple-query execution. ``params`` substitute ``%s`` placeholders
        as escaped literals (client-side; the simple protocol has no binds).
        Only the literal token ``%s`` is a placeholder — other ``%``
        characters (LIKE patterns, modulo) pass through untouched."""
        if params:
            parts = sql.split("%s")
            if len(parts) - 1 != len(params):
                raise ValueError(
                    f"query has {len(parts) - 1} %s placeholders, got {len(params)} params"
                )
            sql = "".join(
                part + (quote_literal(params[i]) if i < len(params) else "")
                for i, part in enumerate(parts)
            )
        self._sock.settimeout(self._query_timeout_s)
        self._send(b"Q", sql.encode() + b"\x00")
        columns: list[str] = []
        rows: list[tuple] = []
        command = ""
        error: PgError | None = None
        while True:
            t, body = self._recv_message()
            if t == b"T":
                (n,) = struct.unpack("!H", body[:2])
                pos = 2
                columns = []
                for _ in range(n):
                    name, pos = self._cstr(body, pos)
                    pos += 18  # table oid, attnum, type oid, len, mod, fmt
                    columns.append(name)
            elif t == b"D":
                (n,) = struct.unpack("!H", body[:2])
                pos = 2
                row = []
                for _ in range(n):
                    (length,) = struct.unpack("!i", body[pos : pos + 4])
                    pos += 4
                    if length == -1:
                        row.append(None)
                    else:
                        row.append(body[pos : pos + length].decode())
                        pos += length
                rows.append(tuple(row))
            elif t == b"C":
                command, _ = self._cstr(body, 0)
            elif t == b"E":
                error = PgError(self._error_fields(body))
            elif t == b"Z":
                if error is not None:
                    raise error
                return QueryResult(columns, rows, command)
            # N (notice), I (empty query), S: ignored

    def close(self) -> None:
        try:
            self._send(b"X", b"")
        except OSError:
            pass
        self._sock.close()

    def __enter__(self) -> "PgConnection":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
