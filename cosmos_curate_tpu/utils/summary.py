"""Run summary: the artifact the benchmark harness reads.

Equivalent capability of the reference's summary writer
(pipelines/video/splitting_pipeline.py:270 ``write_summary``;
benchmarks/summary.py:57-74 schema), including the headline metric
``video_hours_per_day_per_chip`` — the TPU-native analogue of the
reference's ``video_hours_per_day_per_gpu`` (benchmarks/summary.py:96-98).
"""

from __future__ import annotations

import time
from dataclasses import asdict
from typing import Sequence

from cosmos_curate_tpu.data.model import ClipStats, SplitPipeTask
from cosmos_curate_tpu.storage.writers import write_json


def build_summary(
    tasks: Sequence[SplitPipeTask],
    *,
    pipeline_run_time_s: float,
    num_chips: int = 1,
    extra: dict | None = None,
) -> dict:
    stats = ClipStats()
    total_video_duration_s = 0.0
    num_errors = 0
    videos: set[str] = set()
    provenance: dict[str, str] = {}
    for t in tasks:
        if t.stats is not None:
            stats.combine(t.stats)
        # per-model weights provenance stamped by the writer: noise is
        # traceable at the run level, not just per clip meta (ROADMAP 3b)
        provenance.update(getattr(t, "stage_perf", {}).get("weights_provenance") or {})
        if t.video.path not in videos:
            videos.add(t.video.path)
            total_video_duration_s += t.video.metadata.duration_s
            # Video-level errors are copied into every chunk; count them once.
            num_errors += len(t.video.errors)
        num_errors += sum(
            len(c.errors) for c in (*t.video.clips, *t.video.filtered_clips)
        )
    video_hours = total_video_duration_s / 3600.0
    run_days = pipeline_run_time_s / 86400.0 if pipeline_run_time_s > 0 else 0.0
    per_chip = (video_hours / run_days / num_chips) if run_days > 0 and num_chips else 0.0
    summary = {
        "timestamp": time.time(),
        "num_videos": len(videos),
        "total_video_duration_s": total_video_duration_s,
        "pipeline_run_time_s": pipeline_run_time_s,
        "num_chips": num_chips,
        "video_hours_per_day_per_chip": per_chip,
        "num_errors": num_errors,
        **asdict(stats),
    }
    if provenance:
        summary["weights_provenance"] = provenance
    if extra:
        summary.update(extra)
    return summary


def write_summary(path: str, summary: dict) -> None:
    write_json(path, summary)


# Counter fields summed across node summaries; the rest are recomputed or
# taken max-wise (run time = wall clock of the slowest node).
_ADDITIVE = (
    "num_videos",
    "total_video_duration_s",
    "num_errors",
    "num_clips",
    "num_filtered_by_motion",
    "num_filtered_by_aesthetic",
    "num_filtered_by_text",
    "num_filtered_by_semantic",
    "num_filtered_by_dedup",
    "num_transcoded",
    "num_with_embeddings",
    "num_with_captions",
    "num_with_webp",
    "total_clip_duration_s",
)


def merge_node_summaries(output_path: str) -> dict | None:
    """Combine ``summary.json`` (rank 0) + ``summary-node*.json`` into one
    merged ``summary.json`` (reference: the driver node aggregates partition
    results, client/slurm_cli/slurm.py:797). Safe to run repeatedly; returns
    the merged summary or None when no summaries exist yet."""
    import glob
    import json
    import os

    root = output_path.rstrip("/")
    paths = sorted(
        p
        for p in glob.glob(os.path.join(root, "summary*.json"))
        if not p.endswith("summary-merged.json")
    )
    if not paths:
        return None
    summaries = []
    for p in paths:
        with open(p) as f:
            summaries.append(json.load(f))
    merged = dict(summaries[0])
    for s in summaries[1:]:
        for k in _ADDITIVE:
            if k in s:
                merged[k] = merged.get(k, 0) + s[k]
        if s.get("weights_provenance"):
            merged.setdefault("weights_provenance", {}).update(s["weights_provenance"])
        merged["pipeline_run_time_s"] = max(
            merged.get("pipeline_run_time_s", 0.0), s.get("pipeline_run_time_s", 0.0)
        )
        merged["max_clip_duration_s"] = max(
            merged.get("max_clip_duration_s", 0.0), s.get("max_clip_duration_s", 0.0)
        )
        merged["num_chips"] = merged.get("num_chips", 1) + s.get("num_chips", 1)
    video_hours = merged.get("total_video_duration_s", 0.0) / 3600.0
    run_days = merged.get("pipeline_run_time_s", 0.0) / 86400.0
    chips = max(1, merged.get("num_chips", 1))
    merged["video_hours_per_day_per_chip"] = (
        video_hours / run_days / chips if run_days > 0 else 0.0
    )
    merged["merged_from"] = [os.path.basename(p) for p in paths]
    write_json(os.path.join(root, "summary-merged.json"), merged)
    return merged
