"""Run summary: the artifact the benchmark harness reads.

Equivalent capability of the reference's summary writer
(pipelines/video/splitting_pipeline.py:270 ``write_summary``;
benchmarks/summary.py:57-74 schema), including the headline metric
``video_hours_per_day_per_chip`` — the TPU-native analogue of the
reference's ``video_hours_per_day_per_gpu`` (benchmarks/summary.py:96-98).
"""

from __future__ import annotations

import time
from dataclasses import asdict
from typing import Sequence

from cosmos_curate_tpu.data.model import ClipStats, SplitPipeTask
from cosmos_curate_tpu.storage.writers import write_json


def build_summary(
    tasks: Sequence[SplitPipeTask],
    *,
    pipeline_run_time_s: float,
    num_chips: int = 1,
    extra: dict | None = None,
) -> dict:
    stats = ClipStats()
    total_video_duration_s = 0.0
    num_errors = 0
    videos: set[str] = set()
    for t in tasks:
        if t.stats is not None:
            stats.combine(t.stats)
        if t.video.path not in videos:
            videos.add(t.video.path)
            total_video_duration_s += t.video.metadata.duration_s
            # Video-level errors are copied into every chunk; count them once.
            num_errors += len(t.video.errors)
        num_errors += sum(
            len(c.errors) for c in (*t.video.clips, *t.video.filtered_clips)
        )
    video_hours = total_video_duration_s / 3600.0
    run_days = pipeline_run_time_s / 86400.0 if pipeline_run_time_s > 0 else 0.0
    per_chip = (video_hours / run_days / num_chips) if run_days > 0 and num_chips else 0.0
    summary = {
        "timestamp": time.time(),
        "num_videos": len(videos),
        "total_video_duration_s": total_video_duration_s,
        "pipeline_run_time_s": pipeline_run_time_s,
        "num_chips": num_chips,
        "video_hours_per_day_per_chip": per_chip,
        "num_errors": num_errors,
        **asdict(stats),
    }
    if extra:
        summary.update(extra)
    return summary


def write_summary(path: str, summary: dict) -> None:
    write_json(path, summary)
