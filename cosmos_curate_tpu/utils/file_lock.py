"""File-based inter-process lock.

Equivalent capability of the reference's file lock
(cosmos_curate/core/utils/misc/file_lock.py): serialize cross-process
critical sections (weight staging, native-lib builds) via flock.
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import time
from pathlib import Path
from typing import Iterator


@contextlib.contextmanager
def file_lock(path: str | Path, *, timeout_s: float = 60.0) -> Iterator[None]:
    """Exclusive flock on ``path`` (created if absent); raises TimeoutError
    if not acquired within ``timeout_s``."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    fd = os.open(p, os.O_CREAT | os.O_RDWR, 0o600)
    deadline = time.monotonic() + timeout_s
    try:
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except BlockingIOError:
                if time.monotonic() >= deadline:
                    raise TimeoutError(f"could not acquire lock {p} within {timeout_s}s")
                time.sleep(0.05)
        yield
    finally:
        try:
            fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)
