"""Accelerator health gate: retrying TPU liveness probe.

Equivalent capability of the reference's GPU start helper
(cosmos_curate/core/utils/infra/gpu_start_helper.py — a retrying health
gate that blocks pipeline start until the accelerator answers, instead of
letting the first model call crash a worker mid-run).

TPU twist: on this platform a wedged device relay can make ``import jax``
itself block for minutes, so the probe ALWAYS runs in a subprocess with a
timeout — the probing process stays healthy no matter what the plugin does.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def probe_accelerator(timeout_s: float = 120.0) -> bool:
    """One subprocess probe: does ``jax.devices()`` answer with a non-CPU
    backend within the timeout?"""
    code = (
        "import jax, sys; d = jax.devices(); "
        "sys.exit(0 if d and d[0].platform != 'cpu' else 1)"
    )
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, timeout=timeout_s
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def accelerator_health_gate(
    *,
    attempts: int = 3,
    probe_timeout_s: float = 120.0,
    backoff_s: float = 30.0,
    require: bool = False,
) -> bool:
    """Retrying gate (the relay recovers on its own schedule). Returns
    liveness; ``require=True`` raises instead of returning False so a
    TPU-mandatory entry point fails with a clear message up front rather
    than crashing a worker later."""
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        return False  # explicitly CPU-pinned: nothing to gate
    for i in range(attempts):
        if probe_accelerator(probe_timeout_s):
            if i:
                logger.info("accelerator answered on probe %d/%d", i + 1, attempts)
            return True
        if i + 1 < attempts:
            logger.warning(
                "accelerator probe %d/%d failed; retrying in %.0fs",
                i + 1, attempts, backoff_s,
            )
            time.sleep(backoff_s)
    if require:
        raise RuntimeError(
            f"accelerator unhealthy after {attempts} probes x {probe_timeout_s:.0f}s "
            "(TPU relay down?) — rerun with JAX_PLATFORMS=cpu to accept CPU execution"
        )
    logger.warning("accelerator unhealthy after %d probes; continuing on CPU", attempts)
    return False
