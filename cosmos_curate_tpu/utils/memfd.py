"""In-memory file paths: feed byte buffers to path-only APIs without disk IO.

Equivalent capability of the reference's memfd helper
(cosmos_curate/core/utils/misc/memfd.py ``buffer_as_memfd_path``): wraps
``os.memfd_create`` so decoders that only accept file paths (cv2's FFmpeg
backend here) can read encoded video straight from RAM.
"""

from __future__ import annotations

import contextlib
import os
import tempfile
from typing import Iterator


@contextlib.contextmanager
def buffer_as_path(data: bytes, suffix: str = ".mp4") -> Iterator[str]:
    """Yield a readable path for ``data`` with no disk write when possible.

    Uses a memfd (`/proc/self/fd/N`) on Linux; falls back to a temp file.
    """
    try:
        fd = os.memfd_create("curate-buf")
    except (AttributeError, OSError):
        fd = -1
    if fd >= 0:
        try:
            view = memoryview(data)
            written = 0
            while written < len(view):  # os.write caps at ~2 GiB per call
                written += os.write(fd, view[written:])
            yield f"/proc/self/fd/{fd}"
        finally:
            os.close(fd)
        return
    with tempfile.NamedTemporaryFile(suffix=suffix) as f:
        f.write(data)
        f.flush()
        yield f.name
