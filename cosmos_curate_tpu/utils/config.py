"""Config-file pipeline invocation.

Equivalent capability of the reference's config mode
(cosmos_curate/core/utils/config/pipeline_config_loader.py:43
``load_pipeline_config``): a YAML/JSON file whose keys map onto the pipeline
args dataclass — the same schema a job-service invoke payload uses.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Type, TypeVar

T = TypeVar("T")


def load_pipeline_config(path: str, args_cls: Type[T]) -> T:
    text = Path(path).read_text()
    if path.endswith((".yaml", ".yml")):
        import yaml

        data = yaml.safe_load(text)
    else:
        data = json.loads(text)
    if not isinstance(data, dict):
        raise ValueError(f"config {path} must be a mapping, got {type(data).__name__}")
    names = {f.name for f in dataclasses.fields(args_cls)}
    unknown = set(data) - names
    if unknown:
        raise ValueError(f"unknown config keys for {args_cls.__name__}: {sorted(unknown)}")
    # Lists in JSON/YAML arrive for tuple-typed fields; coerce.
    kwargs = {}
    for f in dataclasses.fields(args_cls):
        if f.name not in data:
            continue
        v = data[f.name]
        if isinstance(v, list) and "tuple" in str(f.type):
            v = tuple(v)
        kwargs[f.name] = v
    return args_cls(**kwargs)
