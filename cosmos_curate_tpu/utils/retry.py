"""Retry helpers (reference core/utils/misc/retry_utils.py + tenacity use)."""

from __future__ import annotations

import functools
import time
from typing import Callable, TypeVar

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

T = TypeVar("T")


def retry(
    attempts: int = 3,
    backoff_s: float = 1.0,
    exceptions: tuple[type[BaseException], ...] = (Exception,),
):
    """Exponential-backoff retry decorator."""

    def deco(fn: Callable[..., T]) -> Callable[..., T]:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs) -> T:
            last: BaseException | None = None
            for i in range(max(1, attempts)):
                try:
                    return fn(*args, **kwargs)
                except exceptions as e:
                    last = e
                    if i + 1 < attempts:
                        wait = backoff_s * (2**i)
                        logger.warning(
                            "%s failed (attempt %d/%d): %s; retrying in %.1fs",
                            fn.__name__, i + 1, attempts, e, wait,
                        )
                        time.sleep(wait)
            raise last  # type: ignore[misc]

        return wrapper

    return deco
