"""Logging setup (reference uses loguru; we use stdlib logging with the same
one-line-per-event spirit, configured once per process)."""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False


def _configure_root() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    level = os.environ.get("CURATE_LOG_LEVEL", "INFO").upper()
    # logging.getLevelNamesMapping is 3.11+; the project floor is 3.10
    if hasattr(logging, "getLevelNamesMapping"):
        known_levels = set(logging.getLevelNamesMapping())
    else:
        known_levels = set(logging._nameToLevel)
    if level not in known_levels:
        print(
            f"cosmos_curate_tpu: unknown CURATE_LOG_LEVEL={level!r}; using INFO",
            file=sys.stderr,
        )
        level = "INFO"
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s.%(msecs)03d | %(levelname)-7s | %(name)s:%(lineno)d - %(message)s",
            datefmt="%H:%M:%S",
        )
    )
    root = logging.getLogger("cosmos_curate_tpu")
    root.setLevel(level)
    if not root.handlers:
        root.addHandler(handler)
    root.propagate = False
    _CONFIGURED = True


def get_logger(name: str) -> logging.Logger:
    _configure_root()
    if not name.startswith("cosmos_curate_tpu"):
        name = f"cosmos_curate_tpu.{name}"
    return logging.getLogger(name)
