"""Persistent XLA compilation cache, enabled once per process.

Model stages construct their own jit closures, so a fresh process (or a
fresh model instance whose ``init`` is traced anew) pays full XLA
compilation even for programs compiled seconds earlier by a warmup in the
same session. The persistent cache turns every repeat compile — across
processes, across runs, across the bench's warmup/measure split — into a
disk hit. The reference has no analogue (CUDA kernels ship precompiled);
on TPU this is the idiomatic fix for XLA's compile-once-per-process model.
"""

from __future__ import annotations

import os
import threading

_LOCK = threading.Lock()
_ENABLED = False

CACHE_DIR_ENV = "CURATE_JAX_CACHE_DIR"
DEFAULT_CACHE_DIR = "/tmp/curate_jax_cache"


def enable_persistent_cache(path: str | None = None) -> str:
    """Idempotently point jax at a persistent compilation cache directory.

    Must run before the first compile to capture it; callers at natural
    chokepoints (registry.load_params, bench, dryrun) make that true for
    every model path. Returns the cache dir in use.
    """
    global _ENABLED
    cache_dir = path or os.environ.get(CACHE_DIR_ENV) or DEFAULT_CACHE_DIR
    with _LOCK:
        if _ENABLED:
            return cache_dir
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Default min compile time is 1s; embed/caption programs compile in
        # 0.5-40s, so lower the floor to catch the small-but-repeated ones.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        _ENABLED = True
    return cache_dir
