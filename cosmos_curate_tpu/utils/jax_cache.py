"""Persistent XLA compilation cache, enabled once per process.

Model stages construct their own jit closures, so a fresh process (or a
fresh model instance whose ``init`` is traced anew) pays full XLA
compilation even for programs compiled seconds earlier by a warmup in the
same session. The persistent cache turns every repeat compile — across
processes, across runs, across the bench's warmup/measure split — into a
disk hit. The reference has no analogue (CUDA kernels ship precompiled);
on TPU this is the idiomatic fix for XLA's compile-once-per-process model.
"""

from __future__ import annotations

import os
import threading

_LOCK = threading.Lock()
_ENABLED = False

# Primary knob: CURATE_COMPILE_CACHE = "0"/"off" disables the persistent
# cache entirely, "1"/"on" enables it at the default (or legacy-env) path,
# any other value is the cache base directory itself. Unset = enabled at
# the default path (compiles are paid once per machine, not per process).
COMPILE_CACHE_ENV = "CURATE_COMPILE_CACHE"
# Legacy path-only override, kept for existing deployments.
CACHE_DIR_ENV = "CURATE_JAX_CACHE_DIR"
DEFAULT_CACHE_DIR = "/tmp/curate_jax_cache"


def resolve_cache_base(path: str | None = None) -> str | None:
    """The cache base dir per the knobs, or None when disabled.

    Precedence: explicit ``path`` arg > CURATE_COMPILE_CACHE (off/on/path)
    > CURATE_JAX_CACHE_DIR > the default. An explicit arg wins even over
    an env-level "off" — the caller asked for a specific cache."""
    if path:
        return path
    knob = os.environ.get(COMPILE_CACHE_ENV, "").strip()
    if knob.lower() in ("0", "off", "false", "no"):
        return None
    if knob and knob.lower() not in ("1", "on", "true", "yes"):
        return knob  # a path
    return os.environ.get(CACHE_DIR_ENV) or DEFAULT_CACHE_DIR


def _host_fingerprint() -> str:
    """A short tag of the CPU feature set AND the jax/jaxlib identity.
    XLA:CPU AOT cache entries embed the compile-time target features;
    loading them under a different feature profile logs 'could lead to
    SIGILL' and can actually crash. The features XLA picks depend on the
    jaxlib BUILD, not just /proc/cpuinfo (observed on this box: entries
    compiled with +prefer-no-scatter/+prefer-no-gather by one jaxlib were
    loaded by another with the same cpuinfo flags), so the key must include
    which jaxlib produced the entry."""
    import hashlib
    import platform

    # cache epoch: bump to orphan every entry written before the key grew
    # the jaxlib identity (stale pre-epoch entries caused the SIGILL-risk
    # loader errors in MULTICHIP_r04)
    bits = f"v2:{platform.machine()}:{platform.processor()}"
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    bits += ":" + line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    try:
        import jax
        import jaxlib

        bits += f":{jax.__version__}:{jaxlib.__version__}:{jaxlib.__file__}"
        # build identity, not just version: a force-reinstalled same-version
        # wheel built with different target features lands at the same path
        # — stat the package's native extensions so the key tracks the
        # actual compiled artifacts
        from pathlib import Path

        pkg = Path(jaxlib.__file__).parent
        for so in sorted(pkg.glob("*.so")) + sorted(pkg.glob("**/xla_extension*.so")):
            st = so.stat()
            bits += f":{so.name}:{st.st_size}:{int(st.st_mtime)}"
    except Exception:
        pass
    return hashlib.sha256(bits.encode()).hexdigest()[:10]


def enable_persistent_cache(path: str | None = None) -> str | None:
    """Idempotently point jax at a persistent compilation cache directory.

    Must run before the first compile to capture it; callers at natural
    chokepoints (registry.load_params, DevicePipeline construction, bench,
    dryrun) make that true for every model path. Returns the cache dir in
    use, or None when CURATE_COMPILE_CACHE disables the cache.
    """
    global _ENABLED
    base = resolve_cache_base(path)
    if base is None:
        return None
    cache_dir = os.path.join(base, _host_fingerprint())
    with _LOCK:
        if _ENABLED:
            return cache_dir
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Default min compile time is 1s; embed/caption programs compile in
        # 0.5-40s, so lower the floor to catch the small-but-repeated ones.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
        _ENABLED = True
    return cache_dir
