"""Input discovery with resume semantics.

Equivalent capability of the reference's input builder
(cosmos_curate/pipelines/video/utils/video_pipe_input.py, resume at
splitting_pipeline.py:240-259): list candidate videos under the input prefix,
skip any whose ``processed_videos/`` records are complete (all chunks
present), and build ``SplitPipeTask``s for the rest.
"""

from __future__ import annotations

import json

from cosmos_curate_tpu.data.model import SplitPipeTask, Video
from cosmos_curate_tpu.pipelines.video.stages.writer import video_record_id
from cosmos_curate_tpu.storage.client import get_storage_client
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

VIDEO_SUFFIXES = (".mp4", ".mov", ".avi", ".mkv", ".webm", ".m4v")


def _processed_video_ids(output_path: str) -> set[str]:
    """Video ids whose chunk records are complete."""
    client = get_storage_client(output_path)
    prefix = f"{output_path.rstrip('/')}/processed_videos"
    chunks: dict[str, list[str]] = {}
    for info in client.list_files(prefix, suffixes=(".json",)):
        parts = info.path.replace("\\", "/").split("/")
        if len(parts) < 2:
            continue
        chunks.setdefault(parts[-2], []).append(info.path)
    done: set[str] = set()
    for vid, files in chunks.items():
        try:
            rec = json.loads(client.read_bytes(files[0]))
            if len(files) >= int(rec.get("num_chunks", 1)):
                done.add(vid)
        except Exception:
            logger.warning("unreadable resume record under %s; will reprocess", vid)
    return done


def discover_split_tasks(
    input_path: str,
    output_path: str | None = None,
    *,
    limit: int = 0,
) -> list[SplitPipeTask]:
    """List videos under ``input_path``; skip completed ones when
    ``output_path`` holds resume records; cap at ``limit`` when > 0."""
    client = get_storage_client(input_path)
    done = _processed_video_ids(output_path) if output_path else set()
    tasks: list[SplitPipeTask] = []
    skipped = 0
    for info in client.list_files(input_path, suffixes=VIDEO_SUFFIXES):
        if video_record_id(info.path) in done:
            skipped += 1
            continue
        tasks.append(SplitPipeTask(video=Video(path=info.path)))
        if limit and len(tasks) >= limit:
            break
    logger.info(
        "discovered %d videos under %s (%d already processed, skipped)",
        len(tasks), input_path, skipped,
    )
    return tasks


def discover_multicam_tasks(
    input_path: str,
    output_path: str | None = None,
    *,
    primary_camera: str = "",
    limit: int = 0,
) -> list[SplitPipeTask]:
    """Session-based multicam discovery (reference MULTICAM.md: session =
    a subdirectory of ``input_path``; its video files are time-aligned
    cameras). The primary camera is the one whose filename stem matches
    ``primary_camera``, else the lexicographically first. Resume keys off
    the primary's record id."""
    from collections import defaultdict
    from pathlib import PurePath

    from cosmos_curate_tpu.storage.client import relative_to_prefix

    client = get_storage_client(input_path)
    done = _processed_video_ids(output_path) if output_path else set()
    sessions: dict[str, list[str]] = defaultdict(list)
    for info in client.list_files(input_path, suffixes=VIDEO_SUFFIXES):
        rel = relative_to_prefix(info.path, input_path)
        parts = PurePath(rel).parts if rel else ()
        if len(parts) < 2:
            logger.warning("skipping %s: multicam input expects <session>/<camera>", info.path)
            continue
        sessions[parts[0]].append(info.path)

    tasks: list[SplitPipeTask] = []
    skipped = 0
    for session_id in sorted(sessions):
        paths = sorted(sessions[session_id])
        stems = {PurePath(p).stem: p for p in paths}
        primary_path = stems.get(primary_camera)
        if primary_path is None:
            if primary_camera:
                logger.warning(
                    "session %s has no %r camera; using %s as primary",
                    session_id, primary_camera, PurePath(paths[0]).stem,
                )
            primary_path = paths[0]
        if video_record_id(primary_path) in done:
            skipped += 1
            continue
        videos = [Video(path=primary_path, camera=PurePath(primary_path).stem)]
        videos += [
            Video(path=p, camera=PurePath(p).stem) for p in paths if p != primary_path
        ]
        tasks.append(
            SplitPipeTask(video=videos[0], aux_videos=videos[1:], session_id=session_id)
        )
        if limit and len(tasks) >= limit:
            break
    logger.info(
        "discovered %d multicam sessions under %s (%d already processed, skipped)",
        len(tasks), input_path, skipped,
    )
    return tasks
