"""Input discovery with resume semantics.

Equivalent capability of the reference's input builder
(cosmos_curate/pipelines/video/utils/video_pipe_input.py, resume at
splitting_pipeline.py:240-259): list candidate videos under the input prefix,
skip any whose ``processed_videos/`` records are complete (all chunks
present), and build ``SplitPipeTask``s for the rest.
"""

from __future__ import annotations

import json

from cosmos_curate_tpu.data.model import SplitPipeTask, Video
from cosmos_curate_tpu.pipelines.video.stages.writer import video_record_id
from cosmos_curate_tpu.storage.client import get_storage_client
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

VIDEO_SUFFIXES = (".mp4", ".mov", ".avi", ".mkv", ".webm", ".m4v")


def _processed_video_ids(output_path: str) -> set[str]:
    """Video ids whose chunk records are complete."""
    client = get_storage_client(output_path)
    prefix = f"{output_path.rstrip('/')}/processed_videos"
    chunks: dict[str, list[str]] = {}
    for info in client.list_files(prefix, suffixes=(".json",)):
        parts = info.path.replace("\\", "/").split("/")
        if len(parts) < 2:
            continue
        chunks.setdefault(parts[-2], []).append(info.path)
    done: set[str] = set()
    for vid, files in chunks.items():
        try:
            rec = json.loads(client.read_bytes(files[0]))
            if len(files) >= int(rec.get("num_chunks", 1)):
                done.add(vid)
        except Exception:
            logger.warning("unreadable resume record under %s; will reprocess", vid)
    return done


def discover_split_tasks(
    input_path: str,
    output_path: str | None = None,
    *,
    limit: int = 0,
) -> list[SplitPipeTask]:
    """List videos under ``input_path``; skip completed ones when
    ``output_path`` holds resume records; cap at ``limit`` when > 0."""
    client = get_storage_client(input_path)
    done = _processed_video_ids(output_path) if output_path else set()
    tasks: list[SplitPipeTask] = []
    skipped = 0
    for info in client.list_files(input_path, suffixes=VIDEO_SUFFIXES):
        if video_record_id(info.path) in done:
            skipped += 1
            continue
        tasks.append(SplitPipeTask(video=Video(path=info.path)))
        if limit and len(tasks) >= limit:
            break
    logger.info(
        "discovered %d videos under %s (%d already processed, skipped)",
        len(tasks), input_path, skipped,
    )
    return tasks
