"""The split-annotate pipeline: assembly + entry point.

Equivalent capability of the reference's flagship splitting pipeline
(cosmos_curate/pipelines/video/splitting_pipeline.py: ``_assemble_stages``
:333-884, ``split``:887): download → clip-extract (fixed-stride or shot
detection) → transcode → frame-extract → [filters] → [embed] → [caption] →
write. Model stages are appended as they come online; every configuration
runs end-to-end through the same assembly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from cosmos_curate_tpu.core.pipeline import PipelineConfig, run_pipeline
from cosmos_curate_tpu.core.runner import RunnerInterface
from cosmos_curate_tpu.core.stage import Stage, StageSpec
from cosmos_curate_tpu.data.model import FrameExtractionSignature
from cosmos_curate_tpu.pipelines.video.input_discovery import discover_split_tasks
from cosmos_curate_tpu.pipelines.video.stages.clip_extraction import (
    ClipTranscodingStage,
    FixedStrideExtractorStage,
)
from cosmos_curate_tpu.pipelines.video.stages.download import VideoDownloadStage
from cosmos_curate_tpu.pipelines.video.stages.frame_extraction import ClipFrameExtractionStage
from cosmos_curate_tpu.pipelines.video.stages.writer import ClipWriterStage
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.utils.summary import build_summary, write_summary

logger = get_logger(__name__)


@dataclass
class SplitPipelineArgs:
    input_path: str = ""
    output_path: str = ""
    limit: int = 0
    # clip extraction
    splitting_algorithm: str = "fixed-stride"  # or "transnetv2"
    fixed_stride_len_s: float = 10.0
    min_clip_len_s: float = 2.0
    transnetv2_threshold: float = 0.4
    max_clip_len_s: float = 60.0
    # transcode
    transcode_cpus: int = 4
    clip_chunk_size: int = 64
    # super-resolution after transcode (reference --sr-*,
    # splitting_pipeline.py:1313-1337 / super_resolution_stage.py:189)
    sr: bool = False
    sr_variant: str = "diffusion"  # diffusion | srnet
    sr_window_frames: int = 128
    sr_overlap_frames: int = 64
    sr_sp_size: int = 1
    # frame extraction (uniform size so model stages can stack across clips)
    extract_fps: tuple[float, ...] = (2.0,)
    extract_resize_hw: tuple[int, int] = (224, 224)
    # model stages (enabled as they come online)
    motion_filter: str = "disable"  # disable | score-only | enable
    # estimator: auto (codec MVs with frame-diff fallback) | mv | frame-diff
    motion_backend: str = "auto"
    # calibrated for the frame-diff estimator (see stages/motion_filter.py)
    motion_global_threshold: float = 0.004
    motion_patch_threshold: float = 0.0  # see motion_filter.py: opt-in criterion
    # calibrated for the codec-MV estimator (|mv|/height scale)
    motion_mv_global_threshold: float = 0.001
    motion_mv_patch_threshold: float = 0.0
    aesthetic_threshold: float | None = None
    text_filter: str = "disable"  # disable | score-only | enable
    text_filter_threshold: float = 0.5
    semantic_filter: str = "disable"  # disable | score-only | enable
    semantic_filter_prompt: str = "default"
    embedding_model: str = ""  # "" | "clip" | "video"
    # persistent corpus index (dedup/corpus_index.py): write pending index
    # fragments in-pipeline (ClipWriterStage) and consolidate them into
    # per-cluster shards at end of run
    corpus_index: bool = False
    index_path: str = ""  # "" = <output>/index
    # incremental dedup against that index as clips flow (disable |
    # score-only | enable); enable drops duplicates before the writer
    incremental_dedup: str = "disable"
    dedup_eps: float = 0.07
    dedup_nprobe: int = 0  # 0 = index default
    # multicam sessions: input_path holds <session>/<camera>.mp4 dirs;
    # spans come from the primary camera, aux cameras split time-aligned
    multicam: bool = False
    primary_camera: str = ""  # filename stem; "" = lexicographically first
    captioning: bool = False
    caption_window_len: int = 256
    caption_prompt_variant: str = "default"
    # named VLM flavor (models/vlm/model.py VLM_FLAVORS): base |
    # qwen2vl-2b | qwen25vl-7b | tiny-test
    caption_model: str = "base"
    enhance_captions: bool = False
    t5_embeddings: bool = False
    previews: bool = False
    tracking: bool = False
    tracking_annotated: bool = False
    per_event_captions: bool = False  # implies tracking
    # execution
    num_chips: int = 0  # 0 = discover
    perf_profile: bool = False
    profile_cpu: bool = False
    profile_memory: bool = False
    tracing: bool = False
    stage_save_rate: float = 0.0  # sampled process_data input recording
    stage_save_stages: tuple[str, ...] = ()
    extra_stages: list[Stage | StageSpec] = field(default_factory=list)


def assemble_stages(args: SplitPipelineArgs) -> list[Stage | StageSpec]:
    stages: list[Stage | StageSpec] = [VideoDownloadStage()]
    if args.splitting_algorithm == "transnetv2":
        from cosmos_curate_tpu.pipelines.video.stages.shot_detection import (
            TransNetV2ClipExtractionStage,
        )

        stages.append(
            TransNetV2ClipExtractionStage(
                threshold=args.transnetv2_threshold,
                min_clip_len_s=args.min_clip_len_s,
                max_clip_len_s=args.max_clip_len_s,
            )
        )
    else:
        stages.append(
            FixedStrideExtractorStage(
                clip_len_s=args.fixed_stride_len_s, min_clip_len_s=args.min_clip_len_s
            )
        )
    stages.append(
        ClipTranscodingStage(num_threads=args.transcode_cpus, chunk_size=args.clip_chunk_size)
    )
    if args.sr:
        from cosmos_curate_tpu.pipelines.video.stages.super_resolution import (
            SuperResolutionStage,
        )

        if args.sr_overlap_frames >= args.sr_window_frames:
            # fail fast: the stage's per-clip error handling would otherwise
            # swallow the ValueError and ship a full non-SR output set
            raise ValueError(
                f"--sr-overlap-frames ({args.sr_overlap_frames}) must be < "
                f"--sr-window-frames ({args.sr_window_frames})"
            )

        # directly after transcode (reference inserts SR there,
        # splitting_pipeline.py:553): filters and frame extraction then see
        # the upscaled clips
        stages.append(
            SuperResolutionStage(
                variant=args.sr_variant,
                window_len=args.sr_window_frames,
                overlap=args.sr_overlap_frames,
                sp_size=args.sr_sp_size,
            )
        )
    if args.motion_filter != "disable":
        from cosmos_curate_tpu.pipelines.video.stages.motion_filter import MotionFilterStage

        stages.append(
            MotionFilterStage(
                score_only=args.motion_filter == "score-only",
                global_threshold=args.motion_global_threshold,
                per_patch_threshold=args.motion_patch_threshold,
                backend=args.motion_backend,
                mv_global_threshold=args.motion_mv_global_threshold,
                mv_patch_threshold=args.motion_mv_patch_threshold,
            )
        )
    stages.append(
        ClipFrameExtractionStage(
            signatures=tuple(FrameExtractionSignature("fps", f) for f in args.extract_fps),
            resize_hw=args.extract_resize_hw,
        )
    )
    primary_sig = FrameExtractionSignature("fps", args.extract_fps[0])
    if args.aesthetic_threshold is not None:
        from cosmos_curate_tpu.pipelines.video.stages.aesthetic_filter import AestheticFilterStage

        stages.append(
            AestheticFilterStage(threshold=args.aesthetic_threshold, extraction=primary_sig)
        )
    if args.text_filter != "disable":
        from cosmos_curate_tpu.pipelines.video.stages.artificial_text_filter import (
            ArtificialTextFilterStage,
        )

        stages.append(
            ArtificialTextFilterStage(
                threshold=args.text_filter_threshold,
                score_only=args.text_filter == "score-only",
                extraction=primary_sig,
            )
        )
    if args.semantic_filter != "disable":
        from cosmos_curate_tpu.pipelines.video.stages.semantic_filter import SemanticFilterStage

        stages.append(
            SemanticFilterStage(
                prompt_variant=args.semantic_filter_prompt,
                score_only=args.semantic_filter == "score-only",
                extraction=primary_sig,
                model_flavor=args.caption_model,
            )
        )
    if args.embedding_model:
        from cosmos_curate_tpu.pipelines.video.stages.embedding import ClipEmbeddingStage

        stages.append(ClipEmbeddingStage(variant=args.embedding_model, extraction=primary_sig))
    if args.incremental_dedup != "disable":
        from cosmos_curate_tpu.pipelines.video.stages.dedup_stage import (
            IncrementalDedupStage,
        )

        if not args.embedding_model:
            raise ValueError(
                "--incremental-dedup needs an --embedding-model: dedup "
                "queries the corpus index with this run's clip embeddings"
            )
        # directly after embedding: duplicates are flagged/dropped before
        # captioning, previews, and the writer's embedding/index writes
        stages.append(
            IncrementalDedupStage(
                resolve_index_path(args),
                eps=args.dedup_eps,
                nprobe=args.dedup_nprobe,
                score_only=args.incremental_dedup == "score-only",
            )
        )
    if args.captioning:
        from cosmos_curate_tpu.pipelines.video.stages.captioning import (
            CaptionPrepStage,
            CaptionStage,
        )

        stages.append(
            CaptionPrepStage(window_len=args.caption_window_len, extraction=primary_sig)
        )
        stages.append(
            CaptionStage(
                prompt_variant=args.caption_prompt_variant,
                model_flavor=args.caption_model,
            )
        )
    if args.enhance_captions:
        from cosmos_curate_tpu.pipelines.video.stages.enhance_caption import EnhanceCaptionStage

        stages.append(EnhanceCaptionStage(prompt_variant=args.caption_prompt_variant, model_flavor=args.caption_model))
    if args.t5_embeddings:
        from cosmos_curate_tpu.pipelines.video.stages.caption_embedding import (
            CaptionEmbeddingStage,
        )

        stages.append(CaptionEmbeddingStage(prompt_variant=args.caption_prompt_variant))
    if args.previews:
        from cosmos_curate_tpu.pipelines.video.stages.preview import PreviewStage

        stages.append(PreviewStage(extraction=primary_sig))
    if args.tracking or args.per_event_captions:
        from cosmos_curate_tpu.pipelines.video.stages.tracking import TrackingStage

        stages.append(TrackingStage(write_annotated=args.tracking_annotated))
    if args.per_event_captions:
        from cosmos_curate_tpu.pipelines.video.stages.per_event_caption import (
            PerEventCaptionStage,
        )

        stages.append(PerEventCaptionStage(model_flavor=args.caption_model))
    stages.extend(args.extra_stages)
    stages.append(
        ClipWriterStage(
            args.output_path,
            index_path=resolve_index_path(args) if args.corpus_index else "",
        )
    )
    return stages


def resolve_index_path(args: SplitPipelineArgs) -> str:
    """The corpus-index root this run writes fragments to / queries:
    explicit ``index_path`` or ``<output>/index``."""
    return (args.index_path or f"{args.output_path.rstrip('/')}/index").rstrip("/")


def run_split(
    args: SplitPipelineArgs,
    *,
    runner: RunnerInterface | None = None,
    config: PipelineConfig | None = None,
) -> dict:
    """Build inputs (with resume), run, write summary.json; returns summary."""
    t0 = time.monotonic()
    # retrying accelerator gate (reference gpu_start_helper): catch a dead
    # TPU relay BEFORE spawning workers so the failure mode is one clear
    # action, not N crashed model setups. Opt-in (probing costs a subprocess
    # jax import): CURATE_HEALTH_GATE=on degrades this run to CPU when the
    # TPU is unhealthy; =strict aborts with a clear message instead.
    import os as _os

    gate_mode = _os.environ.get("CURATE_HEALTH_GATE", "off")  # off|on|strict
    if gate_mode in ("on", "strict"):
        from cosmos_curate_tpu.utils.health import accelerator_health_gate

        alive = accelerator_health_gate(
            attempts=3,
            probe_timeout_s=120,
            backoff_s=30,
            require=gate_mode == "strict",
        )
        if not alive:
            logger.warning("health gate: TPU unhealthy — running this job on CPU")
            _os.environ["JAX_PLATFORMS"] = "cpu"
    # live ops plane: export the snapshot dir derived from the output root
    # BEFORE resolving the runner, so every runner (and the workers it
    # spawns) publishes <output>/report/live/status.json — the live
    # counterpart of run_report.json (`top`, `report --follow`, and the
    # service's /v1/jobs/<id>/status all read it)
    from cosmos_curate_tpu.observability.live_status import export_live_status_dir

    export_live_status_dir(args.output_path)
    if runner is None:
        # resolve the default HERE, not inside run_pipeline: the finalize
        # path hands the flight recorder the instance that actually ran,
        # so runner-sourced report sections (dead-letter counts, stage
        # times, overlap) reflect this run instead of falling to empties
        from cosmos_curate_tpu.core.runner import default_runner

        runner = default_runner()
    from cosmos_curate_tpu.parallel.distributed import (
        maybe_initialize_distributed,
        partition_tasks_for_node,
    )

    maybe_initialize_distributed()
    # work-stealing runs call run_pipeline() once per stolen batch, and each
    # run() resets the runner's DLQ accounting — accumulate drops here so
    # finalize reports the whole node, not the last batch
    steal_dead: dict = {"count": 0, "dirs": []}
    index_extra: dict = {}
    run_root = None
    # tracing setup sits immediately before the try whose finally tears it
    # down: anything risky in between (runner resolution, distributed init)
    # raising would otherwise leave tracing enabled with an unexported root
    if args.tracing:
        from cosmos_curate_tpu.observability.flight_recorder import (
            clear_trace_artifacts,
        )
        from cosmos_curate_tpu.observability.tracing import (
            TRACEPARENT_ENV,
            attach_traceparent,
            enable_tracing,
            format_traceparent,
            start_span,
        )
        from cosmos_curate_tpu.parallel.distributed import node_rank_and_count

        rank, num_nodes = node_rank_and_count()
        # a re-run into the same output root must start from a clean trace:
        # stale rotation parts / collected worker files / node-stats
        # sidecars carry the old run's trace ids and drop counts. Multi-node
        # scopes the clear to this rank's own files (peers may already be
        # writing to the shared root)
        clear_trace_artifacts(
            args.output_path, rank=rank if num_nodes > 1 else None
        )
        name = "driver.ndjson" if num_nodes <= 1 else f"driver-n{rank}.ndjson"
        enable_tracing(f"{args.output_path.rstrip('/')}/profile/traces/{name}")
        # join an orchestrator-stamped trace when present, then root every
        # span this node emits on ONE run span: work-stealing calls
        # runner.run() once per claim batch, and each run() opens its own
        # pipeline.run span — without a shared parent a multi-batch run
        # fragments into N trace ids and the flight recorder (and bench's
        # trace_connected) reports a disconnected trace. The root rides the
        # process-level parent, not the contextvar stack, so it survives
        # any thread hop between claim batches.
        attach_traceparent(_os.environ.get(TRACEPARENT_ENV))
        run_root = start_span("run.split", output_path=args.output_path)
        attach_traceparent(format_traceparent(run_root))
    try:
        if args.multicam:
            from cosmos_curate_tpu.pipelines.video.input_discovery import (
                discover_multicam_tasks,
            )

            if args.splitting_algorithm != "fixed-stride":
                raise ValueError(
                    "multicam sessions split fixed-stride only (time-aligned "
                    "spans across cameras; reference MULTICAM.md scope)"
                )
            tasks = discover_multicam_tasks(
                args.input_path,
                args.output_path,
                primary_camera=args.primary_camera,
                limit=args.limit,
            )
        else:
            tasks = discover_split_tasks(
                args.input_path, args.output_path, limit=args.limit
            )
        stages = assemble_stages(args)
        stages = _apply_observability_wrappers(stages, args)
        from cosmos_curate_tpu.parallel.distributed import node_rank_and_count
        from cosmos_curate_tpu.parallel.work_stealing import (
            run_with_stealing,
            stealing_enabled,
        )

        _, n_nodes = node_rank_and_count()
        if n_nodes > 1 and stealing_enabled():
            # shared-ledger mode: nodes pull claim batches until dry, so a
            # skewed input split rebalances instead of idling fast nodes
            from cosmos_curate_tpu.pipelines.video.input_discovery import (
                _processed_video_ids,
            )
            from cosmos_curate_tpu.pipelines.video.stages.writer import video_record_id

            done_cache = {"ts": 0.0, "ids": set()}

            def _task_done(t) -> bool:
                # resume records are the completion signal; one listing per
                # linger poll, not per task
                now = time.monotonic()
                if now - done_cache["ts"] > 5.0:
                    done_cache["ids"] = _processed_video_ids(args.output_path)
                    done_cache["ts"] = now
                return video_record_id(t.video.path) in done_cache["ids"]

            def _run_batch(batch):
                res = run_pipeline(batch, stages, config=config, runner=runner)
                dlq = getattr(runner, "dlq", None)
                n = int(
                    getattr(runner, "dead_lettered", 0)
                    or getattr(dlq, "recorded", 0)
                    or 0
                )
                if n:
                    steal_dead["count"] += n
                    if dlq is not None and getattr(dlq, "recorded", 0):
                        steal_dead["dirs"].append(str(dlq.run_dir))
                return res

            out = run_with_stealing(
                tasks,
                args.output_path,
                _run_batch,
                record_id=lambda t: video_record_id(t.video.path),
                is_done=_task_done,
            )
        else:
            # default: each node takes a disjoint task slice (host-level
            # data parallelism; resume records keep re-runs consistent)
            tasks = partition_tasks_for_node(tasks)
            out = run_pipeline(tasks, stages, config=config, runner=runner) or []
        if args.corpus_index and n_nodes == 1:
            # end-of-run consolidation, BEFORE finalize so its
            # pipeline_index_* aggregates land in run_report.json
            index_extra = _consolidate_corpus_index(args)
    finally:
        if args.tracing:
            from cosmos_curate_tpu.observability.tracing import (
                disable_tracing,
                end_span,
            )

            if run_root is not None:
                end_span(run_root)
            disable_tracing()  # flushes buffered spans through storage
        if args.tracing or args.profile_cpu or args.profile_memory:
            from cosmos_curate_tpu.observability.artifacts import (
                collect_artifacts,
                finalize_delivery,
            )
            from cosmos_curate_tpu.parallel.distributed import node_rank_and_count

            collect_artifacts(args.output_path)
            rank, count = node_rank_and_count()
            extra = None
            if steal_dead["count"]:
                # the last stolen batch's drops are already in the
                # accumulator, so this replaces (not adds to) the
                # runner's last-run()-scoped accounting
                extra = {"dead_lettered": steal_dead["count"]}
                if steal_dead["dirs"]:
                    extra["dlq_run_dir"] = ",".join(dict.fromkeys(steal_dead["dirs"]))
            if count == 1:
                # single node: this process is also the delivery driver.
                # Multi-node runs finalize from the merge-summaries step
                # (cli/local_cli.py), once every node has collected.
                finalize_delivery(args.output_path)
                if args.tracing:
                    # flight recorder: merge spans + dispatch/flow aggregates
                    # + DLQ counts into report/run_report.json (render with
                    # `cosmos-curate-tpu report <output>`)
                    try:
                        from cosmos_curate_tpu.observability.flight_recorder import (
                            write_run_report,
                        )

                        write_run_report(args.output_path, runner=runner, extra=extra)
                    except Exception:
                        logger.exception(
                            "flight recorder failed (run output unaffected)"
                        )
            elif args.tracing:
                # multi-node: the merged report is built at merge-summaries
                # time, when this runner's memory is gone — persist the
                # runner-sourced sections (dead-letter counts, stage times,
                # dispatch/flow aggregates) as a per-node sidecar now
                try:
                    from cosmos_curate_tpu.observability.flight_recorder import (
                        write_node_stats,
                    )

                    write_node_stats(args.output_path, rank, runner, extra=extra)
                except Exception:
                    logger.exception(
                        "node stats sidecar failed (run output unaffected)"
                    )
    elapsed = time.monotonic() - t0
    num_chips = args.num_chips or _discover_num_chips()
    from cosmos_curate_tpu.parallel.distributed import node_rank_and_count

    rank, _ = node_rank_and_count()
    summary = build_summary(
        out, pipeline_run_time_s=elapsed, num_chips=num_chips, extra=index_extra or None
    )
    name = "summary.json" if rank == 0 else f"summary-node{rank}.json"
    write_summary(f"{args.output_path.rstrip('/')}/{name}", summary)
    logger.info(
        "split done: %d videos, %d clips, %.1fs",
        summary["num_videos"], summary["num_clips"], elapsed,
    )
    return summary


def _consolidate_corpus_index(args: SplitPipelineArgs) -> dict:
    """Fold the writer's pending index fragments into per-cluster shards
    (training centroids on the first run). Single-node only: concurrent
    per-node consolidations would race on centroids/meta — multi-node runs
    leave pending fragments for `cosmos-curate-tpu index consolidate`
    after merge (chunk-scoped tags never collide across nodes, so the
    merged pending set folds in one pass; no full `index build` re-read).
    Failures never fail the run."""
    try:
        from cosmos_curate_tpu.dedup.corpus_index import consolidate_index

        mesh = None
        try:
            from cosmos_curate_tpu.parallel.mesh import best_effort_mesh

            mesh = best_effort_mesh()
        except Exception as e:
            logger.warning("no mesh for index consolidation (%s)", e)
        cstats = consolidate_index(resolve_index_path(args), mesh=mesh)
        logger.info(
            "corpus index consolidated: %d vectors in (%d random-provenance refused)",
            cstats["consolidated"], cstats["skipped_random"],
        )
        return {"corpus_index": {**cstats, "path": resolve_index_path(args)}}
    except Exception:
        logger.exception("index consolidation failed (run output unaffected)")
        return {}


def _apply_observability_wrappers(
    stages: list[Stage | StageSpec], args: SplitPipelineArgs
) -> list[Stage | StageSpec]:
    """Inject stage-save and profiling wrappers (dynamic subclassing — the
    reference's zero-stage-code-change approach, profiling.py:1129)."""
    out_root = args.output_path.rstrip("/")
    if args.stage_save_rate > 0:
        from cosmos_curate_tpu.observability.stage_replay import (
            StageSaveConfig,
            stage_save_wrapper,
        )

        cfg = StageSaveConfig(
            output_path=f"{out_root}/stage_save",
            sample_rate=args.stage_save_rate,
            stages=args.stage_save_stages,
        )
        for s in stages:  # wrappers mutate the stage instance in place
            stage_save_wrapper(s.stage if isinstance(s, StageSpec) else s, cfg)
    if args.profile_cpu or args.profile_memory:
        from cosmos_curate_tpu.observability.profiling import (
            ProfilingConfig,
            profiling_wrapper,
        )

        cfg = ProfilingConfig(
            cpu=args.profile_cpu,
            memory=args.profile_memory,
            output_path=f"{out_root}/profile",
        )
        for s in stages:
            profiling_wrapper(s.stage if isinstance(s, StageSpec) else s, cfg)
    return stages


def _discover_num_chips() -> int:
    """TPU chip count for the summary metric. Device discovery can BLOCK
    indefinitely when the TPU tunnel is unhealthy, so it runs under a
    timeout — a metric denominator must never hang the pipeline."""
    import threading

    result: list[int] = []

    def query() -> None:
        try:
            import jax

            result.append(max(1, len([d for d in jax.devices() if d.platform == "tpu"])))
        except Exception:
            result.append(1)

    # daemon thread: a hung device query must block neither the pipeline
    # nor interpreter shutdown
    t = threading.Thread(target=query, daemon=True)
    t.start()
    t.join(timeout=20.0)
    return result[0] if result else 1
