"""Video download + probe stage.

Equivalent capability of the reference's ``VideoDownloader``
(cosmos_curate/pipelines/video/read_write/download_stages.py:44): fetch raw
bytes from any storage backend, probe metadata, record per-item errors on the
task instead of raising (containment model, SURVEY.md §5).
"""

from __future__ import annotations

from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import SplitPipeTask
from cosmos_curate_tpu.storage.client import read_bytes
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.decode import extract_video_metadata

logger = get_logger(__name__)


class VideoDownloadStage(Stage[SplitPipeTask, SplitPipeTask]):
    """IO stage: fractional CPU so many workers overlap network latency."""

    def __init__(self, *, probe_metadata: bool = True) -> None:
        self.probe_metadata = probe_metadata

    @property
    def resources(self) -> Resources:
        return Resources(cpus=0.25)

    @property
    def thread_safe(self) -> bool:
        # pure fetch+probe on the batch's own tasks; storage clients are
        # stateless per call — the pipelined runner may fan this out
        return True

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        for task in tasks:
            # multicam sessions fetch every camera; single-cam = [video]
            for video in task.videos:
                try:
                    video.raw_bytes = read_bytes(video.path)
                    if self.probe_metadata:
                        video.metadata = extract_video_metadata(video.raw_bytes)
                        video.metadata.size_bytes = len(video.raw_bytes)
                        if not video.metadata.is_valid:
                            video.errors["download"] = "invalid or empty video stream"
                except Exception as e:
                    logger.warning("failed to fetch %s: %s", video.path, e)
                    video.errors["download"] = str(e)
        return tasks
