"""Caption-enhancement stage: LM rewrite of existing captions.

Equivalent capability of the reference's ``EnhanceCaptionStage``
(cosmos_curate/pipelines/video/captioning/captioning_stages.py:189 — ChatLM
/ OpenAI caption rewriting). Reuses the caption engine text-only (no vision
prefill), so one model deployment serves both passes.
"""

from __future__ import annotations

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import SplitPipeTask
from cosmos_curate_tpu.models.prompts import ENHANCE_PROMPT
from cosmos_curate_tpu.models.vlm import CaptionRequest, SamplingConfig, VLM_BASE, VLMConfig
from cosmos_curate_tpu.pipelines.video.stages.captioning import _CaptionVLM


class EnhanceCaptionStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        prompt_variant: str = "default",
        cfg: VLMConfig | None = None,
        max_batch: int = 8,
        max_new_tokens: int = 128,
        model_flavor: str | None = None,
    ) -> None:
        from cosmos_curate_tpu.pipelines.video.stages.captioning import (
            _owner_tag,
            resolve_caption_model,
        )

        self.prompt_variant = prompt_variant
        self.max_new_tokens = max_new_tokens
        self.owner = _owner_tag("enhance-caption")
        self._model = resolve_caption_model(cfg, model_flavor, max_batch)
        if self.max_new_tokens >= self._model.cfg.max_seq // 2:
            self.max_new_tokens = self._model.cfg.max_seq // 2

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, entire_tpu_host=True)

    @property
    def batch_size(self) -> int:
        # deep batches keep the engine's continuous batch full across
        # clips (one task per call = every rewrite decoded solo)
        return 16

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        engine = self._model.engine
        assert engine is not None, "setup() not called"
        windows = {}
        for task in tasks:
            for clip in task.video.clips:
                for i, win in enumerate(clip.windows):
                    text = win.caption.get(self.prompt_variant, "")
                    if not text:
                        continue
                    rid = f"{clip.uuid}-{i}"
                    windows[rid] = win
                    pre, ids = self._model.encode_prompt(
                        ENHANCE_PROMPT + text, has_vision=False
                    )
                    engine.add_request(
                        CaptionRequest(
                            request_id=rid,
                            prefix_ids=pre,
                            prompt_ids=ids,
                            sampling=SamplingConfig(max_new_tokens=self.max_new_tokens),
                            owner=self.owner,
                        )
                    )
        if windows:
            for res in engine.run_until_complete(owner=self.owner):
                win = windows.get(res.request_id)
                if win is not None:
                    win.enhanced_caption[self.prompt_variant] = res.text
        return tasks
