"""Clip extraction, transcode, and dynamic re-chunking stages.

Equivalent capability of the reference's clipping stages
(cosmos_curate/pipelines/video/clipping/clip_extraction_stages.py:
``FixedStrideExtractorStage``:664, ``ClipTranscodingStage``:167,
``chunk_tasks``:92): turn a probed video into clip spans, re-encode each span
standalone, then re-chunk one big video task into bounded clip-chunks so a
5-hour video never pins the object store.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import replace

from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import SplitPipeTask, Video
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.encode import transcode_clips
from cosmos_curate_tpu.video.splitter import fixed_stride_spans, make_clips

logger = get_logger(__name__)


class FixedStrideExtractorStage(Stage[SplitPipeTask, SplitPipeTask]):
    """Fixed-duration spans → Clips with deterministic uuid5 ids."""

    def __init__(
        self,
        *,
        clip_len_s: float = 10.0,
        stride_s: float | None = None,
        min_clip_len_s: float = 2.0,
    ) -> None:
        self.clip_len_s = clip_len_s
        self.stride_s = stride_s
        self.min_clip_len_s = min_clip_len_s

    @property
    def thread_safe(self) -> bool:
        return True  # pure span math on the batch's own tasks

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        for task in tasks:
            video = task.video
            if video.errors:
                continue
            spans = fixed_stride_spans(
                video.metadata.duration_s,
                clip_len_s=self.clip_len_s,
                stride_s=self.stride_s,
                min_clip_len_s=self.min_clip_len_s,
            )
            if not spans and video.metadata.duration_s > 0:
                logger.warning(
                    "%s (%.1fs) produced 0 clips: clip_len_s=%.1f with "
                    "min_clip_len_s=%.1f filters everything",
                    video.path, video.metadata.duration_s,
                    self.clip_len_s, self.min_clip_len_s,
                )
            video.clips = make_clips(video.path, spans)
            video.num_total_clips = len(video.clips)
            # multicam: secondary cameras take the PRIMARY's spans verbatim
            # (time-aligned clips, reference MULTICAM.md — fixed-stride
            # only), clipped to each camera's own duration
            for aux in task.aux_videos:
                if aux.errors:
                    continue
                aux_spans = [
                    (a, min(b, aux.metadata.duration_s))
                    for a, b in spans
                    if a < aux.metadata.duration_s
                ]
                aux.clips = make_clips(aux.path, aux_spans)
                aux.num_total_clips = len(aux.clips)
        return tasks


class ClipTranscodingStage(Stage[SplitPipeTask, SplitPipeTask]):
    """Re-encode every clip span as a standalone mp4, then drop the source
    bytes and re-chunk into ``chunk_size``-clip tasks (dynamic chunking)."""

    def __init__(self, *, num_threads: int = 4, chunk_size: int = 64, resize_hw=None) -> None:
        self.num_threads = num_threads
        self.chunk_size = chunk_size
        self.resize_hw = resize_hw

    @property
    def resources(self) -> Resources:
        return Resources(cpus=float(self.num_threads))

    @property
    def thread_safe(self) -> bool:
        # each call builds its own thread pool over the batch's own videos;
        # no cross-call state on self
        return True

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        # One sequential decode pass per video (transcode_clips decodes each
        # source frame exactly once, feeding all spans); videos in the batch
        # (every camera of every task) fan across the thread pool — that is
        # what num_threads CPUs buys.
        with ThreadPoolExecutor(max_workers=self.num_threads) as pool:
            list(pool.map(self._transcode_video, [v for t in tasks for v in t.videos]))
        out: list[SplitPipeTask] = []
        for task in tasks:
            if task.is_multicam:
                # aligned aux clip lists make chunk re-slicing ambiguous;
                # multicam sessions stay one task (reference MULTICAM scope)
                task.video.num_clip_chunks = 1
                task.video.clip_chunk_index = 0
                out.append(task)
            else:
                out.extend(chunk_split_task(task, self.chunk_size))
        return out

    def _transcode_video(self, video) -> None:
        if not video.clips:
            video.release_raw()
            return
        src = video.raw_bytes if video.raw_bytes is not None else video.path
        try:
            from cosmos_curate_tpu.video.decode import get_frame_timestamps

            # same PTS mapping the span producers used (VFR-exact on mp4)
            ts = get_frame_timestamps(src)
            results = transcode_clips(
                src,
                [c.span for c in video.clips],
                resize_hw=self.resize_hw,
                timestamps_s=ts if len(ts) else None,
            )
            for clip, (data, codec) in zip(video.clips, results):
                if not data:
                    clip.errors["transcode"] = "empty output"
                    continue
                clip.encoded_data = data
                clip.encoding_codec = codec
        except Exception as e:
            logger.warning("transcode failed for %s: %s", video.path, e)
            for clip in video.clips:
                if clip.encoded_data is None:
                    clip.errors["transcode"] = str(e)
        video.release_raw()


def chunk_split_task(task: SplitPipeTask, chunk_size: int) -> list[SplitPipeTask]:
    """Split one task's clip list into tasks of ≤ ``chunk_size`` clips; each
    carries a shallow video copy so payloads are disjoint and ``fraction``
    sums to 1 across chunks."""
    video = task.video
    if chunk_size <= 0 or len(video.clips) <= chunk_size:
        video.num_clip_chunks = 1
        video.clip_chunk_index = 0
        return [task]
    chunks = [video.clips[i : i + chunk_size] for i in range(0, len(video.clips), chunk_size)]
    out = []
    for i, clip_group in enumerate(chunks):
        v = Video(
            path=video.path,
            metadata=video.metadata,
            clips=clip_group,
            num_total_clips=video.num_total_clips,
            num_clip_chunks=len(chunks),
            clip_chunk_index=i,
            errors=dict(video.errors),
        )
        # Fresh mutable fields: chunks must not alias each other's perf/stats.
        out.append(replace(task, video=v, stage_perf=dict(task.stage_perf), stats=None))
    return out
