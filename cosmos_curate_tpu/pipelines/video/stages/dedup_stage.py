"""Incremental dedup stage: query the persistent corpus index as clips flow.

The batch dedup pipeline (pipelines/video/dedup.py) runs AFTER a split run
and re-clusters everything; this stage moves dedup INTO the split pipeline —
each task's freshly-embedded clips are queried against the corpus index
(dedup/corpus_index.py) and clips within ``eps`` cosine distance of an
indexed neighbor are flagged (score-only) or dropped (enable) **before**
the writer persists their embeddings — a duplicate costs an index query
instead of captioning, preview, and parquet/index writes downstream.

Weights-provenance gate: when the run's embedding weights are random init
(models/registry.weights_provenance), similarity against the index is
noise — the stage refuses to flag anything (and warns once) unless
``CURATE_INDEX_ALLOW_RANDOM`` opts in, mirroring the writer's refusal to
index random embeddings.
"""

from __future__ import annotations

from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import SplitPipeTask
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class IncrementalDedupStage(Stage[SplitPipeTask, SplitPipeTask]):
    """Flags/drops clips that duplicate an indexed corpus neighbor.

    ``index_path`` names an existing corpus index; when none exists yet
    (first run into a fresh output root) the stage passes everything
    through — the end-of-run consolidation builds the index this run's
    successor will query.
    """

    def __init__(
        self,
        index_path: str,
        *,
        eps: float = 0.07,
        nprobe: int = 0,  # 0 = index default
        score_only: bool = False,
    ) -> None:
        self.index_path = index_path.rstrip("/")
        self.eps = eps
        self.nprobe = nprobe
        self.score_only = score_only
        self._index = None
        self._gate_logged = False

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0)

    def setup(self, worker) -> None:
        from cosmos_curate_tpu.dedup.corpus_index import CorpusIndex

        if not CorpusIndex.exists(self.index_path):
            logger.warning(
                "no corpus index at %s yet — incremental dedup passes "
                "everything through this run (the end-of-run consolidation "
                "builds it)", self.index_path,
            )
            return
        mesh = None
        try:
            from cosmos_curate_tpu.parallel.mesh import best_effort_mesh

            mesh = best_effort_mesh()
        except Exception as e:
            logger.warning("no mesh for index queries (%s); single device", e)
        self._index = CorpusIndex.open(
            self.index_path, mesh=mesh, metrics_name=self.name
        )

    def _provenance_ok(self, model: str) -> bool:
        from cosmos_curate_tpu.dedup.index_store import allow_random_provenance
        from cosmos_curate_tpu.models.registry import weights_provenance

        if weights_provenance(model) != "random" or allow_random_provenance():
            return True
        if not self._gate_logged:
            self._gate_logged = True
            logger.warning(
                "incremental dedup disabled: %s weights are random init — "
                "similarity to the index would be noise (stage a checkpoint "
                "or set CURATE_INDEX_ALLOW_RANDOM=1)", model,
            )
        return False

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        if self._index is None:
            return tasks
        import numpy as np

        from cosmos_curate_tpu.dedup.corpus_index import incremental_dedup

        model = self._index.meta.get("model", "")
        for task in tasks:
            video = task.video
            clips = [c for c in video.clips if model in c.embeddings]
            if not clips or not self._provenance_ok(model):
                continue
            ids = [str(c.uuid) for c in clips]
            vecs = np.stack([c.embeddings[model] for c in clips])
            result = incremental_dedup(
                self._index, ids, vecs,
                eps=self.eps, nprobe=self.nprobe or None,
            )
            dup_of = result["duplicate_of"]
            by_id = {str(c.uuid): c for c in clips}
            for cid in result["removed"]:
                clip = by_id[cid]
                clip.duplicate_of = dup_of.get(cid, "")
                if not self.score_only:
                    clip.filtered_by = "dedup"
            if not self.score_only and result["removed"]:
                removed_set = set(result["removed"])
                video.filtered_clips.extend(
                    c for c in video.clips if str(c.uuid) in removed_set
                )
                video.clips = [
                    c for c in video.clips if str(c.uuid) not in removed_set
                ]
            task.stage_perf["dedup_duplicates"] = (
                task.stage_perf.get("dedup_duplicates", 0) + len(result["removed"])
            )
        return tasks
