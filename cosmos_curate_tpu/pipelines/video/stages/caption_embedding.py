"""Caption-embedding stage (T5 over window captions).

Equivalent capability of the reference's ``_T5Stage``
(cosmos_curate/pipelines/video/captioning/captioning_stages.py:33 — T5-XXL
caption embeddings attached to windows for the cosmos-predict dataset).
"""

from __future__ import annotations

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import SplitPipeTask
from cosmos_curate_tpu.models.t5 import T5_BASE, T5Config, T5EncoderTPU


class CaptionEmbeddingStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(self, *, cfg: T5Config = T5_BASE, prompt_variant: str = "default") -> None:
        self.prompt_variant = prompt_variant
        self._model = T5EncoderTPU(cfg)

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=1.0)

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        windows = []
        texts = []
        for task in tasks:
            for clip in task.video.clips:
                for win in clip.windows:
                    text = win.caption.get(self.prompt_variant) or next(
                        (v for v in win.caption.values() if v), ""
                    )
                    if text:
                        windows.append(win)
                        texts.append(text)
        if texts:
            for win, sample in zip(windows, self._model.encode(texts)):
                win.t5_embedding = sample.embedding
        return tasks
