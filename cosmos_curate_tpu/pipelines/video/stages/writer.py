"""Clip writer stage: persists pipeline output in the curated layout.

Equivalent capability of the reference's ``ClipWriterStage``
(cosmos_curate/pipelines/video/read_write/metadata_writer_stage.py:66) and
output layout (docs/curator/reference/VIDEO_PIPELINES.md:56-91):

    <output>/clips/<clip-uuid>.mp4           transcoded clip
    <output>/previews/<clip-uuid>.webp       preview (when produced)
    <output>/metas/v0/<clip-uuid>.json       clip metadata + captions + scores
    <output>/embeddings/<model>/<chunk>.parquet   clip embeddings
    <output>/processed_videos/<video-id>.json     resume record

Writing the resume record **last** is the crash-safety contract: a video is
only skipped on re-run if all its chunks finished writing.

With ``index_path`` set, each chunk's embeddings are ALSO appended as a
pending corpus-index fragment (dedup/index_store.py — the reference's
in-pipeline lance fragment flow) so the end-of-run consolidation step can
fold the run into the persistent dedup index without re-reading every
parquet. Fragments carry weights provenance (models/registry.py) and
random-init embeddings are refused up front — noise must never become
corpus memory.
"""

from __future__ import annotations

import hashlib
import time
from collections import defaultdict

import numpy as np

from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import Clip, ClipStats, SplitPipeTask
from cosmos_curate_tpu.storage.client import write_bytes
from cosmos_curate_tpu.storage.writers import write_json, write_parquet
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def video_record_id(path: str) -> str:
    return hashlib.sha256(path.encode()).hexdigest()[:24]


def _clip_meta(clip: Clip, provenance: dict | None = None) -> dict:
    meta = {
        "uuid": str(clip.uuid),
        "source_video": clip.source_video,
        "span_start": clip.span[0],
        "span_end": clip.span[1],
        "duration_s": clip.duration_s,
        "codec": clip.encoding_codec,
        "motion_score_global": clip.motion_score_global,
        "motion_score_per_patch_min": clip.motion_score_per_patch_min,
        "aesthetic_score": clip.aesthetic_score,
        "artificial_text_score": clip.artificial_text_score,
        "semantic_pass": clip.semantic_pass,
        "filtered_by": clip.filtered_by,
        "duplicate_of": clip.duplicate_of,
        "embedding_models": sorted(clip.embeddings),
        "tracks": clip.tracks,
        "event_captions": clip.event_captions,
        "windows": [
            {
                "start_frame": w.start_frame,
                "end_frame": w.end_frame,
                "captions": w.caption,
                "enhanced_captions": w.enhanced_caption,
                "has_t5_embedding": w.t5_embedding is not None,
            }
            for w in clip.windows
        ],
        "errors": clip.errors,
    }
    if provenance:
        # per-model weights provenance (models/registry.weights_provenance):
        # "checkpoint:<sha256-12>" or "random" — noise is traceable on every
        # clip record, not just refused at the corpus index
        meta["weights_provenance"] = {
            m: provenance[m] for m in sorted(clip.embeddings) if m in provenance
        }
    return meta


class ClipWriterStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        output_path: str,
        *,
        write_embeddings: bool = True,
        write_previews: bool = True,
        index_path: str = "",
    ) -> None:
        self.output_path = output_path.rstrip("/")
        self.write_embeddings = write_embeddings
        self.write_previews = write_previews
        # corpus-index root for in-pipeline fragment appends ("" disables)
        self.index_path = index_path.rstrip("/")
        self._warned_random_models: set[str] = set()
        # model -> weights_provenance, memoized per stage instance: the
        # registry hashes a checkpoint once per (path, mtime) but still
        # stats the filesystem per call — not a per-clip cost
        self._provenance_memo: dict[str, str] = {}
        # one IndexStore for the run: construction reads meta.json to pin
        # the backend, which against remote storage is 1-2 round-trips —
        # not a per-chunk cost (benign race: duplicate instances agree)
        self._index_store = None

    @property
    def resources(self) -> Resources:
        return Resources(cpus=0.5)

    @property
    def thread_safe(self) -> bool:
        # every write targets clip-uuid / chunk-index-scoped paths, so
        # concurrent batches touch disjoint files; stats live on the task
        return True

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        for task in tasks:
            video = task.video
            stats = ClipStats()
            embedding_rows: dict[str, list[tuple[str, np.ndarray]]] = defaultdict(list)
            for clip in video.clips:
                self._write_clip(
                    clip, stats, embedding_rows,
                    camera=video.camera if task.is_multicam else "",
                )
            if task.is_multicam:
                self._write_aux_cameras(task, stats)
            for clip in video.filtered_clips:
                stats.num_clips += 1
                self._count_filtered(clip, stats)
                write_json(
                    f"{self.output_path}/metas/filtered/{clip.uuid}.json",
                    _clip_meta(clip, self._model_provenance(clip)),
                )
            if self.write_embeddings:
                chunk_tag = f"{video_record_id(video.path)}-{video.clip_chunk_index:05d}"
                for model, rows in embedding_rows.items():
                    write_parquet(
                        f"{self.output_path}/embeddings/{model}/{chunk_tag}.parquet",
                        {
                            "clip_uuid": [r[0] for r in rows],
                            "embedding": [r[1].astype(np.float32).tolist() for r in rows],
                        },
                    )
                    if self.index_path:
                        self._write_index_fragment(chunk_tag, model, rows, task)
            self._write_resume_record(task)
            # Free payloads (kept AND filtered clips): downstream only needs
            # stats/metadata, and filtered clips otherwise pin their mp4 +
            # frame arrays for the rest of the run.
            for clip in (*video.clips, *video.filtered_clips):
                clip.encoded_data = None
                clip.webp_preview = None
                clip.annotated_mp4 = None
                clip.release_frames()
                for w in clip.windows:
                    w.release_payloads()
                    w.t5_embedding = None  # persisted above
            task.stage_perf["clips_written"] = stats.num_clips
            if self._provenance_memo:
                # rides the task back to run_split: build_summary unions
                # these into summary.json's weights_provenance map
                task.stage_perf["weights_provenance"] = dict(self._provenance_memo)
            task.stats = stats
        return tasks

    def _model_provenance(self, clip: Clip) -> dict:
        """Weights provenance per embedding model on ``clip``, memoized —
        stamped into every clip meta (and, via stage_perf, summary.json) so
        a random-weights run is traceable end-to-end, not just refused at
        the corpus index (ROADMAP item 3b)."""
        from cosmos_curate_tpu.models.registry import weights_provenance

        out: dict[str, str] = {}
        for model in clip.embeddings:
            if model not in self._provenance_memo:
                try:
                    self._provenance_memo[model] = weights_provenance(model)
                except Exception:  # provenance must never fail a write
                    self._provenance_memo[model] = "unknown"
            out[model] = self._provenance_memo[model]
        return out

    def _write_index_fragment(
        self, chunk_tag: str, model: str, rows: list, task: SplitPipeTask
    ) -> None:
        """Append this chunk's embeddings as a pending corpus-index fragment
        (consolidated into per-cluster shards at end of run). Chunk tags
        scope fragments to disjoint files, so the stage stays thread-safe.
        Random-provenance embeddings are refused here — before they can
        reach the index — unless CURATE_INDEX_ALLOW_RANDOM opts in."""
        from cosmos_curate_tpu.dedup.index_store import IndexStore, allow_random_provenance
        from cosmos_curate_tpu.models.registry import weights_provenance
        from cosmos_curate_tpu.observability.stage_timer import record_index_ops

        provenance = weights_provenance(model)
        if provenance == "random" and not allow_random_provenance():
            if model not in self._warned_random_models:
                # benign race under concurrent batches: worst case is one
                # duplicate warning, never a poisoned index
                self._warned_random_models.add(model)
                logger.warning(
                    "not indexing %s embeddings: weights provenance is random "
                    "(stage a checkpoint, or set CURATE_INDEX_ALLOW_RANDOM=1)",
                    model,
                )
            record_index_ops(self.name, skipped_random=len(rows))
            task.stage_perf["index_skipped_random"] = (
                task.stage_perf.get("index_skipped_random", 0) + len(rows)
            )
            return
        t0 = time.monotonic()
        if self._index_store is None:
            self._index_store = IndexStore(self.index_path)
        self._index_store.write_pending_fragment(
            f"{chunk_tag}-{model}",
            [r[0] for r in rows],
            np.stack([r[1].astype(np.float32) for r in rows]),
            model=model,
            provenance=provenance,
        )
        record_index_ops(self.name, adds=len(rows), add_s=time.monotonic() - t0)
        task.stage_perf["index_fragment_rows"] = (
            task.stage_perf.get("index_fragment_rows", 0) + len(rows)
        )

    def _write_aux_cameras(self, task: SplitPipeTask, stats: ClipStats) -> None:
        """Secondary cameras land beside the primary under the clip's
        directory: clips/<primary-uuid>/<camera>.mp4 (reference MULTICAM.md
        per-camera clip layout). Aux clips match the primary's by span
        start (a shorter camera simply lacks the tail clips)."""
        for aux in task.aux_videos:
            by_start = {round(c.span[0], 6): c for c in aux.clips}
            for primary_clip in task.video.clips:
                aux_clip = by_start.get(round(primary_clip.span[0], 6))
                if aux_clip is None or not aux_clip.encoded_data:
                    continue
                write_bytes(
                    f"{self.output_path}/clips/{primary_clip.uuid}/{aux.camera}.mp4",
                    aux_clip.encoded_data,
                )
                aux_clip.encoded_data = None
                stats.num_transcoded += 1

    def _write_clip(
        self, clip: Clip, stats: ClipStats, embedding_rows, *, camera: str = ""
    ) -> None:
        stats.num_clips += 1
        stats.total_clip_duration_s += clip.duration_s
        stats.max_clip_duration_s = max(stats.max_clip_duration_s, clip.duration_s)
        if clip.encoded_data:
            dest = (
                f"{self.output_path}/clips/{clip.uuid}/{camera}.mp4"
                if camera
                else f"{self.output_path}/clips/{clip.uuid}.mp4"
            )
            write_bytes(dest, clip.encoded_data)
            clip.encoded_byte_size = len(clip.encoded_data)
            clip.encoded_sha256 = hashlib.sha256(clip.encoded_data).hexdigest()
            clip.encoded_url = dest
            stats.num_transcoded += 1
        if clip.webp_preview and self.write_previews:
            write_bytes(f"{self.output_path}/previews/{clip.uuid}.webp", clip.webp_preview)
            stats.num_with_webp += 1
        if clip.annotated_mp4:
            write_bytes(
                f"{self.output_path}/tracking/{clip.uuid}.mp4", clip.annotated_mp4
            )
        for model, emb in clip.embeddings.items():
            embedding_rows[model].append((str(clip.uuid), emb))
        if clip.embeddings:
            stats.num_with_embeddings += 1
        if any(w.caption for w in clip.windows):
            stats.num_with_captions += 1
        t5 = {
            f"window_{i}": w.t5_embedding
            for i, w in enumerate(clip.windows)
            if w.t5_embedding is not None
        }
        if t5:
            import io as io_mod

            import numpy as np_mod

            sink = io_mod.BytesIO()
            np_mod.savez(sink, **t5)
            write_bytes(f"{self.output_path}/t5_embeddings/{clip.uuid}.npz", sink.getvalue())
        write_json(
            f"{self.output_path}/metas/v0/{clip.uuid}.json",
            _clip_meta(clip, self._model_provenance(clip)),
        )

    @staticmethod
    def _count_filtered(clip: Clip, stats: ClipStats) -> None:
        key = clip.filtered_by
        if key == "motion":
            stats.num_filtered_by_motion += 1
        elif key == "aesthetic":
            stats.num_filtered_by_aesthetic += 1
        elif key == "text":
            stats.num_filtered_by_text += 1
        elif key == "semantic":
            stats.num_filtered_by_semantic += 1
        elif key == "dedup":
            stats.num_filtered_by_dedup += 1

    def _write_resume_record(self, task: SplitPipeTask) -> None:
        # One record per chunk (chunks of a video may be written by different
        # workers on different nodes); a video counts as processed when the
        # number of chunk records matches num_chunks (input_discovery checks).
        video = task.video
        vid = video_record_id(video.path)
        write_json(
            f"{self.output_path}/processed_videos/{vid}/chunk-{video.clip_chunk_index:05d}.json",
            {
                "path": video.path,
                "chunk_index": video.clip_chunk_index,
                "num_chunks": video.num_clip_chunks,
                "num_clips_total": video.num_total_clips,
                "duration_s": video.metadata.duration_s,
                "errors": video.errors,
            },
        )
