"""Embedding stages: per-clip video embeddings on the TPU.

Equivalent capability of the reference's embedding stages
(cosmos_curate/pipelines/video/embedding/internvideo2_stages.py:43/187,
cosmos_embed1_stages.py:43/190 — a CPU frame-prep stage feeding a device
embed stage). The same deliberate CPU/device split: frame prep happens in
``ClipFrameExtractionStage``; this stage batches all clips in a task into
one fixed-shape device call.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import FrameExtractionSignature, SplitPipeTask
from cosmos_curate_tpu.models.clip import CLIPImageEmbeddings
from cosmos_curate_tpu.models.embedder import VIDEO_EMBED_BASE, VideoEmbedConfig, VideoEmbedder
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class ClipEmbeddingStage(Stage[SplitPipeTask, SplitPipeTask]):
    """variant="video": temporal-transformer video embedding;
    variant="clip": mean of normalized CLIP frame embeddings."""

    def __init__(
        self,
        *,
        variant: str = "video",
        video_cfg: VideoEmbedConfig | None = None,
        clip_variant: str = "clip-vit-b16-tpu",
        extraction: FrameExtractionSignature = FrameExtractionSignature("fps", 2.0),
    ) -> None:
        from cosmos_curate_tpu.models.embedder import VIDEO_EMBED_VARIANTS

        if variant != "clip" and variant not in VIDEO_EMBED_VARIANTS:
            raise ValueError(
                f"unknown embedding variant {variant!r}; have "
                f"{['clip', *VIDEO_EMBED_VARIANTS]}"
            )
        self.variant = "clip" if variant == "clip" else "video"
        self.extraction = extraction
        self._model: ModelInterface
        if variant == "clip":
            self._model = CLIPImageEmbeddings(clip_variant)
        elif video_cfg is not None:
            self._model = VideoEmbedder(video_cfg)
        else:
            cfg, model_id = VIDEO_EMBED_VARIANTS[variant]
            self._model = VideoEmbedder(cfg, model_id=model_id)

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=1.0)

    @property
    def model_name(self) -> str:
        return self._model.model_id_names[0]

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        key = self.extraction.key()
        for task in tasks:
            video = task.video
            if self.variant == "video":
                self._embed_video(video, key)
            else:
                self._embed_clip_mean(video, key)
        return tasks

    def _embed_video(self, video, key: str) -> None:
        model: VideoEmbedder = self._model  # type: ignore[assignment]
        batch = []
        targets = []
        t = model.cfg.num_frames
        for clip in video.clips:
            frames = clip.extracted_frames.get(key)
            if frames is None or frames.shape[0] == 0:
                continue
            idx = model.sample_frame_indices(frames.shape[0])
            batch.append(frames[idx])
            targets.append(clip)
        if not batch:
            return
        # uniform spatial size enforced by stacking; prep stage resizes.
        embs = model.encode_clips(np.stack(batch))
        for clip, emb in zip(targets, embs):
            clip.embeddings[self.model_name] = emb

    def _embed_clip_mean(self, video, key: str) -> None:
        model: CLIPImageEmbeddings = self._model  # type: ignore[assignment]
        spans = []
        stacks = []
        offset = 0
        for clip in video.clips:
            frames = clip.extracted_frames.get(key)
            n = 0 if frames is None else frames.shape[0]
            spans.append((offset, offset + n))
            if n:
                stacks.append(frames)
            offset += n
        if offset == 0:
            return
        embs = model.encode_frames(np.concatenate(stacks))
        for clip, (a, b) in zip(video.clips, spans):
            if a == b:
                continue
            mean = embs[a:b].mean(axis=0)
            mean /= np.linalg.norm(mean) + 1e-8
            clip.embeddings[self.model_name] = mean.astype(np.float32)
