"""Embedding stages: per-clip video embeddings on the TPU.

Equivalent capability of the reference's embedding stages
(cosmos_curate/pipelines/video/embedding/internvideo2_stages.py:43/187,
cosmos_embed1_stages.py:43/190 — a CPU frame-prep stage feeding a device
embed stage). The same deliberate CPU/device split: frame prep happens in
``ClipFrameExtractionStage``; this stage batches all clips in a task into
shape-grouped batches that the embedders dispatch through the shared
``DevicePipeline`` (models/device_pipeline.py) — pow2 bucket micro-batches,
double-buffered H2D/compute, readback deferred to the drain — so the MXU
stays fed while the host assembles the next group.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import FrameExtractionSignature, SplitPipeTask
from cosmos_curate_tpu.models.clip import CLIPImageEmbeddings
from cosmos_curate_tpu.models.embedder import VIDEO_EMBED_BASE, VideoEmbedConfig, VideoEmbedder
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Tasks fused per device dispatch (bench.py warms the matching shapes).
EMBED_STAGE_TASK_BATCH = 8


class ClipEmbeddingStage(Stage[SplitPipeTask, SplitPipeTask]):
    """variant="video": temporal-transformer video embedding;
    variant="clip": mean of normalized CLIP frame embeddings."""

    def __init__(
        self,
        *,
        variant: str = "video",
        video_cfg: VideoEmbedConfig | None = None,
        clip_variant: str = "clip-vit-b16-tpu",
        extraction: FrameExtractionSignature = FrameExtractionSignature("fps", 2.0),
    ) -> None:
        from cosmos_curate_tpu.models.embedder import VIDEO_EMBED_VARIANTS
        from cosmos_curate_tpu.models.internvideo2 import IV2_VARIANTS, IV2Embedder

        known = ["clip", *VIDEO_EMBED_VARIANTS, *IV2_VARIANTS]
        if variant not in known:
            raise ValueError(f"unknown embedding variant {variant!r}; have {known}")
        self.variant = "clip" if variant == "clip" else "video"
        self.extraction = extraction
        self._model: ModelInterface
        if variant == "clip":
            self._model = CLIPImageEmbeddings(clip_variant)
        elif variant in IV2_VARIANTS:
            cfg, model_id, require = IV2_VARIANTS[variant]
            self._model = IV2Embedder(cfg, model_id=model_id, require_weights=require)
        elif video_cfg is not None:
            self._model = VideoEmbedder(video_cfg)
        else:
            cfg, model_id = VIDEO_EMBED_VARIANTS[variant]
            self._model = VideoEmbedder(cfg, model_id=model_id)

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=1.0)

    @property
    def model_name(self) -> str:
        return self._model.model_id_names[0]

    @property
    def batch_size(self) -> int:
        # several tasks per call: their clips fuse into per-shape device
        # batches below, so the MXU sees e.g. 32 clips instead of 4 per
        # dispatch
        return EMBED_STAGE_TASK_BATCH

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        key = self.extraction.key()
        if self.variant == "video":
            self._embed_video_batch([t.video for t in tasks], key)
        else:
            self._embed_clip_mean_batch([t.video for t in tasks], key)
        return tasks

    def _embed_video_batch(self, videos, key: str) -> None:
        """encode_clips over every clip of every task in the batch
        (cross-task batching: per-video batches waste the MXU on short
        videos with few clips). Clips group by spatial shape — a
        mixed-resolution corpus without prep-stage resizing embeds per
        group instead of crashing the whole batch."""
        model: VideoEmbedder = self._model  # type: ignore[assignment]
        groups: dict[tuple, tuple[list, list]] = {}
        for video in videos:
            for clip in video.clips:
                frames = clip.extracted_frames.get(key)
                if frames is None or frames.shape[0] == 0:
                    continue
                idx = model.sample_frame_indices(frames.shape[0])
                batch, targets = groups.setdefault(frames.shape[1:], ([], []))
                batch.append(frames[idx])
                targets.append(clip)
        for batch, targets in groups.values():
            embs = model.encode_clips(np.stack(batch))
            for clip, emb in zip(targets, embs):
                clip.embeddings[self.model_name] = emb

    def _embed_clip_mean_batch(self, videos, key: str) -> None:
        """Mean-of-CLIP-frame embeddings, fused across every clip of every
        task in the batch (same cross-task batching as the video variant),
        grouped by frame shape."""
        model: CLIPImageEmbeddings = self._model  # type: ignore[assignment]
        groups: dict[tuple, tuple[list, list]] = {}
        for video in videos:
            for clip in video.clips:
                frames = clip.extracted_frames.get(key)
                if frames is None or frames.shape[0] == 0:
                    continue
                stacks, targets = groups.setdefault(frames.shape[1:], ([], []))
                stacks.append(frames)
                targets.append(clip)
        for stacks, targets in groups.values():
            embs = model.encode_frames(np.concatenate(stacks))
            offset = 0
            for clip, frames in zip(targets, stacks):
                n = frames.shape[0]
                mean = embs[offset : offset + n].mean(axis=0)
                mean /= np.linalg.norm(mean) + 1e-8
                clip.embeddings[self.model_name] = mean.astype(np.float32)
                offset += n
