"""Aesthetic filter stage: CLIP + MLP scoring, threshold filter.

Equivalent capability of the reference's ``AestheticFilterStage``
(cosmos_curate/pipelines/video/filtering/aesthetics/
aesthetic_filter_stages.py:41). The batch across *all clips in the task* is
scored in one logical device call — the TPU-first replacement for
fractional-GPU packing (SURVEY.md §7): aggregate batches, not fractional
devices. Both the CLIP tower and the MLP head dispatch through the shared
``DevicePipeline`` (models/device_pipeline.py): pow2 bucket micro-batches
with overlapped H2D/compute/readback instead of a blocking ``np.asarray``
per call.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import FrameExtractionSignature, SplitPipeTask
from cosmos_curate_tpu.models.clip import CLIPAestheticScorer
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class AestheticFilterStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        threshold: float = 3.5,
        reduction: str = "min",  # min over frames (strict) or "mean"
        clip_variant: str = "clip-vit-l14-tpu",
        extraction: FrameExtractionSignature = FrameExtractionSignature("fps", 2.0),
        score_only: bool = False,
    ) -> None:
        self.threshold = threshold
        self.reduction = reduction
        self.extraction = extraction
        self.score_only = score_only
        self._scorer = CLIPAestheticScorer(clip_variant)

    @property
    def model(self) -> ModelInterface:
        return self._scorer

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=1.0)

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        key = self.extraction.key()
        for task in tasks:
            video = task.video
            # Gather all frames of all clips into one device batch.
            spans: list[tuple[int, int]] = []
            stacks: list[np.ndarray] = []
            offset = 0
            for clip in video.clips:
                frames = clip.extracted_frames.get(key)
                n = 0 if frames is None else frames.shape[0]
                spans.append((offset, offset + n))
                if n:
                    stacks.append(frames)
                offset += n
            if offset == 0:
                continue
            scores = self._scorer.score_frames(np.concatenate(stacks))
            kept = []
            for clip, (a, b) in zip(video.clips, spans):
                if a == b:
                    kept.append(clip)
                    continue
                s = scores[a:b]
                clip.aesthetic_score = float(s.min() if self.reduction == "min" else s.mean())
                if self.score_only or clip.aesthetic_score >= self.threshold:
                    kept.append(clip)
                else:
                    clip.filtered_by = "aesthetic"
                    video.filtered_clips.append(clip)
            video.clips = kept
        return tasks
