"""VLM semantic filter / classifier stages.

Equivalent capability of the reference's semantic filtering
(cosmos_curate/pipelines/video/filtering/aesthetics/semantic_filter_stages.py
:34/185 — ``VllmFilteringStage`` yes/no gate and ``VllmVideoClassifierStage``
type classifier, served by vLLM or API backends). Here both run on the
caption engine: a prompt per clip (first-window frames), the decoded answer
parsed as yes/no or as a class label.

Device dispatch note: this scorer's device work happens inside the caption
engine's continuous-batching loop (models/vlm/engine.py), which already
amortizes readback to one host sync per decode group — the engine is this
stage's DevicePipeline equivalent, so it is exempt from the per-call
micro-batch migration the other scorers went through.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import FrameExtractionSignature, SplitPipeTask
from cosmos_curate_tpu.models.prompts import SEMANTIC_FILTER_PROMPTS
from cosmos_curate_tpu.models.vlm import CaptionRequest, SamplingConfig, VLM_BASE, VLMConfig
from cosmos_curate_tpu.pipelines.video.stages.captioning import _CaptionVLM


def parse_yes_no(text: str) -> bool | None:
    t = text.strip().lower()
    if t.startswith("yes"):
        return True
    if t.startswith("no"):
        return False
    return None


class SemanticFilterStage(Stage[SplitPipeTask, SplitPipeTask]):
    """Drops clips the VLM answers 'no' for (or scores-only)."""

    def __init__(
        self,
        *,
        prompt_variant: str = "default",
        cfg: VLMConfig | None = None,
        max_batch: int = 8,
        model_flavor: str | None = None,
        score_only: bool = False,
        keep_on_unparseable: bool = True,
        num_frames: int = 4,
        extraction: FrameExtractionSignature = FrameExtractionSignature("fps", 2.0),
    ) -> None:
        self.prompt = SEMANTIC_FILTER_PROMPTS[prompt_variant]
        self.score_only = score_only
        self.keep_on_unparseable = keep_on_unparseable
        self.num_frames = num_frames
        self.extraction = extraction
        from cosmos_curate_tpu.pipelines.video.stages.captioning import (
            _owner_tag,
            resolve_caption_model,
        )

        self.owner = _owner_tag("semantic-filter")
        self._model = resolve_caption_model(cfg, model_flavor, max_batch)

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, entire_tpu_host=True)

    @property
    def batch_size(self) -> int:
        # deep batches keep the engine's continuous batch full across
        # clips; the shared filter-question prefix then hits the engine's
        # prefix KV cache on every request after the first
        return 16

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        engine = self._model.engine
        assert engine is not None, "setup() not called"
        key = self.extraction.key()
        targets = {}
        for task in tasks:
            for clip in task.video.clips:
                frames = clip.extracted_frames.get(key)
                if frames is None or frames.shape[0] == 0:
                    continue
                idx = np.linspace(0, frames.shape[0] - 1, self.num_frames).round().astype(int)
                targets[str(clip.uuid)] = clip
                pre, ids = self._model.encode_prompt(self.prompt, has_vision=True)
                engine.add_request(
                    CaptionRequest(
                        request_id=str(clip.uuid),
                        prefix_ids=pre,
                        prompt_ids=ids,
                        frames=frames[idx],
                        frame_fps=self.num_frames / max(clip.duration_s, 1e-6),
                        sampling=SamplingConfig(max_new_tokens=8),
                        owner=self.owner,
                    )
                )
        if not targets:
            return tasks
        verdicts = {
            r.request_id: parse_yes_no(r.text)
            for r in engine.run_until_complete(owner=self.owner)
        }
        for task in tasks:
            kept = []
            for clip in task.video.clips:
                if str(clip.uuid) not in targets:
                    kept.append(clip)  # never evaluated (no frames): keep
                    continue
                verdict = verdicts.get(str(clip.uuid))
                clip.semantic_pass = verdict
                keep = verdict if verdict is not None else self.keep_on_unparseable
                if self.score_only or keep:
                    kept.append(clip)
                else:
                    clip.filtered_by = "semantic"
                    task.video.filtered_clips.append(clip)
            task.video.clips = kept
        return tasks
