"""Super-resolution stage: windowed upscaling with overlap blending.

Equivalent capability of the reference's ``SuperResolutionStage``
(cosmos_curate/pipelines/video/super_resolution/super_resolution_stage.py:189
— 128-frame windows, 64-frame overlap, linear blending, re-encode). Decodes
each clip, upscales window-by-window on the TPU, blends overlaps with
linear ramps, re-encodes the clip at the new resolution.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import SplitPipeTask
from cosmos_curate_tpu.models.super_resolution import SR_BASE, SRConfig, SuperResolutionModel
from cosmos_curate_tpu.parallel.mesh import MeshSpec
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.decode import decode_frames, extract_video_metadata
from cosmos_curate_tpu.video.encode import encode_frames
from cosmos_curate_tpu.video.windowing import overlapping_windows

logger = get_logger(__name__)


def blend_windows(
    windows: list[tuple[int, int, np.ndarray]], total: int
) -> np.ndarray:
    """Linear-ramp blend of overlapping [start, end) frame windows."""
    assert windows
    h, w, c = windows[0][2].shape[1:]
    acc = np.zeros((total, h, w, c), np.float32)
    weight = np.zeros((total, 1, 1, 1), np.float32)
    for start, end, frames in windows:
        n = end - start
        ramp = np.ones(n, np.float32)
        # ramp the leading edge so consecutive windows cross-fade
        lead = min(n, max(1, n // 4))
        if start > 0:
            ramp[:lead] = np.linspace(0.0, 1.0, lead, endpoint=False) + 1e-3
        acc[start:end] += frames[: n].astype(np.float32) * ramp[:, None, None, None]
        weight[start:end, 0, 0, 0] += ramp
    return (acc / np.maximum(weight, 1e-6)).round().astype(np.uint8)


class SuperResolutionStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        cfg: SRConfig | None = None,
        window_len: int = 128,
        overlap: int = 64,
        sp_size: int = 1,
        variant: str = "diffusion",
        diffusion_cfg=None,
    ) -> None:
        """``variant``: "diffusion" (default — the SeedVR2-class windowed
        conditional diffusion denoiser, models/diffusion_sr.py) or
        "srnet" (the lighter single-pass conv net). Passing ``cfg`` (an
        SRConfig) selects srnet; passing both configs is a caller error."""
        self.window_len = window_len
        self.overlap = overlap
        if cfg is not None and diffusion_cfg is not None:
            raise ValueError("pass cfg (srnet) OR diffusion_cfg, not both")
        if cfg is not None:
            if variant == "diffusion":
                logger.info("explicit SRConfig selects the srnet variant")
            variant = "srnet"
        if variant == "diffusion":
            from cosmos_curate_tpu.models.diffusion_sr import (
                DIFF_SR_BASE,
                DiffusionSRModel,
            )

            self._model = DiffusionSRModel(diffusion_cfg or DIFF_SR_BASE, sp_size=sp_size)
        elif variant == "srnet":
            self._model = SuperResolutionModel(cfg or SR_BASE, sp_size=sp_size)
        else:
            raise ValueError(f"unknown SR variant {variant!r}; have diffusion|srnet")

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, entire_tpu_host=True)

    @property
    def mesh_spec(self) -> MeshSpec | None:
        """Sequence-parallel plane (models build a seq-only mesh over
        ``sp_size`` chips); declared so the pre-flight rejects an sp_size
        the cluster cannot tile before any worker spawns."""
        sp = getattr(self._model, "sp_size", 1)
        if sp <= 1:
            return None
        return MeshSpec(dcn=1, data=1, model=1, seq=sp)

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        for task in tasks:
            for clip in task.video.clips:
                if clip.encoded_data is None:
                    continue
                try:
                    meta = extract_video_metadata(clip.encoded_data)
                    frames = decode_frames(clip.encoded_data)
                    if frames.shape[0] == 0:
                        continue
                    spans = overlapping_windows(
                        frames.shape[0], window_len=self.window_len, overlap=self.overlap
                    )
                    # submit the whole tile loop before reading anything
                    # back: window k+1's H2D overlaps window k's compute,
                    # readback resolves in order at drain (DevicePipeline)
                    for a, b in spans:
                        self._model.submit_window(frames[a:b])
                    upscaled = [
                        (a, b, out)
                        for (a, b), out in zip(spans, self._model.drain_windows())
                    ]
                    blended = blend_windows(upscaled, frames.shape[0])
                    clip.encoded_data = encode_frames(blended, fps=meta.fps or 24.0)
                except Exception as e:
                    logger.warning("SR failed for %s: %s", clip.uuid, e)
                    clip.errors["super_resolution"] = str(e)
                    # a failure after partial submits must not leave windows
                    # in flight: the NEXT clip's drain would zip the leftover
                    # results onto its own spans (silent corruption)
                    pipe = self._model.device_pipeline
                    if pipe is not None:
                        pipe.abort()
        return tasks
