"""Clip frame-extraction stage: the CPU prep that feeds every TPU stage.

Equivalent capability of the reference's ``ClipFrameExtractionStage``
(cosmos_curate/pipelines/video/clipping/clip_frame_extraction_stages.py:43):
decode each clip's mp4 once per ``FrameExtractionSignature`` and cache the
frames on the clip so downstream device stages (embedding, aesthetics,
captioning prep) reuse them. The TPU-first reason this stage exists apart
from the model stages: decode is CPU-bound and autoscales independently of
chip-bound inference (SURVEY.md §7 design stance).
"""

from __future__ import annotations

from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import FrameExtractionSignature, SplitPipeTask
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.decode import extract_frames_at_fps

logger = get_logger(__name__)


class ClipFrameExtractionStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        signatures: tuple[FrameExtractionSignature, ...] = (FrameExtractionSignature("fps", 2.0),),
        resize_hw: tuple[int, int] | None = None,
        num_cpus: float = 3.0,
    ) -> None:
        self.signatures = signatures
        self.resize_hw = resize_hw
        self.num_cpus = num_cpus

    @property
    def resources(self) -> Resources:
        return Resources(cpus=self.num_cpus)

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        for task in tasks:
            for clip in task.video.clips:
                if clip.encoded_data is None:
                    continue
                for sig in self.signatures:
                    try:
                        frames = extract_frames_at_fps(
                            clip.encoded_data, target_fps=sig.target_fps, resize_hw=self.resize_hw
                        )
                        if frames.size == 0:
                            clip.errors[f"frames-{sig.key()}"] = "no frames decoded"
                            continue
                        clip.extracted_frames[sig.key()] = frames
                    except Exception as e:
                        logger.warning("frame extraction failed for %s: %s", clip.uuid, e)
                        clip.errors[f"frames-{sig.key()}"] = str(e)
        return tasks
