"""Clip frame-extraction stage: the CPU prep that feeds every TPU stage.

Equivalent capability of the reference's ``ClipFrameExtractionStage``
(cosmos_curate/pipelines/video/clipping/clip_frame_extraction_stages.py:43):
decode each clip's mp4 once and cache frames for every
``FrameExtractionSignature`` so downstream device stages (embedding,
aesthetics, captioning prep) reuse them. The TPU-first reason this stage
exists apart from the model stages: decode is CPU-bound and autoscales
independently of chip-bound inference (SURVEY.md §7 design stance).

Two levels of parallelism, both honoring the declared ``num_cpus``:

- clips fan out across a worker-thread pool (OpenCV's FFmpeg decode
  releases the GIL, so threads scale on real cores);
- all signatures of one clip are served from a SINGLE decode pass
  (``video.decode.extract_frames_multi``) instead of one container
  reopen + full decode per signature.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor

from cosmos_curate_tpu.core.stage import Resources, Stage, WorkerMetadata
from cosmos_curate_tpu.data.model import FrameExtractionSignature, SplitPipeTask
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.decode import extract_frames_multi

logger = get_logger(__name__)


class ClipFrameExtractionStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        signatures: tuple[FrameExtractionSignature, ...] = (FrameExtractionSignature("fps", 2.0),),
        resize_hw: tuple[int, int] | None = None,
        num_cpus: float = 3.0,
    ) -> None:
        self.signatures = signatures
        self.resize_hw = resize_hw
        self.num_cpus = num_cpus
        # created in setup (a live executor must never ride a stage pickle
        # into an engine worker); process_data degrades to serial without it
        self._pool: ThreadPoolExecutor | None = None

    @property
    def resources(self) -> Resources:
        return Resources(cpus=self.num_cpus)

    @property
    def thread_safe(self) -> bool:
        # per-clip decode state is call-local; the executor is shared and
        # itself thread-safe, so concurrent batches interleave fine
        return True

    def setup(self, worker: WorkerMetadata) -> None:
        super().setup(worker)
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(self.num_cpus)),
            thread_name_prefix="frame-extract",
        )

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        clips = [
            clip
            for task in tasks
            for clip in task.video.clips
            if clip.encoded_data is not None
        ]
        pool = self._pool
        if pool is None or len(clips) <= 1:
            for clip in clips:
                self._extract_clip(clip)
        else:
            # list() propagates the first worker exception, if any
            list(pool.map(self._extract_clip, clips))
        return tasks

    def _extract_clip(self, clip) -> None:
        try:
            by_key = extract_frames_multi(
                clip.encoded_data, self.signatures, resize_hw=self.resize_hw
            )
        except Exception as e:
            logger.warning("frame extraction failed for %s: %s", clip.uuid, e)
            for sig in self.signatures:
                clip.errors[f"frames-{sig.key()}"] = str(e)
            return
        for sig in self.signatures:
            frames = by_key[sig.key()]
            if frames.size == 0:
                clip.errors[f"frames-{sig.key()}"] = "no frames decoded"
            else:
                clip.extracted_frames[sig.key()] = frames

    def destroy(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
