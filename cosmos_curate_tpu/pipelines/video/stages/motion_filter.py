"""Motion filter stage: drop (or score) near-static clips.

Equivalent capability of the reference's motion filtering
(cosmos_curate/pipelines/video/filtering/motion/motion_filter_stages.py:40,
motion_vector_backend.py — codec motion vectors → global-mean and
per-patch-min scores). cv2 exposes no codec motion vectors, so the TPU-first
replacement computes the same two statistics from low-fps frame differences
**on device in one jit**: normalized mean |Δframe| globally, and the minimum
over 8×8 spatial patches (catches clips where only a corner moves). Same
semantics (score-only vs filter; two thresholds), different estimator.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.models.batching import pad_batch
from cosmos_curate_tpu.data.model import SplitPipeTask
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.decode import extract_frames_at_fps

logger = get_logger(__name__)

_PATCH_GRID = 8


@jax.jit
def _motion_scores(frames_u8, n_valid):
    """[T_pad, H, W, 3] uint8 (first n_valid real) -> (global, patch_min).

    T is padded to a power of two by the caller so XLA compiles O(log T)
    programs instead of one per distinct clip length (the same shape
    discipline as models/batching.py); padded diffs are masked out.
    """
    x = frames_u8.astype(jnp.float32) / 255.0
    gray = x.mean(axis=-1)
    diff = jnp.abs(gray[1:] - gray[:-1])  # [T_pad-1, H, W]
    t, h, w = diff.shape
    valid = (jnp.arange(t) < (n_valid - 1)).astype(jnp.float32)  # [T_pad-1]
    n = jnp.maximum(n_valid - 1, 1).astype(jnp.float32)
    global_score = (diff.mean(axis=(1, 2)) * valid).sum() / n
    ph, pw = h // _PATCH_GRID, w // _PATCH_GRID
    patches = diff[:, : ph * _PATCH_GRID, : pw * _PATCH_GRID].reshape(
        t, _PATCH_GRID, ph, _PATCH_GRID, pw
    )
    per_patch = (patches.mean(axis=(2, 4)) * valid[:, None, None]).sum(axis=0) / n
    return global_score, per_patch.min()


class MotionFilterStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        score_only: bool = False,
        # Calibrated for THIS estimator on synthetic static/panning/jittery
        # fixtures through a real encode-decode roundtrip
        # (benchmarks/motion_calibration.py): static clips score exactly 0
        # (codecs skip-block static content), the weakest real motion ~0.06;
        # 0.004 sits an order of magnitude below real motion and still
        # catches small-area motion (a 40x40 box on 240x320 scores ~0.01).
        # The reference's 0.00098 default is on its motion-vector scale and
        # does NOT transfer (motion_filter_stages.py:40).
        global_threshold: float = 0.004,
        # The reference's 1e-6 default is tuned for codec motion vectors;
        # our frame-diff estimator yields exact-zero patches on smooth
        # encodes, so the patch criterion defaults OFF (0.0) and is opt-in.
        per_patch_threshold: float = 0.0,
        sample_fps: float = 4.0,
        decode_resize_hw: tuple[int, int] = (128, 128),
    ) -> None:
        self.score_only = score_only
        self.global_threshold = global_threshold
        self.per_patch_threshold = per_patch_threshold
        self.sample_fps = sample_fps
        self.decode_resize_hw = decode_resize_hw

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=0.5 if not self.score_only else 0.25)

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        for task in tasks:
            video = task.video
            kept = []
            for clip in video.clips:
                if clip.encoded_data is None:
                    kept.append(clip)
                    continue
                try:
                    frames = extract_frames_at_fps(
                        clip.encoded_data, target_fps=self.sample_fps, resize_hw=self.decode_resize_hw
                    )
                    if frames.shape[0] < 2:
                        kept.append(clip)
                        continue
                    padded, n = pad_batch(frames)
                    g, p = _motion_scores(padded, n)
                    clip.motion_score_global = float(g)
                    clip.motion_score_per_patch_min = float(p)
                except Exception as e:
                    logger.warning("motion scoring failed for %s: %s", clip.uuid, e)
                    clip.errors["motion"] = str(e)
                    kept.append(clip)
                    continue
                if self.score_only or (
                    clip.motion_score_global >= self.global_threshold
                    and clip.motion_score_per_patch_min >= self.per_patch_threshold
                ):
                    kept.append(clip)
                else:
                    clip.filtered_by = "motion"
                    video.filtered_clips.append(clip)
            video.clips = kept
        return tasks
