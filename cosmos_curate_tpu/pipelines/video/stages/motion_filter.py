"""Motion filter stage: drop (or score) near-static clips.

Equivalent capability of the reference's motion filtering
(cosmos_curate/pipelines/video/filtering/motion/motion_filter_stages.py:40,
motion_vector_backend.py — codec motion vectors → global-mean and
per-patch-min scores). Two estimators behind one stage:

- ``mv`` — REAL codec motion vectors via the native libavcodec binding
  (video/motion_vectors.py, the same ``export_mvs`` mechanism the
  reference's backend rides): per-frame mean |mv|/height globally and the
  per-patch time-mean minimum. Directly comparable semantics — including
  the shared caveat that intra-coded moving content carries no vectors.
- ``frame-diff`` — the TPU-first replacement: the same two statistics from
  low-fps frame differences on device in one jit.

``backend="auto"`` (default) scores with motion vectors when the native
binding and the clip's codec deliver them, frame-diff otherwise. The two
estimators have DIFFERENT score scales, so each carries its own calibrated
thresholds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.models.batching import pad_batch
from cosmos_curate_tpu.data.model import SplitPipeTask
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.decode import extract_frames_at_fps

logger = get_logger(__name__)

_PATCH_GRID = 8


@jax.jit
def _motion_scores(frames_u8, n_valid):
    """[T_pad, H, W, 3] uint8 (first n_valid real) -> (global, patch_min).

    T is padded to a power of two by the caller so XLA compiles O(log T)
    programs instead of one per distinct clip length (the same shape
    discipline as models/batching.py); padded diffs are masked out.
    """
    x = frames_u8.astype(jnp.float32) / 255.0
    gray = x.mean(axis=-1)
    diff = jnp.abs(gray[1:] - gray[:-1])  # [T_pad-1, H, W]
    t, h, w = diff.shape
    valid = (jnp.arange(t) < (n_valid - 1)).astype(jnp.float32)  # [T_pad-1]
    n = jnp.maximum(n_valid - 1, 1).astype(jnp.float32)
    global_score = (diff.mean(axis=(1, 2)) * valid).sum() / n
    ph, pw = h // _PATCH_GRID, w // _PATCH_GRID
    patches = diff[:, : ph * _PATCH_GRID, : pw * _PATCH_GRID].reshape(
        t, _PATCH_GRID, ph, _PATCH_GRID, pw
    )
    per_patch = (patches.mean(axis=(2, 4)) * valid[:, None, None]).sum(axis=0) / n
    return global_score, per_patch.min()


class MotionFilterStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        score_only: bool = False,
        # Calibrated for THIS estimator on synthetic static/panning/jittery
        # fixtures through a real encode-decode roundtrip
        # (benchmarks/motion_calibration.py): static clips score exactly 0
        # (codecs skip-block static content), the weakest real motion ~0.06;
        # 0.004 sits an order of magnitude below real motion and still
        # catches small-area motion (a 40x40 box on 240x320 scores ~0.01).
        # The reference's 0.00098 default is on its motion-vector scale and
        # does NOT transfer (motion_filter_stages.py:40).
        global_threshold: float = 0.004,
        # The reference's 1e-6 default is tuned for codec motion vectors;
        # our frame-diff estimator yields exact-zero patches on smooth
        # encodes, so the patch criterion defaults OFF (0.0) and is opt-in.
        per_patch_threshold: float = 0.0,
        sample_fps: float = 4.0,
        decode_resize_hw: tuple[int, int] = (128, 128),
        # mv | frame-diff | auto (mv with frame-diff fallback)
        backend: str = "auto",
        # MV-scale thresholds (mean |mv| per frame / frame height): static
        # encodes score exactly 0 (skip blocks carry no vectors); a 1 px/
        # frame pan at ANY resolution scores 1/height (~0.01 at 96 px).
        # 0.001 = a tenth of that — an order of magnitude above zero while
        # still keeping slow motion (benchmarks/motion_calibration.py --mv).
        mv_global_threshold: float = 0.001,
        mv_patch_threshold: float = 0.0,
    ) -> None:
        if backend not in ("auto", "mv", "frame-diff"):
            raise ValueError(f"unknown motion backend {backend!r}")
        self.score_only = score_only
        self.global_threshold = global_threshold
        self.per_patch_threshold = per_patch_threshold
        self.sample_fps = sample_fps
        self.decode_resize_hw = decode_resize_hw
        self.backend = backend
        self.mv_global_threshold = mv_global_threshold
        self.mv_patch_threshold = mv_patch_threshold
        self._pipe = None  # DevicePipeline, created lazily in the worker

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=0.5 if not self.score_only else 0.25)

    def _score_mv(self, clip) -> tuple[float, float] | None:
        """Codec-MV scores, or None when the binding/codec yields none."""
        from cosmos_curate_tpu.video.motion_vectors import (
            extract_mv_field,
            mv_motion_scores,
        )

        mv = extract_mv_field(clip.encoded_data)
        if mv is None:
            return None
        return mv_motion_scores(mv)

    def _submit_frame_diff(self, tracker, clip) -> None:
        """Decode + dispatch one clip's frame-diff scoring; result resolves
        at the tracker drain. No-op when there is nothing to score (fewer
        than two frames)."""
        frames = extract_frames_at_fps(
            clip.encoded_data, target_fps=self.sample_fps, resize_hw=self.decode_resize_hw
        )
        if frames.shape[0] < 2:
            return
        padded, n = pad_batch(frames)
        # scalar outputs: no n_valid trim; decode of the NEXT clip overlaps
        # this clip's device compute (the whole point of deferring readback)
        tracker.submit(clip, padded, n)

    def _pipeline(self):
        if getattr(self, "_pipe", None) is None:
            from cosmos_curate_tpu.models.device_pipeline import DevicePipeline

            self._pipe = DevicePipeline("motion-filter", _motion_scores)
        return self._pipe

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        # Phase 1 — score: MV scores resolve synchronously (CPU); frame-diff
        # scores are dispatched through the DevicePipeline as clips decode,
        # then drained once, so per-clip decode and device compute overlap
        # instead of ping-ponging.
        tracker = self._pipeline().track()
        decisions: dict[int, tuple[tuple[float, float] | None, tuple[float, float]]] = {}
        for task in tasks:
            for clip in task.video.clips:
                if clip.encoded_data is None:
                    continue
                thresholds = (self.mv_global_threshold, self.mv_patch_threshold)
                try:
                    scores = None
                    if self.backend in ("auto", "mv"):
                        try:
                            scores = self._score_mv(clip)
                        except Exception as e:
                            # in auto mode ANY MV-path failure (not just "no
                            # vectors") falls through to frame-diff
                            if self.backend == "mv":
                                raise
                            logger.warning(
                                "MV scoring failed for %s (%s); frame-diff", clip.uuid, e
                            )
                    if scores is None and self.backend != "mv":
                        # thresholds must match the estimator that scored
                        thresholds = (self.global_threshold, self.per_patch_threshold)
                        self._submit_frame_diff(tracker, clip)
                    decisions[id(clip)] = (scores, thresholds)
                except Exception as e:
                    logger.warning("motion scoring failed for %s: %s", clip.uuid, e)
                    clip.errors["motion"] = str(e)
                    for lost in tracker.lost_to_abort():
                        # the pipeline aborted: in-flight scores are gone;
                        # error those clips rather than misalign survivors
                        lost.errors["motion"] = f"in-flight score lost to abort: {e}"
        if len(tracker):
            try:
                for clip, (g, p) in tracker.drain():
                    scores, thresholds = decisions[id(clip)]
                    decisions[id(clip)] = ((float(g), float(p)), thresholds)
            except Exception as e:
                logger.warning("motion scoring drain failed: %s", e)
                for clip in tracker.lost_to_abort():
                    clip.errors["motion"] = str(e)
                    decisions.pop(id(clip), None)
        # Phase 2 — filter: apply thresholds in original clip order.
        for task in tasks:
            video = task.video
            kept = []
            for clip in video.clips:
                entry = decisions.get(id(clip))
                if entry is None or entry[0] is None:
                    kept.append(clip)  # nothing scoreable (or errored): keep
                    continue
                (g, p), thresholds = entry
                clip.motion_score_global, clip.motion_score_per_patch_min = g, p
                if self.score_only or (g >= thresholds[0] and p >= thresholds[1]):
                    kept.append(clip)
                else:
                    clip.filtered_by = "motion"
                    video.filtered_clips.append(clip)
            video.clips = kept
        return tasks
