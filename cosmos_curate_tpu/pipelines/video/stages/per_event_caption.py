"""Per-event captioning: describe each tracked object, not the whole clip.

Equivalent capability of the reference's ``PerEventCaptionStage``
(cosmos_curate/pipelines/video/captioning/per_event_caption_stage.py:156 —
VLM captioning over SAM3 tracking outputs). Consumes ``Clip.tracks`` from
the tracking stage: the tracked region is cropped (with margin) across
sampled frames and captioned through the shared engine; results land in
``Clip.event_captions`` parallel to ``tracks``.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import SplitPipeTask
from cosmos_curate_tpu.models.vlm import CaptionRequest, SamplingConfig, VLM_BASE, VLMConfig
from cosmos_curate_tpu.pipelines.video.stages.captioning import _CaptionVLM
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.decode import decode_frames

logger = get_logger(__name__)

EVENT_PROMPT = "Describe the object in this video and what it is doing."


def crop_track(
    frames: np.ndarray,
    track: list[dict],
    *,
    num_frames: int = 4,
    margin: float = 0.5,
    out_size: int = 224,
) -> np.ndarray:
    """Crop the tracked box (with margin) at uniformly sampled track points,
    resized to a FIXED ``out_size`` on host — variable crop shapes would
    recompile the jitted vision encoder once per distinct box size."""
    import cv2

    t, h, w = frames.shape[:3]
    idx = np.linspace(0, len(track) - 1, num_frames).round().astype(int)
    bw = max(p["w"] for p in track)
    bh = max(p["h"] for p in track)
    cw = max(8, min(w, int(bw * (1 + 2 * margin))))
    ch = max(8, min(h, int(bh * (1 + 2 * margin))))
    out = np.zeros((num_frames, out_size, out_size, 3), np.uint8)
    for n, i in enumerate(idx):
        p = track[i]
        fi = min(int(p["frame"]), t - 1)
        cx, cy = p["x"] + p["w"] / 2, p["y"] + p["h"] / 2
        x0 = int(np.clip(cx - cw / 2, 0, w - cw))
        y0 = int(np.clip(cy - ch / 2, 0, h - ch))
        crop = frames[fi, y0 : y0 + ch, x0 : x0 + cw]
        out[n] = cv2.resize(crop, (out_size, out_size), interpolation=cv2.INTER_AREA)
    return out


class PerEventCaptionStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        cfg: VLMConfig | None = None,
        max_batch: int = 8,
        max_new_tokens: int = 64,
        frames_per_event: int = 4,
        model_flavor: str | None = None,
    ) -> None:
        from cosmos_curate_tpu.pipelines.video.stages.captioning import (
            _owner_tag,
            resolve_caption_model,
        )

        self.owner = _owner_tag("per-event-caption")
        self._model = resolve_caption_model(cfg, model_flavor, max_batch)
        self.max_new_tokens = max_new_tokens
        self.frames_per_event = frames_per_event

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, entire_tpu_host=True)

    @property
    def batch_size(self) -> int:
        # deep batches keep the engine's continuous batch full across
        # clips; the shared event-prompt prefix then hits the engine's
        # prefix KV cache on every request after the first
        return 16

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        engine = self._model.engine
        assert engine is not None, "setup() not called"
        targets: dict[str, tuple] = {}
        for task in tasks:
            for clip in task.video.clips:
                if not clip.tracks or clip.encoded_data is None:
                    continue
                try:
                    frames = decode_frames(clip.encoded_data)
                except Exception as e:
                    clip.errors["per_event_caption"] = str(e)
                    continue
                if frames.shape[0] == 0:
                    continue
                # parallel-array contract: same length as tracks even when
                # some requests fail
                clip.event_captions = [""] * len(clip.tracks)
                for k, track in enumerate(clip.tracks):
                    rid = f"{clip.uuid}-ev{k}"
                    crops = crop_track(
                        frames,
                        track,
                        num_frames=self.frames_per_event,
                        out_size=self._model.cfg.vision.image_size,
                    )
                    targets[rid] = (clip, k)
                    pre, ids = self._model.encode_prompt(EVENT_PROMPT, has_vision=True)
                    engine.add_request(
                        CaptionRequest(
                            request_id=rid,
                            prefix_ids=pre,
                            prompt_ids=ids,
                            frames=crops,
                            sampling=SamplingConfig(max_new_tokens=self.max_new_tokens),
                            owner=self.owner,
                        )
                    )
        if not targets:
            return tasks
        for res in engine.run_until_complete(owner=self.owner):
            hit = targets.get(res.request_id)
            if hit is None:
                continue
            clip, k = hit
            clip.event_captions[k] = res.text
        return tasks
