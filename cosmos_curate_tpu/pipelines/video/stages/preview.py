"""Preview stage: webp thumbnails per clip.

Equivalent capability of the reference's ``PreviewStage``
(cosmos_curate/pipelines/video/preview/preview_stages.py:32 — webp preview
per caption window). Animated webp from the extracted frames via PIL.
"""

from __future__ import annotations

import io

import numpy as np

from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import FrameExtractionSignature, SplitPipeTask
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class PreviewStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        max_frames: int = 8,
        target_width: int = 320,
        fps: int = 4,
        extraction: FrameExtractionSignature = FrameExtractionSignature("fps", 2.0),
    ) -> None:
        self.max_frames = max_frames
        self.target_width = target_width
        self.fps = fps
        self.extraction = extraction

    @property
    def resources(self) -> Resources:
        return Resources(cpus=0.5)

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        from PIL import Image

        key = self.extraction.key()
        for task in tasks:
            for clip in task.video.clips:
                frames = clip.extracted_frames.get(key)
                if frames is None or frames.shape[0] == 0:
                    continue
                idx = np.linspace(0, frames.shape[0] - 1, min(self.max_frames, frames.shape[0]))
                images = []
                for i in idx.round().astype(int):
                    img = Image.fromarray(frames[i])
                    if img.width > self.target_width:
                        h = int(img.height * self.target_width / img.width)
                        img = img.resize((self.target_width, h))
                    images.append(img)
                buf = io.BytesIO()
                try:
                    images[0].save(
                        buf,
                        format="WEBP",
                        save_all=len(images) > 1,
                        append_images=images[1:],
                        duration=int(1000 / self.fps),
                        loop=0,
                    )
                    clip.webp_preview = buf.getvalue()
                except Exception as e:
                    logger.warning("preview failed for %s: %s", clip.uuid, e)
                    clip.errors["preview"] = str(e)
        return tasks
