"""Artificial/overlay-text filter stage.

Equivalent capability of the reference's artificial-text filter
(cosmos_curate/pipelines/video/filtering/aesthetics/
artificial_text_filter_stage.py:37 + models/paddle_ocr.py:317-554 —
PaddleOCR overlay-text detection with corner heuristics). PaddleOCR has no
TPU build; the detector here is a device-side *text-likeness* score computed
in one jit: overlay text produces dense horizontal high-contrast strokes
that persist across frames, so we measure temporal-stable horizontal
gradient energy in the frame's border bands (title/subtitle/watermark
regions). A full OCR model can be plugged through the same stage interface.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import FrameExtractionSignature, SplitPipeTask
from cosmos_curate_tpu.models.batching import pad_batch
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_BAND = 0.2  # border band fraction inspected for overlay text


@jax.jit
def _text_likeness(frames_u8, n_valid):
    """uint8 [T_pad, H, W, 3] -> scalar in [0, 1]-ish: temporal-stable
    horizontal-stroke energy in top/bottom bands."""
    x = frames_u8.astype(jnp.float32).mean(axis=-1) / 255.0  # [T, H, W]
    t, h, w = x.shape
    valid = (jnp.arange(t) < n_valid)[:, None, None].astype(jnp.float32)
    # temporal median ~ static overlay; approximate with masked mean
    static = (x * valid).sum(axis=0) / jnp.maximum(n_valid, 1)
    gx = jnp.abs(static[:, 1:] - static[:, :-1])  # horizontal gradients
    band = max(1, int(h * _BAND))
    bands = jnp.concatenate([gx[:band], gx[-band:]], axis=0)
    # dense strokes: fraction of strong-gradient columns in the bands
    strong = (bands > 0.15).astype(jnp.float32)
    return strong.mean() * 10.0  # scaled so typical overlays land near ~1


class ArtificialTextFilterStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        threshold: float = 0.5,
        score_only: bool = False,
        extraction: FrameExtractionSignature = FrameExtractionSignature("fps", 2.0),
    ) -> None:
        self.threshold = threshold
        self.score_only = score_only
        self.extraction = extraction

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=0.25)

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        key = self.extraction.key()
        for task in tasks:
            kept = []
            for clip in task.video.clips:
                frames = clip.extracted_frames.get(key)
                if frames is None or frames.shape[0] == 0:
                    kept.append(clip)
                    continue
                try:
                    padded, n = pad_batch(frames)
                    clip.artificial_text_score = float(_text_likeness(padded, n))
                except Exception as e:
                    logger.warning("text scoring failed for %s: %s", clip.uuid, e)
                    clip.errors["artificial_text"] = str(e)
                    kept.append(clip)
                    continue
                if self.score_only or clip.artificial_text_score < self.threshold:
                    kept.append(clip)
                else:
                    clip.filtered_by = "text"
                    task.video.filtered_clips.append(clip)
            task.video.clips = kept
        return tasks
