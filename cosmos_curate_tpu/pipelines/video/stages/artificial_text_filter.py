"""Artificial/overlay-text filter stage.

Equivalent capability of the reference's artificial-text filter
(cosmos_curate/pipelines/video/filtering/aesthetics/
artificial_text_filter_stage.py:37 + models/paddle_ocr.py:317-554 —
PaddleOCR overlay-text detection with corner heuristics). Two detectors
behind one stage:

- **learned** (default when the ``ocr-detector-tpu`` checkpoint is staged):
  the Flax FCN text detector from models/ocr.py — score is the max fraction
  of frame area covered by detected text regions, the same box-area signal
  the reference derives from PaddleOCR boxes.
- **heuristic** (fallback, and ``mode="heuristic"``): a device-side
  text-likeness score in one jit — temporal-stable horizontal-stroke energy
  in the frame's border bands (title/subtitle/watermark regions).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import FrameExtractionSignature, SplitPipeTask
from cosmos_curate_tpu.models.batching import pad_batch
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_BAND = 0.2  # border band fraction inspected for overlay text


@jax.jit
def _text_likeness(frames_u8, n_valid):
    """uint8 [T_pad, H, W, 3] -> scalar in [0, 1]-ish: temporal-stable
    horizontal-stroke energy in top/bottom bands."""
    x = frames_u8.astype(jnp.float32).mean(axis=-1) / 255.0  # [T, H, W]
    t, h, w = x.shape
    valid = (jnp.arange(t) < n_valid)[:, None, None].astype(jnp.float32)
    # temporal median ~ static overlay; approximate with masked mean
    static = (x * valid).sum(axis=0) / jnp.maximum(n_valid, 1)
    gx = jnp.abs(static[:, 1:] - static[:, :-1])  # horizontal gradients
    band = max(1, int(h * _BAND))
    bands = jnp.concatenate([gx[:band], gx[-band:]], axis=0)
    # dense strokes: fraction of strong-gradient columns in the bands
    strong = (bands > 0.15).astype(jnp.float32)
    return strong.mean() * 10.0  # scaled so typical overlays land near ~1


class ArtificialTextFilterStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        threshold: float = 0.5,
        score_only: bool = False,
        extraction: FrameExtractionSignature = FrameExtractionSignature("fps", 2.0),
        mode: str = "auto",  # auto | learned | heuristic
        learned_threshold: float = 0.02,  # text-area fraction that flags a clip
    ) -> None:
        if mode not in ("auto", "learned", "heuristic"):
            raise ValueError(f"unknown text-filter mode {mode!r}")
        self.threshold = threshold
        self.score_only = score_only
        self.extraction = extraction
        self.mode = mode
        self.learned_threshold = learned_threshold
        self._ocr = None
        self._pipe = None  # DevicePipeline for the heuristic jit, per worker

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=0.25)

    def setup(self, worker=None) -> None:
        if self.mode == "heuristic":
            return
        from cosmos_curate_tpu.models import registry

        if self.mode == "learned" or registry.find_checkpoint("ocr-detector-tpu"):
            from cosmos_curate_tpu.models.ocr import OcrModel

            ocr = OcrModel()
            try:
                # random-init logits would fail OPEN (≈half the heatmap over
                # threshold -> every clip filtered); never accept fallback
                ocr.setup(require_weights=True)
            except RuntimeError as e:
                if self.mode == "learned":
                    raise
                logger.warning(
                    "text filter: learned detector unavailable (%s); using heuristic", e
                )
                return
            self._ocr = ocr
        # auto with no staged checkpoint: stay on the heuristic path

    def _pipeline(self):
        if self._pipe is None:
            from cosmos_curate_tpu.models.device_pipeline import DevicePipeline

            self._pipe = DevicePipeline("text-filter", _text_likeness)
        return self._pipe

    def _score_learned(self, frames) -> tuple[float, float]:
        """Learned-detector score (synchronous; OcrModel owns its jits)."""
        # fixed 4-frame sample: one batch shape -> one XLA compile
        idx = np.linspace(0, len(frames) - 1, 4).astype(int)
        return self._ocr.text_coverage(frames[idx]), self.learned_threshold

    def _score(self, frames) -> tuple[float, float]:
        """Synchronous single-clip (score, threshold) under the active
        detector — the submit-everything path in process_data is the hot
        loop; this is for tests and ad-hoc callers."""
        if self._ocr is not None:
            return self._score_learned(frames)
        padded, n = pad_batch(frames)
        self._pipeline().submit(padded, n)
        return float(self._pipeline().drain()[-1]), self.threshold

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        key = self.extraction.key()
        # Phase 1 — dispatch every heuristic score through the
        # DevicePipeline before reading any back (learned OCR scores stay
        # synchronous); one drain resolves them in submission order.
        scores: dict[int, float] = {}
        thresholds: dict[int, float] = {}
        tracker = self._pipeline().track()
        for task in tasks:
            for clip in task.video.clips:
                frames = clip.extracted_frames.get(key)
                if frames is None or frames.shape[0] == 0:
                    continue
                try:
                    if self._ocr is not None:
                        scores[id(clip)], thresholds[id(clip)] = self._score_learned(frames)
                    else:
                        padded, n = pad_batch(frames)
                        tracker.submit(clip, padded, n)
                        thresholds[id(clip)] = self.threshold
                except Exception as e:
                    logger.warning("text scoring failed for %s: %s", clip.uuid, e)
                    clip.errors["artificial_text"] = str(e)
                    for lost in tracker.lost_to_abort():
                        # pipeline aborted: in-flight scores are gone; error
                        # those clips rather than misalign the drain zip
                        lost.errors["artificial_text"] = f"in-flight score lost to abort: {e}"
        if len(tracker):
            try:
                for clip, score in tracker.drain():
                    scores[id(clip)] = float(score)
            except Exception as e:
                logger.warning("text scoring drain failed: %s", e)
                for clip in tracker.lost_to_abort():
                    clip.errors["artificial_text"] = str(e)
        # Phase 2 — threshold in original clip order.
        for task in tasks:
            kept = []
            for clip in task.video.clips:
                if id(clip) not in scores:
                    kept.append(clip)  # unscoreable or errored: keep
                    continue
                clip.artificial_text_score = scores[id(clip)]
                if self.score_only or clip.artificial_text_score < thresholds[id(clip)]:
                    kept.append(clip)
                else:
                    clip.filtered_by = "text"
                    task.video.filtered_clips.append(clip)
            task.video.clips = kept
        return tasks
