"""Shot-detection clip-extraction stage (TransNetV2 on TPU).

Equivalent capability of the reference's ``TransNetV2ClipExtractionStage``
(cosmos_curate/pipelines/video/clipping/transnetv2_extraction_stages.py:39):
decode frames, run the shot detector, convert per-frame transition
probabilities into filtered/cropped scene spans, emit Clips.
"""

from __future__ import annotations

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import SplitPipeTask
from cosmos_curate_tpu.models.transnetv2 import TransNetV2TPU
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.decode import decode_frames
from cosmos_curate_tpu.video.splitter import make_clips, scene_spans_from_predictions

logger = get_logger(__name__)


class TransNetV2ClipExtractionStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        threshold: float = 0.4,
        min_clip_len_s: float = 2.0,
        max_clip_len_s: float = 60.0,
        crop_s: float = 0.0,
        decode_resize_hw: tuple[int, int] = (27, 48),
        model: TransNetV2TPU | None = None,
    ) -> None:
        self.threshold = threshold
        self.min_clip_len_s = min_clip_len_s
        self.max_clip_len_s = max_clip_len_s
        self.crop_s = crop_s
        self.decode_resize_hw = decode_resize_hw
        self._model = model if model is not None else TransNetV2TPU()

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=1.0)

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        for task in tasks:
            video = task.video
            if video.errors:
                continue
            src = video.raw_bytes if video.raw_bytes is not None else video.path
            try:
                # decode directly at the model's input resolution
                frames = decode_frames(src, resize_hw=self.decode_resize_hw)
                if frames.shape[0] == 0:
                    video.errors["shot_detection"] = "no frames decoded"
                    continue
                probs = self._model.predict_transitions(frames)
                # exact per-frame PTS (mp4 sample tables) keeps spans
                # correct on VFR sources; None falls back to fps mapping
                from cosmos_curate_tpu.video.decode import get_frame_timestamps

                ts = get_frame_timestamps(src)
                spans = scene_spans_from_predictions(
                    probs,
                    fps=video.metadata.fps,
                    threshold=self.threshold,
                    min_scene_len_s=self.min_clip_len_s,
                    max_scene_len_s=self.max_clip_len_s,
                    crop_s=self.crop_s,
                    timestamps_s=ts if len(ts) == len(probs) else None,
                )
                video.clips = make_clips(video.path, spans)
                video.num_total_clips = len(video.clips)
            except Exception as e:
                logger.warning("shot detection failed for %s: %s", video.path, e)
                video.errors["shot_detection"] = str(e)
        return tasks
