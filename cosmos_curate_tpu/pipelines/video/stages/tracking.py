"""Object-tracking stage: prompt boxes → per-frame tracks (+ annotated mp4).

Equivalent capability of the reference's tracking stages
(cosmos_curate/pipelines/video/tracking/tracking_builders.py:40,
sam3_bbox_stage.py:292 — promptable tracking over clips, bbox/instances
metadata, annotated mp4 output). Prompts come either from the caller
(explicit boxes) or an automatic motion-based proposal (highest-motion
region of the first frames); per-event captioning can consume the tracks
exactly as the reference's PerEventCaptionStage does.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import SplitPipeTask
from cosmos_curate_tpu.models.tracker import TemplateTracker, TrackerConfig
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.decode import decode_frames
from cosmos_curate_tpu.video.encode import encode_frames

logger = get_logger(__name__)


def propose_motion_box(
    frames: np.ndarray, box_size_frac: float = 0.25, *, work: int = 128
) -> tuple[float, float, float, float]:
    """Auto-prompt: the region with the most inter-frame motion.

    Operates on a downsampled copy — a full-resolution float32 of a long 4K
    clip would be a multi-GB transient."""
    import cv2

    t, h, w = frames.shape[:3]
    stride = max(1, t // 32)  # ≤ ~32 sampled frames suffice for a motion map
    small = np.stack(
        [
            cv2.resize(f, (work, work), interpolation=cv2.INTER_AREA)
            for f in frames[::stride]
        ]
    )
    gray = small.astype(np.float32).mean(axis=-1)
    diff = np.abs(np.diff(gray, axis=0)).mean(axis=0)  # [work, work]
    bh = bw = max(8, int(work * box_size_frac))
    ii = np.pad(diff, ((1, 0), (1, 0))).cumsum(0).cumsum(1)
    sums = ii[bh:, bw:] - ii[:-bh, bw:] - ii[bh:, :-bw] + ii[:-bh, :-bw]
    iy, ix = np.unravel_index(np.argmax(sums), sums.shape)
    # back to original coordinates
    return (
        float(ix) * w / work,
        float(iy) * h / work,
        float(bw) * w / work,
        float(bh) * h / work,
    )


class TrackingStage(Stage[SplitPipeTask, SplitPipeTask]):
    def __init__(
        self,
        *,
        cfg: TrackerConfig = TrackerConfig(),
        write_annotated: bool = False,
        min_score: float = 0.0,
        mode: str = "auto",  # auto | learned | ncc
        siamese_cfg=None,
        learned_min_score: float = 0.0,
    ) -> None:
        """``min_score`` drops tracks whose mean correlation score (ts²-
        normalized NCC; ~[0.2, 1.2] for solid locks, near 0 for noise)
        falls below it. ``mode`` selects the tracker: the learned siamese
        model (when its checkpoint is staged), the NCC baseline, or auto.
        Siamese scores live on their own (learned-weight) scale, so the
        learned tracker uses ``learned_min_score`` + ``siamese_cfg``, never
        the NCC-calibrated knobs."""
        if mode not in ("auto", "learned", "ncc"):
            raise ValueError(f"unknown tracking mode {mode!r}")
        self.mode = mode
        self._tracker = TemplateTracker(cfg)
        self.write_annotated = write_annotated
        self.min_score = min_score
        self.learned_min_score = learned_min_score
        self._siamese_cfg = siamese_cfg

    def setup(self, worker=None) -> None:
        if self.mode == "ncc":
            return
        from cosmos_curate_tpu.models import registry

        if self.mode == "learned" or registry.find_checkpoint("tracker-siamese-tpu"):
            from cosmos_curate_tpu.models.tracker_learned import SiameseConfig, SiameseTracker

            tracker = SiameseTracker(self._siamese_cfg or SiameseConfig())
            try:
                tracker.setup(require_weights=True)
            except RuntimeError as e:
                if self.mode == "learned":
                    raise
                logger.warning(
                    "tracking: learned tracker unavailable (%s); using NCC baseline", e
                )
                return
            self._tracker = tracker
            self.min_score = self.learned_min_score

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=0.5)

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        import cv2

        for task in tasks:
            for clip in task.video.clips:
                if clip.encoded_data is None:
                    continue
                try:
                    frames = decode_frames(clip.encoded_data)
                    if frames.shape[0] < 2:
                        continue
                    box0 = propose_motion_box(frames)
                    boxes, scores = self._tracker.track(frames, box0)
                    if float(scores.mean()) < self.min_score:
                        # low-confidence track (e.g. static clip where the
                        # motion proposal locked onto noise): don't emit
                        continue
                    track = [
                        {"frame": i, "x": float(b[0]), "y": float(b[1]),
                         "w": float(b[2]), "h": float(b[3]), "score": float(s)}
                        for i, (b, s) in enumerate(zip(boxes, scores))
                    ]
                    clip.tracks.append(track)
                    if self.write_annotated:
                        ann = frames.copy()
                        for i, b in enumerate(boxes):
                            x, y, w, h = (int(v) for v in b)
                            cv2.rectangle(ann[i], (x, y), (x + w, y + h), (255, 64, 64), 2)
                        from cosmos_curate_tpu.video.decode import extract_video_metadata

                        meta = extract_video_metadata(clip.encoded_data)
                        clip.annotated_mp4 = encode_frames(ann, fps=meta.fps or 24.0)
                except Exception as e:
                    logger.warning("tracking failed for %s: %s", clip.uuid, e)
                    clip.errors["tracking"] = str(e)
        return tasks
