"""Captioning stages: CPU prep + TPU engine stage.

Equivalent capability of the reference's captioning path
(cosmos_curate/pipelines/video/captioning/vllm_caption_stage.py:244/413 —
``VllmPrepStage`` windows + model inputs on CPU, ``VllmCaptionStage`` runs
the engine with in-flight batching and two-stage refinement). Same deliberate
CPU/device split here: the prep stage computes caption windows
(windowing_utils ``compute_windows`` semantics) and samples window frames;
the caption stage owns one ``CaptionEngine`` (the chip owner's in-process
pool) and streams every window of every clip through continuous batching.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import FrameExtractionSignature, SplitPipeTask, Window
from cosmos_curate_tpu.models import registry
from cosmos_curate_tpu.models.prompts import REFINEMENT_PROMPT, get_caption_prompt
from cosmos_curate_tpu.models.tokenizer import default_caption_tokenizer
from cosmos_curate_tpu.models.vlm import (
    CaptionEngine,
    CaptionRequest,
    SamplingConfig,
    VLM_BASE,
    VLMConfig,
)
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.windowing import compute_windows

logger = get_logger(__name__)


class CaptionPrepStage(Stage[SplitPipeTask, SplitPipeTask]):
    """CPU prep: cut clips into caption windows and attach window frames."""

    def __init__(
        self,
        *,
        window_len: int = 256,
        remainder_threshold: int = 128,
        frames_per_window: int = 8,
        extraction: FrameExtractionSignature = FrameExtractionSignature("fps", 2.0),
    ) -> None:
        self.window_len = window_len
        self.remainder_threshold = remainder_threshold
        self.frames_per_window = frames_per_window
        self.extraction = extraction

    @property
    def resources(self) -> Resources:
        return Resources(cpus=3.0)

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        key = self.extraction.key()
        for task in tasks:
            for clip in task.video.clips:
                frames = clip.extracted_frames.get(key)
                if frames is None or frames.shape[0] == 0:
                    continue
                # windows are defined over source frames; map to extracted
                # frame indices proportionally
                src_frames = max(
                    1, int(clip.duration_s * task.video.metadata.fps)
                )
                spans = compute_windows(
                    src_frames,
                    window_len=self.window_len,
                    remainder_threshold=self.remainder_threshold,
                )
                n_ext = frames.shape[0]
                clip.windows = []
                for a, b in spans:
                    ea = int(a / src_frames * n_ext)
                    eb = max(ea + 1, int(b / src_frames * n_ext))
                    idx = np.linspace(ea, min(eb, n_ext) - 1, self.frames_per_window)
                    win = Window(start_frame=a, end_frame=b)
                    win.frames = frames[idx.round().astype(int)]
                    clip.windows.append(win)
        return tasks


# One engine per (config, batch) per process: several caption-family stages
# (captioning, enhancement, semantic filter, per-event) in one pipeline must
# share weights + KV cache instead of loading the VLM repeatedly.
_ENGINES: dict[tuple, CaptionEngine] = {}


class _CaptionVLM(ModelInterface):
    MODEL_ID = "caption-vlm-tpu"

    def __init__(
        self,
        cfg: VLMConfig,
        max_batch: int,
        model_id: str | None = None,
        require_weights: bool = False,
    ) -> None:
        self.cfg = cfg
        self.max_batch = max_batch
        self.model_id = model_id or self.MODEL_ID
        self.require_weights = require_weights
        self.engine: CaptionEngine | None = None

    @property
    def model_id_names(self) -> list[str]:
        return [self.model_id]

    def setup(self) -> None:
        # model_id is part of the key: the same architecture under two
        # weight ids must NOT share one engine (the second would silently
        # caption with the first checkpoint's weights)
        key = (self.cfg, self.max_batch, self.model_id)
        engine = _ENGINES.get(key)
        if engine is None:
            engine = CaptionEngine(self.cfg, max_batch=self.max_batch)
            engine.setup()

            def init(seed: int):
                return engine.params

            engine.params = registry.load_params(
                self.model_id, init, require=self.require_weights
            )
            _ENGINES[key] = engine
        self.engine = engine


def resolve_caption_model(
    cfg: VLMConfig | None, model_flavor: str | None, max_batch: int
) -> _CaptionVLM:
    """One resolution rule for every caption-family stage (captioning,
    enhancement, semantic filter, per-event): an explicit flavor selects
    (config, weight id) from VLM_FLAVORS and REQUIRES staged weights for
    the non-default checkpoints — a user asking for qwen25vl-7b must not
    silently get random-init gibberish."""
    if cfg is not None and model_flavor is not None:
        raise ValueError("pass cfg OR model_flavor, not both")
    if model_flavor is not None:
        from cosmos_curate_tpu.models.vlm.model import vlm_flavor

        fcfg, model_id = vlm_flavor(model_flavor)
        require = model_flavor not in ("base", "tiny-test")
        return _CaptionVLM(fcfg, max_batch, model_id=model_id, require_weights=require)
    return _CaptionVLM(cfg or VLM_BASE, max_batch)


class CaptionStage(Stage[SplitPipeTask, SplitPipeTask]):
    """TPU stage: continuous-batching captioning of every clip window."""

    def __init__(
        self,
        *,
        prompt_variant: str = "default",
        cfg: VLMConfig | None = None,
        max_batch: int = 8,
        max_new_tokens: int = 128,
        refine: bool = False,
        model_flavor: str | None = None,
    ) -> None:
        self.prompt_variant = prompt_variant
        self.prompt_text = get_caption_prompt(prompt_variant)
        self.max_new_tokens = max_new_tokens
        self.refine = refine
        self._model = resolve_caption_model(cfg, model_flavor, max_batch)
        # a small-context flavor must clamp generation, not refuse requests
        # (half the context stays available for vision + prompt)
        if self.max_new_tokens >= self._model.cfg.max_seq // 2:
            self.max_new_tokens = self._model.cfg.max_seq // 2
        self.tokenizer = default_caption_tokenizer()
        self._refined_ids: set[str] = set()  # stage-2 bookkeeping (not user data)

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, entire_tpu_host=True)

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        engine = self._model.engine
        assert engine is not None, "setup() not called"
        windows: dict[str, Window] = {}
        for t_i, task in enumerate(tasks):
            for clip in task.video.clips:
                for w_i, win in enumerate(clip.windows):
                    if win.frames is None:
                        continue
                    rid = f"{clip.uuid}-{w_i}"
                    windows[rid] = win
                    engine.add_request(self._make_request(rid, win))
        if not windows:
            return tasks
        results = engine.run_until_complete()
        for res in results:
            win = windows.get(res.request_id)
            if win is None:
                continue
            win.caption[self.prompt_variant] = res.text
        logger.info(
            "captioned %d windows at %.1f output tok/s",
            len(results),
            engine.tokens_per_second,
        )
        for task in tasks:
            task.stage_perf["caption_tokens_per_s"] = engine.tokens_per_second
        return tasks

    def _make_request(self, rid: str, win: Window) -> CaptionRequest:
        prompt_ids = self.tokenizer.encode(self.prompt_text)
        sampling = SamplingConfig(max_new_tokens=self.max_new_tokens)
        on_complete = None
        if self.refine:
            def on_complete(text: str, _rid=rid, _win=win) -> CaptionRequest | None:
                if _rid in self._refined_ids:
                    return None
                self._refined_ids.add(_rid)
                return CaptionRequest(
                    request_id=_rid,
                    prompt_ids=self.tokenizer.encode(REFINEMENT_PROMPT + text),
                    frames=_win.frames,
                    sampling=sampling,
                    on_complete=on_complete,
                )
        return CaptionRequest(
            request_id=rid,
            prompt_ids=prompt_ids,
            frames=win.frames,
            sampling=sampling,
            on_complete=on_complete,
        )
