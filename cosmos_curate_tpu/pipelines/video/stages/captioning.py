"""Captioning stages: CPU prep + TPU engine stage.

Equivalent capability of the reference's captioning path
(cosmos_curate/pipelines/video/captioning/vllm_caption_stage.py:244/413 —
``VllmPrepStage`` windows + model inputs on CPU, ``VllmCaptionStage`` runs
the engine with in-flight batching and two-stage refinement). Same deliberate
CPU/device split here: the prep stage computes caption windows
(windowing_utils ``compute_windows`` semantics) and samples window frames;
the caption stage owns one ``CaptionEngine`` (the chip owner's in-process
pool) and streams every window of every clip through continuous batching.
"""

from __future__ import annotations

import itertools
import time

import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.data.model import FrameExtractionSignature, SplitPipeTask, Window
from cosmos_curate_tpu.models import registry
from cosmos_curate_tpu.models.prompts import REFINEMENT_PROMPT, get_caption_prompt
from cosmos_curate_tpu.models.tokenizer import default_caption_tokenizer
from cosmos_curate_tpu.models.vlm import (
    CaptionEngine,
    CaptionRequest,
    SamplingConfig,
    VLM_BASE,
    VLMConfig,
)
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.windowing import compute_windows

logger = get_logger(__name__)


class CaptionPrepStage(Stage[SplitPipeTask, SplitPipeTask]):
    """CPU prep: cut clips into caption windows and attach window frames."""

    def __init__(
        self,
        *,
        window_len: int = 256,
        remainder_threshold: int = 128,
        frames_per_window: int = 8,
        extraction: FrameExtractionSignature = FrameExtractionSignature("fps", 2.0),
    ) -> None:
        self.window_len = window_len
        self.remainder_threshold = remainder_threshold
        self.frames_per_window = frames_per_window
        self.extraction = extraction

    @property
    def resources(self) -> Resources:
        return Resources(cpus=3.0)

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        key = self.extraction.key()
        for task in tasks:
            for clip in task.video.clips:
                frames = clip.extracted_frames.get(key)
                if frames is None or frames.shape[0] == 0:
                    continue
                # windows are defined over source frames; map to extracted
                # frame indices proportionally
                src_frames = max(
                    1, int(clip.duration_s * task.video.metadata.fps)
                )
                spans = compute_windows(
                    src_frames,
                    window_len=self.window_len,
                    remainder_threshold=self.remainder_threshold,
                )
                n_ext = frames.shape[0]
                clip.windows = []
                for a, b in spans:
                    ea = int(a / src_frames * n_ext)
                    eb = max(ea + 1, int(b / src_frames * n_ext))
                    idx = np.linspace(ea, min(eb, n_ext) - 1, self.frames_per_window)
                    win = Window(start_frame=a, end_frame=b)
                    win.frames = frames[idx.round().astype(int)]
                    # effective sampling rate of the window's frames in
                    # source time (Qwen2.5 temporal m-rope scaling)
                    span_s = (b - a) / max(task.video.metadata.fps, 1e-6)
                    win.frame_fps = self.frames_per_window / max(span_s, 1e-6)
                    clip.windows.append(win)
        return tasks


# Engines are process-level and keyed by (model, dtype, mesh) — see
# models/vlm/shared_engine.py: every caption-family stage (captioning,
# enhancement, semantic filter, per-event) AND every concurrent pipeline in
# the process submits into ONE engine per served model, whose admission
# interleaves their requests (cross-job continuous batching). Each stage
# instance is one engine OWNER: requests carry the stage's unique owner
# tag, so completions route back to the right drive and per-owner fairness
# + accounting have a stable identity.
_OWNER_SEQ = itertools.count()


def _owner_tag(name: str) -> str:
    """A unique, human-readable engine-owner tag for one stage instance."""
    return f"{name}#{next(_OWNER_SEQ)}"


class _CaptionVLM(ModelInterface):
    MODEL_ID = "caption-vlm-tpu"

    def __init__(
        self,
        cfg: VLMConfig,
        max_batch: int,
        model_id: str | None = None,
        require_weights: bool = False,
        hf_chat: bool = False,
        specials: dict[str, int] | None = None,
        kv_lanes: tuple[tuple[int, int], ...] | None = None,
        text_only: bool = False,
    ) -> None:
        self.cfg = cfg
        self.max_batch = max_batch
        self.model_id = model_id or self.MODEL_ID
        self.require_weights = require_weights
        self.hf_chat = hf_chat
        self.specials = specials
        self.kv_lanes = kv_lanes
        self.text_only = text_only
        self.engine: CaptionEngine | None = None
        self._tokenizer = None
        # encode_prompt memo: the HF BPE is pure-Python and the caption
        # prompts are loop-invariant across windows/clips/events
        self._prompt_cache: dict[tuple[str, bool], tuple[list[int], list[int]]] = {}

    def __getstate__(self):
        # engines and tokenizers are worker-local (the engine holds device
        # buffers; the tokenizer may load node-staged files)
        state = self.__dict__.copy()
        state["engine"] = None
        state["_tokenizer"] = None
        state["_prompt_cache"] = {}
        return state

    @property
    def model_id_names(self) -> list[str]:
        return [self.model_id]

    @property
    def tokenizer(self):
        """The tokenizer requests for this model MUST be encoded with.

        A converted HF checkpoint's embedding table is indexed by the
        checkpoint's exact token ids, so hf_chat flavors load
        HFVocabTokenizer from the staged ``vocab.json``/``merges.txt``
        (ADVICE r3: encoding such prompts with the repo BPE feeds wrong
        embedding rows and the eos check never fires). Missing tokenizer
        files fail loudly, like ``require_weights`` does for params.
        """
        if self._tokenizer is None:
            if self.hf_chat:
                from cosmos_curate_tpu.models.tokenizer import HFVocabTokenizer

                registry.maybe_pull_tokenizer_files(self.model_id)
                vocab = registry.find_model_file(self.model_id, "vocab.json")
                merges = registry.find_model_file(self.model_id, "merges.txt")
                if vocab is None or merges is None:
                    raise FileNotFoundError(
                        f"{self.model_id} is a converted-checkpoint flavor: "
                        f"stage its tokenizer files (vocab.json + merges.txt) "
                        f"under weights/{self.model_id}/ — encoding with the "
                        f"repo tokenizer would address wrong embedding rows"
                    )
                self._tokenizer = HFVocabTokenizer.from_gpt2_files(
                    vocab, merges, specials=self.specials
                )
            else:
                self._tokenizer = default_caption_tokenizer()
        return self._tokenizer

    def encode_prompt(
        self, user_text: str, *, has_vision: bool
    ) -> tuple[list[int], list[int]]:
        """(prefix_ids, prompt_ids) for a CaptionRequest in this flavor's
        prompt format: the checkpoint's chat template for hf_chat flavors
        (vision embeddings splice between the two); repo-native flavors put
        the instruction text in the PREFIX (before the vision block) — the
        cache-friendly layout: the engine's shared-prefix KV cache prefills
        it once per (flavor, prompt_variant) instead of once per window.
        Memoized — stages call this per window/clip/event with identical
        text."""
        if has_vision and self.text_only:
            raise ValueError(
                f"{self.model_id} is a TEXT-ONLY flavor (no trained vision "
                f"tower): frame-bearing stages (captioning, semantic filter, "
                f"per-event) cannot use it — pick a VL flavor; the LM flavor "
                f"serves enhancement/chat paths"
            )
        key = (user_text, has_vision)
        hit = self._prompt_cache.get(key)
        if hit is None:
            if self.hf_chat:
                from cosmos_curate_tpu.models.vlm.chat import build_qwen_vl_chat

                hit = build_qwen_vl_chat(
                    self.tokenizer,
                    user_text,
                    has_vision=has_vision,
                    specials=self.specials or None,
                )
            else:
                # all text before the vision block: for a text-only request
                # the token sequence is identical either way, and for a
                # vision request the shared instruction prefix becomes
                # positionally cacheable across windows
                hit = self.tokenizer.encode(user_text), []
            if len(self._prompt_cache) < 4096:  # bound memory on unique texts
                self._prompt_cache[key] = hit
        # copies: requests must not alias the cached lists
        return list(hit[0]), list(hit[1])

    def setup(self) -> None:
        from cosmos_curate_tpu.models.vlm import SharedCaptionEngine

        # build the tokenizer BEFORE the engine: a missing staged
        # tokenizer must fail setup, not first inference
        tokenizer = self.tokenizer

        def loader(engine: CaptionEngine):
            def init(seed: int):
                return engine.params

            return registry.load_params(
                self.model_id, init, require=self.require_weights
            )

        self.engine = SharedCaptionEngine.get(
            self.cfg,
            model_id=self.model_id,
            max_batch=self.max_batch,
            kv_lanes=self.kv_lanes,
            tokenizer=tokenizer,
            loader=loader,
        )


def resolve_caption_model(
    cfg: VLMConfig | None, model_flavor: str | None, max_batch: int
) -> _CaptionVLM:
    """One resolution rule for every caption-family stage (captioning,
    enhancement, semantic filter, per-event): an explicit flavor selects
    the full serving spec from VLM_FLAVORS — architecture, weight id,
    tokenizer/chat handling, and default KV lanes — and REQUIRES staged
    weights for real-checkpoint flavors (a user asking for qwen25vl-7b
    must not silently get random-init gibberish)."""
    if cfg is not None and model_flavor is not None:
        raise ValueError("pass cfg OR model_flavor, not both")
    if model_flavor is not None:
        from cosmos_curate_tpu.models.vlm.model import vlm_flavor

        spec = vlm_flavor(model_flavor)
        return _CaptionVLM(
            spec.cfg,
            max_batch,
            model_id=spec.model_id,
            require_weights=spec.require_weights,
            hf_chat=spec.hf_chat,
            specials=dict(spec.specials) if spec.specials else None,
            kv_lanes=spec.kv_lanes,
            text_only=spec.text_only,
        )
    return _CaptionVLM(cfg or VLM_BASE, max_batch)


class CaptionStage(Stage[SplitPipeTask, SplitPipeTask]):
    """TPU stage: continuous-batching captioning of every clip window."""

    def __init__(
        self,
        *,
        prompt_variant: str = "default",
        cfg: VLMConfig | None = None,
        max_batch: int = 8,
        max_new_tokens: int = 128,
        refine: bool = False,
        model_flavor: str | None = None,
        stage_batch_size: int = 32,
    ) -> None:
        self.prompt_variant = prompt_variant
        self.prompt_text = get_caption_prompt(prompt_variant)
        self.max_new_tokens = max_new_tokens
        self.refine = refine
        # this stage's engine-owner identity: requests are tagged with it,
        # completions route back by it, and the shared engine's cross-job
        # fairness + per-owner accounting key on it
        self.owner = _owner_tag(f"caption-{prompt_variant}")
        self._model = resolve_caption_model(cfg, model_flavor, max_batch)
        # a small-context flavor must clamp generation, not refuse requests
        # (half the context stays available for vision + prompt)
        if self.max_new_tokens >= self._model.cfg.max_seq // 2:
            self.max_new_tokens = self._model.cfg.max_seq // 2
        self._refined_ids: set[str] = set()  # stage-2 bookkeeping (not user data)
        # Deep batches feed the continuous batch: with the runner default of
        # one task per process_data call, every window decoded SOLO — the
        # engine never saw a full slot batch and pipeline tok/s sat at ~30%
        # of standalone. Admission still paces itself (waiting/ready queues
        # + background prep), so a deep batch costs queue memory, not stalls.
        self._stage_batch_size = max(1, stage_batch_size)
        # loop-invariant per-request pieces, resolved once per stage (the
        # prompt encode is also memoized model-side; this skips even the
        # memo lookup and the SamplingConfig rebuild per window)
        self._encoded_prompt: tuple[list[int], list[int]] | None = None
        self._sampling = SamplingConfig(max_new_tokens=self.max_new_tokens)

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, entire_tpu_host=True)

    @property
    def batch_size(self) -> int:
        return self._stage_batch_size

    def process_data(self, tasks: list[SplitPipeTask]) -> list[SplitPipeTask]:
        from cosmos_curate_tpu.observability import stage_timer
        from cosmos_curate_tpu.observability.tracing import traced_span

        engine = self._model.engine
        assert engine is not None, "setup() not called"
        t_start = time.monotonic()
        phases0 = engine.phase_seconds
        stats0 = self._engine_counts(engine)
        windows: dict[str, Window] = {}
        with traced_span("caption.submit", stage=self.name):
            for task in tasks:
                for clip in task.video.clips:
                    for w_i, win in enumerate(clip.windows):
                        if win.frames is None:
                            continue
                        rid = f"{clip.uuid}-{w_i}"
                        windows[rid] = win
                        # non-blocking: the engine preps (vision encode +
                        # embedding) in its background thread while the
                        # run_until_complete loop below decodes — prep of
                        # window N+1 overlaps decode of window N
                        engine.add_request(self._make_request(rid, win))
        if not windows:
            return tasks
        with traced_span("caption.engine", stage=self.name) as span:
            results = engine.run_until_complete(owner=self.owner)
            wall = time.monotonic() - t_start
            phases = self._phase_delta(engine, phases0, stats0, wall)
            phases["requests"] = len(results)
            for k, v in phases.items():
                span.set_attribute(f"caption.{k}", round(v, 4) if isinstance(v, float) else v)
        stage_timer.record_caption_phases(self.name, phases)
        try:
            from cosmos_curate_tpu.engine.metrics import get_metrics

            get_metrics().observe_caption_owners(engine.owner_stats())
        except Exception:  # metrics must never take down the caption path
            pass
        for res in results:
            win = windows.get(res.request_id)
            if win is None:
                continue
            win.caption[self.prompt_variant] = res.text
        logger.info(
            "captioned %d windows at %.1f output tok/s "
            "(prefill %.2fs decode %.2fs idle %.2fs; prefix hits %d, "
            "%d prefill tokens saved)",
            len(results),
            engine.tokens_per_second,
            phases["prefill_s"],
            phases["decode_s"],
            phases["idle_s"],
            phases["prefix_cache_hits"],
            phases["prefix_tokens_saved"],
        )
        for task in tasks:
            task.stage_perf["caption_tokens_per_s"] = engine.tokens_per_second
            task.stage_perf["caption_prefix_cache_hits"] = phases["prefix_cache_hits"]
            task.stage_perf["caption_engine_idle_s"] = round(phases["idle_s"], 4)
            task.stage_perf["caption_kv_blocks_used"] = engine.kv_blocks_used
            task.stage_perf["caption_prefix_block_refs"] = phases["prefix_block_refs"]
        return tasks

    def _engine_counts(self, engine: CaptionEngine) -> dict:
        return {
            "requests": 0,
            "prefill_tokens": engine.prefill_tokens,
            "prefix_cache_hits": engine.prefix_cache_hits,
            "prefix_cache_misses": engine.prefix_cache_misses,
            "prefix_tokens_saved": engine.prefix_tokens_saved,
            "vision_encodes": engine.vision_encodes,
            "vision_reuses": engine.vision_reuses,
            # paged-KV + cross-job signals (engine-wide counters; per-drive
            # deltas like the rest)
            "prefix_block_refs": engine.prefix_block_refs,
            "kv_cow_copies": engine.kv_cow_copies,
            "interleaved_steps": engine.interleaved_decode_steps,
            # paged-attention path: decode steps served without a gathered
            # KV working set, and the view bytes that never materialized
            "paged_kernel_steps": engine.paged_kernel_steps,
            "kv_gather_bytes_avoided": engine.kv_gather_bytes_avoided,
            # per-OWNER, not engine-wide: under a shared engine another
            # job's tokens decode inside this drive's window, and the run
            # report's owner table must not claim them for this stage
            "decode_tokens": engine.owner_decode_tokens.get(self.owner, 0),
        }

    def _phase_delta(
        self, engine: CaptionEngine, phases0: dict, stats0: dict, wall: float
    ) -> dict:
        """Per-phase/cache deltas over this drive. Counters are engine-wide
        — under a shared engine another stage's concurrent drive bleeds in,
        so treat per-stage attribution as approximate there. ``idle_s`` is
        wall minus device phases: the engine-stall time the overlap rework
        exists to shrink."""
        phases = {
            k: engine.phase_seconds[k] - phases0[k] for k in engine.phase_seconds
        }
        now = self._engine_counts(engine)
        counts = {k: now[k] - stats0[k] for k in now}
        busy = phases["prefill_s"] + phases["decode_s"]
        return {
            **phases,
            **counts,
            "wall_s": wall,
            "idle_s": max(0.0, wall - busy),
            # occupancy gauges (absolute, not deltas) + the owner identity
            # for per-owner accounting in the run report
            "owner": self.owner,
            "kv_blocks_total": engine.kv_blocks_total,
            "kv_blocks_peak": engine.kv_blocks_used_peak,
            "kv_blocks_used": engine.kv_blocks_used,
        }

    def _make_request(self, rid: str, win: Window) -> CaptionRequest:
        if self._encoded_prompt is None:
            self._encoded_prompt = self._model.encode_prompt(
                self.prompt_text, has_vision=True
            )
        prefix_ids, prompt_ids = self._encoded_prompt
        sampling = self._sampling
        on_complete = None
        if self.refine:
            def on_complete(text: str, _rid=rid, _win=win) -> CaptionRequest | None:
                if _rid in self._refined_ids:
                    return None
                self._refined_ids.add(_rid)
                pre, ids = self._model.encode_prompt(
                    REFINEMENT_PROMPT + text, has_vision=True
                )
                return CaptionRequest(
                    request_id=_rid,
                    prefix_ids=pre,
                    prompt_ids=ids,
                    frames=_win.frames,
                    frame_fps=_win.frame_fps,
                    sampling=sampling,
                    on_complete=on_complete,
                    # the stage-2 prefix bakes in the window's own caption —
                    # unique per window, so caching it would thrash the
                    # shared-prefix LRU without ever hitting
                    share_prefix=False,
                )
        return CaptionRequest(
            request_id=rid,
            prefix_ids=list(prefix_ids),
            prompt_ids=list(prompt_ids),
            frames=win.frames,
            frame_fps=win.frame_fps,
            sampling=sampling,
            on_complete=on_complete,
            owner=self.owner,
        )
