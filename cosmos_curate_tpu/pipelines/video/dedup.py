"""Semantic dedup pipeline: embeddings parquet → pruned clip set.

Equivalent capability of the reference's dedup pipeline
(cosmos_curate/pipelines/video/dedup_pipeline.py + dedup/: RAFT/NCCL actor
pool + cuML k-means + per-cluster pruning; output layout
docs/curator/reference/VIDEO_PIPELINES.md:196-206). Here the collective
plane is the JAX mesh (dedup/kmeans.py); this module is the IO + orchestration:
read every embeddings parquet under the split output, run semantic_dedup,
write ``dedup/dedup_summary_<eps>.csv`` plus kept/removed id lists.
"""

from __future__ import annotations

import io
import time
from dataclasses import dataclass

import numpy as np

from cosmos_curate_tpu.dedup.kmeans import semantic_dedup
from cosmos_curate_tpu.storage.client import get_storage_client, read_bytes
from cosmos_curate_tpu.storage.writers import write_csv, write_json
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class DedupPipelineArgs:
    input_path: str = ""  # split output root (with embeddings/<model>/)
    output_path: str = ""  # defaults to <input>/dedup
    embedding_model: str = ""  # "" = first found
    eps: float = 0.07
    n_clusters: int = 0  # 0 = sqrt(N)
    max_iters: int = 20
    use_mesh: bool = True


def load_embeddings(input_path: str, model: str = "") -> tuple[list[str], np.ndarray, str]:
    """Read all per-chunk embedding parquets under the split output."""
    import pyarrow.parquet as pq

    client = get_storage_client(input_path)
    root = f"{input_path.rstrip('/')}/embeddings"
    files = list(client.list_files(root, suffixes=(".parquet",)))
    if model:
        files = [f for f in files if f"/embeddings/{model}/" in f.path]
    if not files:
        raise FileNotFoundError(f"no embedding parquets under {root}")
    found_model = files[0].path.rsplit("/embeddings/", 1)[1].split("/", 1)[0]
    # one embedding space only: mixing models would compare incompatible
    # vectors (or crash on dim mismatch)
    files = [f for f in files if f"/embeddings/{found_model}/" in f.path]
    ids: list[str] = []
    vecs: list[np.ndarray] = []
    for f in files:
        table = pq.read_table(io.BytesIO(read_bytes(f.path)))
        ids.extend(table.column("clip_uuid").to_pylist())
        vecs.extend(np.asarray(v, np.float32) for v in table.column("embedding").to_pylist())
    return ids, np.stack(vecs), found_model


def run_dedup(args: DedupPipelineArgs) -> dict:
    t0 = time.monotonic()
    out = (args.output_path or f"{args.input_path.rstrip('/')}/dedup").rstrip("/")
    ids, embeddings, model = load_embeddings(args.input_path, args.embedding_model)
    logger.info("dedup: %d embeddings (%s, dim %d)", len(ids), model, embeddings.shape[1])
    mesh = None
    if args.use_mesh:
        try:
            from cosmos_curate_tpu.parallel.mesh import best_effort_mesh

            mesh = best_effort_mesh()
        except Exception as e:
            logger.warning("no mesh available (%s); single-device kmeans", e)
    result = semantic_dedup(
        embeddings,
        ids,
        n_clusters=args.n_clusters or None,
        eps=args.eps,
        iters=args.max_iters,
        mesh=mesh,
    )
    rows = [
        {
            "clip_uuid": cid,
            "action": "removed",
            "duplicate_of": result["duplicate_of"].get(cid, ""),
        }
        for cid in result["removed"]
    ] + [{"clip_uuid": cid, "action": "kept", "duplicate_of": ""} for cid in result["kept"]]
    write_csv(
        f"{out}/dedup_summary_{args.eps:g}.csv", rows, ["clip_uuid", "action", "duplicate_of"]
    )
    summary = {
        "embedding_model": model,
        "eps": args.eps,
        "num_embeddings": len(ids),
        "num_kept": len(result["kept"]),
        "num_removed": len(result["removed"]),
        "elapsed_s": time.monotonic() - t0,
    }
    write_json(f"{out}/summary.json", summary)
    logger.info(
        "dedup done: kept %d / removed %d in %.1fs",
        summary["num_kept"], summary["num_removed"], summary["elapsed_s"],
    )
    return summary
