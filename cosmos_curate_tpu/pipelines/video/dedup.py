"""Semantic dedup pipeline: embeddings parquet → pruned clip set.

Equivalent capability of the reference's dedup pipeline
(cosmos_curate/pipelines/video/dedup_pipeline.py + dedup/: RAFT/NCCL actor
pool + cuML k-means + per-cluster pruning; output layout
docs/curator/reference/VIDEO_PIPELINES.md:196-206). Here the collective
plane is the JAX mesh (dedup/kmeans.py); this module is the IO + orchestration:
read every embeddings parquet under the split output, run semantic_dedup,
write ``dedup/dedup_summary_<eps>.csv`` plus kept/removed id lists.

Fast path: when a persistent corpus index exists (``<input>/index`` or
``index_path`` — built in-pipeline by ``--corpus-index`` runs or via the
``index`` CLI), ``run_dedup`` QUERIES it instead of re-clustering —
O(probed shards) per batch against the whole curated corpus, not
O(N·K·iters) against this run alone (docs/DEDUP.md).
"""

from __future__ import annotations

import io
import os
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

from cosmos_curate_tpu.dedup.kmeans import semantic_dedup
from cosmos_curate_tpu.storage.client import get_storage_client, read_bytes
from cosmos_curate_tpu.storage.writers import write_csv, write_json
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# bounded fan-out for per-chunk parquet fetches (same knob the engine's
# worker fetch pool uses — one convention for storage-read concurrency)
FETCH_THREADS_ENV = "CURATE_WORKER_FETCH_THREADS"


@dataclass
class DedupPipelineArgs:
    input_path: str = ""  # split output root (with embeddings/<model>/)
    output_path: str = ""  # defaults to <input>/dedup
    embedding_model: str = ""  # "" = first found
    eps: float = 0.07
    n_clusters: int = 0  # 0 = sqrt(N)
    max_iters: int = 20
    use_mesh: bool = True
    # corpus-index fast path: query instead of re-cluster when one exists
    use_index: bool = True
    index_path: str = ""  # "" = <input>/index
    nprobe: int = 0  # 0 = index default


def load_embeddings(input_path: str, model: str = "") -> tuple[list[str], np.ndarray, str]:
    """Read all per-chunk embedding parquets under the split output.

    Chunk fetches+decodes fan out through a bounded thread pool
    (``CURATE_WORKER_FETCH_THREADS``, default 4): object-store GETs are
    latency-bound and pyarrow releases the GIL for the decode, so the
    serial per-chunk loop was pure wasted wall time on wide runs."""
    client = get_storage_client(input_path)
    root = f"{input_path.rstrip('/')}/embeddings"
    files = list(client.list_files(root, suffixes=(".parquet",)))
    if model:
        files = [f for f in files if f"/embeddings/{model}/" in f.path]
    if not files:
        raise FileNotFoundError(f"no embedding parquets under {root}")
    found_model = files[0].path.rsplit("/embeddings/", 1)[1].split("/", 1)[0]
    # one embedding space only: mixing models would compare incompatible
    # vectors (or crash on dim mismatch)
    files = [f for f in files if f"/embeddings/{found_model}/" in f.path]

    def _fetch(path: str) -> tuple[list[str], list[np.ndarray], int]:
        import pyarrow.parquet as pq

        data = read_bytes(path)
        table = pq.read_table(io.BytesIO(data))
        return (
            table.column("clip_uuid").to_pylist(),
            [np.asarray(v, np.float32) for v in table.column("embedding").to_pylist()],
            len(data),
        )

    workers = max(1, int(os.environ.get(FETCH_THREADS_ENV, "4") or 4))
    t0 = time.monotonic()
    if len(files) == 1 or workers == 1:
        parts = [_fetch(f.path) for f in files]
    else:
        with ThreadPoolExecutor(
            max_workers=min(workers, len(files)), thread_name_prefix="embed-fetch"
        ) as pool:
            parts = list(pool.map(_fetch, (f.path for f in files)))
    elapsed = time.monotonic() - t0
    ids: list[str] = []
    vecs: list[np.ndarray] = []
    total_bytes = 0
    for chunk_ids, chunk_vecs, nbytes in parts:
        ids.extend(chunk_ids)
        vecs.extend(chunk_vecs)
        total_bytes += nbytes
    try:
        from cosmos_curate_tpu.observability.stage_timer import record_object_plane

        record_object_plane(
            store_reads=len(files), store_read_bytes=total_bytes,
            store_read_wait_s=elapsed,
        )
    except Exception:  # metrics must never take down the load path
        logger.debug("object-plane recording failed", exc_info=True)
    logger.info(
        "loaded %d embeddings from %d parquets (%.1f MB) in %.2fs (%d fetch threads)",
        len(ids), len(files), total_bytes / 1e6, elapsed, min(workers, len(files)),
    )
    return ids, np.stack(vecs), found_model


def _open_index(args: DedupPipelineArgs, mesh, model: str):
    """The corpus index this run should query, or None (absent/disabled/
    incompatible). One embedding space per index: a model mismatch falls
    back to re-clustering instead of comparing incompatible vectors (or
    crashing on a dim mismatch)."""
    if not args.use_index:
        return None
    from cosmos_curate_tpu.dedup.corpus_index import CorpusIndex

    root = (args.index_path or f"{args.input_path.rstrip('/')}/index").rstrip("/")
    try:
        if not CorpusIndex.exists(root):
            return None
        index = CorpusIndex.open(root, mesh=mesh, metrics_name="run_dedup")
    except Exception as e:
        logger.warning("corpus index at %s unusable (%s); re-clustering", root, e)
        return None
    index_model = index.meta.get("model", "")
    if index_model and model and index_model != model:
        logger.warning(
            "corpus index at %s holds %r embeddings but this run used %r; "
            "re-clustering instead", root, index_model, model,
        )
        return None
    return index


def run_dedup(args: DedupPipelineArgs) -> dict:
    t0 = time.monotonic()
    out = (args.output_path or f"{args.input_path.rstrip('/')}/dedup").rstrip("/")
    ids, embeddings, model = load_embeddings(args.input_path, args.embedding_model)
    logger.info("dedup: %d embeddings (%s, dim %d)", len(ids), model, embeddings.shape[1])
    mesh = None
    if args.use_mesh:
        try:
            from cosmos_curate_tpu.parallel.mesh import best_effort_mesh

            mesh = best_effort_mesh()
        except Exception as e:
            logger.warning("no mesh available (%s); single-device kmeans", e)
    index = _open_index(args, mesh, model)
    if index is not None:
        # fast path: query the persistent index (which may already contain
        # this very run via in-pipeline fragments — incremental_dedup's
        # keep-first ordering handles self-matches) instead of re-running
        # Lloyd over everything
        from cosmos_curate_tpu.dedup.corpus_index import incremental_dedup

        method = "index_query"
        logger.info(
            "dedup fast path: querying corpus index at %s (%d indexed vectors)",
            index.store.root, index.meta.get("num_vectors", 0),
        )
        result = incremental_dedup(
            index, ids, embeddings, eps=args.eps, nprobe=args.nprobe or None
        )
    else:
        method = "recluster"
        result = semantic_dedup(
            embeddings,
            ids,
            n_clusters=args.n_clusters or None,
            eps=args.eps,
            iters=args.max_iters,
            mesh=mesh,
        )
    rows = [
        {
            "clip_uuid": cid,
            "action": "removed",
            "duplicate_of": result["duplicate_of"].get(cid, ""),
        }
        for cid in result["removed"]
    ] + [{"clip_uuid": cid, "action": "kept", "duplicate_of": ""} for cid in result["kept"]]
    write_csv(
        f"{out}/dedup_summary_{args.eps:g}.csv", rows, ["clip_uuid", "action", "duplicate_of"]
    )
    summary = {
        "embedding_model": model,
        "eps": args.eps,
        "method": method,
        "index_path": index.store.root if index is not None else "",
        "num_embeddings": len(ids),
        "num_kept": len(result["kept"]),
        "num_removed": len(result["removed"]),
        "elapsed_s": time.monotonic() - t0,
    }
    write_json(f"{out}/summary.json", summary)
    logger.info(
        "dedup done: kept %d / removed %d in %.1fs",
        summary["num_kept"], summary["num_removed"], summary["elapsed_s"],
    )
    return summary
