"""Shard-dataset pipeline: curated clips → bucketed webdataset tars.

Equivalent capability of the reference's sharding pipeline
(cosmos_curate/pipelines/video/sharding_pipeline.py + download_stages.py:232
``DownloadPackUpload``; layout docs/curator/reference/VIDEO_PIPELINES.md:
256-284): read the split output (clips/, metas/v0/, embeddings/), honor an
optional dedup kept-list, bucket by dimensions, and write
``<output>/<bucket>/shard-NNNNN.tar`` webdataset shards plus an index.
"""

from __future__ import annotations

import json
import time
from collections import defaultdict
from dataclasses import dataclass

import numpy as np

from cosmos_curate_tpu.dataset.dimensions import bucket_for
from cosmos_curate_tpu.dataset.webdataset import ShardWriter, encode_sample_parts
from cosmos_curate_tpu.storage.client import get_storage_client, read_bytes
from cosmos_curate_tpu.storage.writers import write_json
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.video.decode import extract_video_metadata

logger = get_logger(__name__)


@dataclass
class ShardPipelineArgs:
    input_path: str = ""  # split output root
    output_path: str = ""
    dedup_csv: str = ""  # optional dedup_summary csv; "" = keep all
    max_samples_per_shard: int = 512
    max_bytes_per_shard: int = 256 << 20
    include_embeddings: bool = True


def _kept_ids(dedup_csv: str) -> set[str] | None:
    if not dedup_csv:
        return None
    import csv as csv_mod
    import io

    text = read_bytes(dedup_csv).decode()
    return {
        row["clip_uuid"]
        for row in csv_mod.DictReader(io.StringIO(text))
        if row["action"] == "kept"
    }


def _load_embedding_index(input_path: str) -> dict[str, np.ndarray]:
    import io

    import pyarrow.parquet as pq

    client = get_storage_client(input_path)
    out: dict[str, np.ndarray] = {}
    for f in client.list_files(f"{input_path.rstrip('/')}/embeddings", suffixes=(".parquet",)):
        table = pq.read_table(io.BytesIO(read_bytes(f.path)))
        for cid, vec in zip(
            table.column("clip_uuid").to_pylist(), table.column("embedding").to_pylist()
        ):
            out[cid] = np.asarray(vec, np.float32)
    return out


def run_shard(args: ShardPipelineArgs) -> dict:
    t0 = time.monotonic()
    root = args.input_path.rstrip("/")
    out_root = args.output_path.rstrip("/")
    client = get_storage_client(root)
    kept = _kept_ids(args.dedup_csv)
    embeddings = _load_embedding_index(root) if args.include_embeddings else {}

    writers: dict[str, ShardWriter] = {}
    counts: dict[str, int] = defaultdict(int)
    skipped = 0
    for meta_info in client.list_files(f"{root}/metas/v0", suffixes=(".json",)):
        meta = json.loads(read_bytes(meta_info.path))
        cid = meta["uuid"]
        if kept is not None and cid not in kept:
            skipped += 1
            continue
        clip_path = f"{root}/clips/{cid}.mp4"
        if not client.exists(clip_path):
            continue
        mp4 = read_bytes(clip_path)
        vm = extract_video_metadata(mp4)
        bucket = bucket_for(vm.width, vm.height, vm.num_frames).key
        if bucket not in writers:
            writers[bucket] = ShardWriter(
                f"{out_root}/{bucket}",
                max_bytes_per_shard=args.max_bytes_per_shard,
                max_samples_per_shard=args.max_samples_per_shard,
            )
        # any produced caption variant counts ("default" preferred)
        captions = []
        for w in meta.get("windows", []):
            caps = w.get("captions") or {}
            text = caps.get("default") or next((v for v in caps.values() if v), "")
            if text:
                captions.append(text)
        arrays = {}
        if cid in embeddings:
            arrays["embedding"] = embeddings[cid]
        writers[bucket].add_sample(
            cid,
            encode_sample_parts(
                mp4=mp4,
                meta=meta,
                arrays=arrays,
                text="\n".join(c for c in captions if c) or None,
            ),
        )
        counts[bucket] += 1

    index = {}
    for bucket, writer in writers.items():
        index[bucket] = {"num_samples": counts[bucket], "shards": writer.close()}
    summary = {
        "num_samples": sum(counts.values()),
        "num_buckets": len(writers),
        "num_skipped_by_dedup": skipped,
        "elapsed_s": time.monotonic() - t0,
        "buckets": index,
    }
    write_json(f"{out_root}/index.json", summary)
    logger.info(
        "shard done: %d samples into %d buckets in %.1fs",
        summary["num_samples"], summary["num_buckets"], summary["elapsed_s"],
    )
    return summary
