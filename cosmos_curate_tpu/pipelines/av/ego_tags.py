"""Ego-motion tag taxonomy + derivation from estimated trajectories.

Equivalent capability of the reference's ego tag enums and clip-tag rows
(cosmos_curate/pipelines/av/utils/postgres_schema.py:240-296 —
``EgoSpeedTier`` / ``EgoAccelerationType`` / ``EgoManeuverType`` feeding
``ClipTag``). The reference derives tags from CAN-bus / GPS session data;
without sensor feeds, this module classifies the phase-correlation
trajectory (pipelines/av/trajectory.py) — per-frame image-space egomotion —
into the same tiers, so the ``clip_tag`` table carries real, queryable
motion taxonomy for every clip.

All tag values are the enum ``value`` strings; columns with no estimator
(country, road_type, ...) stay 'unknown'.
"""

from __future__ import annotations

from enum import Enum

import numpy as np


class EgoSpeedTier(str, Enum):
    """Speed tier (reference postgres_schema.py:240)."""

    high = "high"
    medium = "medium"
    low = "low"
    stand_still = "stand_still"
    unknown = "unknown"


class EgoAccelerationType(str, Enum):
    """Acceleration behavior (reference postgres_schema.py:266)."""

    fast_accel = "fast_accel"
    slow_accel = "slow_accel"
    fast_decel = "fast_decel"
    slow_decel = "slow_decel"
    maintain = "maintain"
    brake = "brake"
    unknown = "unknown"


class EgoManeuverType(str, Enum):
    """Maneuver class (reference postgres_schema.py:281)."""

    reverse = "reverse"
    change_lane_left = "lane_change_left"
    change_lane_right = "lane_change_right"
    left_turn = "left_turn"
    right_turn = "right_turn"
    curve_left = "curve_left"
    curve_right = "curve_right"
    straight = "straight"
    non_straight = "non_straight"
    unknown = "unknown"


# image-space speed thresholds in pixels/second at the trajectory
# estimator's working resolution (128x128 @ 4 fps, trajectory.py:133);
# calibrated so a full-frame pan in ~2 s reads as 'high'
_SPEED_STAND_STILL = 2.0
_SPEED_LOW = 12.0
_SPEED_MEDIUM = 40.0

# relative speed change over the clip that counts as accel/decel
_ACCEL_SLOW = 0.25
_ACCEL_FAST = 0.75
# mean |heading change| per step (radians) separating straight / curve / turn
_CURVE_RAD = 0.15
_TURN_RAD = 0.45


def derive_ego_tags(positions: np.ndarray, fps: float) -> dict[str, str]:
    """Trajectory positions [T, 2] (pixels, cumulative) at ``fps`` ->
    {ego_speed, ego_acceleration, ego_curve, ego_turn} tag values."""
    pos = np.asarray(positions, np.float32)
    if pos.shape[0] < 3:
        return {
            "ego_speed": EgoSpeedTier.unknown.value,
            "ego_acceleration": EgoAccelerationType.unknown.value,
            "ego_curve": EgoManeuverType.unknown.value,
            "ego_turn": EgoManeuverType.unknown.value,
        }
    steps = np.diff(pos, axis=0)  # [T-1, 2]
    speeds = np.hypot(steps[:, 0], steps[:, 1]) * fps  # px/s per step
    mean_speed = float(speeds.mean())

    if mean_speed < _SPEED_STAND_STILL:
        speed = EgoSpeedTier.stand_still
    elif mean_speed < _SPEED_LOW:
        speed = EgoSpeedTier.low
    elif mean_speed < _SPEED_MEDIUM:
        speed = EgoSpeedTier.medium
    else:
        speed = EgoSpeedTier.high

    # acceleration: compare mean speed over the clip's back half vs front
    # half — robust to single-step phase-correlation outliers
    half = len(speeds) // 2
    front = float(speeds[:half].mean()) if half else mean_speed
    back = float(speeds[half:].mean())
    base = max(front, _SPEED_STAND_STILL)
    rel = (back - front) / base
    if speed is EgoSpeedTier.stand_still:
        accel = EgoAccelerationType.maintain
    elif rel > _ACCEL_FAST:
        accel = EgoAccelerationType.fast_accel
    elif rel > _ACCEL_SLOW:
        accel = EgoAccelerationType.slow_accel
    elif rel < -_ACCEL_FAST:
        accel = EgoAccelerationType.brake if back < _SPEED_STAND_STILL else EgoAccelerationType.fast_decel
    elif rel < -_ACCEL_SLOW:
        accel = EgoAccelerationType.slow_decel
    else:
        accel = EgoAccelerationType.maintain

    # heading analysis over steps with real motion (tiny steps have
    # meaningless angles)
    moving = steps[np.hypot(steps[:, 0], steps[:, 1]) * fps > _SPEED_STAND_STILL]
    if moving.shape[0] < 2 or speed is EgoSpeedTier.stand_still:
        return {
            "ego_speed": speed.value,
            "ego_acceleration": accel.value,
            "ego_curve": EgoManeuverType.straight.value,
            "ego_turn": EgoManeuverType.straight.value,
        }
    angles = np.arctan2(moving[:, 1], moving[:, 0])
    # wrap heading deltas into (-pi, pi]
    dyaw = np.angle(np.exp(1j * np.diff(angles)))
    mean_abs = float(np.abs(dyaw).mean())
    net = float(dyaw.sum())  # signed total heading change; y is image-down
    if mean_abs < _CURVE_RAD:
        curve = turn = EgoManeuverType.straight
    elif mean_abs < _TURN_RAD:
        curve = EgoManeuverType.curve_right if net > 0 else EgoManeuverType.curve_left
        turn = EgoManeuverType.straight
    else:
        curve = EgoManeuverType.non_straight
        turn = EgoManeuverType.right_turn if net > 0 else EgoManeuverType.left_turn
    return {
        "ego_speed": speed.value,
        "ego_acceleration": accel.value,
        "ego_curve": curve.value,
        "ego_turn": turn.value,
    }
