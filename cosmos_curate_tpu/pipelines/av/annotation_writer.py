"""AV annotation writers: per-clip JSON artifacts + clip_caption DB rows.

Equivalent capability of the reference's annotation writer family
(av/writers/annotation_writer_stage.py:36-340): the JSON layout

- ``{prefix}/metas/{clip_uuid}.json`` — one annotation document per clip
  (clip identity, spans, per-variant caption chains, video geometry),
- ``{prefix}/processed_sessions/{session}.json`` — session-level record,
- ``{prefix}/processed_session_chunks/{session}_{chunk}.json`` — chunk
  record (this pipeline processes whole sessions: chunk 0),

and the ``clip_caption`` DB rows (make_db_row.py:231 ``make_clip_caption``
-> postgres_schema.ClipCaption): per (clip, version, prompt_type) window
frame bounds, window captions, the packaged t5-embedding URL, and the run
id. URLs follow the packaging layout
(``datasets/{dataset}/{variant}/{session}.tar``, packaging.py
``package_t5_embeddings_e``).

All JSON writes go through the URL-aware storage client, so the same code
lands artifacts on local disk or object storage.
"""

from __future__ import annotations

from typing import Any

from cosmos_curate_tpu.pipelines.av.packaging import t5_session_tar_url
from cosmos_curate_tpu.pipelines.av.state_db import (
    CAPTION_VERSION,
    CaptionAnnotationRow,
    parse_caption_variant,
)
from cosmos_curate_tpu.storage.writers import write_json
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _caption_chain(variants: dict[str, str], base: str) -> list[tuple[int, str]]:
    """Ordered (window_index, caption) pairs for one prompt variant:
    window 0 is the bare variant name, later windows ride as
    ``{base}#w{k}`` — parsed with the SAME rule the state db uses
    (state_db.parse_caption_variant), so a variant name that merely
    contains '#w' round-trips instead of being dropped. The PARSED index
    travels with the text so frame bounds stay correct when a middle
    window's caption is absent (e.g. a failed request on resume)."""
    chain = [
        (k, text)
        for name, text in variants.items()
        for b, k in (parse_caption_variant(name),)
        if b == base
    ]
    return sorted(chain)


def write_clip_annotations(
    db,
    output_prefix: str,
    *,
    version: str = CAPTION_VERSION,
    run_id: str = "",
    dataset: str = "av-dataset",
    window_frames: int = 8,
    framerate: float = 1.0,
    height: int | None = None,
    width: int | None = None,
    states: tuple[str, ...] = ("captioned", "packaged"),
    limit: int = 0,
) -> dict[str, int]:
    """Emit the annotation JSON layout + clip_caption DB rows for every
    captioned clip in ``db`` (at most ``limit`` clips when set). Returns
    artifact counts."""
    prefix = output_prefix.rstrip("/")
    sessions: dict[str, list] = {}
    n_clips = 0
    for state in states:
        for clip in db.clips(state=state):
            if limit and n_clips >= limit:
                break
            sessions.setdefault(clip.session_id, []).append(clip)
            n_clips += 1
    n_meta = n_rows = 0
    for session_id, clips in sorted(sessions.items()):
        rows: list[CaptionAnnotationRow] = []
        for clip in clips:
            variants = db.variant_captions(clip.clip_uuid)
            bases = sorted({parse_caption_variant(v)[0] for v in variants})
            chains = {b: _caption_chain(variants, b) for b in bases}
            # caption-frame space (clips caption at `framerate`); the last
            # window clamps to the clip's actual frame count — matching the
            # bounds run_av_shard packs into the tars (pipeline.py:485)
            clip_frames = max(
                1, int(round((clip.span_end - clip.span_start) * framerate))
            )
            doc: dict[str, Any] = {
                "uuid": clip.clip_uuid,
                "session": session_id,
                "camera": clip.camera,
                "span_start": clip.span_start,
                "span_end": clip.span_end,
                "framerate": framerate,
                "height": height,
                "width": width,
                "captions": {b: [t for _, t in chains[b]] for b in bases},
            }
            write_json(f"{prefix}/metas/{clip.clip_uuid}.json", doc)
            n_meta += 1
            for base in bases:
                chain = chains[base]
                # clip_caption arrays are POSITIONAL (entry k = window k,
                # state_db.py module docstring): emit dense arrays up to the
                # last captioned window, "" where a middle window's caption
                # is absent, so caption-state reads round-trip unchanged
                n_win = chain[-1][0] + 1 if chain else 0
                by_k = dict(chain)
                rows.append(
                    CaptionAnnotationRow(
                        clip_uuid=clip.clip_uuid,
                        version=version,
                        prompt_type=base,
                        window_start_frame=[
                            min(k * window_frames, clip_frames) for k in range(n_win)
                        ],
                        window_end_frame=[
                            min((k + 1) * window_frames, clip_frames)
                            for k in range(n_win)
                        ],
                        window_caption=[by_k.get(k, "") for k in range(n_win)],
                        t5_embedding_url=t5_session_tar_url(
                            prefix, dataset, session_id,
                            clip.span_start, clip.span_end,
                        ),
                        run_uuid=run_id,
                    )
                )
        if rows:
            db.add_caption_annotations(rows)
            n_rows += len(rows)
        session_doc = {
            "session": session_id,
            "num_clips": len(clips),
            "clip_uuids": [c.clip_uuid for c in clips],
            "version": version,
            "run_uuid": run_id,
        }
        write_json(f"{prefix}/processed_sessions/{session_id}.json", session_doc)
        write_json(
            f"{prefix}/processed_session_chunks/{session_id}_0.json",
            {**session_doc, "session_chunk_index": 0},
        )
    logger.info(
        "wrote %d clip annotation JSONs + %d clip_caption rows for %d sessions",
        n_meta, n_rows, len(sessions),
    )
    return {"metas": n_meta, "rows": n_rows, "sessions": len(sessions)}
