"""AV pipeline state database.

Equivalent capability of the reference's Postgres clip-state layer
(cosmos_curate/pipelines/av/utils/postgres_schema.py + core/utils/db/ —
``PostgresDB``, ``DbRetrier``; core/managers/postgres_cli.py): sessions and
clips move through ingest → split → caption states with retried writes.
Backed by sqlite (stdlib, serverless) — the schema and the retry wrapper
carry over to a Postgres driver unchanged when one is available.
"""

from __future__ import annotations

import sqlite3
import time
from dataclasses import dataclass
from pathlib import Path

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS sessions (
    session_id TEXT PRIMARY KEY,
    num_cameras INTEGER NOT NULL,
    state TEXT NOT NULL DEFAULT 'ingested',
    created_s REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS clips (
    clip_uuid TEXT PRIMARY KEY,
    session_id TEXT NOT NULL,
    camera TEXT NOT NULL,
    span_start REAL NOT NULL,
    span_end REAL NOT NULL,
    state TEXT NOT NULL DEFAULT 'split',
    caption TEXT DEFAULT '',
    FOREIGN KEY (session_id) REFERENCES sessions (session_id)
);
CREATE INDEX IF NOT EXISTS idx_clips_session ON clips (session_id);
CREATE INDEX IF NOT EXISTS idx_clips_state ON clips (state);
CREATE TABLE IF NOT EXISTS clip_captions (
    clip_uuid TEXT NOT NULL,
    variant TEXT NOT NULL,
    caption TEXT NOT NULL,
    PRIMARY KEY (clip_uuid, variant)
);
CREATE TABLE IF NOT EXISTS clip_caption (
    clip_uuid TEXT NOT NULL,
    version TEXT NOT NULL,
    prompt_type TEXT NOT NULL,
    window_start_frame TEXT NOT NULL,
    window_end_frame TEXT NOT NULL,
    window_caption TEXT NOT NULL,
    t5_embedding_url TEXT NOT NULL,
    run_uuid TEXT NOT NULL,
    created_s REAL NOT NULL,
    PRIMARY KEY (clip_uuid, version, prompt_type)
);
"""


def _db_retry(fn):
    """Retried execution for transient lock/busy failures (reference
    DbRetrier, db/database_utils.py:28) — the shared retry helper with
    sqlite's transient exception."""
    from cosmos_curate_tpu.utils.retry import retry

    return retry(attempts=5, backoff_s=0.2, exceptions=(sqlite3.OperationalError,))(fn)()


@dataclass
class ClipRow:
    clip_uuid: str
    session_id: str
    camera: str
    span_start: float
    span_end: float
    state: str = "split"
    caption: str = ""


class AVStateDB:
    def __init__(self, path: str) -> None:
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        self._conn = sqlite3.connect(path, timeout=10.0)
        self._conn.executescript(_SCHEMA)

    def upsert_session(self, session_id: str, num_cameras: int) -> None:
        def op():
            with self._conn:
                self._conn.execute(
                    "INSERT INTO sessions (session_id, num_cameras, created_s) "
                    "VALUES (?, ?, ?) ON CONFLICT(session_id) DO UPDATE SET "
                    "num_cameras = excluded.num_cameras",
                    (session_id, num_cameras, time.time()),
                )
        _db_retry(op)

    def set_session_state(self, session_id: str, state: str) -> None:
        def op():
            with self._conn:
                self._conn.execute(
                    "UPDATE sessions SET state = ? WHERE session_id = ?", (state, session_id)
                )
        _db_retry(op)

    def sessions(self, state: str | None = None) -> list[tuple[str, int, str]]:
        q = "SELECT session_id, num_cameras, state FROM sessions"
        args: tuple = ()
        if state:
            q += " WHERE state = ?"
            args = (state,)
        return list(self._conn.execute(q, args))

    def add_clips(self, rows: list[ClipRow]) -> None:
        # Re-splitting produces the same deterministic clip ids; an existing
        # row's state/caption must survive (a second 'av split' run must not
        # wipe captions) — only identity fields update on conflict.
        def op():
            with self._conn:
                self._conn.executemany(
                    "INSERT INTO clips "
                    "(clip_uuid, session_id, camera, span_start, span_end, state, caption) "
                    "VALUES (?, ?, ?, ?, ?, ?, ?) "
                    "ON CONFLICT(clip_uuid) DO UPDATE SET "
                    "session_id = excluded.session_id, camera = excluded.camera, "
                    "span_start = excluded.span_start, span_end = excluded.span_end",
                    [
                        (r.clip_uuid, r.session_id, r.camera, r.span_start, r.span_end, r.state, r.caption)
                        for r in rows
                    ],
                )
        _db_retry(op)

    def clips(self, *, session_id: str | None = None, state: str | None = None) -> list[ClipRow]:
        q = "SELECT clip_uuid, session_id, camera, span_start, span_end, state, caption FROM clips"
        conds, args = [], []
        if session_id:
            conds.append("session_id = ?")
            args.append(session_id)
        if state:
            conds.append("state = ?")
            args.append(state)
        if conds:
            q += " WHERE " + " AND ".join(conds)
        return [ClipRow(*row) for row in self._conn.execute(q, args)]

    def set_caption(self, clip_uuid: str, caption: str, variant: str = "default") -> None:
        """Store one prompt-variant's caption (reference AV clips carry a
        caption per prompt variant, captioning_stages.py:156). The default
        variant also fills the clips.caption column and advances state."""
        def op():
            with self._conn:
                self._conn.execute(
                    "INSERT INTO clip_captions (clip_uuid, variant, caption) "
                    "VALUES (?, ?, ?) ON CONFLICT(clip_uuid, variant) "
                    "DO UPDATE SET caption = excluded.caption",
                    (clip_uuid, variant, caption),
                )
                # Only the default variant advances state: 'captioned' must
                # guarantee a non-empty clips.caption (packaging reads it),
                # even if an extra variant finished while the primary failed.
                if variant == "default":
                    self._conn.execute(
                        "UPDATE clips SET caption = ?, state = 'captioned' WHERE clip_uuid = ?",
                        (caption, clip_uuid),
                    )
        _db_retry(op)

    def variant_captions(self, clip_uuid: str) -> dict[str, str]:
        return dict(
            self._conn.execute(
                "SELECT variant, caption FROM clip_captions WHERE clip_uuid = ?",
                (clip_uuid,),
            )
        )

    def set_clip_state(self, clip_uuid: str, state: str) -> None:
        def op():
            with self._conn:
                self._conn.execute(
                    "UPDATE clips SET state = ? WHERE clip_uuid = ?", (state, clip_uuid)
                )
        _db_retry(op)

    def add_caption_annotations(self, rows: list["CaptionAnnotationRow"]) -> None:
        """Bulk-write clip_caption annotation rows (reference
        AnnotationDbWriterStage.write_data, annotation_writer_stage.py:93
        -> postgres_schema.ClipCaption). Window lists ride as JSON text so
        sqlite and Postgres share one schema."""
        import json as _json

        def op():
            with self._conn:
                self._conn.executemany(
                    "INSERT INTO clip_caption (clip_uuid, version, prompt_type, "
                    "window_start_frame, window_end_frame, window_caption, "
                    "t5_embedding_url, run_uuid, created_s) "
                    "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?) "
                    "ON CONFLICT(clip_uuid, version, prompt_type) DO UPDATE SET "
                    "window_start_frame = excluded.window_start_frame, "
                    "window_end_frame = excluded.window_end_frame, "
                    "window_caption = excluded.window_caption, "
                    "t5_embedding_url = excluded.t5_embedding_url, "
                    "run_uuid = excluded.run_uuid",
                    [
                        (
                            r.clip_uuid, r.version, r.prompt_type,
                            _json.dumps(r.window_start_frame),
                            _json.dumps(r.window_end_frame),
                            _json.dumps(r.window_caption),
                            r.t5_embedding_url, r.run_uuid, time.time(),
                        )
                        for r in rows
                    ],
                )
        _db_retry(op)

    def caption_annotations(self, clip_uuid: str | None = None) -> list["CaptionAnnotationRow"]:
        import json as _json

        q = (
            "SELECT clip_uuid, version, prompt_type, window_start_frame, "
            "window_end_frame, window_caption, t5_embedding_url, run_uuid "
            "FROM clip_caption"
        )
        args: tuple = ()
        if clip_uuid:
            q += " WHERE clip_uuid = ?"
            args = (clip_uuid,)
        return [
            CaptionAnnotationRow(
                row[0], row[1], row[2],
                _json.loads(row[3]), _json.loads(row[4]), _json.loads(row[5]),
                row[6], row[7],
            )
            for row in self._conn.execute(q, args)
        ]

    def close(self) -> None:
        self._conn.close()


@dataclass
class CaptionAnnotationRow:
    """One clip_caption table row (reference postgres_schema.py:153):
    per-(clip, version, prompt_type) window frame bounds + captions and
    the packaged t5 embedding URL."""

    clip_uuid: str
    version: str
    prompt_type: str
    window_start_frame: list[int]
    window_end_frame: list[int]
    window_caption: list[str]
    t5_embedding_url: str
    run_uuid: str


_PG_SCHEMA = _SCHEMA.replace("REAL", "DOUBLE PRECISION")


class PostgresAVStateDB:
    """Same state API over a real Postgres (reference PostgresDB,
    core/utils/db/), via the SDK-free wire client (utils/pg_client.py).
    The SQL here is written in the dialect intersection: identical
    statements run on both backends."""

    # SQLSTATEs worth retrying: serialization/deadlock/lock + admin shutdown
    _TRANSIENT_SQLSTATES = ("40001", "40P01", "55P03", "57P03")

    def __init__(self, dsn: str) -> None:
        from cosmos_curate_tpu.utils.pg_client import parse_dsn

        self._conn_kwargs = parse_dsn(dsn)
        self._conn = self._connect()
        for stmt in _PG_SCHEMA.split(";"):
            if stmt.strip():
                self._retry_execute(stmt)

    def _connect(self):
        from cosmos_curate_tpu.utils.pg_client import PgConnection

        return PgConnection(**self._conn_kwargs)

    def _retry_execute(self, sql: str, params: tuple = ()):
        """Transient-only retries, with reconnect on a dead socket (a
        desynced/closed connection can never serve the retry otherwise).
        Permanent PgErrors (syntax, constraint) surface immediately —
        matching the sqlite twin's OperationalError-only policy."""
        from cosmos_curate_tpu.utils.pg_client import PgError

        last: Exception | None = None
        for attempt in range(5):
            try:
                return self._conn.execute(sql, params)
            except (ConnectionError, OSError) as e:
                last = e
                try:
                    self._conn.close()
                except Exception:
                    pass
                try:
                    self._conn = self._connect()
                except (ConnectionError, OSError) as e2:
                    last = e2
            except PgError as e:
                if e.fields.get("C") not in self._TRANSIENT_SQLSTATES:
                    raise
                last = e
            time.sleep(min(0.2 * 2**attempt, 2.0))
        raise last  # type: ignore[misc]

    def upsert_session(self, session_id: str, num_cameras: int) -> None:
        self._retry_execute(
            "INSERT INTO sessions (session_id, num_cameras, created_s) "
            "VALUES (%s, %s, %s) ON CONFLICT(session_id) DO UPDATE SET "
            "num_cameras = excluded.num_cameras",
            (session_id, num_cameras, time.time()),
        )

    def set_session_state(self, session_id: str, state: str) -> None:
        self._retry_execute(
            "UPDATE sessions SET state = %s WHERE session_id = %s", (state, session_id)
        )

    def sessions(self, state: str | None = None) -> list[tuple[str, int, str]]:
        q = "SELECT session_id, num_cameras, state FROM sessions"
        params: tuple = ()
        if state:
            q += " WHERE state = %s"
            params = (state,)
        res = self._retry_execute(q, params)
        return [(r[0], int(r[1]), r[2]) for r in res.rows]

    def add_clips(self, rows: list[ClipRow], *, chunk: int = 500) -> None:
        from cosmos_curate_tpu.utils.pg_client import quote_literal

        for i in range(0, len(rows), chunk):
            values = ", ".join(
                "(%s)" % ", ".join(
                    quote_literal(v)
                    for v in (r.clip_uuid, r.session_id, r.camera, r.span_start,
                              r.span_end, r.state, r.caption)
                )
                for r in rows[i : i + chunk]
            )
            self._retry_execute(
                "INSERT INTO clips "
                "(clip_uuid, session_id, camera, span_start, span_end, state, caption) "
                f"VALUES {values} "
                "ON CONFLICT(clip_uuid) DO UPDATE SET "
                "session_id = excluded.session_id, camera = excluded.camera, "
                "span_start = excluded.span_start, span_end = excluded.span_end"
            )

    def clips(self, *, session_id: str | None = None, state: str | None = None) -> list[ClipRow]:
        q = "SELECT clip_uuid, session_id, camera, span_start, span_end, state, caption FROM clips"
        conds, params = [], []
        if session_id:
            conds.append("session_id = %s")
            params.append(session_id)
        if state:
            conds.append("state = %s")
            params.append(state)
        if conds:
            q += " WHERE " + " AND ".join(conds)
        res = self._retry_execute(q, tuple(params))
        return [
            ClipRow(r[0], r[1], r[2], float(r[3]), float(r[4]), r[5], r[6] or "")
            for r in res.rows
        ]

    def set_caption(self, clip_uuid: str, caption: str, variant: str = "default") -> None:
        self._retry_execute(
            "INSERT INTO clip_captions (clip_uuid, variant, caption) "
            "VALUES (%s, %s, %s) ON CONFLICT(clip_uuid, variant) "
            "DO UPDATE SET caption = excluded.caption",
            (clip_uuid, variant, caption),
        )
        if variant == "default":
            self._retry_execute(
                "UPDATE clips SET caption = %s, state = 'captioned' WHERE clip_uuid = %s",
                (caption, clip_uuid),
            )

    def variant_captions(self, clip_uuid: str) -> dict[str, str]:
        res = self._retry_execute(
            "SELECT variant, caption FROM clip_captions WHERE clip_uuid = %s", (clip_uuid,)
        )
        return dict(res.rows)

    def set_clip_state(self, clip_uuid: str, state: str) -> None:
        self._retry_execute(
            "UPDATE clips SET state = %s WHERE clip_uuid = %s", (state, clip_uuid)
        )

    def add_caption_annotations(
        self, rows: list[CaptionAnnotationRow], *, chunk: int = 500
    ) -> None:
        """Chunked multi-row VALUES like add_clips: one round trip per 500
        rows instead of one per row."""
        import json as _json

        from cosmos_curate_tpu.utils.pg_client import quote_literal

        now = time.time()
        for i in range(0, len(rows), chunk):
            values = ", ".join(
                "(%s)" % ", ".join(
                    quote_literal(v)
                    for v in (
                        r.clip_uuid, r.version, r.prompt_type,
                        _json.dumps(r.window_start_frame),
                        _json.dumps(r.window_end_frame),
                        _json.dumps(r.window_caption),
                        r.t5_embedding_url, r.run_uuid, now,
                    )
                )
                for r in rows[i : i + chunk]
            )
            self._retry_execute(
                "INSERT INTO clip_caption (clip_uuid, version, prompt_type, "
                "window_start_frame, window_end_frame, window_caption, "
                "t5_embedding_url, run_uuid, created_s) "
                f"VALUES {values} "
                "ON CONFLICT(clip_uuid, version, prompt_type) DO UPDATE SET "
                "window_start_frame = excluded.window_start_frame, "
                "window_end_frame = excluded.window_end_frame, "
                "window_caption = excluded.window_caption, "
                "t5_embedding_url = excluded.t5_embedding_url, "
                "run_uuid = excluded.run_uuid"
            )

    def caption_annotations(self, clip_uuid: str | None = None) -> list[CaptionAnnotationRow]:
        import json as _json

        q = (
            "SELECT clip_uuid, version, prompt_type, window_start_frame, "
            "window_end_frame, window_caption, t5_embedding_url, run_uuid "
            "FROM clip_caption"
        )
        params: tuple = ()
        if clip_uuid:
            q += " WHERE clip_uuid = %s"
            params = (clip_uuid,)
        res = self._retry_execute(q, params)
        return [
            CaptionAnnotationRow(
                r[0], r[1], r[2],
                _json.loads(r[3]), _json.loads(r[4]), _json.loads(r[5]),
                r[6], r[7],
            )
            for r in res.rows
        ]

    def close(self) -> None:
        self._conn.close()


def open_state_db(path_or_dsn: str):
    """sqlite file path, object-store sqlite URL, or postgres:// DSN ->
    the matching backend."""
    if path_or_dsn.startswith(("postgres://", "postgresql://")):
        return PostgresAVStateDB(path_or_dsn)
    if path_or_dsn.startswith(("s3://", "gs://", "az://")):
        from cosmos_curate_tpu.pipelines.av.downloaders import RemoteSyncedStateDB

        return RemoteSyncedStateDB(path_or_dsn)
    return AVStateDB(path_or_dsn)
