"""AV pipeline state database.

Equivalent capability of the reference's Postgres clip-state layer
(cosmos_curate/pipelines/av/utils/postgres_schema.py + core/utils/db/ —
``PostgresDB``, ``DbRetrier``; core/managers/postgres_cli.py): sessions and
clips move through ingest → split → caption states with retried writes.
Backed by sqlite (stdlib, serverless) — the schema and the retry wrapper
carry over to a Postgres driver unchanged when one is available.

Schema shape follows the reference's table family
(postgres_schema.py:40-237): ``run`` (one row per pipeline invocation),
``clipped_session`` (one row per split session), ``video_span`` (one row
per encoded clip with geometry + content hash), ``clip_caption`` (window
caption arrays per prompt type) and ``clip_tag`` (ego-motion taxonomy).
Captions are stored ONLY in ``clip_caption``: the caption arrays are
positional — entry ``k`` is caption window ``k``; a window whose caption
has not arrived yet holds an empty string. Frame bounds start as ``-1``
placeholders at caption time and are rewritten with real bounds by the
annotation writer (annotation_writer.py).
"""

from __future__ import annotations

import dataclasses
import json
import sqlite3
import time
from dataclasses import dataclass
from pathlib import Path

from cosmos_curate_tpu.storage.retry import sleep_backoff
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# version tag for caption-state rows written by the caption pipeline; the
# annotation writer defaults to the same tag so its bound/url rewrites land
# on the caption rows rather than beside them
CAPTION_VERSION = "v0"

_SCHEMA = """
CREATE TABLE IF NOT EXISTS sessions (
    session_id TEXT PRIMARY KEY,
    num_cameras INTEGER NOT NULL,
    state TEXT NOT NULL DEFAULT 'ingested',
    created_s REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS clips (
    clip_uuid TEXT PRIMARY KEY,
    session_id TEXT NOT NULL,
    camera TEXT NOT NULL,
    span_start REAL NOT NULL,
    span_end REAL NOT NULL,
    state TEXT NOT NULL DEFAULT 'split',
    caption TEXT DEFAULT '',
    FOREIGN KEY (session_id) REFERENCES sessions (session_id)
);
CREATE INDEX IF NOT EXISTS idx_clips_session ON clips (session_id);
CREATE INDEX IF NOT EXISTS idx_clips_state ON clips (state);
CREATE TABLE IF NOT EXISTS clip_caption (
    clip_uuid TEXT NOT NULL,
    version TEXT NOT NULL,
    prompt_type TEXT NOT NULL,
    window_start_frame TEXT NOT NULL,
    window_end_frame TEXT NOT NULL,
    window_caption TEXT NOT NULL,
    t5_embedding_url TEXT NOT NULL,
    run_uuid TEXT NOT NULL,
    created_s REAL NOT NULL,
    PRIMARY KEY (clip_uuid, version, prompt_type)
);
CREATE TABLE IF NOT EXISTS run (
    run_uuid TEXT PRIMARY KEY,
    run_type TEXT NOT NULL,
    pipeline_version TEXT NOT NULL DEFAULT '',
    description TEXT NOT NULL DEFAULT '',
    params TEXT NOT NULL DEFAULT '{}',
    created_s REAL NOT NULL
);
CREATE TABLE IF NOT EXISTS clipped_session (
    session_uuid TEXT NOT NULL,
    version TEXT NOT NULL,
    source_session TEXT NOT NULL,
    num_cameras INTEGER NOT NULL,
    split_algo_name TEXT NOT NULL,
    encoder TEXT NOT NULL,
    run_uuid TEXT NOT NULL DEFAULT '',
    created_s REAL NOT NULL,
    PRIMARY KEY (session_uuid, version, split_algo_name, encoder)
);
CREATE TABLE IF NOT EXISTS video_span (
    clip_uuid TEXT NOT NULL,
    version TEXT NOT NULL,
    session_uuid TEXT NOT NULL,
    camera TEXT NOT NULL,
    span_index INTEGER NOT NULL,
    split_algo_name TEXT NOT NULL,
    span_start REAL NOT NULL,
    span_end REAL NOT NULL,
    encoder TEXT NOT NULL,
    url TEXT NOT NULL,
    byte_size INTEGER NOT NULL DEFAULT 0,
    duration REAL NOT NULL DEFAULT 0,
    framerate REAL NOT NULL DEFAULT 0,
    num_frames INTEGER NOT NULL DEFAULT 0,
    height INTEGER NOT NULL DEFAULT 0,
    width INTEGER NOT NULL DEFAULT 0,
    sha256 TEXT NOT NULL DEFAULT '',
    run_uuid TEXT NOT NULL DEFAULT '',
    created_s REAL NOT NULL,
    PRIMARY KEY (clip_uuid, version, split_algo_name, encoder)
);
CREATE INDEX IF NOT EXISTS idx_video_span_session ON video_span (session_uuid);
CREATE TABLE IF NOT EXISTS clip_tag (
    clip_uuid TEXT NOT NULL,
    version TEXT NOT NULL,
    country TEXT NOT NULL DEFAULT 'unknown',
    traffic TEXT NOT NULL DEFAULT 'unknown',
    ego_speed TEXT NOT NULL DEFAULT 'unknown',
    ego_acceleration TEXT NOT NULL DEFAULT 'unknown',
    ego_curve TEXT NOT NULL DEFAULT 'unknown',
    ego_turn TEXT NOT NULL DEFAULT 'unknown',
    osm_features TEXT NOT NULL DEFAULT 'unknown',
    road_type TEXT NOT NULL DEFAULT 'unknown',
    visibility TEXT NOT NULL DEFAULT 'unknown',
    road_surface TEXT NOT NULL DEFAULT 'unknown',
    illumination TEXT NOT NULL DEFAULT 'unknown',
    run_uuid TEXT NOT NULL DEFAULT '',
    created_s REAL NOT NULL,
    PRIMARY KEY (clip_uuid, version)
);
"""


def _db_retry(fn):
    """Retried execution for transient lock/busy failures (reference
    DbRetrier, db/database_utils.py:28) — the shared retry helper with
    sqlite's transient exception."""
    from cosmos_curate_tpu.utils.retry import retry

    return retry(attempts=5, backoff_s=0.2, exceptions=(sqlite3.OperationalError,))(fn)()


@dataclass
class ClipRow:
    clip_uuid: str
    session_id: str
    camera: str
    span_start: float
    span_end: float
    state: str = "split"
    caption: str = ""


@dataclass
class CaptionAnnotationRow:
    """One clip_caption table row (reference postgres_schema.py:153):
    per-(clip, version, prompt_type) window frame bounds + captions and
    the packaged t5 embedding URL. Arrays are positional over caption
    windows; an absent window's caption is an empty string."""

    clip_uuid: str
    version: str
    prompt_type: str
    window_start_frame: list[int]
    window_end_frame: list[int]
    window_caption: list[str]
    t5_embedding_url: str
    run_uuid: str


@dataclass
class RunRow:
    """One pipeline invocation (reference postgres_schema.Run:61)."""

    run_uuid: str
    run_type: str
    pipeline_version: str = ""
    description: str = ""
    params: str = "{}"  # JSON text of pipeline args


@dataclass
class ClippedSessionRow:
    """One split session (reference postgres_schema.ClippedSession:76)."""

    session_uuid: str
    version: str
    source_session: str
    num_cameras: int
    split_algo_name: str
    encoder: str
    run_uuid: str = ""


@dataclass
class VideoSpanRow:
    """One encoded clip span with geometry + content hash (reference
    postgres_schema.VideoSpan:106). ``camera`` is the camera NAME (the
    reference uses integer camera ids; sessions here name cameras)."""

    clip_uuid: str
    version: str
    session_uuid: str
    camera: str
    span_index: int
    split_algo_name: str
    span_start: float
    span_end: float
    encoder: str
    url: str
    byte_size: int = 0
    duration: float = 0.0
    framerate: float = 0.0
    num_frames: int = 0
    height: int = 0
    width: int = 0
    sha256: str = ""
    run_uuid: str = ""


@dataclass
class ClipTagRow:
    """Ego-motion / scene tag taxonomy for one clip (reference
    postgres_schema.ClipTag:210). Values come from the ego-tag enums
    (pipelines/av/ego_tags.py); 'unknown' where no estimator ran."""

    clip_uuid: str
    version: str
    country: str = "unknown"
    traffic: str = "unknown"
    ego_speed: str = "unknown"
    ego_acceleration: str = "unknown"
    ego_curve: str = "unknown"
    ego_turn: str = "unknown"
    osm_features: str = "unknown"
    road_type: str = "unknown"
    visibility: str = "unknown"
    road_surface: str = "unknown"
    illumination: str = "unknown"
    run_uuid: str = ""


# table -> (row dataclass, upsert key columns); the generic add/get paths in
# both backends are driven by this metadata so each new table costs one
# dataclass + one schema block, not four hand-written methods
_GENERIC_TABLES: dict[str, tuple[type, tuple[str, ...]]] = {
    "run": (RunRow, ("run_uuid",)),
    "clipped_session": (
        ClippedSessionRow,
        ("session_uuid", "version", "split_algo_name", "encoder"),
    ),
    "video_span": (VideoSpanRow, ("clip_uuid", "version", "split_algo_name", "encoder")),
    "clip_tag": (ClipTagRow, ("clip_uuid", "version")),
}

# with `from __future__ import annotations` dataclass field types are
# strings; PG result cells arrive as text and need coercing back
_FIELD_COERCE = {"int": int, "float": float, "str": str}


def _generic_columns(table: str) -> list[str]:
    cls, _ = _GENERIC_TABLES[table]
    return [f.name for f in dataclasses.fields(cls)]


def _upsert_sql(table: str, values_sql: str) -> str:
    cls, key = _GENERIC_TABLES[table]
    cols = _generic_columns(table) + ["created_s"]
    # created_s is creation time: a re-run's upsert must not reset it
    non_key = [c for c in cols if c not in key and c != "created_s"]
    return (
        f"INSERT INTO {table} ({', '.join(cols)}) VALUES {values_sql} "
        f"ON CONFLICT({', '.join(key)}) DO UPDATE SET "
        + ", ".join(f"{c} = excluded.{c}" for c in non_key)
    )


def _coerce_row(table: str, raw: tuple):
    cls, _ = _GENERIC_TABLES[table]
    vals = [
        _FIELD_COERCE.get(f.type, str)(v)
        for f, v in zip(dataclasses.fields(cls), raw)
    ]
    return cls(*vals)


def _variants_from_caption_rows(rows) -> dict[str, str]:
    """(prompt_type, window_caption_json) pairs -> {variant_name: caption}:
    entry 0 is the bare prompt type, window k > 0 rides as
    '{prompt_type}#w{k}'. Empty (not-yet-captioned) windows are omitted."""
    out: dict[str, str] = {}
    for prompt_type, caps_json in rows:
        for k, text in enumerate(json.loads(caps_json)):
            if text:
                out[prompt_type if k == 0 else f"{prompt_type}#w{k}"] = text
    return out


class _GenericTablesMixin:
    """The reference-shaped provenance-table accessors, shared by both
    backends over their ``_add_rows`` / ``_get_rows`` primitives."""

    def add_run(self, row: "RunRow") -> None:
        self._add_rows("run", [row])

    def runs(self, run_type: str | None = None) -> list["RunRow"]:
        return self._get_rows("run", {"run_type": run_type})

    def add_clipped_sessions(self, rows: list["ClippedSessionRow"]) -> None:
        self._add_rows("clipped_session", rows)

    def clipped_sessions(
        self, source_session: str | None = None
    ) -> list["ClippedSessionRow"]:
        return self._get_rows("clipped_session", {"source_session": source_session})

    def add_video_spans(self, rows: list["VideoSpanRow"]) -> None:
        self._add_rows("video_span", rows)

    def video_spans(
        self, clip_uuid: str | None = None, session_uuid: str | None = None
    ) -> list["VideoSpanRow"]:
        return self._get_rows(
            "video_span", {"clip_uuid": clip_uuid, "session_uuid": session_uuid}
        )

    def add_clip_tags(self, rows: list["ClipTagRow"]) -> None:
        self._add_rows("clip_tag", rows)

    def clip_tags(self, clip_uuid: str | None = None) -> list["ClipTagRow"]:
        return self._get_rows("clip_tag", {"clip_uuid": clip_uuid})


def parse_caption_variant(variant: str) -> tuple[str, int]:
    """'default#w3' -> ('default', 3); plain names are window 0. The
    ``#w{k}`` suffix is the storage convention run_av_caption uses for
    later caption windows (pipeline.py run_av_caption)."""
    base, sep, tail = variant.rpartition("#w")
    if sep and tail.isdigit():
        return base, int(tail)
    return variant, 0


def _merge_caption_window(
    existing: tuple[list, list, list] | None, k: int, caption: str
) -> tuple[list, list, list]:
    """Extend the positional (starts, ends, captions) arrays to cover
    window ``k`` and set its caption. New windows get -1 frame-bound
    placeholders (real bounds arrive with the annotation writer)."""
    starts, ends, caps = existing if existing else ([], [], [])
    while len(caps) <= k:
        caps.append("")
        starts.append(-1)
        ends.append(-1)
    caps[k] = caption
    return starts, ends, caps


class AVStateDB(_GenericTablesMixin):
    def __init__(self, path: str) -> None:
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        self._conn = sqlite3.connect(path, timeout=10.0)
        self._conn.executescript(_SCHEMA)
        self._migrate_legacy_captions()

    def _migrate_legacy_captions(self) -> None:
        """Port rows from the pre-round-5 ``clip_captions`` (variant,
        caption) table into ``clip_caption`` window arrays, then drop it.
        Clip states are NOT touched: a packaged clip must not regress to
        'captioned' just because its caption rows moved tables."""
        has = self._conn.execute(
            "SELECT name FROM sqlite_master WHERE type='table' AND name='clip_captions'"
        ).fetchone()
        if not has:
            return
        def migrate():
            with self._conn:
                # write statement FIRST: sqlite takes the database write
                # lock here, so no still-running old-version writer can add
                # a row between our read and the DROP (it would be silently
                # destroyed with the table)
                self._conn.execute("DELETE FROM clip_captions WHERE rowid < 0")
                legacy = list(
                    self._conn.execute(
                        "SELECT clip_uuid, variant, caption FROM clip_captions"
                    )
                )
                for cid, variant, caption in legacy:
                    base, k = parse_caption_variant(variant)
                    self._store_window_caption(cid, base, k, caption)
                self._conn.execute("DROP TABLE clip_captions")
                return legacy

        try:
            legacy = _db_retry(migrate)
        except sqlite3.OperationalError:
            still_there = self._conn.execute(
                "SELECT name FROM sqlite_master WHERE type='table' "
                "AND name='clip_captions'"
            ).fetchone()
            if not still_there:
                # a concurrent opener migrated + dropped first
                return
            # migration failed with legacy data still present: readers
            # would silently see ZERO captions for those clips — refuse
            raise
        if legacy:
            logger.info(
                "migrated %d legacy clip_captions rows into clip_caption", len(legacy)
            )

    def upsert_session(self, session_id: str, num_cameras: int) -> None:
        def op():
            with self._conn:
                self._conn.execute(
                    "INSERT INTO sessions (session_id, num_cameras, created_s) "
                    "VALUES (?, ?, ?) ON CONFLICT(session_id) DO UPDATE SET "
                    "num_cameras = excluded.num_cameras",
                    (session_id, num_cameras, time.time()),
                )
        _db_retry(op)

    def set_session_state(self, session_id: str, state: str) -> None:
        def op():
            with self._conn:
                self._conn.execute(
                    "UPDATE sessions SET state = ? WHERE session_id = ?", (state, session_id)
                )
        _db_retry(op)

    def sessions(self, state: str | None = None) -> list[tuple[str, int, str]]:
        q = "SELECT session_id, num_cameras, state FROM sessions"
        args: tuple = ()
        if state:
            q += " WHERE state = ?"
            args = (state,)
        return list(self._conn.execute(q, args))

    def add_clips(self, rows: list[ClipRow]) -> None:
        # Re-splitting produces the same deterministic clip ids; an existing
        # row's state/caption must survive (a second 'av split' run must not
        # wipe captions) — only identity fields update on conflict.
        def op():
            with self._conn:
                self._conn.executemany(
                    "INSERT INTO clips "
                    "(clip_uuid, session_id, camera, span_start, span_end, state, caption) "
                    "VALUES (?, ?, ?, ?, ?, ?, ?) "
                    "ON CONFLICT(clip_uuid) DO UPDATE SET "
                    "session_id = excluded.session_id, camera = excluded.camera, "
                    "span_start = excluded.span_start, span_end = excluded.span_end",
                    [
                        (r.clip_uuid, r.session_id, r.camera, r.span_start, r.span_end, r.state, r.caption)
                        for r in rows
                    ],
                )
        _db_retry(op)

    def clips(self, *, session_id: str | None = None, state: str | None = None) -> list[ClipRow]:
        q = "SELECT clip_uuid, session_id, camera, span_start, span_end, state, caption FROM clips"
        conds, args = [], []
        if session_id:
            conds.append("session_id = ?")
            args.append(session_id)
        if state:
            conds.append("state = ?")
            args.append(state)
        if conds:
            q += " WHERE " + " AND ".join(conds)
        return [ClipRow(*row) for row in self._conn.execute(q, args)]

    def _store_window_caption(self, clip_uuid: str, base: str, k: int, caption: str) -> None:
        """Merge one window caption into the row's positional arrays.
        MUST run inside a transaction (the callers' ``with self._conn``):
        the seed INSERT is a write, so sqlite takes the database write lock
        BEFORE the read-merge-update — two processes captioning different
        windows of the same clip serialize instead of losing updates."""
        self._conn.execute(
            "INSERT INTO clip_caption (clip_uuid, version, prompt_type, "
            "window_start_frame, window_end_frame, window_caption, "
            "t5_embedding_url, run_uuid, created_s) "
            "VALUES (?, ?, ?, '[]', '[]', '[]', '', '', ?) "
            "ON CONFLICT(clip_uuid, version, prompt_type) DO NOTHING",
            (clip_uuid, CAPTION_VERSION, base, time.time()),
        )
        row = self._conn.execute(
            "SELECT window_start_frame, window_end_frame, window_caption "
            "FROM clip_caption WHERE clip_uuid = ? AND version = ? AND prompt_type = ?",
            (clip_uuid, CAPTION_VERSION, base),
        ).fetchone()
        starts, ends, caps = _merge_caption_window(
            tuple(json.loads(v) for v in row), k, caption
        )
        # t5_embedding_url / run_uuid are untouched: the annotation writer
        # owns those fields
        self._conn.execute(
            "UPDATE clip_caption SET window_start_frame = ?, "
            "window_end_frame = ?, window_caption = ? "
            "WHERE clip_uuid = ? AND version = ? AND prompt_type = ?",
            (
                json.dumps(starts), json.dumps(ends), json.dumps(caps),
                clip_uuid, CAPTION_VERSION, base,
            ),
        )

    def set_caption(self, clip_uuid: str, caption: str, variant: str = "default") -> None:
        """Store one prompt-variant caption window in ``clip_caption``
        (reference AV clips carry a caption list per prompt variant,
        captioning_stages.py:156). Window 0 of the default variant also
        fills the clips.caption column and advances state."""
        base, k = parse_caption_variant(variant)

        def op():
            with self._conn:
                self._store_window_caption(clip_uuid, base, k, caption)
                # Only the default variant's window 0 advances state:
                # 'captioned' must guarantee a non-empty clips.caption
                # (packaging reads it), even if an extra variant finished
                # while the primary failed.
                if base == "default" and k == 0:
                    self._conn.execute(
                        "UPDATE clips SET caption = ?, state = 'captioned' WHERE clip_uuid = ?",
                        (caption, clip_uuid),
                    )
        _db_retry(op)

    def variant_captions(self, clip_uuid: str) -> dict[str, str]:
        """{variant_name: caption} reconstructed from the positional window
        arrays (see _variants_from_caption_rows)."""
        return _variants_from_caption_rows(
            self._conn.execute(
                "SELECT prompt_type, window_caption FROM clip_caption "
                "WHERE clip_uuid = ? AND version = ?",
                (clip_uuid, CAPTION_VERSION),
            )
        )

    def set_clip_state(self, clip_uuid: str, state: str) -> None:
        def op():
            with self._conn:
                self._conn.execute(
                    "UPDATE clips SET state = ? WHERE clip_uuid = ?", (state, clip_uuid)
                )
        _db_retry(op)

    def add_caption_annotations(self, rows: list[CaptionAnnotationRow]) -> None:
        """Bulk-write clip_caption annotation rows (reference
        AnnotationDbWriterStage.write_data, annotation_writer_stage.py:93
        -> postgres_schema.ClipCaption). Window lists ride as JSON text so
        sqlite and Postgres share one schema."""
        def op():
            with self._conn:
                self._conn.executemany(
                    "INSERT INTO clip_caption (clip_uuid, version, prompt_type, "
                    "window_start_frame, window_end_frame, window_caption, "
                    "t5_embedding_url, run_uuid, created_s) "
                    "VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?) "
                    "ON CONFLICT(clip_uuid, version, prompt_type) DO UPDATE SET "
                    "window_start_frame = excluded.window_start_frame, "
                    "window_end_frame = excluded.window_end_frame, "
                    "window_caption = excluded.window_caption, "
                    "t5_embedding_url = excluded.t5_embedding_url, "
                    "run_uuid = excluded.run_uuid",
                    [
                        (
                            r.clip_uuid, r.version, r.prompt_type,
                            json.dumps(r.window_start_frame),
                            json.dumps(r.window_end_frame),
                            json.dumps(r.window_caption),
                            r.t5_embedding_url, r.run_uuid, time.time(),
                        )
                        for r in rows
                    ],
                )
        _db_retry(op)

    def caption_annotations(self, clip_uuid: str | None = None) -> list[CaptionAnnotationRow]:
        q = (
            "SELECT clip_uuid, version, prompt_type, window_start_frame, "
            "window_end_frame, window_caption, t5_embedding_url, run_uuid "
            "FROM clip_caption"
        )
        args: tuple = ()
        if clip_uuid:
            q += " WHERE clip_uuid = ?"
            args = (clip_uuid,)
        return [
            CaptionAnnotationRow(
                row[0], row[1], row[2],
                json.loads(row[3]), json.loads(row[4]), json.loads(row[5]),
                row[6], row[7],
            )
            for row in self._conn.execute(q, args)
        ]

    # -- generic reference-shaped tables (run / clipped_session / video_span
    #    / clip_tag) -------------------------------------------------------

    def _add_rows(self, table: str, rows: list) -> None:
        if not rows:
            return
        n = len(_generic_columns(table)) + 1  # + created_s
        sql = _upsert_sql(table, "(" + ", ".join("?" * n) + ")")
        now = time.time()
        data = [dataclasses.astuple(r) + (now,) for r in rows]

        def op():
            with self._conn:
                self._conn.executemany(sql, data)
        _db_retry(op)

    def _get_rows(self, table: str, where: dict[str, str]) -> list:
        cols = _generic_columns(table)
        q = f"SELECT {', '.join(cols)} FROM {table}"
        conds = {k: v for k, v in where.items() if v is not None}
        if conds:
            q += " WHERE " + " AND ".join(f"{c} = ?" for c in conds)
        return [
            _coerce_row(table, row)
            for row in self._conn.execute(q, tuple(conds.values()))
        ]

    def close(self) -> None:
        self._conn.close()


_PG_SCHEMA = _SCHEMA.replace("REAL", "DOUBLE PRECISION")


class PostgresAVStateDB(_GenericTablesMixin):
    """Same state API over a real Postgres (reference PostgresDB,
    core/utils/db/), via the SDK-free wire client (utils/pg_client.py).
    The SQL here is written in the dialect intersection: identical
    statements run on both backends."""

    # SQLSTATEs worth retrying: serialization/deadlock/lock + admin shutdown
    _TRANSIENT_SQLSTATES = ("40001", "40P01", "55P03", "57P03")

    def __init__(self, dsn: str) -> None:
        from cosmos_curate_tpu.utils.pg_client import parse_dsn

        self._conn_kwargs = parse_dsn(dsn)
        self._conn = self._connect()
        for stmt in _PG_SCHEMA.split(";"):
            if stmt.strip():
                self._retry_execute(stmt)
        self._migrate_legacy_captions()

    def _connect(self):
        from cosmos_curate_tpu.utils.pg_client import PgConnection

        return PgConnection(**self._conn_kwargs)

    def _with_retries(self, fn):
        """Transient-only retries, with reconnect on a dead socket (a
        desynced/closed connection can never serve the retry otherwise).
        Permanent PgErrors (syntax, constraint) surface immediately —
        matching the sqlite twin's OperationalError-only policy. ``fn``
        receives the CURRENT connection (it changes across reconnects)."""
        from cosmos_curate_tpu.utils.pg_client import PgError

        last: Exception | None = None
        for attempt in range(5):
            try:
                return fn(self._conn)
            except (ConnectionError, OSError) as e:
                last = e
                try:
                    self._conn.close()
                except Exception:
                    pass
                try:
                    self._conn = self._connect()
                except (ConnectionError, OSError) as e2:
                    last = e2
            except PgError as e:
                if e.fields.get("C") not in self._TRANSIENT_SQLSTATES:
                    raise
                last = e
            sleep_backoff(attempt, base=0.2, cap=2.0)
        raise last  # type: ignore[misc]

    def _retry_execute(self, sql: str, params: tuple = ()):
        return self._with_retries(lambda conn: conn.execute(sql, params))

    def _migrate_legacy_captions(self) -> None:
        """Port pre-round-5 ``clip_captions`` rows into ``clip_caption``
        window arrays, then drop the legacy table (see the sqlite twin)."""
        res = self._retry_execute(
            "SELECT table_name FROM information_schema.tables "
            "WHERE table_name = 'clip_captions'"
        )
        if not any(r[0] == "clip_captions" for r in res.rows):
            return
        from cosmos_curate_tpu.utils.pg_client import PgError

        try:
            def txn(conn):
                # exclusive table lock FIRST: blocks concurrent old-version
                # writers (including new INSERTs, which row locks would not)
                # until the migrate-and-drop commits, so no caption written
                # mid-migration is destroyed with the table
                conn.execute("LOCK TABLE clip_captions IN ACCESS EXCLUSIVE MODE")
                legacy = conn.execute(
                    "SELECT clip_uuid, variant, caption FROM clip_captions"
                ).rows
                for cid, variant, caption in legacy:
                    base, k = parse_caption_variant(variant)
                    self._store_window_caption_on(conn, cid, base, k, caption)
                conn.execute("DROP TABLE clip_captions")
                return legacy

            legacy = self._retry_txn(txn)
        except PgError:
            res = self._retry_execute(
                "SELECT table_name FROM information_schema.tables "
                "WHERE table_name = 'clip_captions'"
            )
            if not any(r[0] == "clip_captions" for r in res.rows):
                # a concurrent opener migrated + dropped first (42P01)
                return
            # legacy table still present after a failed migration (e.g. no
            # DROP privilege): swallowing this would make every pre-upgrade
            # caption silently invisible — refuse
            raise
        if legacy:
            logger.info(
                "migrated %d legacy clip_captions rows into clip_caption", len(legacy)
            )

    def upsert_session(self, session_id: str, num_cameras: int) -> None:
        self._retry_execute(
            "INSERT INTO sessions (session_id, num_cameras, created_s) "
            "VALUES (%s, %s, %s) ON CONFLICT(session_id) DO UPDATE SET "
            "num_cameras = excluded.num_cameras",
            (session_id, num_cameras, time.time()),
        )

    def set_session_state(self, session_id: str, state: str) -> None:
        self._retry_execute(
            "UPDATE sessions SET state = %s WHERE session_id = %s", (state, session_id)
        )

    def sessions(self, state: str | None = None) -> list[tuple[str, int, str]]:
        q = "SELECT session_id, num_cameras, state FROM sessions"
        params: tuple = ()
        if state:
            q += " WHERE state = %s"
            params = (state,)
        res = self._retry_execute(q, params)
        return [(r[0], int(r[1]), r[2]) for r in res.rows]

    def add_clips(self, rows: list[ClipRow], *, chunk: int = 500) -> None:
        from cosmos_curate_tpu.utils.pg_client import quote_literal

        for i in range(0, len(rows), chunk):
            values = ", ".join(
                "(%s)" % ", ".join(
                    quote_literal(v)
                    for v in (r.clip_uuid, r.session_id, r.camera, r.span_start,
                              r.span_end, r.state, r.caption)
                )
                for r in rows[i : i + chunk]
            )
            self._retry_execute(
                "INSERT INTO clips "
                "(clip_uuid, session_id, camera, span_start, span_end, state, caption) "
                f"VALUES {values} "
                "ON CONFLICT(clip_uuid) DO UPDATE SET "
                "session_id = excluded.session_id, camera = excluded.camera, "
                "span_start = excluded.span_start, span_end = excluded.span_end"
            )

    def clips(self, *, session_id: str | None = None, state: str | None = None) -> list[ClipRow]:
        q = "SELECT clip_uuid, session_id, camera, span_start, span_end, state, caption FROM clips"
        conds, params = [], []
        if session_id:
            conds.append("session_id = %s")
            params.append(session_id)
        if state:
            conds.append("state = %s")
            params.append(state)
        if conds:
            q += " WHERE " + " AND ".join(conds)
        res = self._retry_execute(q, tuple(params))
        return [
            ClipRow(r[0], r[1], r[2], float(r[3]), float(r[4]), r[5], r[6] or "")
            for r in res.rows
        ]

    def _retry_txn(self, fn):
        """Run ``fn(conn)`` inside BEGIN/COMMIT under the shared retry
        policy (_with_retries); ROLLBACK on any failure."""
        def txn(conn):
            conn.execute("BEGIN")
            try:
                out = fn(conn)
                conn.execute("COMMIT")
                return out
            except BaseException:
                try:
                    conn.execute("ROLLBACK")
                except Exception:
                    pass
                raise
        return self._with_retries(txn)

    def _store_window_caption_on(
        self, conn, clip_uuid: str, base: str, k: int, caption: str
    ) -> None:
        """Seed-then-lock merge of one window caption: the DO NOTHING insert
        guarantees a row exists, the SELECT ... FOR UPDATE serializes
        concurrent writers on it — two workers captioning different windows
        of the same clip cannot lose each other's updates. NO transaction
        management here: the caller supplies the enclosing transaction."""
        conn.execute(
            "INSERT INTO clip_caption (clip_uuid, version, prompt_type, "
            "window_start_frame, window_end_frame, window_caption, "
            "t5_embedding_url, run_uuid, created_s) "
            "VALUES (%s, %s, %s, '[]', '[]', '[]', '', '', %s) "
            "ON CONFLICT(clip_uuid, version, prompt_type) DO NOTHING",
            (clip_uuid, CAPTION_VERSION, base, time.time()),
        )
        res = conn.execute(
            "SELECT window_start_frame, window_end_frame, window_caption "
            "FROM clip_caption WHERE clip_uuid = %s AND version = %s "
            "AND prompt_type = %s FOR UPDATE",
            (clip_uuid, CAPTION_VERSION, base),
        )
        starts, ends, caps = _merge_caption_window(
            tuple(json.loads(v) for v in res.rows[0]), k, caption
        )
        conn.execute(
            "UPDATE clip_caption SET window_start_frame = %s, "
            "window_end_frame = %s, window_caption = %s "
            "WHERE clip_uuid = %s AND version = %s AND prompt_type = %s",
            (
                json.dumps(starts), json.dumps(ends), json.dumps(caps),
                clip_uuid, CAPTION_VERSION, base,
            ),
        )

    def _store_window_caption(self, clip_uuid: str, base: str, k: int, caption: str) -> None:
        self._retry_txn(
            lambda conn: self._store_window_caption_on(conn, clip_uuid, base, k, caption)
        )

    def set_caption(self, clip_uuid: str, caption: str, variant: str = "default") -> None:
        base, k = parse_caption_variant(variant)
        self._store_window_caption(clip_uuid, base, k, caption)
        if base == "default" and k == 0:
            self._retry_execute(
                "UPDATE clips SET caption = %s, state = 'captioned' WHERE clip_uuid = %s",
                (caption, clip_uuid),
            )

    def variant_captions(self, clip_uuid: str) -> dict[str, str]:
        res = self._retry_execute(
            "SELECT prompt_type, window_caption FROM clip_caption "
            "WHERE clip_uuid = %s AND version = %s",
            (clip_uuid, CAPTION_VERSION),
        )
        return _variants_from_caption_rows(res.rows)

    def set_clip_state(self, clip_uuid: str, state: str) -> None:
        self._retry_execute(
            "UPDATE clips SET state = %s WHERE clip_uuid = %s", (state, clip_uuid)
        )

    def add_caption_annotations(
        self, rows: list[CaptionAnnotationRow], *, chunk: int = 500
    ) -> None:
        """Chunked multi-row VALUES like add_clips: one round trip per 500
        rows instead of one per row."""
        from cosmos_curate_tpu.utils.pg_client import quote_literal

        now = time.time()
        for i in range(0, len(rows), chunk):
            values = ", ".join(
                "(%s)" % ", ".join(
                    quote_literal(v)
                    for v in (
                        r.clip_uuid, r.version, r.prompt_type,
                        json.dumps(r.window_start_frame),
                        json.dumps(r.window_end_frame),
                        json.dumps(r.window_caption),
                        r.t5_embedding_url, r.run_uuid, now,
                    )
                )
                for r in rows[i : i + chunk]
            )
            self._retry_execute(
                "INSERT INTO clip_caption (clip_uuid, version, prompt_type, "
                "window_start_frame, window_end_frame, window_caption, "
                "t5_embedding_url, run_uuid, created_s) "
                f"VALUES {values} "
                "ON CONFLICT(clip_uuid, version, prompt_type) DO UPDATE SET "
                "window_start_frame = excluded.window_start_frame, "
                "window_end_frame = excluded.window_end_frame, "
                "window_caption = excluded.window_caption, "
                "t5_embedding_url = excluded.t5_embedding_url, "
                "run_uuid = excluded.run_uuid"
            )

    def caption_annotations(self, clip_uuid: str | None = None) -> list[CaptionAnnotationRow]:
        q = (
            "SELECT clip_uuid, version, prompt_type, window_start_frame, "
            "window_end_frame, window_caption, t5_embedding_url, run_uuid "
            "FROM clip_caption"
        )
        params: tuple = ()
        if clip_uuid:
            q += " WHERE clip_uuid = %s"
            params = (clip_uuid,)
        res = self._retry_execute(q, params)
        return [
            CaptionAnnotationRow(
                r[0], r[1], r[2],
                json.loads(r[3]), json.loads(r[4]), json.loads(r[5]),
                r[6], r[7],
            )
            for r in res.rows
        ]

    # -- generic reference-shaped tables -----------------------------------

    def _add_rows(self, table: str, rows: list, *, chunk: int = 500) -> None:
        from cosmos_curate_tpu.utils.pg_client import quote_literal

        if not rows:
            return
        now = time.time()
        for i in range(0, len(rows), chunk):
            values = ", ".join(
                "(%s)" % ", ".join(
                    quote_literal(v) for v in dataclasses.astuple(r) + (now,)
                )
                for r in rows[i : i + chunk]
            )
            self._retry_execute(_upsert_sql(table, values))

    def _get_rows(self, table: str, where: dict[str, str]) -> list:
        cols = _generic_columns(table)
        q = f"SELECT {', '.join(cols)} FROM {table}"
        conds = {k: v for k, v in where.items() if v is not None}
        if conds:
            q += " WHERE " + " AND ".join(f"{c} = %s" for c in conds)
        res = self._retry_execute(q, tuple(conds.values()))
        return [_coerce_row(table, r) for r in res.rows]

    def close(self) -> None:
        self._conn.close()


def open_state_db(path_or_dsn: str):
    """sqlite file path, object-store sqlite URL, or postgres:// DSN ->
    the matching backend."""
    if path_or_dsn.startswith(("postgres://", "postgresql://")):
        return PostgresAVStateDB(path_or_dsn)
    if path_or_dsn.startswith(("s3://", "gs://", "az://")):
        from cosmos_curate_tpu.pipelines.av.downloaders import RemoteSyncedStateDB

        return RemoteSyncedStateDB(path_or_dsn)
    return AVStateDB(path_or_dsn)
