"""AV download plane: concurrent clip fetch + remote state-db sync.

Equivalent capability of the reference's AV downloaders
(cosmos_curate/pipelines/av/downloaders/download_stages.py — ClipDownloader
:363-446 concurrent per-clip S3 fetch with per-clip error isolation;
SqliteDownloader :282-360 per-session sqlite pulled from object storage):
the caption/packaging steps run on different nodes than split, so clips and
session state arrive through the storage layer, prefetched so the TPU
engine never waits on IO.
"""

from __future__ import annotations

import queue
import threading
from pathlib import Path
from typing import Callable, Generator, Iterable

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_REMOTE = ("s3://", "gs://", "az://")


def prefetch_clips(
    rows: Iterable,
    root: str,
    *,
    target_fps: float = 1.0,
    resize_hw: tuple[int, int] = (224, 224),
    workers: int = 4,
    decode: Callable | None = None,
) -> Generator[tuple[str, "object"], None, None]:
    """Yield ``(clip_uuid, frames)`` with download+decode overlapped.

    A small thread pool fetches ``{root}/clips/<uuid>.mp4`` through the
    URL-aware storage client and decodes; results stream out in completion
    order with bounded buffering (2x workers), so the consumer (the caption
    engine) overlaps with IO instead of alternating. Per-clip failures are
    logged and skipped — one missing clip never kills the run (reference
    download_stages.py:413-435 does the same with a worker pool)."""
    from cosmos_curate_tpu.storage.client import read_bytes
    from cosmos_curate_tpu.video.decode import extract_frames_at_fps

    decode = decode or (
        lambda data: extract_frames_at_fps(data, target_fps=target_fps, resize_hw=resize_hw)
    )
    rows = list(rows)
    if not rows:
        return
    workers = max(1, min(workers, len(rows)))
    out: queue.Queue = queue.Queue(maxsize=2 * workers)
    idx_lock = threading.Lock()
    it = iter(rows)
    _DONE = object()
    cancelled = threading.Event()

    def _put(item) -> bool:
        # Bounded put that gives up when the consumer abandoned the
        # generator, so worker threads (and their decoded-frame payloads)
        # don't leak for the life of the process.
        while not cancelled.is_set():
            try:
                out.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def work() -> None:
        while not cancelled.is_set():
            with idx_lock:
                row = next(it, None)
            if row is None:
                break
            uuid = getattr(row, "clip_uuid", row)
            path = f"{root.rstrip('/')}/clips/{uuid}.mp4"
            try:
                frames = decode(read_bytes(path))
            except FileNotFoundError:
                logger.warning("clip %s missing at %s; skipping", uuid, path)
                continue
            except Exception:
                logger.exception("clip %s failed to fetch/decode; skipping", uuid)
                continue
            if not _put((uuid, frames)):
                return
        _put(_DONE)

    threads = [threading.Thread(target=work, daemon=True) for _ in range(workers)]
    for t in threads:
        t.start()
    try:
        done = 0
        while done < workers:
            item = out.get()
            if item is _DONE:
                done += 1
                continue
            yield item
    finally:
        cancelled.set()
        # Drain so any worker blocked on a full queue can observe the flag.
        try:
            while True:
                out.get_nowait()
        except queue.Empty:
            pass
        for t in threads:
            t.join(timeout=5.0)


class RemoteSyncedStateDB:
    """SqliteDownloader equivalent: a state DB whose sqlite file lives in
    object storage. Pulled down at open, pushed back on close. Single-writer
    per DB file (matching the reference's per-session sqlite model) — two
    simultaneous writers would lose one side's updates."""

    def __init__(self, remote_path: str, *, cache_dir: str | None = None) -> None:
        import hashlib
        import os
        import tempfile

        from cosmos_curate_tpu.pipelines.av.state_db import AVStateDB
        from cosmos_curate_tpu.storage.client import get_storage_client

        # last-writer-wins push: on a multi-node launch (slurm runs the SAME
        # command on every node) concurrent pushes silently drop rows —
        # fail loud instead. Use postgres:// for shared multi-node state.
        num_nodes = int(os.environ.get("CURATE_NUM_NODES", "1"))
        if num_nodes > 1 and not os.environ.get("CURATE_ALLOW_REMOTE_DB_MULTINODE"):
            raise RuntimeError(
                f"remote sqlite state ({remote_path}) is single-writer but "
                f"CURATE_NUM_NODES={num_nodes}; use a postgres:// DSN for "
                "multi-node runs (or set CURATE_ALLOW_REMOTE_DB_MULTINODE=1 "
                "if each node uses a distinct db path)"
            )

        self._remote = remote_path
        self._client = get_storage_client(remote_path)
        digest = hashlib.sha256(remote_path.encode()).hexdigest()[:16]
        base = Path(cache_dir or tempfile.gettempdir()) / "curate_av_state"
        base.mkdir(parents=True, exist_ok=True)
        # Per-process local name: a stale file from a crashed run (or a
        # concurrent same-host process on the same remote path) must never
        # be silently reopened as if it were the remote's current state.
        self._local = base / f"{digest}.{os.getpid()}.sqlite"
        if self._local.exists():
            self._local.unlink()
        if self._client.exists(remote_path):
            self._local.write_bytes(self._client.read_bytes(remote_path))
            logger.info("pulled state db %s -> %s", remote_path, self._local)
        self._db = AVStateDB(str(self._local))
        self._closed = False

    def __getattr__(self, name):
        return getattr(self._db, name)

    def close(self) -> None:
        if self._closed:
            return
        self._db.close()
        self._client.write_bytes(self._remote, self._local.read_bytes())
        logger.info("pushed state db %s -> %s", self._local, self._remote)
        self._local.unlink(missing_ok=True)
        self._closed = True


def is_remote(path: str) -> bool:
    return path.startswith(_REMOTE)
