"""AV (autonomous-vehicle) multi-camera pipeline: ingest → split → caption
→ shard.

Equivalent capability of the reference's AV pipelines
(cosmos_curate/pipelines/av/run_pipeline.py — the same four subcommands over
multi-camera capture sessions, with clip state in Postgres and AV-specific
captioning/packaging stages). Sessions are groups of synchronized camera
files named ``<session>_<camera>.mp4``; clip state lives in the AVStateDB
(sqlite locally, same schema as a Postgres deployment); splitting and
captioning reuse the video stages with the "av" prompt variant.
"""

from __future__ import annotations

import re
import time
from collections import defaultdict
from dataclasses import dataclass
from pathlib import PurePath

from cosmos_curate_tpu.core.pipeline import run_pipeline
from cosmos_curate_tpu.core.runner import RunnerInterface
from cosmos_curate_tpu.data.model import FrameExtractionSignature, SplitPipeTask, Video
from cosmos_curate_tpu.pipelines.av.state_db import ClipRow, open_state_db
from cosmos_curate_tpu.storage.client import get_storage_client
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_SESSION_RE = re.compile(r"^(?P<session>.+?)_(?P<camera>[A-Za-z0-9\-]+)$")


# caption-time frame sampling rate; T5 tar window metadata is expressed in
# this frame space
AV_CAPTION_FPS = 1.0


@dataclass
class AVPipelineArgs:
    input_path: str = ""
    output_path: str = ""
    db_path: str = ""  # sqlite path or postgres:// DSN; default <output>/av_state.sqlite
    clip_len_s: float = 10.0
    min_clip_len_s: float | None = None  # default: min(2.0, clip_len_s)
    caption_prompt_variant: str = "av"
    # extra prompt variants captioned per clip (reference AV clips carry one
    # caption per variant, captioning_stages.py:156)
    extra_caption_variants: tuple[str, ...] = ()
    # windowed captioning (reference CaptionWindow, av_data_model.py:195 +
    # get_clip_window_mappings:562): long clips caption in frame windows —
    # the primary variant captions every window, extra variants the front
    # window only (mirrors the reference's default-vs-front policy)
    caption_window_frames: int = 8
    limit: int = 0
    # dataset name in the packaged layout (reference datasets/{name}/...)
    dataset_name: str = "av-dataset"
    # shard-time T5 packaging: none | e (embeddings-first, one tar per
    # session) | h (hierarchical part_NNNNNN/t5_NNNNNN.tar)
    t5_packaging: str = "none"
    # shard-time mp4 clip-session tars (reference ClipPackagingStage)
    clip_packaging: bool = False

    @property
    def resolved_db(self) -> str:
        return self.db_path or f"{self.output_path.rstrip('/')}/av_state.sqlite"


def discover_sessions(input_path: str) -> dict[str, dict[str, str]]:
    """session_id -> {camera: path} from <session>_<camera>.mp4 names."""
    client = get_storage_client(input_path)
    sessions: dict[str, dict[str, str]] = defaultdict(dict)
    for info in client.list_files(input_path, suffixes=(".mp4", ".mov", ".mkv")):
        stem = PurePath(info.path).stem
        m = _SESSION_RE.match(stem)
        if not m:
            logger.warning("skipping %s: name not <session>_<camera>", info.path)
            continue
        sessions[m.group("session")][m.group("camera")] = info.path
    return dict(sessions)


def run_av_ingest(args: AVPipelineArgs) -> dict:
    sessions = discover_sessions(args.input_path)
    db = open_state_db(args.resolved_db)
    try:
        for sid, cams in sessions.items():
            db.upsert_session(sid, len(cams))
        return {"num_sessions": len(sessions), "db": args.resolved_db}
    finally:
        db.close()


def run_av_split(args: AVPipelineArgs, *, runner: RunnerInterface | None = None) -> dict:
    from cosmos_curate_tpu.pipelines.video.stages.clip_extraction import (
        ClipTranscodingStage,
        FixedStrideExtractorStage,
    )
    from cosmos_curate_tpu.pipelines.video.stages.download import VideoDownloadStage
    from cosmos_curate_tpu.pipelines.video.stages.frame_extraction import (
        ClipFrameExtractionStage,
    )
    from cosmos_curate_tpu.pipelines.video.stages.writer import ClipWriterStage

    t0 = time.monotonic()
    sessions = discover_sessions(args.input_path)
    db = open_state_db(args.resolved_db)
    try:
        tasks = []
        cam_of_path: dict[str, tuple[str, str]] = {}
        processed_sids: set[str] = set()
        for sid, cams in sorted(sessions.items()):
            for cam, path in sorted(cams.items()):
                tasks.append(SplitPipeTask(video=Video(path=path)))
                cam_of_path[path] = (sid, cam)
            processed_sids.add(sid)
            if args.limit and len(tasks) >= args.limit:
                break
        min_len = (
            args.min_clip_len_s
            if args.min_clip_len_s is not None
            else min(2.0, args.clip_len_s)
        )
        stages = [
            VideoDownloadStage(),
            FixedStrideExtractorStage(clip_len_s=args.clip_len_s, min_clip_len_s=min_len),
            ClipTranscodingStage(),
            ClipFrameExtractionStage(
                signatures=(FrameExtractionSignature("fps", 2.0),), resize_hw=(224, 224)
            ),
            ClipWriterStage(args.output_path),
        ]
        out = run_pipeline(tasks, stages, runner=runner) or []
        # provenance rows mirroring the reference's run / clipped_session /
        # video_span tables (postgres_schema.py:61-150): one run row per
        # invocation, one clipped_session per split session, one video_span
        # per encoded clip with geometry + content hash
        import dataclasses as _dc
        import json as _json
        import uuid as _uuid

        from cosmos_curate_tpu import __version__
        from cosmos_curate_tpu.pipelines.av.state_db import (
            CAPTION_VERSION,
            ClippedSessionRow,
            RunRow,
            VideoSpanRow,
        )

        run_uuid = str(_uuid.uuid4())
        split_algo = "fixed-stride"
        rows = []
        span_rows = []
        span_index: dict[str, int] = defaultdict(int)
        encoders: dict[str, set[str]] = defaultdict(set)
        for task in out:
            sid, cam = cam_of_path.get(task.video.path, ("unknown", "unknown"))
            meta = task.video.metadata
            for clip in task.video.clips:
                rows.append(
                    ClipRow(
                        clip_uuid=str(clip.uuid),
                        session_id=sid,
                        camera=cam,
                        span_start=clip.span[0],
                        span_end=clip.span[1],
                    )
                )
                # span_index is the clip's position in the session timeline:
                # it must advance for EVERY clip so a failed middle transcode
                # doesn't shift later clips' indexes between runs
                idx = span_index[f"{sid}/{cam}"]
                span_index[f"{sid}/{cam}"] += 1
                # a span row asserts an mp4 on disk — clips whose transcode
                # failed (encoded_data never produced) must not mint one
                if not clip.encoded_byte_size:
                    continue
                if clip.encoding_codec:
                    encoders[sid].add(clip.encoding_codec)
                span_rows.append(
                    VideoSpanRow(
                        clip_uuid=str(clip.uuid),
                        version=CAPTION_VERSION,
                        session_uuid=_session_uuid(sid),
                        camera=cam,
                        span_index=idx,
                        split_algo_name=split_algo,
                        span_start=clip.span[0],
                        span_end=clip.span[1],
                        encoder=clip.encoding_codec,
                        # the destination the writer ACTUALLY wrote, not a
                        # re-derivation of its layout rule
                        url=clip.encoded_url,
                        byte_size=clip.encoded_byte_size,
                        duration=clip.duration_s,
                        framerate=meta.fps,
                        num_frames=int(round(clip.duration_s * meta.fps)),
                        height=meta.height,
                        width=meta.width,
                        sha256=clip.encoded_sha256,
                        run_uuid=run_uuid,
                    )
                )
        db.add_clips(rows)
        db.add_video_spans(span_rows)
        db.add_run(
            RunRow(
                run_uuid=run_uuid,
                run_type="split",
                pipeline_version=__version__,
                params=_json.dumps(_dc.asdict(args)),
            )
        )
        # per-session encoder set (PK includes encoder, as in the reference):
        # sessions with NO successful transcode write no row — an empty
        # encoder would mint a second PK when a later re-split succeeds
        db.add_clipped_sessions(
            [
                ClippedSessionRow(
                    session_uuid=_session_uuid(sid),
                    version=CAPTION_VERSION,
                    source_session=sid,
                    num_cameras=len(sessions.get(sid, {})),
                    split_algo_name=split_algo,
                    encoder=",".join(sorted(encoders[sid])),
                    run_uuid=run_uuid,
                )
                for sid in sorted(processed_sids)
                if encoders.get(sid)
            ]
        )
        for sid in processed_sids:  # only sessions actually processed
            db.set_session_state(sid, "split")
        return {
            "num_sessions": len(processed_sids),
            "num_clips": len(rows),
            "run_uuid": run_uuid,
            "elapsed_s": time.monotonic() - t0,
        }
    finally:
        db.close()


def _session_uuid(session_id: str) -> str:
    """Deterministic session uuid (reference sessions carry uuids; ours are
    derived from the name so re-splitting upserts the same rows), minted
    with the repo-wide uuid5 chain (data/model.py deterministic_id)."""
    from cosmos_curate_tpu.data.model import deterministic_id

    return str(deterministic_id("av-session", session_id))


def run_av_caption(args: AVPipelineArgs, *, engine=None) -> dict:
    """Caption split clips (state 'split') with the AV prompt; store in db."""
    from cosmos_curate_tpu.models.prompts import get_caption_prompt
    from cosmos_curate_tpu.models.tokenizer import default_caption_tokenizer
    from cosmos_curate_tpu.models.vlm import CaptionEngine, CaptionRequest, SamplingConfig, VLM_BASE
    t0 = time.monotonic()
    db = open_state_db(args.resolved_db)
    tok = default_caption_tokenizer()
    variants = [args.caption_prompt_variant, *args.extra_caption_variants]
    prompts = {v: get_caption_prompt(v) for v in variants}
    variant_req: dict[str, tuple[list[int], int]] = {}  # filled once engine exists
    try:
        todo = db.clips(state="split")
        if args.limit:
            todo = todo[: args.limit]
        # gather work BEFORE building the engine: a no-op resume run must
        # not pay the full model load
        import numpy as np

        w = max(1, args.caption_window_frames)

        def clip_windows(frames: "np.ndarray") -> list["np.ndarray"]:
            """Fixed-size caption windows; the ragged tail is padded to w by
            repeating the last frame so the jitted vision encoder sees ONE
            frame-count shape (a fresh XLA compile per residue otherwise)."""
            wins = []
            for i in range(0, frames.shape[0], w):
                win = frames[i : i + w]
                if win.shape[0] < w:
                    pad = np.repeat(win[-1:], w - win.shape[0], axis=0)
                    win = np.concatenate([win, pad], axis=0)
                wins.append(win)
            return wins

        num_windows = 0
        num_captioned = 0
        # chunked gather→caption→store: memory stays bounded by chunk size,
        # not the full backlog of decoded frames; within a chunk the fetch+
        # decode fans out over a thread pool (downloaders.prefetch_clips) so
        # the engine overlaps with IO
        from cosmos_curate_tpu.pipelines.av.downloaders import prefetch_clips

        chunk_size = 32
        for start in range(0, len(todo), chunk_size):
            chunk_pending = [
                (cid, frames)
                for cid, frames in prefetch_clips(
                    todo[start : start + chunk_size],
                    args.output_path,
                    target_fps=AV_CAPTION_FPS,
                    resize_hw=(224, 224),
                )
                if frames.shape[0] > 0
            ]
            if not chunk_pending:
                continue
            if engine is None:
                engine = CaptionEngine(VLM_BASE, max_batch=8)
                engine.setup()
            if not variant_req:
                # per-variant prompt ids + clamped generation budget are
                # loop-invariant (windows are padded to exactly w frames):
                # encode once, not per window
                for v in variants:
                    ids = tok.encode(prompts[v])
                    variant_req[v] = (ids, engine.fit_max_new_tokens(96, ids, n_frames=w))
            for cid, frames in chunk_pending:
                windows = clip_windows(frames)
                for variant in variants:
                    # primary variant captions every window; extras front-only
                    sel = windows if variant == variants[0] else windows[:1]
                    for k, win in enumerate(sel):
                        num_windows += 1
                        # prompt + clamped budget computed once per variant
                        # (fit_max_new_tokens keeps the vision block from
                        # being rejected on small-context configs)
                        ids, max_new = variant_req[variant]
                        engine.add_request(
                            CaptionRequest(
                                request_id=f"{cid}::{variant}::w{k}",
                                prompt_ids=ids,
                                frames=win,
                                frame_fps=AV_CAPTION_FPS,
                                sampling=SamplingConfig(max_new_tokens=max_new),
                            )
                        )
            num_captioned += len(chunk_pending)
            for res in engine.run_until_complete():
                cid_variant, _, wtag = res.request_id.rpartition("::")
                cid, _, variant = cid_variant.rpartition("::")
                k = int(wtag[1:])
                name = "default" if variant == variants[0] else variant
                if k == 0:
                    # window 0 of the primary fills clips.caption + advances
                    db.set_caption(cid, res.text, name)
                else:
                    # later windows: stored per-window (reference keeps a
                    # caption list per variant over caption windows)
                    db.set_caption(cid, res.text, f"{name}#w{k}")
        if num_captioned == 0:
            return {"num_captioned": 0, "tokens_per_s": 0.0, "elapsed_s": time.monotonic() - t0}
        return {
            "num_captioned": num_captioned,
            "num_windows": num_windows,
            "num_variants": len(variants),
            "tokens_per_s": engine.tokens_per_second,
            "elapsed_s": time.monotonic() - t0,
        }
    finally:
        db.close()


def run_av_annotate(args: AVPipelineArgs) -> dict:
    """Write per-clip annotation JSON artifacts + clip_caption DB rows
    (reference AnnotationJsonWriterStage + AnnotationDbWriterStage,
    av/writers/annotation_writer_stage.py:36-340)."""
    import uuid as _uuid

    from cosmos_curate_tpu.pipelines.av.annotation_writer import write_clip_annotations

    import dataclasses as _dc
    import json as _json

    from cosmos_curate_tpu import __version__
    from cosmos_curate_tpu.pipelines.av.state_db import RunRow

    t0 = time.monotonic()
    db = open_state_db(args.resolved_db)
    run_id = str(_uuid.uuid4())
    try:
        counts = write_clip_annotations(
            db,
            args.output_path,
            run_id=run_id,
            dataset=args.dataset_name,
            window_frames=args.caption_window_frames,
            framerate=AV_CAPTION_FPS,
            limit=args.limit,
        )
        db.add_run(
            RunRow(
                run_uuid=run_id,
                run_type="annotate",
                pipeline_version=__version__,
                params=_json.dumps(_dc.asdict(args)),
            )
        )
        return {**counts, "run_uuid": run_id, "elapsed_s": time.monotonic() - t0}
    finally:
        db.close()


def run_av_package(args: AVPipelineArgs, *, encoder=None) -> dict:
    """Package captioned clips into the cosmos-predict2 dataset layout.

    Equivalent capability of the reference's cosmos-predict2 dataset writer
    (pipelines/av/writers/cosmos_predict2_writer_stage.py:288-555), emitting
    the SAME directory/file layout — ``datasets/{name}/videos/{view}/
    {uuid}.mp4``, ``metas/{view}/{uuid}.txt``, ``t5_xxl/{view}/{uuid}.pkl``
    — so downstream predict2 loaders consume either output unchanged. Clip
    state advances to 'packaged'; sessions whose clips are all packaged
    advance too.
    """
    from cosmos_curate_tpu.models.t5 import T5_BASE, T5EncoderTPU
    from cosmos_curate_tpu.pipelines.av.packaging import write_cosmos_predict2_clip
    from cosmos_curate_tpu.storage.client import read_bytes

    t0 = time.monotonic()
    root = args.output_path.rstrip("/")
    db = open_state_db(args.resolved_db)
    try:
        todo = db.clips(state="captioned")
        if args.limit:
            todo = todo[: args.limit]
        if not todo:
            return {"num_packaged": 0, "elapsed_s": time.monotonic() - t0}
        if encoder is None:
            encoder = T5EncoderTPU(T5_BASE)
            encoder.setup()
        packaged = 0
        windows = [_window_texts(db, r.clip_uuid, r.caption) for r in todo]
        flat = [t for ws in windows for t in ws]
        encoded = iter(encoder.encode(flat))
        per_clip = [[next(encoded) for _ in ws] for ws in windows]
        for row, encs in zip(todo, per_clip):
            try:
                clip_bytes = read_bytes(f"{root}/clips/{row.clip_uuid}.mp4")
            except FileNotFoundError:
                logger.warning("clip %s missing on disk; skipping", row.clip_uuid)
                continue
            write_cosmos_predict2_clip(
                root,
                args.dataset_name,
                row.camera,
                row.clip_uuid,
                video_bytes=clip_bytes,
                caption=row.caption,
                t5_embeddings=[e.embedding for e in encs],
            )
            db.set_clip_state(row.clip_uuid, "packaged")
            packaged += 1
        # sessions whose clips are all packaged advance
        for sid, _, _state in db.sessions():
            states = {c.state for c in db.clips(session_id=sid)}
            if states and states <= {"packaged"}:
                db.set_session_state(sid, "packaged")
        return {"num_packaged": packaged, "elapsed_s": time.monotonic() - t0}
    finally:
        db.close()


def run_av_shard(args: AVPipelineArgs) -> dict:
    summary = {}
    if args.t5_packaging in ("e", "h"):
        summary |= _shard_t5_packaging(args)
    if args.clip_packaging:
        summary |= _shard_clip_packaging(args)
    from cosmos_curate_tpu.pipelines.video.shard import ShardPipelineArgs, run_shard

    return summary | run_shard(
        ShardPipelineArgs(
            input_path=args.output_path,
            output_path=f"{args.output_path.rstrip('/')}/shards",
        )
    )


def _window_texts(db, clip_uuid: str, fallback: str) -> list[str]:
    """Per-clip caption WINDOW texts (reference CaptionWindow: window k of
    the primary variant is stored as 'default#wk' by run_av_caption)."""
    vc = db.variant_captions(clip_uuid)
    wins = [vc.get("default", fallback)]
    k = 1
    while f"default#w{k}" in vc:
        wins.append(vc[f"default#w{k}"])
        k += 1
    return wins


def _shard_clip_packaging(args: AVPipelineArgs) -> dict:
    """Mp4 clip-session tars (reference ClipPackagingStage,
    av/writers/dataset_writer_stage.py:140-236): each synchronized span's
    per-camera clips + exact per-frame timestamps (from the MP4 sample
    tables) tar up together."""
    import uuid as uuid_mod

    from cosmos_curate_tpu.pipelines.av.packaging import (
        CameraClipMedia,
        ClipSessionMedia,
        package_clip_sessions,
    )
    from cosmos_curate_tpu.storage.client import read_bytes
    from cosmos_curate_tpu.video.mp4_index import Mp4ParseError, parse_mp4_video_index

    root = args.output_path.rstrip("/")
    db = open_state_db(args.resolved_db)
    try:
        # group FIRST (rows only), then read + tar one clip-session at a
        # time — memory is bounded by a single session's clips, not the
        # whole dataset's mp4 bytes
        by_span: dict[tuple, list] = {}
        for row in db.clips():
            if row.state not in ("captioned", "packaged"):
                continue
            key = (row.session_id, round(row.span_start, 3), round(row.span_end, 3))
            by_span.setdefault(key, []).append(row)
        num_tars = 0
        for key, rows in by_span.items():
            csu = uuid_mod.uuid5(uuid_mod.NAMESPACE_URL, f"{key[0]}:{key[1]}:{key[2]}")
            sample = ClipSessionMedia(session_uuid=str(csu))
            for row in rows:
                try:
                    data = read_bytes(f"{root}/clips/{row.clip_uuid}.mp4")
                except FileNotFoundError:
                    logger.warning(
                        "clip %s missing; skipping from clip tar", row.clip_uuid
                    )
                    continue
                try:
                    idx = parse_mp4_video_index(data)
                    ts_ms = [int(round(t * 1000)) for t in idx.pts_s]
                except Mp4ParseError:
                    ts_ms = []
                sample.cameras[row.camera] = CameraClipMedia(
                    video_bytes=data, timestamps_ms=ts_ms
                )
            if sample.cameras:
                package_clip_sessions(
                    [sample], root, args.dataset_name, log_summary=False
                )
                num_tars += 1
        logger.info("packaged %d clip-session tars for %s", num_tars, args.dataset_name)
        return {"num_clip_tars": num_tars}
    finally:
        db.close()


def _shard_t5_packaging(args: AVPipelineArgs) -> dict:
    """Shard-time T5 tar packaging (reference T5EmbeddingPackagingStageE/H,
    av/writers/dataset_writer_stage.py:238/400): regroup the per-clip
    ``t5_xxl/{view}/{uuid}.pkl`` files written by ``av package`` into
    clip-session tars (E) or hierarchical part tars (H).

    A "clip-session" is one synchronized span across a session's cameras
    (the reference's clip_session_uuid) — grouped here by
    (session_id, span_start, span_end), NOT by whole session, so every clip
    of a long multi-clip camera lands in its own tar.
    """
    import pickle
    import uuid as uuid_mod

    from cosmos_curate_tpu.pipelines.av.packaging import (
        CameraWindows,
        SessionSample,
        package_t5_embeddings_e,
        package_t5_embeddings_h,
    )
    from cosmos_curate_tpu.storage.client import read_bytes

    root = args.output_path.rstrip("/")
    db = open_state_db(args.resolved_db)
    try:
        by_span: dict[tuple, SessionSample] = {}
        for row in db.clips(state="packaged"):
            path = (
                f"{root}/datasets/{args.dataset_name}/t5_xxl/{row.camera}/"
                f"{row.clip_uuid}.pkl"
            )
            try:
                embeddings = pickle.loads(read_bytes(path))
            except FileNotFoundError:
                logger.warning("no packaged t5 for clip %s; skipping", row.clip_uuid)
                continue
            key = (row.session_id, round(row.span_start, 3), round(row.span_end, 3))
            if key not in by_span:
                from cosmos_curate_tpu.pipelines.av.packaging import t5_session_uuid

                by_span[key] = SessionSample(
                    session_uuid=t5_session_uuid(
                        row.session_id, row.span_start, row.span_end
                    )
                )
            # window frame indices are in caption-frame space (clips are
            # captioned at AV_CAPTION_FPS, run_av_caption); window k spans
            # [k*w, min((k+1)*w, n)) caption frames
            n_frames = max(
                1, int(round((row.span_end - row.span_start) * AV_CAPTION_FPS))
            )
            caps = _window_texts(db, row.clip_uuid, row.caption)
            n_win = len(embeddings)
            # run_av_caption windows are caption_window_frames wide with a
            # ragged tail — use the SAME width, not a reconstruction
            w = max(1, args.caption_window_frames)
            by_span[key].cameras[row.camera] = CameraWindows(
                clip_uuid=row.clip_uuid,
                captions=[
                    caps[i] if i < len(caps) else row.caption for i in range(n_win)
                ],
                embeddings=list(embeddings),
                window_start_frames=[i * w for i in range(n_win)],
                window_end_frames=[min((i + 1) * w, n_frames) for i in range(n_win)],
            )
        samples = list(by_span.values())
        if args.t5_packaging == "e":
            tars = package_t5_embeddings_e(samples, root, args.dataset_name)
        else:
            tars = package_t5_embeddings_h(samples, root, args.dataset_name)
        return {"num_t5_tars": len(tars), "t5_packaging": args.t5_packaging}
    finally:
        db.close()
