"""AV dataset packaging writers matching the reference's output layouts.

Equivalent capability of the reference's packaging-writer family
(pipelines/av/writers/):

- :func:`write_cosmos_predict2_clip` — CosmosPredict2WriterStage
  (cosmos_predict2_writer_stage.py:288-555): per clip,
  ``datasets/{name}/videos/{view}/{uuid}.mp4``,
  ``metas/{view}/{uuid}.txt`` and ``t5_xxl/{view}/{uuid}.pkl``.
- :func:`package_t5_embeddings_e` — T5EmbeddingPackagingStageE
  (dataset_writer_stage.py:238-398, embeddings-first): one tar per
  clip-session per T5 variant at ``datasets/{name}/{variant}/{session}.tar``
  holding ``{session}.{camera}.bin`` + ``{session}.{camera}.json``.
- :func:`package_t5_embeddings_h` — T5EmbeddingPackagingStageH
  (dataset_writer_stage.py:400-…, hierarchical): window-indexed tars at
  ``datasets/{name}/{variant}/part_{p:06d}/t5_{i:06d}.tar`` with a sidecar
  ``t5_{i:06d}.json`` metadata map, bounded embeddings per tar and tars
  per part.

All writes go through the URL-aware storage client, so the same code lands
the layout on a local root or object storage; a consumer of the reference's
dataset layout finds byte-identical directory structure. Embedding payloads
are pickled numpy arrays (the serialization the downstream cosmos-predict2
loaders expect); tars are deterministic (sorted entries, fixed mtime).
"""

from __future__ import annotations

import io
import json
import pickle
import tarfile
from dataclasses import dataclass, field

import numpy as np

from cosmos_curate_tpu.storage.client import write_bytes
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class CameraWindows:
    """One camera's contribution to a clip-session: per-window captions and
    T5 embeddings (index k = caption window k)."""

    clip_uuid: str
    captions: list[str] = field(default_factory=list)
    embeddings: list[np.ndarray] = field(default_factory=list)
    window_start_frames: list[int] = field(default_factory=list)
    window_end_frames: list[int] = field(default_factory=list)


@dataclass
class SessionSample:
    """A clip-session across its cameras (reference ClipSample)."""

    session_uuid: str
    cameras: dict[str, CameraWindows] = field(default_factory=dict)


def _tar_bytes(items: list[tuple[bytes, str]]) -> bytes:
    """Deterministic in-memory tar (reference _create_tar_bytes)."""
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w") as tf:
        for data, name in items:
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = 0
            tf.addfile(info, io.BytesIO(data))
    return buf.getvalue()


def predict2_paths(root: str, dataset: str, camera: str, clip_uuid: str) -> dict[str, str]:
    base = f"{root.rstrip('/')}/datasets/{dataset}"
    return {
        "video": f"{base}/videos/{camera}/{clip_uuid}.mp4",
        "meta": f"{base}/metas/{camera}/{clip_uuid}.txt",
        "t5": f"{base}/t5_xxl/{camera}/{clip_uuid}.pkl",
    }


def write_cosmos_predict2_clip(
    root: str,
    dataset: str,
    camera: str,
    clip_uuid: str,
    *,
    video_bytes: bytes,
    caption: str,
    t5_embeddings: list[np.ndarray],
) -> dict[str, str]:
    """Write one clip's predict2 triplet; returns the three paths.

    ``t5_embeddings`` is the per-CAPTION-WINDOW embedding list (reference
    CaptionWindow semantics: one T5 embedding per window; single-window
    clips pickle a one-element list, matching the reference's layout)."""
    paths = predict2_paths(root, dataset, camera, clip_uuid)
    write_bytes(paths["video"], video_bytes)
    write_bytes(paths["meta"], caption.encode())
    write_bytes(paths["t5"], pickle.dumps([np.asarray(e) for e in t5_embeddings]))
    return paths


def write_prefix_embeddings_cache(
    root: str,
    dataset: str,
    camera: str,
    prefix_embeddings: dict[str, np.ndarray],
) -> str:
    """Predict2 per-view prompt-prefix embedding cache
    (cosmos_predict2_writer_stage.py:220-286)."""
    path = f"{root.rstrip('/')}/datasets/{dataset}/cache/prefix_t5_embeddings_{camera}.pkl"
    write_bytes(path, pickle.dumps({k: np.asarray(v) for k, v in prefix_embeddings.items()}))
    return path


@dataclass
class CameraClipMedia:
    """One camera's media for a clip-session tar."""

    video_bytes: bytes
    timestamps_ms: list[int] = field(default_factory=list)
    trajectory: np.ndarray | None = None


@dataclass
class ClipSessionMedia:
    session_uuid: str
    cameras: dict[str, CameraClipMedia] = field(default_factory=dict)


def package_clip_sessions(
    samples: list["ClipSessionMedia"],
    root: str,
    dataset: str,
    *,
    subdir: str = "clips",
    log_summary: bool = True,
) -> list[str]:
    """Mp4 clip-session tars (reference ClipPackagingStage,
    dataset_writer_stage.py:140-236): one tar per clip-session holding, per
    camera, ``{session}.{camera}.mp4`` (the encoded clip),
    ``{session}.{camera}.json`` (per-frame timestamps as
    ``[{"frame_num": n, "timestamp": ms}, ...]``) and optionally
    ``{session}.{camera}.bin`` (the egomotion trajectory)."""
    base = f"{root.rstrip('/')}/datasets/{dataset}/{subdir}"
    written: list[str] = []
    for sample in samples:
        items: list[tuple[bytes, str]] = []
        for camera in sorted(sample.cameras):
            media = sample.cameras[camera]
            name = f"{sample.session_uuid}.{camera}"
            items.append((media.video_bytes, f"{name}.mp4"))
            meta = [
                {"frame_num": i, "timestamp": int(ts_ms)}
                for i, ts_ms in enumerate(media.timestamps_ms)
            ]
            items.append((json.dumps(meta).encode(), f"{name}.json"))
            if media.trajectory is not None:
                items.append((np.asarray(media.trajectory).tobytes(), f"{name}.bin"))
        path = f"{base}/{sample.session_uuid}.tar"
        write_bytes(path, _tar_bytes(items))
        written.append(path)
    if log_summary:
        logger.info("packaged %d clip-session tars under %s", len(written), base)
    return written


def t5_session_uuid(session_id: str, span_start: float, span_end: float) -> str:
    """Deterministic clip-session id keying the packaged tars (span-keyed
    uuid5). Single source of truth shared by the shard packer and the
    annotation DB rows."""
    import uuid as _uuid

    return str(
        _uuid.uuid5(
            _uuid.NAMESPACE_URL,
            f"{session_id}:{round(span_start, 3)}:{round(span_end, 3)}",
        )
    )


def t5_session_tar_url(
    root: str,
    dataset: str,
    session_id: str,
    span_start: float,
    span_end: float,
    variant: str = "t5_xxl",
) -> str:
    """The exact tar URL ``package_t5_embeddings_e`` writes for one
    clip-session — annotation DB rows must record THIS url, not a
    lookalike."""
    csu = t5_session_uuid(session_id, span_start, span_end)
    return f"{root.rstrip('/')}/datasets/{dataset}/{variant}/{csu}.tar"


def package_t5_embeddings_e(
    samples: list[SessionSample],
    root: str,
    dataset: str,
    *,
    variant: str = "t5_xxl",
    window: int = 0,
) -> list[str]:
    """Embeddings-first tars: one tar per clip-session for ONE T5 variant.

    A ``SessionSample`` carries one variant's per-WINDOW embeddings, so a
    multi-variant dataset calls this once per variant with that variant's
    samples (the reference packs its T5_VARIANTS from parallel per-variant
    embedding lists, dataset_writer_stage.py:238-398 — same tar layout).
    Tar members per camera: ``{session}.{camera}.bin`` (pickled embedding
    for ``window``) and ``{session}.{camera}.json`` holding
    ``[clip_uuid, [caption], [start_frame], [end_frame]]``.
    """
    written: list[str] = []
    base = f"{root.rstrip('/')}/datasets/{dataset}"
    for sample in samples:
        items: list[tuple[bytes, str]] = []
        for camera in sorted(sample.cameras):
            cw = sample.cameras[camera]
            if window >= len(cw.embeddings):
                logger.warning(
                    "session %s camera %s lacks window %d embedding; skipping member",
                    sample.session_uuid, camera, window,
                )
                continue
            name = f"{sample.session_uuid}.{camera}"
            items.append((pickle.dumps(np.asarray(cw.embeddings[window])), f"{name}.bin"))
            meta = [
                cw.clip_uuid,
                [cw.captions[window] if window < len(cw.captions) else ""],
                [cw.window_start_frames[window] if window < len(cw.window_start_frames) else 0],
                [cw.window_end_frames[window] if window < len(cw.window_end_frames) else 0],
            ]
            items.append((json.dumps(meta).encode(), f"{name}.json"))
        path = f"{base}/{variant}/{sample.session_uuid}.tar"
        write_bytes(path, _tar_bytes(items))
        written.append(path)
    logger.info("packaged %d embeddings-first tars under %s", len(written), base)
    return written


def package_t5_embeddings_h(
    samples: list[SessionSample],
    root: str,
    dataset: str,
    *,
    variant: str = "t5_xxl",
    window: int = 0,
    embeddings_per_tar: int = 16,
    tars_per_part: int = 1000,
) -> list[str]:
    """Hierarchical tars: sessions accumulate into fixed-size tars grouped
    into parts — ``{variant}/part_{p:06d}/t5_{i:06d}.tar`` plus a sidecar
    ``t5_{i:06d}.json`` mapping session → camera → metadata
    (T5EmbeddingPackagingStageH's layout)."""
    base = f"{root.rstrip('/')}/datasets/{dataset}/{variant}"
    written: list[str] = []
    items: list[tuple[bytes, str]] = []
    metadata: dict[str, dict[str, list]] = {}
    tar_idx = 0

    def flush() -> None:
        nonlocal items, metadata, tar_idx
        if not items:
            return
        part = tar_idx // tars_per_part
        prefix = f"{base}/part_{part:06d}/t5_{tar_idx % tars_per_part:06d}"
        write_bytes(f"{prefix}.tar", _tar_bytes(items))
        write_bytes(f"{prefix}.json", json.dumps(metadata).encode())
        written.append(f"{prefix}.tar")
        items, metadata = [], {}
        tar_idx += 1

    count = 0
    for sample in samples:
        for camera in sorted(sample.cameras):
            cw = sample.cameras[camera]
            if window >= len(cw.embeddings):
                continue
            name = f"{sample.session_uuid}.{camera}"
            items.append((pickle.dumps(np.asarray(cw.embeddings[window])), f"{name}.bin"))
            metadata.setdefault(sample.session_uuid, {})[camera] = [
                dataset,
                [cw.captions[window] if window < len(cw.captions) else ""],
                [cw.window_start_frames[window] if window < len(cw.window_start_frames) else 0],
                [cw.window_end_frames[window] if window < len(cw.window_end_frames) else 0],
            ]
            count += 1
            if count % embeddings_per_tar == 0:
                flush()
    flush()
    logger.info("packaged %d hierarchical tars under %s", len(written), base)
    return written
