"""Egomotion trajectory extraction for AV clips.

Equivalent capability of the reference's trajectory task family
(cosmos_curate/pipelines/av/utils/av_data_model.py:469 ``ClipForTrajectory``
/ :491 ``AvSessionTrajectoryTask`` — per-clip vehicle-motion artifacts
consumed by the sharding/packaging steps).

TPU-first estimator: global inter-frame translation by **phase
correlation** — FFT of consecutive grayscale frames, normalized cross-power
spectrum, inverse FFT, argmax = displacement. The whole clip runs in ONE
jitted program (batched over frame pairs, no Python per frame); cumulative
summation of the per-frame displacements yields the 2D egomotion trajectory
in pixel units, plus summary stats (path length, net displacement, max
step) used to classify drive segments (straight/turning/stationary).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


@functools.partial(jax.jit, static_argnames=())
def _phase_correlate_pairs(gray: jax.Array) -> jax.Array:
    """gray [T, H, W] float32 -> per-pair displacement [T-1, 2] (dx, dy).

    Hann-windowed phase correlation: peak of IFFT(F1 * conj(F2) / |.|).
    Displacements are wrapped from FFT coordinates into [-H/2, H/2)."""
    t, h, w = gray.shape
    wy = jnp.hanning(h)[:, None]
    wx = jnp.hanning(w)[None, :]
    windowed = gray * (wy * wx)[None]
    f = jnp.fft.rfft2(windowed)
    cross = f[:-1] * jnp.conj(f[1:])
    cross = cross / jnp.maximum(jnp.abs(cross), 1e-9)
    corr = jnp.fft.irfft2(cross, s=(h, w))  # [T-1, H, W]
    flat_idx = corr.reshape(corr.shape[0], -1).argmax(axis=-1)
    py = flat_idx // w
    px = flat_idx % w
    # wrap: a peak at H-2 means displacement -2
    dy = jnp.where(py > h // 2, py - h, py).astype(jnp.float32)
    dx = jnp.where(px > w // 2, px - w, px).astype(jnp.float32)
    return jnp.stack([dx, dy], axis=-1)


def estimate_trajectory(frames_u8: np.ndarray) -> dict:
    """uint8 [T, H, W, 3] -> trajectory dict.

    Returns: ``positions`` [T, 2] cumulative (x, y) displacement in pixels
    (position 0 is the origin), ``steps`` [T-1, 2], and summary stats."""
    if frames_u8.shape[0] < 2:
        zeros = np.zeros((frames_u8.shape[0], 2), np.float32)
        return {
            "positions": zeros,
            "steps": np.zeros((0, 2), np.float32),
            "path_length": 0.0,
            "net_displacement": 0.0,
            "max_step": 0.0,
            "motion_class": "stationary",
        }
    # grayscale on host: pad_batch needs host arrays anyway, so a jnp
    # reduction here would round-trip the full stack device->host->device
    gray = frames_u8.astype(np.float32).mean(axis=-1) / 255.0
    from cosmos_curate_tpu.models.batching import pad_batch

    padded, n = pad_batch(gray)  # pow2 T-buckets: few compiles
    steps = np.asarray(_phase_correlate_pairs(jnp.asarray(padded)))[: n - 1]
    positions = np.concatenate(
        [np.zeros((1, 2), np.float32), np.cumsum(steps, axis=0)], axis=0
    )
    lengths = np.hypot(steps[:, 0], steps[:, 1])
    path_length = float(lengths.sum())
    net = float(np.hypot(*positions[-1]))
    max_step = float(lengths.max()) if len(lengths) else 0.0
    # simple drive-segment classification on the displacement geometry
    if path_length < 1.0 * len(steps) * 0.05 + 1.0:
        motion = "stationary"
    elif net > 0.7 * path_length:
        motion = "straight"
    else:
        motion = "turning"
    return {
        "positions": positions,
        "steps": steps,
        "path_length": path_length,
        "net_displacement": net,
        "max_step": max_step,
        "motion_class": motion,
    }


def run_av_trajectory(args) -> dict:
    """Per-clip trajectory artifacts for all split/captioned clips:
    ``trajectories/<uuid>.npy`` (positions), a stats row in the summary,
    and one ``clip_tag`` row per clip with the ego-motion taxonomy derived
    from the trajectory (reference ClipTag, postgres_schema.py:210)."""
    import json
    import time as time_mod
    import uuid as uuid_mod
    from pathlib import Path

    from cosmos_curate_tpu import __version__
    from cosmos_curate_tpu.pipelines.av.ego_tags import derive_ego_tags
    from cosmos_curate_tpu.pipelines.av.state_db import (
        CAPTION_VERSION,
        ClipTagRow,
        RunRow,
        open_state_db,
    )
    from cosmos_curate_tpu.storage.client import read_bytes
    from cosmos_curate_tpu.video.decode import extract_frames_at_fps

    t0 = time_mod.monotonic()
    root = args.output_path.rstrip("/")
    if "://" in root:
        # clips are read through the URL-aware storage client, but
        # trajectories are written with local paths — a remote output root
        # would silently land in a local "s3:/..." directory.
        raise ValueError(
            f"av trajectory writes locally; output_path {root!r} must be a "
            "local directory (sync to object storage afterwards)"
        )
    db = open_state_db(args.resolved_db)
    stats = []
    tag_rows = []
    run_uuid = str(uuid_mod.uuid4())
    try:
        todo = [
            r
            for r in db.clips()
            if r.state in ("split", "captioned", "packaged")
        ]
        if args.limit:
            todo = todo[: args.limit]
        out_dir = Path(root) / "trajectories"
        out_dir.mkdir(parents=True, exist_ok=True)
        for row in todo:
            try:
                clip_bytes = read_bytes(f"{root}/clips/{row.clip_uuid}.mp4")
            except FileNotFoundError:
                continue
            frames = extract_frames_at_fps(clip_bytes, target_fps=4.0, resize_hw=(128, 128))
            if frames.shape[0] < 2:
                continue
            traj = estimate_trajectory(frames)
            np.save(out_dir / f"{row.clip_uuid}.npy", traj["positions"])
            ego = derive_ego_tags(traj["positions"], fps=4.0)
            tag_rows.append(
                ClipTagRow(
                    clip_uuid=row.clip_uuid,
                    version=CAPTION_VERSION,
                    run_uuid=run_uuid,
                    **ego,
                )
            )
            stats.append(
                {
                    "clip_uuid": row.clip_uuid,
                    "camera": row.camera,
                    "path_length": traj["path_length"],
                    "net_displacement": traj["net_displacement"],
                    "motion_class": traj["motion_class"],
                    **ego,
                }
            )
        (Path(root) / "trajectories" / "stats.json").write_text(json.dumps(stats, indent=1))
        db.add_clip_tags(tag_rows)
        if tag_rows:
            db.add_run(
                RunRow(
                    run_uuid=run_uuid,
                    run_type="trajectory",
                    pipeline_version=__version__,
                )
            )
        return {
            "num_trajectories": len(stats),
            "num_clip_tags": len(tag_rows),
            "elapsed_s": time_mod.monotonic() - t0,
        }
    finally:
        db.close()
