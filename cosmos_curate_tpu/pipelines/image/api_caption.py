"""API-backed image captioning via OpenAI-compatible chat endpoints.

Equivalent capability of the reference's API caption stages
(cosmos_curate/pipelines/image/captioning/image_api_caption_stages.py:234-593
— ImageOpenAIPrepStage / ImageOpenAICaptionStage / ImageGeminiCaptionStage:
caption through a hosted multimodal endpoint instead of the local model).
One stage speaking the OpenAI chat-completions dialect covers any
compatible server (hosted APIs, vLLM/llama.cpp serving, a gateway in front
of Gemini). stdlib urllib only; concurrency via a small thread pool;
per-image retry with backoff; failures recorded per task, never fatal.
"""

from __future__ import annotations

import base64
import json
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.pipelines.image.annotate import ImageTask
from cosmos_curate_tpu.storage.retry import sleep_backoff
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class ImageApiCaptionStage(Stage[ImageTask, ImageTask]):
    def __init__(
        self,
        *,
        base_url: str,
        model: str = "default",
        api_key: str = "",
        prompt: str = "Describe this image in one detailed sentence.",
        max_tokens: int = 128,
        timeout_s: float = 60.0,
        max_retries: int = 3,
        concurrency: int = 4,
    ) -> None:
        # accept both conventions: a server root or an OpenAI-SDK-style
        # base_url already ending in /v1
        self.base_url = base_url.rstrip("/").removesuffix("/v1")
        self.model_name = model
        self.api_key = api_key
        self.prompt = prompt
        self.max_tokens = max_tokens
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.concurrency = max(1, concurrency)

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0)

    _MEDIA_TYPES = {
        ".png": "image/png",
        ".webp": "image/webp",
        ".bmp": "image/bmp",
        ".jpg": "image/jpeg",
        ".jpeg": "image/jpeg",
    }

    def _payload(self, task: ImageTask) -> bytes:
        suffix = "." + task.path.rsplit(".", 1)[-1].lower() if "." in task.path else ""
        media = self._MEDIA_TYPES.get(suffix, "image/jpeg")
        b64 = base64.b64encode(task.raw_bytes or b"").decode()
        return json.dumps(
            {
                "model": self.model_name,
                "max_tokens": self.max_tokens,
                "messages": [
                    {
                        "role": "user",
                        "content": [
                            {"type": "text", "text": self.prompt},
                            {
                                "type": "image_url",
                                "image_url": {"url": f"data:{media};base64,{b64}"},
                            },
                        ],
                    }
                ],
            }
        ).encode()

    def _caption_one(self, task: ImageTask) -> None:
        url = f"{self.base_url}/v1/chat/completions"
        payload = self._payload(task)
        last: Exception | None = None
        for attempt in range(self.max_retries):
            req = urllib.request.Request(
                url, data=payload, method="POST",
                headers={"content-type": "application/json"},
            )
            if self.api_key:
                req.add_header("authorization", f"Bearer {self.api_key}")
            try:
                with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                    body = json.loads(resp.read())
                task.caption = body["choices"][0]["message"]["content"].strip()
                return
            except urllib.error.HTTPError as e:
                last = e
                if e.code not in (429, 500, 502, 503, 504):
                    break  # 4xx won't heal on retry
            except (
                urllib.error.URLError,
                ConnectionError,
                TimeoutError,
                # malformed 200 bodies: non-JSON, empty choices, null message
                ValueError,
                KeyError,
                IndexError,
                TypeError,
                AttributeError,
            ) as e:
                last = e
            if attempt + 1 < self.max_retries:
                sleep_backoff(attempt)
        task.errors["api_caption"] = repr(last)

    def process_data(self, tasks: list[ImageTask]) -> list[ImageTask]:
        live = [t for t in tasks if t.raw_bytes is not None and not t.filtered_by]
        if not live:
            return tasks
        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            list(pool.map(self._caption_one, live))
        done = sum(1 for t in live if t.caption)
        if done < len(live):
            logger.warning(
                "api captioning: %d/%d images failed", len(live) - done, len(live)
            )
        return tasks
