"""Image semantic filter + classifier stages.

Equivalent capability of the reference's image filtering
(cosmos_curate/pipelines/image/filtering/filter_stages.py:54
``ImageSemanticFilterStage`` — rejects images whose VLM filter-caption
matches rejection criteria — and :137 ``ImageClassifierStage`` — assigns a
class label parsed from a VLM answer). Both run on the shared caption
engine like the video twins (pipelines/video/stages/semantic_filter.py).
"""

from __future__ import annotations

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.models.prompts import SEMANTIC_FILTER_PROMPTS
from cosmos_curate_tpu.models.tokenizer import default_caption_tokenizer
from cosmos_curate_tpu.models.vlm import CaptionRequest, SamplingConfig, VLM_BASE, VLMConfig
from cosmos_curate_tpu.pipelines.image.annotate import ImageTask
from cosmos_curate_tpu.pipelines.video.stages.captioning import _CaptionVLM
from cosmos_curate_tpu.pipelines.video.stages.semantic_filter import parse_yes_no


class ImageSemanticFilterStage(Stage[ImageTask, ImageTask]):
    """Marks images the VLM answers 'no' for as filtered (or scores only)."""

    def __init__(
        self,
        *,
        prompt_variant: str = "image-default",
        user_prompt: str | None = None,
        cfg: VLMConfig = VLM_BASE,
        max_batch: int = 8,
        score_only: bool = False,
        keep_on_unparseable: bool = True,
    ) -> None:
        self.prompt = user_prompt or SEMANTIC_FILTER_PROMPTS[prompt_variant]
        self.score_only = score_only
        self.keep_on_unparseable = keep_on_unparseable
        self._model = _CaptionVLM(cfg, max_batch)
        self.tokenizer = default_caption_tokenizer()

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, entire_tpu_host=True)

    def process_data(self, tasks: list[ImageTask]) -> list[ImageTask]:
        engine = self._model.engine
        assert engine is not None, "setup() not called"
        targets: dict[str, ImageTask] = {}
        for t in tasks:
            if t.pixels is None or t.filtered_by:
                continue
            targets[t.path] = t
            engine.add_request(
                CaptionRequest(
                    request_id=t.path,
                    prompt_ids=self.tokenizer.encode(self.prompt),
                    frames=t.pixels[None],
                    sampling=SamplingConfig(max_new_tokens=8),
                )
            )
        if not targets:
            return tasks
        for res in engine.run_until_complete():
            t = targets.get(res.request_id)
            if t is None:
                continue
            verdict = parse_yes_no(res.text)
            t.semantic_pass = verdict  # recorded even in score-only mode
            keep = verdict if verdict is not None else self.keep_on_unparseable
            if not self.score_only and not keep:
                t.filtered_by = "semantic"
        return tasks


class ImageClassifierStage(Stage[ImageTask, ImageTask]):
    """Assigns ``task.label`` from a label set via a VLM answer (reference
    ImageClassifierStage capability)."""

    def __init__(
        self,
        labels: tuple[str, ...] = ("photo", "illustration", "screenshot", "document"),
        *,
        cfg: VLMConfig = VLM_BASE,
        max_batch: int = 8,
        unknown_label: str = "unknown",
    ) -> None:
        if not labels:
            raise ValueError("labels must be non-empty")
        self.labels = labels
        self.unknown_label = unknown_label
        self.prompt = (
            "Classify this image into exactly one of these categories: "
            + ", ".join(labels)
            + ". Answer with only the category name."
        )
        self._model = _CaptionVLM(cfg, max_batch)
        self.tokenizer = default_caption_tokenizer()

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, entire_tpu_host=True)

    def parse_label(self, text: str) -> str:
        t = text.strip().lower()
        # exact answer first; then longest label first, so 'clip art' isn't
        # shadowed by its substring 'art'
        for label in self.labels:
            if t == label.lower():
                return label
        for label in sorted(self.labels, key=len, reverse=True):
            if label.lower() in t:
                return label
        return self.unknown_label

    def process_data(self, tasks: list[ImageTask]) -> list[ImageTask]:
        engine = self._model.engine
        assert engine is not None, "setup() not called"
        targets: dict[str, ImageTask] = {}
        for t in tasks:
            if t.pixels is None or t.filtered_by:
                continue
            targets[t.path] = t
            engine.add_request(
                CaptionRequest(
                    request_id=t.path,
                    prompt_ids=self.tokenizer.encode(self.prompt),
                    frames=t.pixels[None],
                    sampling=SamplingConfig(max_new_tokens=12),
                )
            )
        if not targets:
            return tasks
        for res in engine.run_until_complete():
            t = targets.get(res.request_id)
            if t is not None:
                t.label = self.parse_label(res.text)
        return tasks
