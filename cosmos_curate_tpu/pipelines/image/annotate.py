"""Image annotate pipeline: curation for still images.

Equivalent capability of the reference's image pipeline
(cosmos_curate/pipelines/image/: run_pipeline.py → annotate_pipeline.py,
stages image_embedding_stages.py:45-286, filter_stages.py:54/137,
image_vllm_stages.py:330/418, ImageWriterStage): load → embed (CLIP) →
aesthetic filter → [semantic filter] → [caption] → write, on the same
CuratorStage machinery as the video pipelines — the stages below run
unchanged on the SequentialRunner or the streaming engine.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.core.pipeline import PipelineConfig, run_pipeline
from cosmos_curate_tpu.core.runner import RunnerInterface
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.core.tasks import PipelineTask
from cosmos_curate_tpu.models.clip import AestheticScorer, CLIPImageEmbeddings
from cosmos_curate_tpu.models.prompts import get_caption_prompt
from cosmos_curate_tpu.models.tokenizer import default_caption_tokenizer
from cosmos_curate_tpu.models.vlm import CaptionRequest, SamplingConfig, VLM_BASE, VLMConfig
from cosmos_curate_tpu.pipelines.video.stages.captioning import _CaptionVLM
from cosmos_curate_tpu.storage.client import get_storage_client, read_bytes, write_bytes
from cosmos_curate_tpu.storage.writers import write_json, write_parquet
from cosmos_curate_tpu.utils.logging import get_logger
from cosmos_curate_tpu.utils.summary import write_summary

logger = get_logger(__name__)

IMAGE_SUFFIXES = (".jpg", ".jpeg", ".png", ".webp", ".bmp")


@dataclass
class ImageTask(PipelineTask):
    path: str = ""
    raw_bytes: bytes | None = None
    pixels: np.ndarray | None = None  # uint8 [H, W, 3] RGB
    width: int = 0
    height: int = 0
    embedding: np.ndarray | None = None
    aesthetic_score: float | None = None
    caption: str = ""
    label: str = ""
    semantic_pass: bool | None = None
    filtered_by: str = ""
    errors: dict[str, str] = field(default_factory=dict)


class ImageLoadStage(Stage[ImageTask, ImageTask]):
    @property
    def resources(self) -> Resources:
        return Resources(cpus=0.25)

    def process_data(self, tasks: list[ImageTask]) -> list[ImageTask]:
        import cv2

        for t in tasks:
            try:
                t.raw_bytes = read_bytes(t.path)
                bgr = cv2.imdecode(np.frombuffer(t.raw_bytes, np.uint8), cv2.IMREAD_COLOR)
                if bgr is None:
                    t.errors["load"] = "undecodable image"
                    continue
                t.pixels = cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB)
                t.height, t.width = t.pixels.shape[:2]
            except Exception as e:
                t.errors["load"] = str(e)
        return tasks


class ImageEmbeddingStage(Stage[ImageTask, ImageTask]):
    def __init__(self, *, clip_variant: str = "clip-vit-b16-tpu", resize_hw=(224, 224)) -> None:
        self._model = CLIPImageEmbeddings(clip_variant)
        self.resize_hw = resize_hw

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=1.0)

    @property
    def batch_size(self) -> int:
        return 32

    def process_data(self, tasks: list[ImageTask]) -> list[ImageTask]:
        import cv2

        live = [t for t in tasks if t.pixels is not None]
        if live:
            batch = np.stack(
                [cv2.resize(t.pixels, self.resize_hw[::-1], interpolation=cv2.INTER_AREA) for t in live]
            )
            embs = self._model.encode_frames(batch)
            for t, e in zip(live, embs):
                t.embedding = e
        return tasks


class ImageVideoEmbeddingStage(Stage[ImageTask, ImageTask]):
    """Embeds stills through the temporal video embedder by repeating the
    frame (reference ImageCosmosEmbed1EmbeddingStage /
    ImageInternVideo2EmbeddingStage, image_embedding_stages.py:45/132 — the
    video-embedding space shared between clips and images enables joint
    dedup/search across both)."""

    def __init__(self, *, variant: str = "video", video_cfg=None) -> None:
        from cosmos_curate_tpu.models.embedder import VIDEO_EMBED_VARIANTS, VideoEmbedder

        if video_cfg is not None:
            self._model = VideoEmbedder(video_cfg)
        else:
            if variant not in VIDEO_EMBED_VARIANTS:
                raise ValueError(
                    f"unknown embedder variant {variant!r}; have {sorted(VIDEO_EMBED_VARIANTS)}"
                )
            cfg, model_id = VIDEO_EMBED_VARIANTS[variant]
            self._model = VideoEmbedder(cfg, model_id=model_id)

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=1.0)

    @property
    def batch_size(self) -> int:
        return 16

    def process_data(self, tasks: list[ImageTask]) -> list[ImageTask]:
        import cv2

        live = [t for t in tasks if t.pixels is not None]
        if not live:
            return tasks
        n_frames = self._model.cfg.num_frames
        s = self._model.cfg.vit.image_size
        frames = np.stack(
            [
                np.repeat(
                    cv2.resize(t.pixels, (s, s), interpolation=cv2.INTER_AREA)[None],
                    n_frames,
                    axis=0,
                )
                for t in live
            ]
        )
        embs = self._model.encode_clips(frames)
        for t, e in zip(live, embs):
            t.embedding = e
        return tasks


class ImageAestheticFilterStage(Stage[ImageTask, ImageTask]):
    def __init__(self, *, threshold: float = 3.5, score_only: bool = False, embedding_dim: int = 512) -> None:
        self.threshold = threshold
        self.score_only = score_only
        self._model = AestheticScorer(embedding_dim)

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=0.5, tpus=0.25)

    @property
    def batch_size(self) -> int:
        return 32

    def process_data(self, tasks: list[ImageTask]) -> list[ImageTask]:
        live = [t for t in tasks if t.embedding is not None]
        if live:
            scores = self._model.score(np.stack([t.embedding for t in live]))
            for t, s in zip(live, scores):
                t.aesthetic_score = float(s)
                if not self.score_only and t.aesthetic_score < self.threshold:
                    t.filtered_by = "aesthetic"
        return tasks


class ImageCaptionStage(Stage[ImageTask, ImageTask]):
    def __init__(
        self,
        *,
        prompt_variant: str = "short",
        cfg: VLMConfig = VLM_BASE,
        max_batch: int = 8,
        max_new_tokens: int = 64,
    ) -> None:
        self.prompt_text = get_caption_prompt(prompt_variant)
        self.max_new_tokens = max_new_tokens
        self._model = _CaptionVLM(cfg, max_batch)
        self.tokenizer = default_caption_tokenizer()

    @property
    def model(self) -> ModelInterface:
        return self._model

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, entire_tpu_host=True)

    def process_data(self, tasks: list[ImageTask]) -> list[ImageTask]:
        engine = self._model.engine
        assert engine is not None
        targets = {}
        for t in tasks:
            if t.pixels is None or t.filtered_by:
                continue
            targets[t.path] = t
            engine.add_request(
                CaptionRequest(
                    request_id=t.path,
                    prompt_ids=self.tokenizer.encode(self.prompt_text),
                    frames=t.pixels[None],
                    sampling=SamplingConfig(max_new_tokens=self.max_new_tokens),
                )
            )
        if targets:
            for res in engine.run_until_complete():
                if res.request_id in targets:
                    targets[res.request_id].caption = res.text
        return tasks


class ImageWriterStage(Stage[ImageTask, ImageTask]):
    def __init__(self, output_path: str) -> None:
        self.output_path = output_path.rstrip("/")

    @property
    def resources(self) -> Resources:
        return Resources(cpus=0.5)

    def process_data(self, tasks: list[ImageTask]) -> list[ImageTask]:
        import hashlib

        rows = []
        for t in tasks:
            iid = hashlib.sha256(t.path.encode()).hexdigest()[:24]
            meta = {
                "id": iid,
                "path": t.path,
                "width": t.width,
                "height": t.height,
                "aesthetic_score": t.aesthetic_score,
                "caption": t.caption,
                "label": t.label,
                "semantic_pass": t.semantic_pass,
                "filtered_by": t.filtered_by,
                "errors": t.errors,
            }
            write_json(f"{self.output_path}/metas/{iid}.json", meta)
            if not t.filtered_by and t.raw_bytes and not t.errors:
                write_bytes(
                    f"{self.output_path}/images/{iid}{_suffix(t.path)}", t.raw_bytes
                )
            if t.embedding is not None:
                rows.append((iid, t.embedding))
            if not t.errors:
                # resume record certifies completed processing; errored
                # images (possibly transient IO) must be retried on re-run
                write_json(f"{self.output_path}/processed_images/{iid}.json", {"path": t.path})
            t.raw_bytes = None
            t.pixels = None
        if rows:
            import uuid as uuid_mod

            write_parquet(
                f"{self.output_path}/embeddings/clip/{uuid_mod.uuid4().hex[:12]}.parquet",
                {
                    "image_id": [r[0] for r in rows],
                    "embedding": [r[1].astype(np.float32).tolist() for r in rows],
                },
            )
        return tasks


def _suffix(path: str) -> str:
    from pathlib import PurePath

    return PurePath(path).suffix.lower() or ".jpg"


@dataclass
class ImagePipelineArgs:
    input_path: str = ""
    output_path: str = ""
    limit: int = 0
    aesthetic_threshold: float | None = None
    captioning: bool = False
    caption_prompt_variant: str = "short"
    # VLM semantic filter (reference ImageSemanticFilterStage)
    semantic_filter: str = "disable"  # disable | score-only | enable
    semantic_filter_prompt: str | None = None
    # VLM classifier (reference ImageClassifierStage); empty = off
    classifier_labels: tuple[str, ...] = ()
    # OpenAI-compatible API captioning instead of the local engine
    api_caption_url: str = ""
    api_caption_model: str = "default"
    api_caption_key: str = ""  # falls back to $CURATE_API_KEY


def discover_image_tasks(input_path: str, output_path: str | None = None, *, limit: int = 0):
    import hashlib
    import json as json_mod

    client = get_storage_client(input_path)
    done: set[str] = set()
    if output_path:
        prefix = f"{output_path.rstrip('/')}/processed_images"
        for info in client.list_files(prefix, suffixes=(".json",)):
            try:
                done.add(json_mod.loads(read_bytes(info.path))["path"])
            except Exception:
                pass
    tasks = []
    for info in client.list_files(input_path, suffixes=IMAGE_SUFFIXES):
        if info.path in done:
            continue
        tasks.append(ImageTask(path=info.path))
        if limit and len(tasks) >= limit:
            break
    logger.info("discovered %d images under %s (%d done)", len(tasks), input_path, len(done))
    return tasks


def run_image_annotate(
    args: ImagePipelineArgs,
    *,
    runner: RunnerInterface | None = None,
    config: PipelineConfig | None = None,
    extra_stages: list[Stage] | None = None,
) -> dict:
    t0 = time.monotonic()
    tasks = discover_image_tasks(args.input_path, args.output_path, limit=args.limit)
    stages: list[Stage] = [ImageLoadStage(), ImageEmbeddingStage()]
    if args.aesthetic_threshold is not None:
        stages.append(ImageAestheticFilterStage(threshold=args.aesthetic_threshold))
    if args.semantic_filter != "disable":
        from cosmos_curate_tpu.pipelines.image.filters import ImageSemanticFilterStage

        stages.append(
            ImageSemanticFilterStage(
                user_prompt=args.semantic_filter_prompt,
                score_only=args.semantic_filter == "score-only",
            )
        )
    if args.classifier_labels:
        from cosmos_curate_tpu.pipelines.image.filters import ImageClassifierStage

        stages.append(ImageClassifierStage(labels=args.classifier_labels))
    if args.api_caption_url:
        from cosmos_curate_tpu.pipelines.image.api_caption import ImageApiCaptionStage

        import os

        stages.append(
            ImageApiCaptionStage(
                base_url=args.api_caption_url,
                model=args.api_caption_model,
                api_key=args.api_caption_key or os.environ.get("CURATE_API_KEY", ""),
            )
        )
    elif args.captioning:
        stages.append(ImageCaptionStage(prompt_variant=args.caption_prompt_variant))
    stages.extend(extra_stages or [])
    stages.append(ImageWriterStage(args.output_path))
    out = run_pipeline(tasks, stages, config=config, runner=runner) or []
    elapsed = time.monotonic() - t0
    summary = {
        "num_images": len(out),
        "num_embedded": sum(1 for t in out if t.embedding is not None),
        "num_filtered": sum(1 for t in out if t.filtered_by),
        "num_captioned": sum(1 for t in out if t.caption),
        "num_errors": sum(len(t.errors) for t in out),
        "pipeline_run_time_s": elapsed,
    }
    write_summary(f"{args.output_path.rstrip('/')}/summary.json", summary)
    logger.info("image annotate done: %s", summary)
    return summary
