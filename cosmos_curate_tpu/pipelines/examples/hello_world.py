"""Hello-world pipeline: the smallest end-to-end example.

Equivalent of the reference's hello-world example
(cosmos_curate/pipelines/examples/hello_world_pipeline.py): a CPU stage
uppercases text, then a tiny JAX model stage (GPT2-class scoring is the
reference's demo; ours runs a jitted token-sum "model" so the example works
on any device including a real TPU) annotates each task.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from cosmos_curate_tpu.core.pipeline import run_pipeline
from cosmos_curate_tpu.core.runner import RunnerInterface, SequentialRunner
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.core.tasks import PipelineTask


@dataclass
class HelloTask(PipelineTask):
    text: str = ""
    score: float | None = None
    device: str = ""


class UppercaseStage(Stage[HelloTask, HelloTask]):
    @property
    def resources(self) -> Resources:
        return Resources(cpus=0.5)

    def process_data(self, tasks: list[HelloTask]) -> list[HelloTask]:
        for t in tasks:
            t.text = t.text.upper()
        return tasks


class JaxScoreStage(Stage[HelloTask, HelloTask]):
    """Scores text with a jitted device computation (demo of the device
    boundary: host bytes -> device array -> jit -> host scalar)."""

    def __init__(self) -> None:
        self._fn = None

    @property
    def resources(self) -> Resources:
        return Resources(cpus=1.0, tpus=1.0)

    @property
    def batch_size(self) -> int:
        return 8

    def setup(self, worker) -> None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def score(tokens):
            return jnp.tanh(tokens.astype(jnp.float32) / 128.0).mean(axis=-1)

        self._fn = score
        self._device = jax.devices()[0].platform

    def process_data(self, tasks: list[HelloTask]) -> list[HelloTask]:
        import numpy as np

        batch = np.zeros((len(tasks), 64), np.uint8)
        for i, t in enumerate(tasks):
            raw = t.text.encode()[:64]
            batch[i, : len(raw)] = np.frombuffer(raw, np.uint8)
        scores = np.asarray(self._fn(batch))
        for t, s in zip(tasks, scores):
            t.score = float(s)
            t.device = self._device
        return tasks


def run_hello_world(
    texts: list[str] | None = None, runner: RunnerInterface | None = None
) -> list[HelloTask]:
    texts = texts or [f"hello world {i}" for i in range(10)]
    tasks = [HelloTask(text=t) for t in texts]
    out = run_pipeline(
        tasks,
        [UppercaseStage(), JaxScoreStage()],
        runner=runner or SequentialRunner(),
    )
    return out or []
