"""Dynamic task-chunking demo pipeline.

Equivalent of the reference's chunking demo
(cosmos_curate/pipelines/examples/demo_task_chunking_pipeline.py:58-73):
shows a stage emitting a different number of tasks than it received — the
mechanism that bounds memory on multi-hour videos (one video task → N
clip-chunk tasks) — and a downstream stage consuming the chunks.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from cosmos_curate_tpu.core.pipeline import run_pipeline
from cosmos_curate_tpu.core.runner import RunnerInterface, SequentialRunner
from cosmos_curate_tpu.core.stage import Resources, Stage
from cosmos_curate_tpu.core.tasks import PipelineTask


@dataclass
class WorkItem(PipelineTask):
    name: str = ""
    payload: list = field(default_factory=list)
    chunk_index: int = 0
    num_chunks: int = 1

    @property
    def fraction(self) -> float:
        return 1.0 / max(1, self.num_chunks)


class ProduceStage(Stage):
    """Emits one big task per input (simulating a long video's clip list)."""

    def __init__(self, items_per_task: int = 100):
        self.items_per_task = items_per_task

    def process_data(self, tasks):
        return [
            WorkItem(name=t.name, payload=list(range(self.items_per_task)))
            for t in tasks
        ]


class ChunkStage(Stage):
    """Dynamic chunking: one task in → ceil(len/chunk) tasks out."""

    def __init__(self, chunk_size: int = 16):
        self.chunk_size = chunk_size

    @property
    def resources(self) -> Resources:
        return Resources(cpus=0.25)

    def process_data(self, tasks):
        out = []
        for t in tasks:
            chunks = [
                t.payload[i : i + self.chunk_size]
                for i in range(0, len(t.payload), self.chunk_size)
            ]
            for i, chunk in enumerate(chunks):
                out.append(
                    WorkItem(name=t.name, payload=chunk, chunk_index=i, num_chunks=len(chunks))
                )
        return out


class SumStage(Stage):
    def process_data(self, tasks):
        for t in tasks:
            t.payload = [sum(t.payload)]
        return tasks


def run_chunking_demo(
    num_inputs: int = 3, runner: RunnerInterface | None = None
) -> list[WorkItem]:
    tasks = [WorkItem(name=f"video_{i}") for i in range(num_inputs)]
    out = run_pipeline(
        tasks,
        [ProduceStage(), ChunkStage(), SumStage()],
        runner=runner or SequentialRunner(),
    )
    return out or []
