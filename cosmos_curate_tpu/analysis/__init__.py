"""Static analysis for cosmos-curate-tpu: build-time correctness tooling.

Two complementary passes (both surfaced through ``cosmos-curate-tpu lint``
and ``scripts/run_static_checks.sh``):

- ``graph_lint``: semantic validation of a ``PipelineSpec`` before any
  worker spawns — stage-to-stage task-type flow, duplicate stage names,
  STREAMING-mode resource feasibility, contradictory resource requests.
  Wired into ``run_pipeline`` as an on-by-default pre-flight.
- ``ast_lint``: a rule-driven AST checker over the package source encoding
  this repo's real hazard classes (lock discipline in the engine, stdlib
  calls newer than the interpreter floor, host transfers under ``jax.jit``,
  silent exception swallowing in worker loops). Rules live in
  ``analysis/rules/`` and are configured via ``[tool.curate-lint]`` in
  ``pyproject.toml``.
"""

from cosmos_curate_tpu.analysis.common import Finding, LintConfig, Severity
from cosmos_curate_tpu.analysis.graph_lint import (
    PipelineValidationError,
    lint_pipeline_spec,
    validate_pipeline_spec,
)

__all__ = [
    "Finding",
    "LintConfig",
    "Severity",
    "PipelineValidationError",
    "lint_pipeline_spec",
    "validate_pipeline_spec",
]
