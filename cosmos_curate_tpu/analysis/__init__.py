"""Static analysis for cosmos-curate-tpu: build-time correctness tooling.

Three complementary passes (all surfaced through ``cosmos-curate-tpu lint``
and ``scripts/run_static_checks.sh``):

- ``graph_lint``: semantic validation of a ``PipelineSpec`` before any
  worker spawns — stage-to-stage task-type flow, duplicate stage names,
  STREAMING-mode resource feasibility, contradictory resource requests,
  and mesh-divisibility of stage-declared ``MeshSpec``\\ s against
  ``ClusterShape.num_tpu_chips``. Wired into ``run_pipeline`` as an
  on-by-default pre-flight.
- ``ast_lint``: a rule-driven AST checker over the package source encoding
  this repo's real hazard classes (lock discipline in the engine, stdlib
  calls newer than the interpreter floor, host transfers under ``jax.jit``,
  silent exception swallowing in worker loops, mesh-axis literals outside
  the parallel/axes.py registry, hardcoded device counts,
  with_sharding_constraint outside jit). Rules live in ``analysis/rules/``
  and are configured via ``[tool.curate-lint]`` in ``pyproject.toml``.
- ``shard_check``: device-free verification of the TPU sharding layer —
  ``jax.eval_shape`` over an ``AbstractMesh`` validates every registered
  sharded entry point's ``PartitionSpec``/``shard_map`` axis names,
  divisibility and replicated-params HBM budget with zero device
  allocation (``lint --shard-check``).
"""

from cosmos_curate_tpu.analysis.common import Finding, LintConfig, Severity
from cosmos_curate_tpu.analysis.graph_lint import (
    PipelineValidationError,
    lint_pipeline_spec,
    validate_pipeline_spec,
)
from cosmos_curate_tpu.analysis.shard_check import (
    AbstractInput,
    ShardContract,
    mesh_tiling_errors,
    parse_mesh_spec,
    run_shard_check,
)

__all__ = [
    "AbstractInput",
    "Finding",
    "LintConfig",
    "Severity",
    "PipelineValidationError",
    "ShardContract",
    "lint_pipeline_spec",
    "mesh_tiling_errors",
    "parse_mesh_spec",
    "run_shard_check",
    "validate_pipeline_spec",
]
