"""Shared vocabulary for both analysis passes: findings, severities,
suppression comments, and the ``[tool.curate-lint]`` config loaded from
``pyproject.toml``.

The config loader must run on the 3.10 floor, where ``tomllib`` does not
exist; it prefers ``tomllib`` when available and otherwise falls back to a
minimal parser that understands exactly the subset ``pyproject.toml`` uses
here (table headers, string values, flat string lists).
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field
from pathlib import Path


class Severity(enum.Enum):
    ERROR = "error"
    WARNING = "warning"

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        return self.value


@dataclass(frozen=True)
class Finding:
    """One diagnostic, formatted as ``file:line rule-id message``."""

    file: str
    line: int
    rule: str
    message: str
    severity: Severity = Severity.ERROR

    def render(self) -> str:
        return f"{self.file}:{self.line} {self.rule} {self.message}"

    def to_json(self) -> str:
        """One NDJSON line (the ``lint --json`` machine interface, shared
        by every pillar — AST rules, graph, shardcheck, concurrency — so
        CI annotators never parse the human rendering)."""
        import json

        return json.dumps(
            {
                "rule": self.rule,
                "file": self.file,
                "line": self.line,
                "severity": self.severity.value,
                "message": self.message,
            },
            sort_keys=True,
        )


# -- suppression comments ---------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*curate-lint:\s*disable(?P<scope>-file)?=(?P<rules>[\w\-,* ]+)")


def parse_suppressions(source: str) -> tuple[dict[int, set[str]], set[str]]:
    """-> (line -> suppressed rule ids, file-wide suppressed rule ids).

    ``# curate-lint: disable=<rule>[,<rule>...]`` suppresses matching
    findings on its own line and, when the comment stands alone, on the
    next line (so a suppression can sit above the flagged statement).
    ``# curate-lint: disable-file=<rule>`` anywhere suppresses the rule for
    the whole file. ``all`` (or ``*``) matches every rule.
    """
    per_line: dict[int, set[str]] = {}
    file_wide: set[str] = set()
    for lineno, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group("rules").split(",") if r.strip()}
        rules = {"all" if r == "*" else r for r in rules}
        if m.group("scope"):
            file_wide |= rules
            continue
        per_line.setdefault(lineno, set()).update(rules)
        if text[: m.start()].strip() == "":  # comment-only line: covers the next one
            per_line.setdefault(lineno + 1, set()).update(rules)
    return per_line, file_wide


def is_suppressed(
    finding: Finding, per_line: dict[int, set[str]], file_wide: set[str]
) -> bool:
    for rules in (file_wide, per_line.get(finding.line, set())):
        if "all" in rules or finding.rule in rules:
            return True
    return False


# -- configuration ----------------------------------------------------------


@dataclass
class LintConfig:
    enable: list[str] = field(default_factory=list)  # empty = all rules
    disable: list[str] = field(default_factory=list)
    exclude: list[str] = field(default_factory=list)
    # (major, minor) interpreter floor for the min-python rule.
    python_floor: tuple[int, int] = (3, 10)
    # shardcheck defaults (analysis/shard_check.py): the mesh extents the
    # pass resolves specs against ("data=2,seq=2"; unnamed axes = 1) and
    # the per-device HBM budget for the replicated-params estimate
    # (0 disables the budget warning).
    shard_mesh: str = ""
    shard_hbm_gb: float = 0.0

    def rule_enabled(self, rule_id: str) -> bool:
        if rule_id in self.disable:
            return False
        return not self.enable or rule_id in self.enable


_FLOOR_RE = re.compile(r">=\s*(\d+)\.(\d+)")


def _parse_floor(spec: str) -> tuple[int, int] | None:
    m = _FLOOR_RE.search(spec or "")
    if m:
        return int(m.group(1)), int(m.group(2))
    m = re.fullmatch(r"\s*(\d+)\.(\d+)\s*", spec or "")
    if m:
        return int(m.group(1)), int(m.group(2))
    return None


def _toml_tables(text: str) -> dict[str, dict[str, object]]:
    """Fallback TOML subset parser: ``[table]`` headers, ``key = value``
    with string / flat string-list / number / bool values. Enough for the
    two fields the linter reads; anything fancier should come through
    ``tomllib`` on 3.11+."""
    tables: dict[str, dict[str, object]] = {}
    current: dict[str, object] = tables.setdefault("", {})
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            name = line.strip("[]").strip().strip('"')
            current = tables.setdefault(name, {})
            continue
        m = re.match(r"([\w\-\.\"]+)\s*=\s*(.+)$", line)
        if not m:
            continue
        key = m.group(1).strip('"')
        val = m.group(2).strip()
        # strip a trailing comment outside of quotes/brackets (best effort)
        if "#" in val and not val.startswith(("'", '"', "[")):
            val = val.split("#", 1)[0].strip()
        if val.startswith("[") and val.endswith("]"):
            items = re.findall(r"""["']([^"']*)["']""", val)
            current[key] = items
        elif val and val[0] in "\"'":
            current[key] = val[1:-1]
        elif val in ("true", "false"):
            current[key] = val == "true"
        else:
            try:
                current[key] = float(val) if "." in val else int(val)
            except ValueError:
                current[key] = val
    return tables


def _load_pyproject(path: Path) -> dict:
    text = path.read_text(encoding="utf-8")
    try:
        import tomllib  # py3.11+  # curate-lint: disable=min-python

        return tomllib.loads(text)
    except ImportError:
        tables = _toml_tables(text)
        return {
            "project": tables.get("project", {}),
            "tool": {"curate-lint": tables.get("tool.curate-lint", {})},
        }


def find_pyproject(start: Path | None = None) -> Path | None:
    here = (start or Path(__file__)).resolve()
    for parent in [here] + list(here.parents):
        cand = parent / "pyproject.toml"
        if cand.is_file():
            return cand
    return None


def load_config(pyproject: Path | None = None) -> LintConfig:
    """Build a ``LintConfig`` from ``[tool.curate-lint]`` + the
    ``project.requires-python`` floor; missing file/section -> defaults."""
    cfg = LintConfig()
    path = pyproject or find_pyproject()
    if path is None or not path.is_file():
        return cfg
    try:
        data = _load_pyproject(path)
    except (OSError, ValueError):
        return cfg
    floor = _parse_floor(str(data.get("project", {}).get("requires-python", "")))
    if floor:
        cfg.python_floor = floor
    section = data.get("tool", {}).get("curate-lint", {})
    cfg.enable = [str(r) for r in section.get("enable", [])]
    cfg.disable = [str(r) for r in section.get("disable", [])]
    cfg.exclude = [str(p) for p in section.get("exclude", cfg.exclude)]
    override = _parse_floor(str(section.get("python-floor", "")))
    if override:
        cfg.python_floor = override
    cfg.shard_mesh = str(section.get("shard-mesh", cfg.shard_mesh))
    try:
        cfg.shard_hbm_gb = float(section.get("shard-hbm-gb", cfg.shard_hbm_gb))
    except (TypeError, ValueError):
        # leaving 0.0 would silently disable the budget check the user
        # explicitly configured — say why
        from cosmos_curate_tpu.utils.logging import get_logger

        get_logger(__name__).warning(
            "[tool.curate-lint] shard-hbm-gb=%r is not a number; "
            "HBM-budget check disabled",
            section.get("shard-hbm-gb"),
        )
    return cfg
