"""Schema & wire-compat verifier — the fifth lint pillar (``lint --schema``).

Long-lived fleets (ROADMAP item 1) mean version skew is a steady state:
a driver and its agents, or a restarted service and its journal, are
routinely one build apart. Every cross-process or cross-restart format is
therefore a **contract surface**, and this pass makes each one checkable:

Pass 1 — *extract*: build the current schema of every registered surface
straight from the code. Wire frames (``engine/remote_plane.py``
dataclasses) and ``JobRecord`` are introspected with
``dataclasses.fields``; JSON documents (journal envelope, DLQ meta, index
manifests, run_report, live status, node-stats, BENCH rows) are extracted
from the writer's AST — dict literals are required fields, conditional
``doc["k"] = ...`` assignments are optional fields, dynamic keys become an
explicit ``<dynamic>`` marker; the object-channel GET tuple's arity and
element types come from its ``IfExp``.

Pass 2 — *diff*: compare against the checked-in goldens under
``analysis/schemas/`` and classify every drift:

- **additive** (new field/schema) without a version bump →
  ``schema-additive-no-bump`` ERROR: old readers would silently drop the
  field; bump the surface's version so they can tell.
- **breaking** (removal, type change, required-flag change) without a
  bump → ``schema-breaking-no-bump`` ERROR.
- breaking WITH a bump but no registered migration shim for a durable
  surface → ``schema-missing-migration`` ERROR: the bump alone leaves
  version-N−1 records unreadable.
- any drift WITH a proper bump (and shim where required) →
  ``schema-stale-golden`` WARNING: run ``lint --schema --update`` to
  re-snapshot the golden and commit both.
- version going BACKWARDS → ``schema-version-backwards`` ERROR.

Versions come from the two enforcement points, never from this file:
``PROTOCOL_VERSION`` (``engine/remote_plane.py``; skew is rejected at the
Hello/HelloAck handshake) for wire surfaces, and
``utils/schema_stamp.SCHEMA_VERSIONS`` (stamped into every durable
document; readers shim old versions forward) for durable ones. The dynamic
twin of this pass is the skew-fuzz harness in
``tests/analysis/test_schema_check.py`` + ``tests/engine`` version-skew
tests.
"""

from __future__ import annotations

import ast
import dataclasses
import json
from pathlib import Path
from typing import Any, Callable

from cosmos_curate_tpu.analysis.common import Finding, Severity

REPO_ROOT = Path(__file__).resolve().parents[2]
GOLDEN_DIR = Path(__file__).resolve().parent / "schemas"

# the explicit marker for computed keys (f-strings, variables): the golden
# records THAT dynamic keys exist, not what they expand to
DYNAMIC_KEY = "<dynamic>"


# -- schema model ------------------------------------------------------------
#
# A surface schema is plain JSON so goldens diff cleanly in review:
#   {"surface": ..., "kind": "wire"|"durable", "version": N,
#    "schemas": {name: {"fields": {field: {"type": str, "required": bool}}}}}


def _field(type_: str, required: bool) -> dict:
    return {"type": type_, "required": required}


def _infer_type(node: ast.AST | None) -> str:
    """Coarse, deterministic type label for a field's value expression.
    Deliberately conservative: anything not obviously typed is ``any`` so
    refactors that keep the shape do not churn goldens."""
    if node is None:
        return "any"
    if isinstance(node, ast.Constant):
        return type(node.value).__name__
    if isinstance(node, ast.Dict):
        return "dict"
    if isinstance(node, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(node, ast.Tuple):
        return "tuple"
    if isinstance(node, (ast.DictComp, ast.SetComp)):
        return "dict" if isinstance(node, ast.DictComp) else "set"
    if isinstance(node, ast.Compare) or (
        isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not)
    ):
        return "bool"
    if isinstance(node, ast.JoinedStr):
        return "str"
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else ""
        )
        return {
            "round": "float", "float": "float", "int": "int", "len": "int",
            "str": "str", "bool": "bool", "list": "list", "sorted": "list",
            "dict": "dict", "sum": "any", "min": "any", "max": "any",
        }.get(name, "any")
    return "any"


def _merge_field(fields: dict[str, dict], key: str, type_: str, required: bool) -> None:
    """Union of multiple writes to one key: required if ANY unconditional
    write exists; conflicting inferred types widen to ``any``."""
    prev = fields.get(key)
    if prev is None:
        fields[key] = _field(type_, required)
        return
    if prev["type"] != type_:
        fields[key] = _field("any", prev["required"] or required)
    else:
        prev["required"] = prev["required"] or required


# -- AST extraction of dict-shaped documents --------------------------------


def _find_function(tree: ast.Module, func: str, cls: str | None = None) -> ast.AST:
    scope: Any = tree
    if cls is not None:
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == cls:
                scope = node
                break
        else:
            raise LookupError(f"class {cls} not found")
    for node in scope.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name == func:
            return node
    raise LookupError(f"function {func} not found" + (f" in class {cls}" if cls else ""))


def _dict_literal_fields(node: ast.Dict, fields: dict[str, dict], required: bool) -> None:
    for k, v in zip(node.keys, node.values):
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            _merge_field(fields, k.value, _infer_type(v), required)
        elif k is None:
            # **splat: contents unknowable statically
            _merge_field(fields, DYNAMIC_KEY, "any", False)
        else:
            _merge_field(fields, DYNAMIC_KEY, _infer_type(v), False)


def _unwrap_stamp(node: ast.AST, fields: dict[str, dict], required: bool) -> ast.AST:
    """Unwrap ``json.dumps(...)`` and ``schema_stamp.stamp({...}, "s")``
    wrappers (recording the stamp field) so the inner dict literal is
    harvested — the journal writer's idiom is ``json.dumps(stamp({...}))``."""
    while isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) and node.args:
        if node.func.attr == "stamp":
            _merge_field(fields, "schema_version", "int", required)
            node = node.args[0]
        elif node.func.attr == "dumps":
            node = node.args[0]
        else:
            break
    return node


def extract_dict_shape(
    path: Path, func: str, var: str, *, cls: str | None = None
) -> dict[str, dict]:
    """Schema of the dict built in variable ``var`` inside ``func``.

    Rules (the writer idioms this repo actually uses):
    - ``var = {...}`` / ``var.update({...})`` / ``return stamp({...})``
      outside any branch → required fields;
    - the same inside ``if``/``for``/``while``/``except`` → optional;
    - ``var["k"] = ...`` → required or optional by the same nesting test;
    - ``var.setdefault("k", v)`` → required (present after the call);
    - ``var[f"..."] = ...`` or non-constant keys → the ``<dynamic>``
      marker, so the golden records that computed keys exist;
    - ``schema_stamp.stamp(var, "surface")`` → ``schema_version`` field.
    """
    tree = ast.parse(path.read_text(encoding="utf-8"))
    fn = _find_function(tree, func, cls)
    fields: dict[str, dict] = {}

    def value_for(node: ast.AST, required: bool) -> None:
        node = _unwrap_stamp(node, fields, required)
        if isinstance(node, ast.Dict):
            _dict_literal_fields(node, fields, required)
        elif isinstance(node, ast.IfExp):
            # both arms contribute; keys not in both arms stay optional
            for arm in (node.body, node.orelse):
                value_for(arm, False)

    def visit(node: ast.AST, conditional: bool) -> None:
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
            and node is not fn
        ):
            return  # nested defs are other scopes
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == var:
                    value_for(node.value, not conditional)
                elif (
                    isinstance(tgt, ast.Subscript)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == var
                ):
                    key = tgt.slice
                    if isinstance(key, ast.Constant) and isinstance(key.value, str):
                        _merge_field(
                            fields, key.value, _infer_type(node.value), not conditional
                        )
                    else:
                        _merge_field(fields, DYNAMIC_KEY, _infer_type(node.value), False)
        elif isinstance(node, ast.Return) and node.value is not None:
            value_for(node.value, not conditional)
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
                if f.value.id == var and f.attr == "update" and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Dict):
                        _dict_literal_fields(arg, fields, not conditional)
                    else:
                        _merge_field(fields, DYNAMIC_KEY, "any", False)
                elif f.value.id == var and f.attr == "setdefault" and node.args:
                    key = node.args[0]
                    val = node.args[1] if len(node.args) > 1 else None
                    if isinstance(key, ast.Constant) and isinstance(key.value, str):
                        _merge_field(fields, key.value, _infer_type(val), not conditional)
                    else:
                        _merge_field(fields, DYNAMIC_KEY, _infer_type(val), False)
                elif f.attr == "stamp" and any(
                    isinstance(a, ast.Name) and a.id == var for a in node.args
                ):
                    _merge_field(fields, "schema_version", "int", not conditional)
        # branch/loop/handler bodies are conditional; `with` bodies are not
        # (they always execute)
        branch = conditional or isinstance(node, (ast.If, ast.For, ast.While, ast.Try))
        for child in ast.iter_child_nodes(node):
            visit(child, branch)

    for child in ast.iter_child_nodes(fn):
        visit(child, False)
    return {"fields": dict(sorted(fields.items()))}


def extract_stamped_literal(path: Path, func: str, *, cls: str | None = None) -> dict[str, dict]:
    """Schema of the FIRST ``schema_stamp.stamp({literal}, ...)`` call in
    ``func`` — for writers that stamp an inline document (e.g. the index
    MANIFEST.json pointer) rather than building a named variable."""
    tree = ast.parse(path.read_text(encoding="utf-8"))
    fn = _find_function(tree, func, cls)
    fields: dict[str, dict] = {}
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "stamp"
            and node.args
            and isinstance(node.args[0], ast.Dict)
        ):
            _merge_field(fields, "schema_version", "int", True)
            _dict_literal_fields(node.args[0], fields, True)
            break
    if not fields:
        raise LookupError(f"no stamp({{literal}}) call in {func}")
    return {"fields": dict(sorted(fields.items()))}


# -- dataclass + tuple extraction -------------------------------------------


def extract_dataclass(cls: type) -> dict[str, dict]:
    fields: dict[str, dict] = {}
    for f in dataclasses.fields(cls):
        required = (
            f.default is dataclasses.MISSING
            and f.default_factory is dataclasses.MISSING
        )
        fields[f.name] = _field(str(f.type), required)
    return {"fields": dict(sorted(fields.items()))}


def extract_frames(module) -> dict[str, dict]:
    """Every frame in the module's ``WIRE_FRAMES`` registry (frames ride
    cloudpickle, so the class set + field set IS the wire schema). Falls
    back to every dataclass defined in the module when no registry exists."""
    frames = getattr(module, "WIRE_FRAMES", None)
    if frames is None:
        frames = [
            obj
            for _name, obj in sorted(vars(module).items())
            if isinstance(obj, type)
            and dataclasses.is_dataclass(obj)
            and obj.__module__ == module.__name__
        ]
    return {cls.__name__: extract_dataclass(cls) for cls in frames}


def extract_get_tuple(path: Path) -> dict[str, dict]:
    """The object-channel GET request: ``("get", name, nonce, tp) if tp
    else ("get", name, nonce)`` — positional fields, the 4th optional."""
    tree = ast.parse(path.read_text(encoding="utf-8"))
    fn = _find_function(tree, "_open_get")
    for node in ast.walk(fn):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "req"
            and isinstance(node.value, ast.IfExp)
        ):
            arms = [node.value.body, node.value.orelse]
            if not all(isinstance(a, ast.Tuple) for a in arms):
                break
            long = max(arms, key=lambda t: len(t.elts))
            short = min(arms, key=lambda t: len(t.elts))
            fields: dict[str, dict] = {}
            for i, el in enumerate(long.elts):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    type_ = f"str:{el.value}"  # the literal tag is contract
                elif isinstance(el, ast.Name) and el.id == "nonce":
                    type_ = "bytes"
                else:
                    type_ = "str"
                fields[str(i)] = _field(type_, i < len(short.elts))
            return {"get-request": {"fields": fields}}
    raise LookupError("object_channel._open_get request tuple not found")


# -- the registry ------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Surface:
    """One contract surface: where its schema comes from and which version
    constant governs it."""

    name: str
    kind: str  # "wire" | "durable"
    file: str  # repo-relative, for findings
    version: Callable[[], int]
    extract: Callable[[], dict[str, dict]]  # schema name -> {"fields": ...}


def _protocol_version() -> int:
    from cosmos_curate_tpu.engine import remote_plane

    return int(remote_plane.PROTOCOL_VERSION)


def _schema_version(surface: str) -> Callable[[], int]:
    def get() -> int:
        from cosmos_curate_tpu.utils import schema_stamp

        return int(schema_stamp.SCHEMA_VERSIONS[surface])

    return get


def _x_remote_plane() -> dict[str, dict]:
    from cosmos_curate_tpu.engine import remote_plane

    return extract_frames(remote_plane)


def _x_object_channel() -> dict[str, dict]:
    return extract_get_tuple(REPO_ROOT / "cosmos_curate_tpu/engine/object_channel.py")


def _x_job_journal() -> dict[str, dict]:
    from cosmos_curate_tpu.service.job_queue import JobRecord

    p = REPO_ROOT / "cosmos_curate_tpu/service/job_queue.py"
    return {
        "envelope": extract_dict_shape(p, "append", "line", cls="JobJournal"),
        "JobRecord": extract_dataclass(JobRecord),
    }


def _x_dlq_meta() -> dict[str, dict]:
    p = REPO_ROOT / "cosmos_curate_tpu/engine/dead_letter.py"
    return {"meta": extract_dict_shape(p, "record", "meta", cls="DeadLetterQueue")}


def _x_index_manifest() -> dict[str, dict]:
    p = REPO_ROOT / "cosmos_curate_tpu/dedup/index_store.py"
    return {
        "manifest": extract_dict_shape(p, "build_live_manifest", "manifest", cls="IndexStore"),
        "pointer": extract_stamped_literal(p, "publish_manifest", cls="IndexStore"),
    }


def _x_run_report() -> dict[str, dict]:
    p = REPO_ROOT / "cosmos_curate_tpu/observability/flight_recorder.py"
    return {"report": extract_dict_shape(p, "build_run_report", "report")}


def _x_node_stats() -> dict[str, dict]:
    p = REPO_ROOT / "cosmos_curate_tpu/observability/flight_recorder.py"
    return {"stats": extract_dict_shape(p, "write_node_stats", "stats")}


def _x_live_status() -> dict[str, dict]:
    p = REPO_ROOT / "cosmos_curate_tpu/observability/live_status.py"
    return {
        "status": extract_dict_shape(p, "publish", "snapshot", cls="LiveStatusPublisher")
    }


def _x_bench_row() -> dict[str, dict]:
    return {"row": extract_dict_shape(REPO_ROOT / "bench.py", "main", "record")}


SURFACES: tuple[Surface, ...] = (
    Surface(
        "remote-plane", "wire", "cosmos_curate_tpu/engine/remote_plane.py",
        _protocol_version, _x_remote_plane,
    ),
    Surface(
        "object-channel", "wire", "cosmos_curate_tpu/engine/object_channel.py",
        _protocol_version, _x_object_channel,
    ),
    Surface(
        "job-journal", "durable", "cosmos_curate_tpu/service/job_queue.py",
        _schema_version("job-journal"), _x_job_journal,
    ),
    Surface(
        "dlq-meta", "durable", "cosmos_curate_tpu/engine/dead_letter.py",
        _schema_version("dlq-meta"), _x_dlq_meta,
    ),
    Surface(
        "index-manifest", "durable", "cosmos_curate_tpu/dedup/index_store.py",
        _schema_version("index-manifest"), _x_index_manifest,
    ),
    Surface(
        "run-report", "durable", "cosmos_curate_tpu/observability/flight_recorder.py",
        _schema_version("run-report"), _x_run_report,
    ),
    Surface(
        "node-stats", "durable", "cosmos_curate_tpu/observability/flight_recorder.py",
        _schema_version("node-stats"), _x_node_stats,
    ),
    Surface(
        "live-status", "durable", "cosmos_curate_tpu/observability/live_status.py",
        _schema_version("live-status"), _x_live_status,
    ),
    Surface(
        "bench-row", "durable", "bench.py", _schema_version("bench-row"), _x_bench_row,
    ),
)


def extract_surface(surface: Surface) -> dict:
    return {
        "surface": surface.name,
        "kind": surface.kind,
        "version": surface.version(),
        "schemas": surface.extract(),
    }


# -- diffing + drift classification -----------------------------------------


def _diff_schemas(gold: dict, cur: dict) -> tuple[list[str], list[str]]:
    """-> (additive drifts, breaking drifts) as human-readable deltas."""
    additive: list[str] = []
    breaking: list[str] = []
    gold_schemas, cur_schemas = gold.get("schemas", {}), cur.get("schemas", {})
    for name in sorted(set(gold_schemas) | set(cur_schemas)):
        if name not in cur_schemas:
            breaking.append(f"schema {name!r} removed")
            continue
        if name not in gold_schemas:
            additive.append(f"schema {name!r} added")
            continue
        gf = gold_schemas[name].get("fields", {})
        cf = cur_schemas[name].get("fields", {})
        for field_name in sorted(set(gf) | set(cf)):
            if field_name not in cf:
                breaking.append(f"{name}.{field_name} removed")
            elif field_name not in gf:
                additive.append(f"{name}.{field_name} added")
            else:
                g, c = gf[field_name], cf[field_name]
                if g["type"] != c["type"]:
                    breaking.append(
                        f"{name}.{field_name} type {g['type']} -> {c['type']}"
                    )
                if g["required"] != c["required"]:
                    breaking.append(
                        f"{name}.{field_name} "
                        f"{'required -> optional' if g['required'] else 'optional -> required'}"
                    )
    return additive, breaking


def classify_drift(
    surface: Surface,
    gold: dict | None,
    cur: dict,
    *,
    has_migration: Callable[[str, int], bool] | None = None,
) -> list[Finding]:
    """The drift rules (docs/STATIC_ANALYSIS.md, "drift classes"). Pure —
    the seeded-drift tests feed synthetic gold/cur pairs straight in."""
    if has_migration is None:
        from cosmos_curate_tpu.utils import schema_stamp

        has_migration = schema_stamp.has_migration
    f = lambda rule, msg, sev=Severity.ERROR: Finding(  # noqa: E731
        surface.file, 1, rule, f"[{surface.name}] {msg}", sev
    )
    if gold is None:
        return [
            f(
                "schema-missing-golden",
                "no golden snapshot checked in; run `lint --schema --update` "
                "and commit analysis/schemas/",
            )
        ]
    gold_v, cur_v = int(gold.get("version", 1)), int(cur["version"])
    additive, breaking = _diff_schemas(gold, cur)
    if cur_v < gold_v:
        return [
            f(
                "schema-version-backwards",
                f"version went backwards: golden v{gold_v}, code v{cur_v} — "
                "published versions never decrease",
            )
        ]
    if not additive and not breaking:
        if cur_v > gold_v:
            return [
                f(
                    "schema-stale-golden",
                    f"version bumped v{gold_v} -> v{cur_v} with no schema change; "
                    "run `lint --schema --update` to re-snapshot the golden",
                    Severity.WARNING,
                )
            ]
        return []
    deltas = "; ".join(breaking + additive)
    if cur_v == gold_v:
        if breaking:
            return [
                f(
                    "schema-breaking-no-bump",
                    f"BREAKING drift without a version bump (still v{cur_v}): "
                    f"{deltas} — old peers/records would misread silently; bump "
                    + (
                        "PROTOCOL_VERSION in engine/remote_plane.py"
                        if surface.kind == "wire"
                        else f"SCHEMA_VERSIONS[{surface.name!r}] AND register a "
                        "migration shim in utils/schema_stamp.MIGRATIONS"
                    ),
                )
            ]
        return [
            f(
                "schema-additive-no-bump",
                f"additive drift without a version bump (still v{cur_v}): "
                f"{deltas} — old readers cannot tell they are missing fields; "
                + (
                    "bump PROTOCOL_VERSION in engine/remote_plane.py"
                    if surface.kind == "wire"
                    else f"bump SCHEMA_VERSIONS[{surface.name!r}] in utils/schema_stamp.py"
                ),
            )
        ]
    # version bumped: breaking drift on a durable surface additionally
    # needs a shim from every superseded version the bump skipped over
    if breaking and surface.kind == "durable":
        missing = [v for v in range(gold_v, cur_v) if not has_migration(surface.name, v)]
        if missing:
            return [
                f(
                    "schema-missing-migration",
                    f"breaking drift bumped v{gold_v} -> v{cur_v} ({deltas}) but "
                    f"no migration shim is registered for version(s) "
                    f"{', '.join(map(str, missing))} — version-N−1 records would "
                    "be unreadable; add ({0}, v) entries to "
                    "utils/schema_stamp.MIGRATIONS".format(surface.name),
                )
            ]
    return [
        f(
            "schema-stale-golden",
            f"drift acknowledged by bump v{gold_v} -> v{cur_v} ({deltas}); run "
            "`lint --schema --update` to re-snapshot the golden",
            Severity.WARNING,
        )
    ]


# -- entry points ------------------------------------------------------------


def golden_path(surface: Surface) -> Path:
    return GOLDEN_DIR / f"{surface.name}.json"


def load_golden(surface: Surface) -> dict | None:
    p = golden_path(surface)
    if not p.exists():
        return None
    return json.loads(p.read_text(encoding="utf-8"))


def run_schema_check(update: bool = False) -> list[Finding]:
    """``lint --schema`` (and ``--update``): extract every surface, diff
    against goldens, classify. ``update`` rewrites the goldens instead of
    reporting drift (extraction errors still report)."""
    findings: list[Finding] = []
    for surface in SURFACES:
        try:
            cur = extract_surface(surface)
        except Exception as e:  # extraction must never crash the gate
            findings.append(
                Finding(
                    surface.file, 1, "schema-extract-error",
                    f"[{surface.name}] schema extraction failed: {e}",
                )
            )
            continue
        if update:
            GOLDEN_DIR.mkdir(parents=True, exist_ok=True)
            golden_path(surface).write_text(
                json.dumps(cur, indent=1, sort_keys=True) + "\n", encoding="utf-8"
            )
            continue
        findings.extend(classify_drift(surface, load_golden(surface), cur))
    return findings


def describe() -> dict:
    """Machine-readable pillar summary (``--list-rules`` / tooling)."""
    from cosmos_curate_tpu.utils import schema_stamp

    return {
        "surfaces": {
            s.name: {"kind": s.kind, "file": s.file, "version": s.version()}
            for s in SURFACES
        },
        **schema_stamp.describe(),
    }
