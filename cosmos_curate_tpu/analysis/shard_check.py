"""shardcheck: device-free verification of the TPU sharding/shape layer.

Third pillar of curate-lint next to graph_lint (pipeline-graph semantics)
and ast_lint (source hazards). The sharding layer is the whole point of the
TPU port — every NCCL plane became a ``jax.sharding.Mesh`` — yet a typo'd
axis name, a non-divisible batch, or a mis-specced ``shard_map`` otherwise
only fails minutes into a run on real chips. This pass catches all three at
build time, on CPU, with **zero device allocation**:

- every contract's ``PartitionSpec`` axes are checked against the declared
  ``MeshSpec`` (existence, one-use-per-spec, divisibility of the sharded
  dimension by the axis extent — including the ``shard_batch`` padding
  contract, which downgrades batch non-divisibility to a pad-waste
  warning);
- the forward itself runs under ``jax.eval_shape`` over a
  ``jax.sharding.AbstractMesh`` — ``shard_map`` axis names and per-device
  block shapes are verified by JAX's own tracing machinery, no TPUs (or
  even XLA compilation) involved;
- per-device bytes for replicated parameters are estimated from the
  abstract init, warning when a spec would blow the declared HBM budget.

Entry points: :func:`run_shard_check` (library),
``cosmos-curate-tpu lint --shard-check`` (CLI), and
``scripts/run_static_checks.sh`` (the CI gate). The ``run_pipeline``
pre-flight reuses :func:`mesh_tiling_errors` to validate stage-declared
``MeshSpec``\\ s against ``ClusterShape.num_tpu_chips``
(analysis/graph_lint.py). Defaults (mesh, HBM budget) come from
``[tool.curate-lint]`` in pyproject.toml.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Callable, Sequence

from cosmos_curate_tpu.analysis.common import Finding, LintConfig, Severity, load_config
from cosmos_curate_tpu.parallel.axes import BATCH_AXES, MESH_AXES, SEQ
from cosmos_curate_tpu.parallel.mesh import MeshSpec

_SHARD_FILE = "<shard-check>"

# One dimension's sharding: unsharded, one axis, or a multi-axis product.
DimAxes = None | str | tuple[str, ...]


@dataclass(frozen=True)
class AbstractInput:
    """One input operand as (shape, dtype, per-dimension axis spec)."""

    shape: tuple[int, ...]
    dtype: str = "float32"
    spec: tuple[DimAxes, ...] = ()
    name: str = "input"


@dataclass(frozen=True)
class ShardContract:
    """One checkable sharded entry point — a model forward or a
    shard_map'd kernel.

    ``init`` abstractly builds the parameter tree (called under
    ``jax.eval_shape``; used for the HBM estimate and passed to
    ``forward``). ``forward`` is eval_shape'd with ``ShapeDtypeStruct``
    stand-ins for every input; when ``needs_mesh`` it receives an
    ``AbstractMesh`` built from the resolved ``MeshSpec`` as its first
    argument, so the real ``shard_map`` call sites are exercised.
    ``pads_batch`` marks entry points that ride ``shard_batch``'s pad
    contract: a non-divisible leading dim pads instead of failing, so it
    reports as a pad-waste warning rather than an error.
    """

    name: str
    inputs: tuple[AbstractInput, ...]
    forward: Callable[..., Any] | None = None
    init: Callable[[], Any] | None = None
    needs_mesh: bool = False
    pads_batch: bool = False
    where: str = ""  # source pointer shown in findings

    def describe(self) -> str:
        return f"{self.name} ({self.where})" if self.where else self.name


# -- mesh-spec arithmetic (no jax; shared with the run_pipeline pre-flight) --


def parse_mesh_spec(text: str) -> MeshSpec:
    """``"data=2,model=4"`` -> MeshSpec; unnamed axes default to extent 1
    (NOT -1: the lint pass must stay device-free, so nothing is left to
    absorb a discovered device count unless requested with an explicit
    ``axis=-1``)."""
    extents = {a: 1 for a in MESH_AXES}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition("=")
        key = key.strip()
        if not sep or key not in MESH_AXES:
            raise ValueError(
                f"bad mesh spec entry '{part}': expected axis=extent with axis "
                f"in {', '.join(MESH_AXES)}"
            )
        try:
            extents[key] = int(value)
        except ValueError as e:
            raise ValueError(f"bad mesh extent in '{part}'") from e
    return MeshSpec(**extents)


def mesh_tiling_errors(spec: MeshSpec, num_chips: int) -> list[str]:
    """Why ``spec`` cannot tile a cluster of ``num_chips`` chips (empty =
    it can). Unlike ``MeshSpec.resolve`` this allows the mesh to cover a
    *subset* of the cluster (a stage's host-local mesh vs. the cluster
    total), so the check is divisibility, not equality."""
    errors = spec.extent_errors()
    if errors:
        return errors
    dims = spec.extents()
    fixed = math.prod(d for d in dims if d > 0)
    if fixed > num_chips:
        errors.append(
            f"mesh {dims} needs {fixed} chip(s) at its fixed axes but the "
            f"cluster declares {num_chips}"
        )
    elif num_chips % fixed:
        errors.append(
            f"mesh {dims} cannot tile {num_chips} chip(s): fixed-axes product "
            f"{fixed} does not divide the chip count"
        )
    return errors


def _resolve_mesh(
    spec: MeshSpec, num_devices: int | None, findings: list[Finding]
) -> dict[str, int] | None:
    """Concrete per-axis extents for the pass. With no device count given,
    the spec must be fully specified (no -1) — device discovery is exactly
    what this pass avoids. A fully-specified mesh may cover a *subset* of
    an explicit ``num_devices`` (a host-local mesh on a larger cluster):
    the requirement is tiling, not equality."""
    extents = spec.extents()
    has_free = any(d == -1 for d in extents)
    if num_devices is None:
        if has_free:
            findings.append(
                Finding(
                    _SHARD_FILE, 0, "shard-mesh-spec",
                    f"mesh {extents} has a -1 axis; pass an explicit "
                    "device count (--devices) or specify every extent",
                )
            )
            return None
        num_devices = math.prod(extents)
    if has_free:
        try:
            return spec.resolve(num_devices)
        except ValueError as e:
            findings.append(Finding(_SHARD_FILE, 0, "shard-mesh-spec", str(e)))
            return None
    errors = mesh_tiling_errors(spec, num_devices)
    if errors:
        findings.extend(
            Finding(_SHARD_FILE, 0, "shard-mesh-spec", msg) for msg in errors
        )
        return None
    return dict(zip(spec.axis_names(), extents))


# -- static spec checks ------------------------------------------------------


def _dim_axes(entry: DimAxes) -> tuple[str, ...]:
    if entry is None:
        return ()
    return entry if isinstance(entry, tuple) else (entry,)


def _check_input_spec(
    contract: ShardContract,
    inp: AbstractInput,
    mesh: dict[str, int],
    findings: list[Finding],
) -> bool:
    """Static half: axis existence, one-use-per-spec, divisibility.
    Returns False when errors make the abstract forward pointless."""
    ok = True
    label = f"{contract.describe()} input '{inp.name}'"
    if len(inp.spec) > len(inp.shape):
        findings.append(
            Finding(
                _SHARD_FILE, 0, "shard-rank-mismatch",
                f"{label}: spec has {len(inp.spec)} entries for a rank-"
                f"{len(inp.shape)} array {inp.shape}",
            )
        )
        return False
    used: set[str] = set()
    for dim, entry in enumerate(inp.spec):
        extent = 1
        for axis in _dim_axes(entry):
            if axis not in mesh:
                findings.append(
                    Finding(
                        _SHARD_FILE, 0, "shard-unknown-axis",
                        f"{label}: dim {dim} sharded over axis '{axis}' which "
                        f"is not in the mesh {dict(mesh)}"
                        + (
                            ""
                            if axis in MESH_AXES
                            else f" (nor the canonical registry: {', '.join(MESH_AXES)})"
                        ),
                    )
                )
                ok = False
                continue
            if axis in used:
                findings.append(
                    Finding(
                        _SHARD_FILE, 0, "shard-duplicate-axis",
                        f"{label}: axis '{axis}' used more than once in one spec",
                    )
                )
                ok = False
            used.add(axis)
            extent *= mesh[axis]
        if extent > 1 and inp.shape[dim] % extent:
            if contract.pads_batch and dim == 0:
                pad = (-inp.shape[dim]) % extent
                findings.append(
                    Finding(
                        _SHARD_FILE, 0, "shard-pad-waste",
                        f"{label}: batch dim {inp.shape[dim]} pads by {pad} row(s) "
                        f"to fill {extent} shard(s) "
                        f"({100.0 * pad / (inp.shape[dim] + pad):.0f}% padding waste)",
                        severity=Severity.WARNING,
                    )
                )
            else:
                findings.append(
                    Finding(
                        _SHARD_FILE, 0, "shard-indivisible",
                        f"{label}: dim {dim} of size {inp.shape[dim]} is not "
                        f"divisible by its sharding extent {extent} "
                        f"({'×'.join(_dim_axes(entry))})",
                    )
                )
                ok = False
    return ok


# -- abstract (eval_shape) checks -------------------------------------------


def _abstract_mesh(mesh: dict[str, int]):
    from jax.sharding import AbstractMesh

    shape_tuple = tuple(mesh.items())
    try:
        return AbstractMesh(shape_tuple)
    except TypeError:
        # newer JAX signature: AbstractMesh(axis_sizes, axis_names)
        return AbstractMesh(tuple(mesh.values()), tuple(mesh.keys()))


def _shape_structs(inputs: Sequence[AbstractInput]):
    import jax
    import jax.numpy as jnp

    return [jax.ShapeDtypeStruct(i.shape, jnp.dtype(i.dtype)) for i in inputs]


def _param_bytes(params: Any) -> int:
    import jax

    return sum(
        math.prod(leaf.shape) * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(params)
        if hasattr(leaf, "shape")
    )


def _check_abstract_flow(
    contract: ShardContract,
    mesh: dict[str, int],
    hbm_gb: float,
    findings: list[Finding],
) -> None:
    """Abstract half: eval_shape the init (HBM estimate) and the forward
    (shape flow + shard_map spec validation via AbstractMesh)."""
    import jax

    params = None
    if contract.init is not None:
        try:
            params = jax.eval_shape(contract.init)
        except Exception as e:
            findings.append(
                Finding(
                    _SHARD_FILE, 0, "shard-shape-flow",
                    f"{contract.describe()}: abstract init failed: "
                    f"{type(e).__name__}: {_trim(e)}",
                )
            )
            return
        if hbm_gb > 0:
            # Params are replicated unless a contract shards them, so the
            # per-device cost is the full tree. Activations are workload-
            # dependent and excluded; this is a floor, not a ceiling.
            per_device = _param_bytes(params)
            if per_device > hbm_gb * 2**30:
                findings.append(
                    Finding(
                        _SHARD_FILE, 0, "shard-hbm-budget",
                        f"{contract.describe()}: replicated params need "
                        f"{per_device / 2**30:.2f} GiB per device, over the "
                        f"declared HBM budget of {hbm_gb:g} GiB — shard them "
                        "(nn.with_partitioning) or shrink the model",
                        severity=Severity.WARNING,
                    )
                )
    if contract.forward is None:
        return
    forward = contract.forward
    if contract.needs_mesh:
        # the mesh is static configuration, not a traced operand: close
        # over it so eval_shape only sees abstract arrays
        amesh = _abstract_mesh(mesh)
        inner = forward
        forward = lambda *arrays: inner(amesh, *arrays)  # noqa: E731
    args: list[Any] = []
    if params is not None:
        args.append(params)
    args.extend(_shape_structs(contract.inputs))
    try:
        jax.eval_shape(forward, *args)
    except KeyError as e:
        findings.append(
            Finding(
                _SHARD_FILE, 0, "shard-unknown-axis",
                f"{contract.describe()}: shard_map names axis {e} which is "
                f"absent from the mesh {dict(mesh)}",
            )
        )
    except Exception as e:
        findings.append(
            Finding(
                _SHARD_FILE, 0, "shard-shape-flow",
                f"{contract.describe()}: abstract forward failed: "
                f"{type(e).__name__}: {_trim(e)}",
            )
        )


def _trim(e: Exception, limit: int = 300) -> str:
    text = " ".join(str(e).split())
    return text if len(text) <= limit else text[: limit - 3] + "..."


# -- the contract registry ---------------------------------------------------


def default_contracts(mesh: dict[str, int]) -> list[ShardContract]:
    """Contracts for the repo's sharded entry points, sized from tiny test
    configs (shape semantics are identical to the production configs; the
    checks scale-invariantly cover axis names and divisibility).

    ``mesh`` lets sequence-parallel contracts pick batch/frame counts that
    exercise the declared ``seq`` extent rather than hardcoding one.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from cosmos_curate_tpu.models.diffusion_sr import DIFF_SR_TINY_TEST, DenoiserUNet, ddim_sample
    from cosmos_curate_tpu.models.super_resolution import SR_TINY_TEST, SRNet
    from cosmos_curate_tpu.parallel.ring_attention import ring_attention
    from cosmos_curate_tpu.parallel.sharding import shard_map
    from cosmos_curate_tpu.parallel.ulysses import ulysses_attention

    seq = max(1, mesh.get(SEQ, 1))
    contracts: list[ShardContract] = []

    # models/super_resolution.py — frames sharded over 'seq' (sp_size > 1)
    sr = SRNet(SR_TINY_TEST)

    def sr_init():
        return sr.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, 16, 3), jnp.uint8))

    def sr_forward(amesh, params, frames):
        spec = P(SEQ, None, None, None)
        return shard_map(
            lambda p, f: sr.apply(p, f),
            mesh=amesh, in_specs=(P(), spec), out_specs=spec,
        )(params, frames)

    contracts.append(
        ShardContract(
            name="super-resolution-tpu",
            where="models/super_resolution.py",
            inputs=(
                AbstractInput((4 * seq, 16, 16, 3), "uint8", (SEQ,), name="frames"),
            ),
            init=sr_init,
            forward=sr_forward,
            needs_mesh=True,
        )
    )

    # models/diffusion_sr.py — window chunks sharded over 'seq'
    cfg = DIFF_SR_TINY_TEST
    dsr = DenoiserUNet(cfg)
    side = 16 * cfg.scale

    def dsr_init():
        dummy = jnp.zeros((cfg.window, side, side, 3), jnp.float32)
        return dsr.init(jax.random.PRNGKey(0), dummy, dummy, jnp.float32(0.5))

    def dsr_forward(amesh, params, conds, keys):
        def sample_chunks(p, c, k):
            return jax.vmap(lambda ci, ki: ddim_sample(dsr, p, ci, cfg, ki))(c, k)

        return shard_map(
            sample_chunks, mesh=amesh,
            in_specs=(P(), P(SEQ), P(SEQ)), out_specs=P(SEQ),
        )(params, conds, keys)

    contracts.append(
        ShardContract(
            name="diffusion-sr-tpu",
            where="models/diffusion_sr.py",
            inputs=(
                AbstractInput((seq, cfg.window, side, side, 3), "float32", (SEQ,), name="conds"),
                AbstractInput((seq, 2), "uint32", (SEQ,), name="keys"),
            ),
            init=dsr_init,
            forward=dsr_forward,
            needs_mesh=True,
        )
    )

    # parallel/ring_attention.py — sequence sharded over 'seq'
    attn_spec = (None, None, SEQ, None)
    attn_shape = (1, 4, 8 * seq, 8)
    contracts.append(
        ShardContract(
            name="ring-attention",
            where="parallel/ring_attention.py",
            inputs=tuple(
                AbstractInput(attn_shape, "float32", attn_spec, name=n)
                for n in ("q", "k", "v")
            ),
            forward=lambda amesh, q, k, v: ring_attention(q, k, v, amesh),
            needs_mesh=True,
        )
    )

    # parallel/ulysses.py — heads must also divide the 'seq' extent
    ul_shape = (1, 4 * seq, 8 * seq, 8)
    contracts.append(
        ShardContract(
            name="ulysses-attention",
            where="parallel/ulysses.py",
            inputs=tuple(
                AbstractInput(ul_shape, "float32", attn_spec, name=n)
                for n in ("q", "k", "v")
            ),
            forward=lambda amesh, q, k, v: ulysses_attention(q, k, v, amesh),
            needs_mesh=True,
        )
    )

    # parallel/sharding.py — the shard_batch host→device pad contract
    contracts.append(
        ShardContract(
            name="shard-batch",
            where="parallel/sharding.py",
            inputs=(AbstractInput((32, 512), "float32", (BATCH_AXES,), name="batch"),),
            pads_batch=True,
        )
    )

    # dedup/corpus_index.py — the IVF query matmul: queries sharded over the
    # batch axes (shard_batch pad contract), the probed corpus shard
    # replicated; the real shard_map call site is traced abstractly
    from cosmos_curate_tpu.dedup.corpus_index import query_matmul

    contracts.append(
        ShardContract(
            name="ivf-query",
            where="dedup/corpus_index.py",
            inputs=(
                AbstractInput((32, 64), "float32", (BATCH_AXES,), name="queries"),
                AbstractInput((128, 64), "float32", (), name="corpus"),
            ),
            forward=lambda amesh, q, c: query_matmul(amesh, q, c, top_k=4),
            needs_mesh=True,
            pads_batch=True,
        )
    )

    # models/vlm/paged_kv.py — the caption engine's block-table KV gather:
    # slot rows (tables) shard over the batch axes for data-parallel engine
    # replicas, the block pool is replicated; the real shard_map call site
    # is traced abstractly (same [L, NB, bs, Hkv, Dh] pool layout the
    # engine compiles, tiny extents)
    from cosmos_curate_tpu.models.vlm.paged_kv import paged_gather

    pool_shape = (2, 9, 4, 2, 8)  # [L, n_blocks, block_size, Hkv, Dh]
    contracts.append(
        ShardContract(
            name="vlm-paged-gather",
            where="models/vlm/paged_kv.py",
            inputs=(
                AbstractInput(pool_shape, "bfloat16", (), name="pool_k"),
                AbstractInput(pool_shape, "bfloat16", (), name="pool_v"),
                AbstractInput((8, 2), "int32", (BATCH_AXES,), name="tables"),
            ),
            forward=lambda amesh, pk, pv, t: paged_gather(amesh, pk, pv, t),
            needs_mesh=True,
            pads_batch=True,
        )
    )

    # ops/paged_attention.py — head-parallel paged attention: queries, the
    # KV block pools, and the output shard their Hkv dimension over the
    # model axis (tensor parallelism over KV heads); block tables and
    # per-row lengths replicate. The real shard_map call site is traced
    # abstractly on the XLA reference path (use_kernel=False keeps the
    # trace device-free).
    from cosmos_curate_tpu.models.vlm.paged_kv import paged_head_update
    from cosmos_curate_tpu.ops.paged_attention import paged_head_attention

    from cosmos_curate_tpu.parallel.axes import MODEL

    contracts.append(
        ShardContract(
            name="vlm-paged-head-attention",
            where="ops/paged_attention.py",
            inputs=(
                AbstractInput(
                    (8, 1, 2, 4, 8), "bfloat16",
                    (None, None, MODEL, None, None), name="q",
                ),
                AbstractInput(
                    pool_shape, "bfloat16", (None, None, None, MODEL, None),
                    name="pool_k",
                ),
                AbstractInput(
                    pool_shape, "bfloat16", (None, None, None, MODEL, None),
                    name="pool_v",
                ),
                AbstractInput((8, 2), "int32", (), name="tables"),
                AbstractInput((8,), "int32", (), name="write_index"),
                AbstractInput((8,), "int32", (), name="kv_len"),
            ),
            forward=lambda amesh, q, pk, pv, t, wi, kl: paged_head_attention(
                amesh, q, pk, pv, t, wi, kl, use_kernel=False
            ),
            needs_mesh=True,
        )
    )

    # models/vlm/paged_kv.py — the matching head-parallel pool scatter: each
    # model-axis shard writes a chunk's K/V into its own head plane through
    # the replicated block table.
    contracts.append(
        ShardContract(
            name="vlm-paged-head-scatter",
            where="models/vlm/paged_kv.py",
            inputs=(
                AbstractInput(
                    pool_shape, "bfloat16", (None, None, None, MODEL, None),
                    name="pool_k",
                ),
                AbstractInput(
                    pool_shape, "bfloat16", (None, None, None, MODEL, None),
                    name="pool_v",
                ),
                AbstractInput((8, 1, 2, 8), "bfloat16", (None, None, MODEL, None), name="k"),
                AbstractInput((8, 1, 2, 8), "bfloat16", (None, None, MODEL, None), name="v"),
                AbstractInput((8, 2), "int32", (), name="tables"),
                AbstractInput((8,), "int32", (), name="write_index"),
            ),
            forward=lambda amesh, pk, pv, k, v, t, wi: paged_head_update(
                amesh, pk, pv, k, v, t, wi, layer_index=1
            ),
            needs_mesh=True,
        )
    )
    return contracts


# -- entry points ------------------------------------------------------------


def check_contract(
    contract: ShardContract, mesh: dict[str, int], *, hbm_gb: float = 0.0
) -> list[Finding]:
    """All findings for one contract against resolved mesh extents."""
    findings: list[Finding] = []
    static_ok = True
    for inp in contract.inputs:
        static_ok &= _check_input_spec(contract, inp, mesh, findings)
    # A spec that already failed statically would only re-raise the same
    # problem (more opaquely) out of tracing — skip the abstract half.
    if static_ok:
        _check_abstract_flow(contract, mesh, hbm_gb, findings)
    return findings


def run_shard_check(
    mesh_spec: MeshSpec | None = None,
    *,
    num_devices: int | None = None,
    hbm_gb: float | None = None,
    contracts: Sequence[ShardContract] | None = None,
    config: LintConfig | None = None,
) -> list[Finding]:
    """The shape-flow pass: resolve the mesh, then check every contract.

    Defaults come from ``[tool.curate-lint]``: ``shard-mesh`` (e.g.
    ``"data=2,seq=2"``), ``shard-hbm-gb``. Explicit arguments win.
    """
    config = config or load_config()
    if mesh_spec is None:
        mesh_spec = (
            parse_mesh_spec(config.shard_mesh)
            if config.shard_mesh
            else MeshSpec(dcn=1, data=1, model=1, seq=1)
        )
    if hbm_gb is None:
        hbm_gb = config.shard_hbm_gb
    findings: list[Finding] = []
    mesh = _resolve_mesh(mesh_spec, num_devices, findings)
    if mesh is None:
        return findings
    for contract in contracts if contracts is not None else default_contracts(mesh):
        findings.extend(check_contract(contract, mesh, hbm_gb=hbm_gb))
    return findings
