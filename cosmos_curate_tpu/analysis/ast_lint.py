"""AST-lint driver: run the rule set over files/directories.

Entry points: :func:`run_lint` (library), the ``cosmos-curate-tpu lint``
subcommand (cli/lint_cli.py) and ``scripts/run_static_checks.sh``. Each
finding renders as ``file:line rule-id message``; the process exits nonzero
when anything survives suppression. Suppress with
``# curate-lint: disable=<rule>`` on (or directly above) the flagged line,
or ``# curate-lint: disable-file=<rule>`` anywhere in the file.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, Sequence

from cosmos_curate_tpu.analysis.common import (
    Finding,
    LintConfig,
    find_pyproject,
    is_suppressed,
    load_config,
    parse_suppressions,
)
from cosmos_curate_tpu.analysis.rules import Rule, RuleContext, all_rules


def iter_python_files(paths: Sequence[str | Path], exclude: Sequence[str]) -> list[Path]:
    """Expand targets to .py files. A target that does not exist (or is a
    non-Python file) raises: a typoed path must fail the gate loudly, not
    exit 0 as 'clean' having linted nothing."""
    out: list[Path] = []
    for p in paths:
        path = Path(p)
        if path.is_file() and path.suffix == ".py":
            out.append(path)
        elif path.is_dir():
            out.extend(sorted(path.rglob("*.py")))
        elif path.is_file():
            raise ValueError(f"not a Python file: {path}")
        else:
            raise ValueError(f"no such file or directory: {path}")
    root = _repo_root()

    def excluded(f: Path) -> bool:
        rel = _rel(f, root)
        return any(pat and pat in rel for pat in exclude)

    return [f for f in out if not excluded(f)]


def _repo_root() -> Path:
    pyproject = find_pyproject()
    return pyproject.parent if pyproject else Path.cwd()


def _rel(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def lint_file(
    path: Path, config: LintConfig, rules: Iterable[Rule], root: Path | None = None
) -> list[Finding]:
    root = root or _repo_root()
    rel = _rel(path, root)
    try:
        source = path.read_text(encoding="utf-8")
    except (OSError, UnicodeDecodeError) as e:
        return [Finding(rel, 0, "io-error", f"cannot read file: {e}")]
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        return [Finding(rel, e.lineno or 0, "parse-error", f"syntax error: {e.msg}")]
    ctx = RuleContext(path=path, rel_path=rel, tree=tree, source=source, config=config)
    findings: list[Finding] = []
    for rule in rules:
        if config.rule_enabled(rule.rule_id):
            findings.extend(rule.check(ctx))
    per_line, file_wide = parse_suppressions(source)
    kept = [f for f in findings if not is_suppressed(f, per_line, file_wide)]
    kept.sort(key=lambda f: (f.line, f.rule))
    return kept


def run_lint(
    paths: Sequence[str | Path],
    config: LintConfig | None = None,
    rule_ids: Sequence[str] | None = None,
) -> list[Finding]:
    """Lint ``paths`` (files or directories); returns surviving findings.

    ``rule_ids`` narrows the run to specific rules (CLI ``--rules``),
    overriding the config's enable list.
    """
    config = config or load_config()
    rules = all_rules()
    if rule_ids:
        wanted = set(rule_ids)
        unknown = wanted - {r.rule_id for r in rules}
        if unknown:
            raise ValueError(f"unknown rule id(s): {', '.join(sorted(unknown))}")
        rules = [r for r in rules if r.rule_id in wanted]
        # an explicit --rules selection overrides the config's enable/disable
        config = LintConfig(
            enable=sorted(wanted),
            disable=[],
            exclude=config.exclude,
            python_floor=config.python_floor,
        )
    root = _repo_root()
    findings: list[Finding] = []
    for f in iter_python_files(paths, config.exclude):
        findings.extend(lint_file(f, config, rules, root))
    return findings
