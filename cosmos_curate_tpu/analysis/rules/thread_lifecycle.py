"""thread-lifecycle: a thread that can outlive shutdown.

``threading.Thread(...)`` without ``daemon=True`` keeps the interpreter
alive at exit; a non-daemon background thread with no reachable ``.join()``
on a shutdown path leaks past every clean-shutdown contract the service
relies on (compaction, live-status, prep, and watchdog threads must all
stop when their owner stops).

A ``Thread(...)`` call passes when ANY of:

- ``daemon=True`` is passed to the constructor;
- the created thread is bound to a name (``t = Thread(...)`` or
  ``self._t = Thread(...)``) and that name's ``.daemon = True`` is set or
  ``.join(...)`` is called somewhere in the same file (a join anywhere is
  taken as the shutdown path — this is a lint, not a model checker);
- ``daemon=...`` is passed a non-literal expression (the caller is
  forwarding a policy decision; we trust it).

Everything else — an anonymous ``Thread(...).start()``, a named thread
that is never joined nor daemonized — is flagged. Suppress deliberate
leaks with ``# curate-lint: disable=thread-lifecycle`` and a reason.
"""

from __future__ import annotations

import ast

from cosmos_curate_tpu.analysis.common import Finding
from cosmos_curate_tpu.analysis.rules import Rule, RuleContext


def _binding_name(parents: dict[ast.AST, ast.AST], call: ast.Call) -> str | None:
    """The name a Thread(...) result is bound to: ``t`` / ``self._t`` for
    direct assignments, walking through trivial wrappers is not attempted."""
    node: ast.AST = call
    parent = parents.get(node)
    while parent is not None and isinstance(parent, (ast.Await,)):
        node = parent
        parent = parents.get(node)
    if isinstance(parent, ast.Assign):
        for t in parent.targets:
            if isinstance(t, ast.Name):
                return t.id
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id in ("self", "cls")
            ):
                return t.attr
    if isinstance(parent, ast.AnnAssign):
        t = parent.target
        if isinstance(t, ast.Name):
            return t.id
        if (
            isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name)
            and t.value.id in ("self", "cls")
        ):
            return t.attr
    return None


def _name_of(expr: ast.expr) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id in ("self", "cls")
    ):
        return expr.attr
    return None


class ThreadLifecycleRule(Rule):
    rule_id = "thread-lifecycle"
    description = (
        "threading.Thread without daemon=True and without a .join() on any "
        "shutdown/close path — background threads must not outlive their owner"
    )

    def check(self, ctx: RuleContext) -> list[Finding]:
        tree = ctx.tree
        parents: dict[ast.AST, ast.AST] = {}
        joined: set[str] = set()
        daemonized: set[str] = set()
        thread_calls: list[ast.Call] = []
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
            if isinstance(node, ast.Call):
                func = node.func
                final = (
                    func.attr if isinstance(func, ast.Attribute)
                    else func.id if isinstance(func, ast.Name) else None
                )
                if final == "Thread":
                    thread_calls.append(node)
                elif final == "join" and isinstance(func, ast.Attribute):
                    name = _name_of(func.value)
                    if name is not None:
                        joined.add(name)
            elif isinstance(node, ast.Assign):
                # t.daemon = True / self._t.daemon = True
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and t.attr == "daemon"
                        and isinstance(node.value, ast.Constant)
                        and node.value.value is True
                    ):
                        name = _name_of(t.value)
                        if name is not None:
                            daemonized.add(name)

        findings: list[Finding] = []
        for call in thread_calls:
            verdict = self._check_thread(call, parents, joined, daemonized)
            if verdict is not None:
                findings.append(
                    Finding(ctx.rel_path, call.lineno, self.rule_id, verdict)
                )
        return findings

    def _check_thread(
        self,
        call: ast.Call,
        parents: dict[ast.AST, ast.AST],
        joined: set[str],
        daemonized: set[str],
    ) -> str | None:
        for kw in call.keywords:
            if kw.arg == "daemon":
                if isinstance(kw.value, ast.Constant):
                    if kw.value.value is True:
                        return None
                    # daemon=False is an explicit non-daemon: still needs a join
                    break
                return None  # forwarded expression: trust the caller
        name = _binding_name(parents, call)
        if name is None:
            return (
                "anonymous non-daemon Thread: it can neither be joined nor "
                "daemonized after start — pass daemon=True or bind and join it"
            )
        if name in joined or name in daemonized:
            return None
        return (
            f"thread '{name}' is neither daemon=True nor joined anywhere in "
            "this file: it outlives shutdown — join it on the close path or "
            "make it a daemon"
        )
