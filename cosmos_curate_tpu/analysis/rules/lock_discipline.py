"""lock-discipline: thread-shared attribute mutated outside its lock.

The hazard class this encodes is the one the engine's control plane lives
one edit away from (engine/remote_plane.py, engine/remote_agent.py,
engine/object_channel.py): a class starts ``threading.Thread`` workers and
mutates ``self`` attributes from more than one thread, but only some of the
mutation sites hold ``self._lock``.

Two heuristics, both reported under this rule id, applied only to classes
that start threads in files under ``engine/``:

1. *inconsistent guard*: an attribute is mutated both inside and outside a
   ``with self._lock:`` block (``__init__`` is exempt — construction happens
   before any thread exists).
2. *cross-thread unguarded*: an attribute is mutated without a lock in a
   thread-reachable method (a ``Thread(target=self.X)`` target, or a method
   it transitively calls) while also being mutated from the main context or
   a different thread target — or the thread target is spawned inside a
   loop (one instance per connection/request), making the method concurrent
   with itself.

Attributes holding thread-safe primitives (assigned ``threading.Event()``,
``Lock()``, ``queue.Queue()`` etc. in ``__init__``) are exempt: calling
``.set()``/``.clear()`` on an Event is their intended cross-thread use.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from cosmos_curate_tpu.analysis.common import Finding
from cosmos_curate_tpu.analysis.rules import Rule, RuleContext

# Method names that mutate their receiver in place.
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "add", "discard", "appendleft", "popleft",
    "sort", "reverse",
}

# Constructors whose instances are safe to poke from any thread.
_THREAD_SAFE_TYPES = {
    "Event", "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Barrier", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
}

_LOCKISH = ("lock", "mutex", "cond")


def _dotted_final(node: ast.expr) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _self_attr(node: ast.expr) -> str | None:
    """``self.X`` -> 'X'."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _self_rooted_base(node: ast.expr) -> str | None:
    """Leftmost ``self.X`` under subscripts/attribute chains:
    ``self.X[k]``, ``self.X.y`` -> 'X'."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        direct = _self_attr(node)
        if direct is not None:
            return direct
        node = node.value
    return None


@dataclass
class _Mutation:
    attr: str
    method: str
    lineno: int
    in_lock: bool


@dataclass
class _ClassFacts:
    name: str
    starts_threads: bool = False
    # thread-target method name -> spawned inside a loop (multi-instance)
    targets: dict[str, bool] = field(default_factory=dict)
    calls: dict[str, set[str]] = field(default_factory=dict)  # self-call graph
    mutations: list[_Mutation] = field(default_factory=list)
    safe_attrs: set[str] = field(default_factory=set)


class _MethodScanner:
    def __init__(self, facts: _ClassFacts, method: str) -> None:
        self.facts = facts
        self.method = method

    def scan(self, node: ast.AST, *, in_lock: bool = False, in_loop: bool = False) -> None:
        for child in ast.iter_child_nodes(node):
            self._scan_node(child, in_lock=in_lock, in_loop=in_loop)

    def _scan_node(self, node: ast.AST, *, in_lock: bool, in_loop: bool) -> None:
        if isinstance(node, ast.ClassDef):
            return  # nested classes are analyzed on their own
        if isinstance(node, ast.With):
            holds = in_lock or any(
                self._is_lock_expr(item.context_expr) for item in node.items
            )
            for stmt in node.body:
                self._scan_node(stmt, in_lock=holds, in_loop=in_loop)
            return
        if isinstance(node, (ast.For, ast.While)):
            # the test/iter parts evaluate once per iteration too
            for child in ast.iter_child_nodes(node):
                self._scan_node(child, in_lock=in_lock, in_loop=True)
            return
        if isinstance(node, ast.Call):
            self._scan_call(node, in_lock=in_lock, in_loop=in_loop)
        self._record_mutation(node, in_lock)
        self.scan(node, in_lock=in_lock, in_loop=in_loop)

    @staticmethod
    def _is_lock_expr(expr: ast.expr) -> bool:
        attr = _self_rooted_base(expr)
        if attr is None and isinstance(expr, ast.Call):
            attr = _self_rooted_base(expr.func)
        return attr is not None and any(t in attr.lower() for t in _LOCKISH)

    def _scan_call(self, node: ast.Call, *, in_lock: bool, in_loop: bool) -> None:
        final = _dotted_final(node.func)
        if final == "Thread":
            self.facts.starts_threads = True
            for kw in node.keywords:
                if kw.arg == "target":
                    target = _self_attr(kw.value)
                    if target is not None:
                        prev = self.facts.targets.get(target, False)
                        self.facts.targets[target] = prev or in_loop
        # self-call graph edge
        callee = _self_attr(node.func)
        if callee is not None:
            self.facts.calls.setdefault(self.method, set()).add(callee)
        # in-place mutator on a self-rooted receiver
        if isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATORS:
            base = _self_rooted_base(node.func.value)
            if base is not None:
                self._add(base, node.lineno, in_lock)

    def _record_mutation(self, node: ast.AST, in_lock: bool) -> None:
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    self._record_target(el, node, in_lock)
            else:
                self._record_target(t, node, in_lock)

    def _record_target(self, t: ast.expr, node: ast.AST, in_lock: bool) -> None:
        attr = _self_rooted_base(t)
        if attr is not None:
            self._add(attr, getattr(node, "lineno", 0), in_lock)

    def _add(self, attr: str, lineno: int, in_lock: bool) -> None:
        self.facts.mutations.append(_Mutation(attr, self.method, lineno, in_lock))


def _collect_facts(cls: ast.ClassDef) -> _ClassFacts:
    facts = _ClassFacts(cls.name)
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if item.name == "__init__":
            for stmt in ast.walk(item):
                if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                    ctor = _dotted_final(stmt.value.func)
                    if ctor in _THREAD_SAFE_TYPES:
                        for t in stmt.targets:
                            attr = _self_attr(t)
                            if attr is not None:
                                facts.safe_attrs.add(attr)
        _MethodScanner(facts, item.name).scan(item)
    return facts


def _reachable(facts: _ClassFacts) -> tuple[dict[str, set[str]], set[str]]:
    """-> (thread target -> methods reachable from it, multi-instance
    method set)."""
    per_target: dict[str, set[str]] = {}
    multi: set[str] = set()
    for target, in_loop in facts.targets.items():
        seen: set[str] = set()
        stack = [target]
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            stack.extend(facts.calls.get(m, ()))
        per_target[target] = seen
        if in_loop:
            multi |= seen
    return per_target, multi


class LockDisciplineRule(Rule):
    rule_id = "lock-discipline"
    description = (
        "engine classes that start threads must guard every mutation of a "
        "thread-shared self attribute with the same lock"
    )

    def check(self, ctx: RuleContext) -> list[Finding]:
        if "engine/" not in ctx.rel_path.replace("\\", "/"):
            return []
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(ctx, node))
        return findings

    def _check_class(self, ctx: RuleContext, cls: ast.ClassDef) -> list[Finding]:
        facts = _collect_facts(cls)
        if not facts.starts_threads:
            return []
        per_target, multi = _reachable(facts)
        thread_methods = set().union(*per_target.values()) if per_target else set()

        by_attr: dict[str, list[_Mutation]] = {}
        for m in facts.mutations:
            if m.attr in facts.safe_attrs:
                continue
            by_attr.setdefault(m.attr, []).append(m)

        findings: list[Finding] = []
        reported: set[tuple[str, int]] = set()

        def report(mut: _Mutation, why: str) -> None:
            key = (mut.attr, mut.lineno)
            if key in reported:
                return
            reported.add(key)
            findings.append(
                Finding(
                    ctx.rel_path, mut.lineno, self.rule_id,
                    f"self.{mut.attr} in {cls.name}.{mut.method} is mutated "
                    f"without holding the lock: {why}",
                )
            )

        for attr, muts in sorted(by_attr.items()):
            locked = [m for m in muts if m.in_lock]
            unguarded = [
                m for m in muts if not m.in_lock and m.method != "__init__"
            ]
            if not unguarded:
                continue
            # heuristic 1: inconsistently guarded
            if locked:
                lines = ", ".join(str(m.lineno) for m in locked[:4])
                for m in unguarded:
                    report(
                        m,
                        f"the same attribute is guarded elsewhere (line(s) "
                        f"{lines}); hold the lock here too",
                    )
                continue
            # heuristic 2: unguarded cross-thread mutation
            thread_muts = [m for m in unguarded if m.method in thread_methods]
            main_muts = [
                m
                for m in muts
                if m.method not in thread_methods and m.method != "__init__"
            ]
            if not thread_muts:
                continue
            touched_targets = {
                t for t, reach in per_target.items()
                if any(m.method in reach for m in thread_muts)
            }
            cross_thread = bool(main_muts) or len(touched_targets) > 1
            self_concurrent = any(m.method in multi for m in thread_muts)
            if cross_thread or self_concurrent:
                why = (
                    "the method runs on multiple threads at once"
                    if self_concurrent and not cross_thread
                    else "the attribute is also mutated from another thread context"
                )
                for m in thread_muts:
                    report(m, why)
                for m in main_muts:
                    if not m.in_lock:
                        report(m, "the attribute is also mutated from a worker thread")
        return findings
