"""min-python: stdlib API newer than the project's interpreter floor.

The exact failure class that cost this repo 56 test files at collection:
``logging.getLevelNamesMapping()`` is 3.11-only, the runtime floor is 3.10,
and nothing flagged it until pytest hit the AttributeError. The floor comes
from ``project.requires-python`` in ``pyproject.toml`` (overridable via
``[tool.curate-lint] python-floor``), so declaring the floor once keeps the
code and the rule in lockstep.

Guarded usage is not flagged: imports inside ``try/except ImportError`` and
attribute uses inside an ``if hasattr(mod, "name")`` branch are exactly how
version-gated code should look.
"""

from __future__ import annotations

import ast

from cosmos_curate_tpu.analysis.common import Finding
from cosmos_curate_tpu.analysis.rules import Rule, RuleContext

# module.name -> first Python version providing it
_STDLIB_MIN: dict[str, tuple[int, int]] = {
    "logging.getLevelNamesMapping": (3, 11),
    "enum.StrEnum": (3, 11),
    "enum.ReprEnum": (3, 11),
    "enum.verify": (3, 11),
    "datetime.UTC": (3, 11),
    "asyncio.TaskGroup": (3, 11),
    "asyncio.timeout": (3, 11),
    "asyncio.timeout_at": (3, 11),
    "asyncio.Runner": (3, 11),
    "asyncio.Barrier": (3, 11),
    "contextlib.chdir": (3, 11),
    "hashlib.file_digest": (3, 11),
    "inspect.getmembers_static": (3, 11),
    "math.cbrt": (3, 11),
    "math.exp2": (3, 11),
    "operator.call": (3, 11),
    "typing.Self": (3, 11),
    "typing.Never": (3, 11),
    "typing.LiteralString": (3, 11),
    "typing.Required": (3, 11),
    "typing.NotRequired": (3, 11),
    "typing.assert_never": (3, 11),
    "typing.assert_type": (3, 11),
    "typing.reveal_type": (3, 11),
    "typing.dataclass_transform": (3, 11),
    "typing.override": (3, 12),
    "typing.TypeAliasType": (3, 12),
    "itertools.batched": (3, 12),
    "math.sumprod": (3, 12),
    "calendar.Month": (3, 12),
    "os.process_cpu_count": (3, 13),
    "copy.replace": (3, 13),
    "argparse.BooleanOptionalAction": (3, 9),  # kept for floors below 3.9
}

# whole modules introduced after 3.x
_STDLIB_MODULE_MIN: dict[str, tuple[int, int]] = {
    "tomllib": (3, 11),
    "wsgiref.types": (3, 11),
}


def _ver(v: tuple[int, int]) -> str:
    return f"{v[0]}.{v[1]}"


class MinPythonRule(Rule):
    rule_id = "min-python"
    description = (
        "flags stdlib APIs newer than the interpreter floor declared in "
        "pyproject.toml requires-python"
    )

    def check(self, ctx: RuleContext) -> list[Finding]:
        floor = ctx.config.python_floor
        findings: list[Finding] = []
        # module alias -> canonical module name, for `import logging as log`
        aliases: dict[str, str] = {}
        guarded_imports = _import_error_guarded_lines(ctx.tree)
        hasattr_guards = _hasattr_guarded(ctx.tree)

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = a.name.split(".")[0]
                    need = _STDLIB_MODULE_MIN.get(a.name)
                    if need and need > floor and node.lineno not in guarded_imports:
                        findings.append(
                            self._finding(ctx, node.lineno, f"module {a.name}", need, floor)
                        )
            elif isinstance(node, ast.ImportFrom):
                if node.module is None or node.level:
                    continue
                need_mod = _STDLIB_MODULE_MIN.get(node.module)
                if need_mod and need_mod > floor and node.lineno not in guarded_imports:
                    findings.append(
                        self._finding(ctx, node.lineno, f"module {node.module}", need_mod, floor)
                    )
                    continue
                for a in node.names:
                    key = f"{node.module}.{a.name}"
                    need = _STDLIB_MIN.get(key)
                    if need and need > floor and node.lineno not in guarded_imports:
                        findings.append(self._finding(ctx, node.lineno, key, need, floor))
            elif isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
                mod = aliases.get(node.value.id)
                if mod is None:
                    continue
                key = f"{mod}.{node.attr}"
                need = _STDLIB_MIN.get(key)
                if need and need > floor:
                    # guards are recorded under the receiver name as written
                    # (`hasattr(log, ...)` for `import logging as log`), so
                    # match on that, not the canonical module name
                    used = f"{node.value.id}.{node.attr}"
                    if used in hasattr_guards.get(node.lineno, set()):
                        continue
                    findings.append(self._finding(ctx, node.lineno, key, need, floor))
        return findings

    def _finding(
        self, ctx: RuleContext, lineno: int, what: str,
        need: tuple[int, int], floor: tuple[int, int],
    ) -> Finding:
        return Finding(
            ctx.rel_path, lineno, self.rule_id,
            f"{what} requires Python {_ver(need)}+ but the project floor is "
            f"{_ver(floor)} (pyproject.toml requires-python); use a "
            "version-gated fallback or raise the floor",
        )


def _import_error_guarded_lines(tree: ast.Module) -> set[int]:
    """Line numbers of import statements inside try blocks whose handlers
    catch ImportError/ModuleNotFoundError/Exception."""
    lines: set[int] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        catches = False
        for h in node.handlers:
            names = []
            if h.type is None:
                catches = True
            elif isinstance(h.type, ast.Tuple):
                names = [getattr(e, "id", getattr(e, "attr", "")) for e in h.type.elts]
            else:
                names = [getattr(h.type, "id", getattr(h.type, "attr", ""))]
            if {"ImportError", "ModuleNotFoundError", "Exception", "BaseException"} & set(names):
                catches = True
        if not catches:
            continue
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.Import, ast.ImportFrom)):
                    lines.add(sub.lineno)
    return lines


def _hasattr_guarded(tree: ast.Module) -> dict[int, set[str]]:
    """line -> {"mod.attr", ...} usable there because an enclosing ``if``
    tested ``hasattr(mod, "attr")``."""
    guarded: dict[int, set[str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.If):
            continue
        keys: set[str] = set()
        for call in ast.walk(node.test):
            if (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Name)
                and call.func.id == "hasattr"
                and len(call.args) == 2
                and isinstance(call.args[0], ast.Name)
                and isinstance(call.args[1], ast.Constant)
            ):
                keys.add(f"{call.args[0].id}.{call.args[1].value}")
        if not keys:
            continue
        for stmt in node.body:
            for sub in ast.walk(stmt):
                lineno = getattr(sub, "lineno", None)
                if lineno is not None:
                    guarded.setdefault(lineno, set()).update(keys)
    return guarded
