"""sync-readback: blocking host readback directly on a jit result.

``np.asarray(jit_fn(...))`` (and ``jax.device_get`` on a jit call) in
model/stage code serializes the four engines the async device pipeline
exists to overlap: the host blocks until the device finishes AND the D2H
transfer completes before it can even start preparing the next batch.
The device-pipeline PR (models/device_pipeline.py) removed every instance
from the hot paths; this rule keeps the pattern from creeping back.

Scope: ``cosmos_curate_tpu/models/`` and ``pipelines/*/stages/`` — the
code that drives devices. ``models/device_pipeline.py`` itself is exempt:
its drain IS the one sanctioned readback point.

Detection is name-based, not type-based: a name counts as jit-derived
when the file binds it (directly or via ``self.``) from

- a ``jax.jit(...)``/``pjit(...)`` call (walked through wrappers like
  ``shard_map``), or
- a call to a same-file function whose body contains ``jax.jit``
  (the ``_jitted_apply``-factory idiom), or
- it matches the repo's jit-holder naming convention (``_apply``,
  ``_sample``, ``_jitted*`` attributes).

Flagged: ``np.asarray(<jit-name>(...))`` / ``np.array(...)`` /
``jax.device_get(...)`` on such a call. ``np.asarray(x)`` on a plain
variable is not flagged (the dispatch already happened; the rule targets
the call-and-block-inline idiom).
"""

from __future__ import annotations

import ast
import re

from cosmos_curate_tpu.analysis.common import Finding
from cosmos_curate_tpu.analysis.rules import Rule, RuleContext

_NUMPY_CONVERTERS = {"asarray", "array", "ascontiguousarray", "asanyarray"}
_JIT_HOLDER_CONVENTION = re.compile(r"^_(jitted\w*|apply|sample)$")
_EXEMPT = ("models/device_pipeline.py",)


def _in_scope(rel: str) -> bool:
    rel = rel.replace("\\", "/")
    if any(rel.endswith(e) for e in _EXEMPT):
        return False
    if "cosmos_curate_tpu/models/" in rel or rel.startswith("models/"):
        return True
    return "/stages/" in rel and "pipelines/" in rel


def _numpy_aliases(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    names.add(a.asname or "numpy")
    return names or {"np", "numpy"}


def _contains_jax_jit(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Attribute) and f.attr in ("jit", "pjit"):
                return True
            if isinstance(f, ast.Name) and f.id in ("jit", "pjit"):
                return True
    return False


def _collect_jit_names(tree: ast.Module) -> set[str]:
    """Names (bare or ``self.<attr>`` attrs) bound from jit-producing
    expressions, including through same-file jit factories."""
    factories: set[str] = {
        node.name
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and _contains_jax_jit(node)
    }

    def value_is_jitty(value: ast.expr) -> bool:
        if _contains_jax_jit(value):
            return True
        for n in ast.walk(value):
            if (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Name)
                and n.func.id in factories
            ):
                return True
        return False

    names: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if value is None or not value_is_jitty(value):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
            elif isinstance(t, ast.Attribute):
                names.add(t.attr)
    return names


def _callee_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


class SyncReadbackRule(Rule):
    rule_id = "sync-readback"
    description = (
        "np.asarray / jax.device_get blocking directly on a jit call in "
        "model/stage code — dispatch through models/device_pipeline.py "
        "(submit + deferred drain) instead"
    )

    def check(self, ctx: RuleContext) -> list[Finding]:
        if not _in_scope(ctx.rel_path):
            return []
        np_names = _numpy_aliases(ctx.tree)
        jit_names = _collect_jit_names(ctx.tree)

        def is_jit_call(expr: ast.expr) -> bool:
            if not isinstance(expr, ast.Call):
                return False
            name = _callee_name(expr)
            if name is None:
                return False
            return name in jit_names or bool(_JIT_HOLDER_CONVENTION.match(name))

        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute) or not isinstance(f.value, ast.Name):
                continue
            owner, attr = f.value.id, f.attr
            flagged = None
            if owner in np_names and attr in _NUMPY_CONVERTERS:
                if node.args and is_jit_call(node.args[0]):
                    flagged = f"{owner}.{attr}(<jit call>)"
            elif owner == "jax" and attr == "device_get":
                # device_get has no deferred form at all — flag any use in
                # device-driving code, jit call or not
                flagged = "jax.device_get(...)"
            if flagged:
                findings.append(
                    Finding(
                        ctx.rel_path, node.lineno, self.rule_id,
                        f"{flagged} blocks the host on device compute + D2H "
                        "inline; submit through DevicePipeline and drain "
                        "(models/device_pipeline.py) so transfer, compute, "
                        "and readback overlap",
                    )
                )
        return findings
