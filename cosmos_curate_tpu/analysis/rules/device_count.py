"""hardcoded-device-count: global device discovery baked into shapes.

Code that derives array shapes or mesh geometry from ``len(jax.devices())``
/ ``jax.device_count()`` — or slices the raw device list — silently changes
meaning with the topology it happens to run on: a batch sized on the v5e-8
dev pod is wrong on the v5p-256 production slice, and a sliced device list
ignores the mesh the rest of the pipeline agreed on. Device counts belong
in ONE place: ``parallel/mesh.py`` (``MeshSpec.resolve`` / the mesh
constructors) and the cluster shape the pipeline declares
(``ClusterShape.num_tpu_chips``). Everything else should read extents off
the mesh (``mesh.shape[axis]``).

Flagged outside ``parallel/``:

- ``jax.device_count()`` / ``jax.local_device_count()``;
- ``len(jax.devices())`` / ``len(jax.local_devices())``;
- slicing the device list (``jax.devices()[:n]``) — build the mesh with
  ``parallel.mesh`` helpers (``seq_mesh``, ``local_mesh``) instead.

``jax.devices()[0].platform`` (the constant-index platform probe) and
filtered discovery (``[d for d in jax.devices() if ...]`` in the engine's
resource discovery) stay clean.
"""

from __future__ import annotations

import ast

from cosmos_curate_tpu.analysis.common import Finding
from cosmos_curate_tpu.analysis.rules import Rule, RuleContext

_DEVICE_LIST_FNS = {"devices", "local_devices"}
_DEVICE_COUNT_FNS = {"device_count", "local_device_count"}
_EXEMPT_PATH = "parallel/"


def _is_device_list_call(node: ast.expr, jax_names: set[str]) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in _DEVICE_LIST_FNS
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id in jax_names
    )


class HardcodedDeviceCountRule(Rule):
    rule_id = "hardcoded-device-count"
    description = (
        "device counts baked into shapes: len(jax.devices()), "
        "jax.device_count(), or slicing the raw device list outside "
        "parallel/mesh.py"
    )

    def check(self, ctx: RuleContext) -> list[Finding]:
        if _EXEMPT_PATH in ctx.rel_path:
            return []
        jax_names = {"jax"}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax":
                        jax_names.add(a.asname or "jax")
        findings: list[Finding] = []

        def flag(lineno: int, what: str, fix: str) -> None:
            findings.append(
                Finding(
                    ctx.rel_path, lineno, self.rule_id,
                    f"{what}: {fix} (device counts belong to parallel/mesh.py "
                    "and the declared ClusterShape, not call sites)",
                )
            )

        for node in ast.walk(ctx.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _DEVICE_COUNT_FNS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in jax_names
            ):
                flag(
                    node.lineno,
                    f"{node.func.value.id}.{node.func.attr}()",
                    "read the extent off the mesh (mesh.shape[axis] / "
                    "MeshSpec.resolve)",
                )
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "len"
                and node.args
                and _is_device_list_call(node.args[0], jax_names)
            ):
                flag(
                    node.lineno,
                    "len(jax.devices())",
                    "read the extent off the mesh (mesh.shape[axis] / "
                    "MeshSpec.resolve)",
                )
            elif (
                isinstance(node, ast.Subscript)
                and _is_device_list_call(node.value, jax_names)
                and isinstance(node.slice, ast.Slice)
            ):
                flag(
                    node.lineno,
                    "slicing jax.devices()",
                    "build the mesh via parallel.mesh helpers "
                    "(seq_mesh/local_mesh/best_effort_mesh)",
                )
        return findings
