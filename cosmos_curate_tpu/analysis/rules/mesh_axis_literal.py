"""mesh-axis-literal: axis-name strings scattered outside the registry.

Mesh axis names are load-bearing strings: a typo'd axis in a
``PartitionSpec`` / ``shard_map`` spec / ``Mesh(axis_names=...)`` fails
minutes into a run on real chips with an opaque trace error. The canonical
registry (``parallel/axes.py``) exists so axis names flow from ONE place —
this rule flags any string literal used as an axis name:

- arguments of ``PartitionSpec(...)`` (including its ubiquitous ``P``
  alias), ``named_sharding``, ``batch_sharding`` and ``local_mesh`` calls
  (strings nested in tuples/lists included);
- ``axis_names=`` / ``axis_name=`` / ``seq_axis=`` / ``batch_axes=``
  keyword values on any call (``Mesh``, collectives, shard_map helpers);
- defaults of function parameters named like axis parameters
  (``axis_name``, ``*_axis``, ``*_axes``).

Literals that are not even canonical axis names get a sharper message —
that is the typo this rule exists for. The registry module itself is
exempt (it defines the strings).
"""

from __future__ import annotations

import ast

from cosmos_curate_tpu.analysis.common import Finding
from cosmos_curate_tpu.analysis.rules import Rule, RuleContext
from cosmos_curate_tpu.parallel.axes import MESH_AXES

_SPEC_CALLS = {"PartitionSpec", "named_sharding", "batch_sharding", "local_mesh"}
_AXIS_KWARGS = {"axis_names", "axis_name", "seq_axis", "batch_axes"}
_REGISTRY_FILE = "parallel/axes.py"


def _partition_spec_aliases(tree: ast.Module) -> set[str]:
    """Names ``PartitionSpec`` is imported as (the ``P`` idiom)."""
    names = set(_SPEC_CALLS)
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name == "PartitionSpec" and a.asname:
                    names.add(a.asname)
    return names


def _axis_param(name: str) -> bool:
    return name in _AXIS_KWARGS or name.endswith(("_axis", "_axes"))


def _string_constants(expr: ast.expr) -> list[tuple[int, str]]:
    out: list[tuple[int, str]] = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.append((node.lineno, node.value))
    return out


class MeshAxisLiteralRule(Rule):
    rule_id = "mesh-axis-literal"
    description = (
        "mesh axis names as raw string literals in PartitionSpec/shard_map/"
        "Mesh specs instead of the parallel/axes.py registry"
    )

    def check(self, ctx: RuleContext) -> list[Finding]:
        if ctx.rel_path.endswith(_REGISTRY_FILE):
            return []
        findings: list[Finding] = []
        spec_calls = _partition_spec_aliases(ctx.tree)

        def flag(lineno: int, value: str, where: str) -> None:
            if value in MESH_AXES:
                const = value.upper()
                msg = (
                    f"axis literal '{value}' in {where}: use "
                    f"cosmos_curate_tpu.parallel.axes.{const} (the canonical "
                    "mesh-axis registry)"
                )
            else:
                msg = (
                    f"'{value}' in {where} is not a canonical mesh axis "
                    f"(registry: {', '.join(MESH_AXES)} — parallel/axes.py)"
                )
            findings.append(Finding(ctx.rel_path, lineno, self.rule_id, msg))

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                func = node.func
                callee = (
                    func.id
                    if isinstance(func, ast.Name)
                    else func.attr if isinstance(func, ast.Attribute) else ""
                )
                if callee in spec_calls:
                    for arg in node.args:
                        for lineno, value in _string_constants(arg):
                            flag(lineno, value, f"{callee}(...)")
                for kw in node.keywords:
                    if kw.arg and kw.arg in _AXIS_KWARGS:
                        for lineno, value in _string_constants(kw.value):
                            flag(lineno, value, f"{callee or 'call'}({kw.arg}=...)")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                pos = args.posonlyargs + args.args
                for param, default in zip(pos[len(pos) - len(args.defaults):], args.defaults):
                    if _axis_param(param.arg):
                        for lineno, value in _string_constants(default):
                            flag(lineno, value, f"default of parameter '{param.arg}'")
                for param, default in zip(args.kwonlyargs, args.kw_defaults):
                    if default is not None and _axis_param(param.arg):
                        for lineno, value in _string_constants(default):
                            flag(lineno, value, f"default of parameter '{param.arg}'")
        return findings
