"""Rule registry for the AST linter.

A rule is an object with a ``rule_id``, a one-line ``description`` and a
``check(ctx: RuleContext) -> list[Finding]`` method. Rules are registered
explicitly here (no import-time magic): adding a rule means adding a module
under ``analysis/rules/`` and listing it in :func:`all_rules`.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path

from cosmos_curate_tpu.analysis.common import Finding, LintConfig


@dataclass
class RuleContext:
    """Everything a rule may look at for one file."""

    path: Path  # absolute path on disk
    rel_path: str  # repo-relative, POSIX-style; used in findings and scoping
    tree: ast.Module
    source: str
    config: LintConfig


class Rule:
    rule_id: str = ""
    description: str = ""

    def check(self, ctx: RuleContext) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


def all_rules() -> list[Rule]:
    from cosmos_curate_tpu.analysis.rules.ad_hoc_backoff import AdHocBackoffRule
    from cosmos_curate_tpu.analysis.rules.blocking_in_async import BlockingInAsyncRule
    from cosmos_curate_tpu.analysis.rules.device_count import HardcodedDeviceCountRule
    from cosmos_curate_tpu.analysis.rules.jit_transfer import JitTransferRule
    from cosmos_curate_tpu.analysis.rules.lock_discipline import LockDisciplineRule
    from cosmos_curate_tpu.analysis.rules.mesh_axis_literal import MeshAxisLiteralRule
    from cosmos_curate_tpu.analysis.rules.min_python import MinPythonRule
    from cosmos_curate_tpu.analysis.rules.sharding_constraint import (
        ShardingConstraintOutsideJitRule,
    )
    from cosmos_curate_tpu.analysis.rules.silent_swallow import SilentSwallowRule
    from cosmos_curate_tpu.analysis.rules.sync_readback import SyncReadbackRule
    from cosmos_curate_tpu.analysis.rules.thread_lifecycle import ThreadLifecycleRule

    return [
        LockDisciplineRule(),
        ThreadLifecycleRule(),
        BlockingInAsyncRule(),
        MinPythonRule(),
        JitTransferRule(),
        SilentSwallowRule(),
        AdHocBackoffRule(),
        MeshAxisLiteralRule(),
        HardcodedDeviceCountRule(),
        ShardingConstraintOutsideJitRule(),
        SyncReadbackRule(),
    ]
