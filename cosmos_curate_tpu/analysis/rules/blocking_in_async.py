"""blocking-in-async: synchronous blocking calls inside ``async def``.

An event loop runs every coroutine on one thread: a single ``os.fsync``
(milliseconds on a good day, seconds on a loaded disk) freezes EVERY
in-flight request, not just the one that made it. The service found this
the hard way — the dispatcher journaled job transitions with a
flush+fsync directly on the loop, so interactive-lane submissions paid
for batch-job journaling (service/app.py now offloads appends to a
single-thread executor).

The call table is shared with the concurrency verifier's
blocking-under-lock analysis (``analysis/concurrency_check.py``) — the
same calls that stall a lock's waiters stall an event loop:

- ``os.fsync``/``os.fdatasync``, ``time.sleep`` (use ``asyncio.sleep``),
  ``subprocess.run/Popen/...``, ``shutil.copy*/move``;
- socket ``accept``/``recv*``/``sendall`` (use loop transports or
  ``sock_*`` wrappers);
- blocking ``Queue.put``/``get`` on queue-ish receivers (``asyncio.Queue``
  is awaited, so its put/get never match the call shape flagged here);
- ``thread/proc/worker/agent``-ish ``.join()``;
- repo contract: ``*journal*.append(...)`` / ``.compact(...)`` — the
  JobJournal fsyncs before returning by durability contract, so calling
  it from a coroutine is an fsync on the loop in disguise.

Blocking rarely sits lexically in the coroutine — the service's fsync hid
two frames down (``async invoke`` → ``record_transition`` →
``journal.append``). So the rule is transitive within a file: it first
maps every SYNC function to the blocking calls reachable through
same-file calls, then flags an ``async def`` both for direct hits and for
calling a sync function whose closure blocks (the finding names the
chain).

Nested ``def``/``lambda`` bodies are skipped: the dominant idiom for
fixing a finding is wrapping the call for ``run_in_executor`` /
``asyncio.to_thread``, and the wrapper executes on an executor thread.
``await``-ed expressions are fine by construction (awaitables yield).
"""

from __future__ import annotations

import ast
import re

from cosmos_curate_tpu.analysis.common import Finding
from cosmos_curate_tpu.analysis.rules import Rule, RuleContext

_JOURNALISH = re.compile(r"journal", re.IGNORECASE)


def _receiver(func: ast.expr) -> str | None:
    """Best-effort receiver name of an attribute call: ``a.b.c()`` -> "b",
    ``x.get()`` -> "x" (matching concurrency_check's receiver heuristics)."""
    if not isinstance(func, ast.Attribute):
        return None
    v = func.value
    if isinstance(v, ast.Name):
        return v.id
    if isinstance(v, ast.Attribute):
        return v.attr
    return None


def _blocking_desc(node: ast.Call) -> str | None:
    """The shared blocking-call table, minus the lock-specific entries
    (cv.wait, jit dispatch) that need held-lock context to judge."""
    from cosmos_curate_tpu.analysis.concurrency_check import (
        _QUEUEISH,
        _JOINABLE,
        _SOCKET_BLOCKERS,
        _SUBPROCESS_BLOCKERS,
    )

    func = node.func
    attr = func.attr if isinstance(func, ast.Attribute) else None
    recv = _receiver(func)
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        owner = func.value.id
        if owner == "os" and attr in ("fsync", "fdatasync"):
            return f"os.{attr}()"
        if owner == "time" and attr == "sleep":
            return "time.sleep() (use asyncio.sleep)"
        if owner == "subprocess" and attr in _SUBPROCESS_BLOCKERS:
            return f"subprocess.{attr}()"
        if owner == "shutil" and attr in ("copy", "copy2", "copytree", "move"):
            return f"shutil.{attr}()"
    if attr in _SOCKET_BLOCKERS:
        return f".{attr}() (socket)"
    if attr in ("put", "get") and recv and _QUEUEISH.search(recv):
        if not any(
            kw.arg == "block"
            and isinstance(kw.value, ast.Constant)
            and kw.value.value is False
            for kw in node.keywords
        ):
            return f"blocking {recv}.{attr}()"
    if attr == "join" and recv and _JOINABLE.search(recv):
        return f"{recv}.join()"
    if attr in ("append", "compact") and recv and _JOURNALISH.search(recv):
        # JobJournal.append flush+fsyncs before returning (durability
        # before ack); from a coroutine that is an fsync on the loop
        return f"{recv}.{attr}() (fsyncs by contract)"
    return None


def _local_callee(func: ast.expr) -> str | None:
    """Name of a same-file callee: ``self.foo(...)`` / ``obj.foo(...)`` /
    ``foo(...)`` -> "foo". Resolution is by bare name — methods of OTHER
    objects that happen to share a local function's name can false-match,
    which suppression comments cover (precision over a type checker we
    don't have)."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class _BodyScanner(ast.NodeVisitor):
    """Collects blocking calls + local-callee names lexically inside ONE
    function, skipping nested function scopes and awaited expressions."""

    def __init__(self) -> None:
        self.hits: list[tuple[int, str]] = []
        self.calls: list[tuple[int, str]] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass  # nested sync def: its body runs wherever it is called

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass  # nested coroutine: flagged when visited as its own root

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass  # lambdas are the run_in_executor wrapper idiom

    def visit_Await(self, node: ast.Await) -> None:
        # the awaited call itself yields; its ARGUMENTS still evaluate
        # synchronously on the loop
        if isinstance(node.value, ast.Call):
            for arg in node.value.args:
                self.visit(arg)
            for kw in node.value.keywords:
                self.visit(kw.value)
        else:
            self.visit(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        desc = _blocking_desc(node)
        if desc is not None:
            self.hits.append((node.lineno, desc))
        callee = _local_callee(node.func)
        if callee is not None:
            self.calls.append((node.lineno, callee))
        self.generic_visit(node)


def _scan(node: ast.FunctionDef | ast.AsyncFunctionDef) -> _BodyScanner:
    scanner = _BodyScanner()
    for stmt in node.body:
        scanner.visit(stmt)
    return scanner


def _sync_blocking_closure(
    tree: ast.Module,
) -> dict[str, str]:
    """sync function name -> description of the blocking call reachable
    from it through same-file sync calls (fixed-point over the call map)."""
    scans: dict[str, _BodyScanner] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            scans[node.name] = _scan(node)
    blocking: dict[str, str] = {
        name: s.hits[0][1] for name, s in scans.items() if s.hits
    }
    changed = True
    while changed:
        changed = False
        for name, s in scans.items():
            if name in blocking:
                continue
            for _lineno, callee in s.calls:
                if callee != name and callee in blocking:
                    blocking[name] = f"{callee}() → {blocking[callee]}"
                    changed = True
                    break
    return blocking


class BlockingInAsyncRule(Rule):
    rule_id = "blocking-in-async"
    description = (
        "synchronous blocking call (fsync/sleep/subprocess/socket/queue/"
        "join/journal-append) inside an async def: stalls every coroutine "
        "on the event loop"
    )

    def check(self, ctx: RuleContext) -> list[Finding]:
        rel = ctx.rel_path.replace("\\", "/")
        if rel.startswith("tests/"):
            return []
        has_async = any(
            isinstance(n, ast.AsyncFunctionDef) for n in ast.walk(ctx.tree)
        )
        if not has_async:
            return []
        sync_blocking = _sync_blocking_closure(ctx.tree)
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            scanner = _scan(node)
            hits = list(scanner.hits)
            direct_lines = {lineno for lineno, _ in hits}
            for lineno, callee in scanner.calls:
                if callee in sync_blocking and lineno not in direct_lines:
                    hits.append(
                        (lineno, f"{callee}() → {sync_blocking[callee]}")
                    )
            for lineno, desc in sorted(hits):
                findings.append(
                    Finding(
                        ctx.rel_path, lineno, self.rule_id,
                        f"{desc} inside `async def {node.name}` blocks the "
                        "event loop for every coroutine; offload with "
                        "loop.run_in_executor(...) or use the async "
                        "equivalent",
                    )
                )
        return findings
