"""jit-transfer: host-device transfer smells inside jitted functions.

``.item()``, ``float(x)`` / ``int(x)``, ``np.asarray``, ``jax.device_get``
inside a function decorated with ``jax.jit``/``pjit`` either force a
blocking device->host transfer per call or raise a ``TracerConversionError``
at trace time — both are bugs you want at lint time, not on the TPU.

The rule only inspects functions whose decorator list mentions ``jit`` or
``pjit`` (directly, dotted, or wrapped in ``functools.partial``), so plain
NumPy code is never flagged.
"""

from __future__ import annotations

import ast

from cosmos_curate_tpu.analysis.common import Finding
from cosmos_curate_tpu.analysis.rules import Rule, RuleContext

_JIT_NAMES = {"jit", "pjit"}
_TRANSFER_METHODS = {"item", "tolist", "numpy", "__array__"}
_CAST_BUILTINS = {"float", "int", "bool", "complex"}
_NUMPY_CONVERTERS = {"asarray", "array", "ascontiguousarray", "asanyarray"}


def _mentions_jit(expr: ast.expr) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in _JIT_NAMES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _JIT_NAMES:
            return True
    return False


def _numpy_aliases(tree: ast.Module) -> set[str]:
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("numpy", "numpy.ma"):
                    names.add(a.asname or "numpy")
    return names or {"np", "numpy", "onp"}


def _jax_aliases(tree: ast.Module) -> set[str]:
    names = {"jax"}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax":
                    names.add(a.asname or "jax")
    return names


class JitTransferRule(Rule):
    rule_id = "jit-transfer"
    description = (
        "host-device transfers (.item(), float()/int() on arrays, "
        "np.asarray, jax.device_get) inside jax.jit/pjit-compiled functions"
    )

    def check(self, ctx: RuleContext) -> list[Finding]:
        findings: list[Finding] = []
        np_names = _numpy_aliases(ctx.tree)
        jax_names = _jax_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(_mentions_jit(d) for d in node.decorator_list):
                continue
            findings.extend(self._check_jit_body(ctx, node, np_names, jax_names))
        return findings

    def _check_jit_body(
        self,
        ctx: RuleContext,
        fn: ast.AST,
        np_names: set[str],
        jax_names: set[str],
    ) -> list[Finding]:
        findings: list[Finding] = []
        fn_name = getattr(fn, "name", "<fn>")
        shape_names, traced_names = _classify_locals(fn)

        def flag(lineno: int, what: str, why: str) -> None:
            findings.append(
                Finding(
                    ctx.rel_path, lineno, self.rule_id,
                    f"{what} inside jitted function '{fn_name}' {why}",
                )
            )

        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                if func.attr in _TRANSFER_METHODS and not isinstance(
                    func.value, ast.Name
                ):
                    flag(node.lineno, f".{func.attr}()",
                         "forces a blocking device->host transfer per call")
                elif func.attr in _TRANSFER_METHODS and isinstance(func.value, ast.Name):
                    # obj.item() — can't see the type, but in jit context the
                    # receiver is almost always a traced array
                    flag(node.lineno, f"{func.value.id}.{func.attr}()",
                         "forces a blocking device->host transfer per call")
                elif (
                    isinstance(func.value, ast.Name)
                    and func.value.id in np_names
                    and func.attr in _NUMPY_CONVERTERS
                ):
                    flag(node.lineno, f"{func.value.id}.{func.attr}()",
                         "materializes the traced array on the host "
                         "(use jnp equivalents)")
                elif (
                    isinstance(func.value, ast.Name)
                    and func.value.id in jax_names
                    and func.attr == "device_get"
                ):
                    flag(node.lineno, f"{func.value.id}.device_get()",
                         "pulls values to the host mid-computation")
            elif isinstance(func, ast.Name) and func.id in _CAST_BUILTINS:
                if (
                    node.args
                    and not isinstance(node.args[0], ast.Constant)
                    and _references_traced(node.args[0], shape_names, traced_names)
                ):
                    flag(node.lineno, f"{func.id}()",
                         "concretizes a traced value (TracerConversionError "
                         "at trace time, or a silent host sync)")
        return findings


def _is_shape_expr(expr: ast.expr) -> bool:
    """Shape arithmetic yields static Python ints under tracing —
    ``x.shape``, ``x.ndim``, ``len(x)`` — safe to cast."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in ("shape", "ndim", "size"):
            return True
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "len"
        ):
            return True
    return False


def _classify_locals(fn: ast.AST) -> tuple[set[str], set[str]]:
    """-> (names bound from shape-ish expressions, names that may hold
    traced arrays: parameters + every other local binding)."""
    shape_names: set[str] = set()
    traced: set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            traced.add(a.arg)
        if args.vararg:
            traced.add(args.vararg.arg)
        if args.kwarg:
            traced.add(args.kwarg.arg)
    for node in ast.walk(fn):
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets, value = list(node.targets), node.value
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)) and node.value is not None:
            targets, value = [node.target], node.value
        elif isinstance(node, ast.For):
            targets, value = [node.target], node.iter
        else:
            continue
        bucket = shape_names if _is_shape_expr(value) else traced
        for t in targets:
            for el in t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]:
                if isinstance(el, ast.Name):
                    bucket.add(el.id)
    return shape_names - traced, traced


def _references_traced(expr: ast.expr, shape_names: set[str], traced: set[str]) -> bool:
    """True when the expression touches a name that may be a traced array.
    Names never bound locally (module constants) and shape-derived ints
    don't count, so ``int(h * _BAND)`` with ``h`` from ``x.shape`` is
    clean while ``int(loss)`` is flagged."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id in traced and node.id not in shape_names:
            return True
    return False
