"""sharding-constraint-outside-jit: a layout annotation that does nothing.

``with_sharding_constraint`` tells XLA where an intermediate value must
live *inside a compiled computation*. Outside ``jax.jit`` there is no
compiler to constrain: depending on JAX version the call is an eager
device_put (a surprise blocking transfer) or an error — either way the
author's intent ("annotate the layout mid-computation") silently did not
happen, and the real resharding cost appears somewhere else.

The rule flags calls to ``with_sharding_constraint`` (bare, dotted, or
``jax.lax.``-qualified) whose enclosing function is not jit-compiled.
"Jit-compiled" means: decorated with ``jit``/``pjit`` (directly, dotted,
or via ``functools.partial``), wrapped by name in a ``jax.jit(...)`` call
anywhere in the file, or nested inside such a function (inner defs are
traced with the outer). Module-level calls are always flagged.
"""

from __future__ import annotations

import ast

from cosmos_curate_tpu.analysis.common import Finding
from cosmos_curate_tpu.analysis.rules import Rule, RuleContext
from cosmos_curate_tpu.analysis.rules.jit_transfer import _mentions_jit

_TARGET = "with_sharding_constraint"


def _jit_wrapped_names(tree: ast.Module) -> set[str]:
    """Function names passed to a jit/pjit call somewhere in the file
    (``fwd = jax.jit(fwd)``, ``jax.jit(shard_map(step, ...))``)."""
    wrapped: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _mentions_jit(node.func):
            for arg in ast.walk(node):
                if isinstance(arg, ast.Name):
                    wrapped.add(arg.id)
    return wrapped


def _is_target_call(node: ast.Call) -> bool:
    func = node.func
    if isinstance(func, ast.Name):
        return func.id == _TARGET
    return isinstance(func, ast.Attribute) and func.attr == _TARGET


class ShardingConstraintOutsideJitRule(Rule):
    rule_id = "sharding-constraint-outside-jit"
    description = (
        "with_sharding_constraint outside a jit-compiled function "
        "(no compiler to constrain: eager transfer or error)"
    )

    def check(self, ctx: RuleContext) -> list[Finding]:
        findings: list[Finding] = []
        wrapped = _jit_wrapped_names(ctx.tree)

        def visit(node: ast.AST, inside_jit: bool) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    child_jitted = (
                        inside_jit
                        or any(_mentions_jit(d) for d in child.decorator_list)
                        or child.name in wrapped
                    )
                    visit(child, child_jitted)
                    continue
                if (
                    not inside_jit
                    and isinstance(child, ast.Call)
                    and _is_target_call(child)
                ):
                    findings.append(
                        Finding(
                            ctx.rel_path, child.lineno, self.rule_id,
                            "with_sharding_constraint outside a jit-compiled "
                            "function has no compile-time effect — move it "
                            "inside the jitted computation, or use "
                            "jax.device_put with a NamedSharding for eager "
                            "placement",
                        )
                    )
                visit(child, inside_jit)

        visit(ctx.tree, False)
        return findings
