"""silent-swallow: broad exception handlers that drop errors in worker loops.

A bare ``except:`` / ``except Exception:`` inside an engine worker loop
that neither re-raises, logs, nor performs any remediation turns a
systematic failure (every batch poisoned, a dead socket, a full disk) into
silent data loss at petabyte scale. Scoped to files under ``engine/`` and
to handlers lexically inside a ``for``/``while`` loop — the hot paths where
a swallowed exception repeats forever.

A handler counts as *silent* only when its body contains no ``raise``, no
log-like call (``logger.*``, ``logging.*``, ``print``, ``warnings.warn``,
``traceback.print_exc``) and no other call at all (so cleanup/remediation
handlers — ``proc.terminate()``, ``sock.close()`` — are not flagged).
"""

from __future__ import annotations

import ast

from cosmos_curate_tpu.analysis.common import Finding
from cosmos_curate_tpu.analysis.rules import Rule, RuleContext

_BROAD = {"Exception", "BaseException"}
_LOG_METHODS = {"debug", "info", "warning", "error", "exception", "critical", "log", "warn"}


def _is_broad(handler: ast.ExceptHandler) -> str | None:
    """-> the broad exception name, or None for narrow handlers."""
    t = handler.type
    if t is None:
        return "bare except"
    names = []
    if isinstance(t, ast.Tuple):
        names = [getattr(e, "id", getattr(e, "attr", "")) for e in t.elts]
    else:
        names = [getattr(t, "id", getattr(t, "attr", ""))]
    for n in names:
        if n in _BROAD:
            return f"except {n}"
    return None


def _is_silent(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if isinstance(node, ast.Call):
            return False  # any call = logging or remediation
        # `except Exception as e: err = e` propagates the error by hand
        # (e.g. raise-after-cleanup loops) — not a swallow
        if (
            handler.name
            and isinstance(node, ast.Name)
            and node.id == handler.name
            and isinstance(node.ctx, ast.Load)
        ):
            return False
    return True


class SilentSwallowRule(Rule):
    rule_id = "silent-swallow"
    description = (
        "bare/broad except with no re-raise, no log and no remediation "
        "inside engine worker loops"
    )

    def check(self, ctx: RuleContext) -> list[Finding]:
        if "engine/" not in ctx.rel_path.replace("\\", "/"):
            return []
        findings: list[Finding] = []
        self._walk(ctx, ctx.tree, in_loop=False, findings=findings)
        return findings

    def _walk(
        self, ctx: RuleContext, node: ast.AST, *, in_loop: bool, findings: list[Finding]
    ) -> None:
        for child in ast.iter_child_nodes(node):
            child_in_loop = in_loop or isinstance(child, (ast.For, ast.While))
            if isinstance(child, ast.ExceptHandler) and in_loop:
                broad = _is_broad(child)
                if broad and _is_silent(child):
                    findings.append(
                        Finding(
                            ctx.rel_path, child.lineno, self.rule_id,
                            f"{broad} inside a worker loop swallows errors "
                            "silently: re-raise, log, or narrow the exception "
                            "type",
                        )
                    )
            # function boundaries reset loop context: a handler inside a
            # nested function is only "in a loop" via its own loops
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                self._walk(ctx, child, in_loop=False, findings=findings)
            else:
                self._walk(ctx, child, in_loop=child_in_loop, findings=findings)
        return
