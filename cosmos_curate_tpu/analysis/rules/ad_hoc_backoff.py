"""ad-hoc-backoff: hand-rolled exponential-backoff sleeps.

The repo once carried four copies of ``time.sleep(min(2.0**attempt * 0.2,
5.0))`` — all without jitter, so a fleet of workers that saw the same
outage retried in lockstep and re-created the thundering herd on every
backoff step. The canonical helper (``storage/retry.py:sleep_backoff``)
adds full jitter and one shared schedule; this rule keeps new copies from
creeping back in.

Flags any ``time.sleep(expr)`` / bare ``sleep(expr)`` call whose argument
contains an exponentiation (``2 ** attempt``) — the signature of a
hand-rolled schedule — in every file except ``storage/retry.py`` itself.
"""

from __future__ import annotations

import ast

from cosmos_curate_tpu.analysis.common import Finding
from cosmos_curate_tpu.analysis.rules import Rule, RuleContext


def _is_sleep_call(node: ast.Call) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "sleep":
        # time.sleep / <anything>.sleep — Event.wait-style APIs don't
        # collide because their attr is not "sleep"
        return isinstance(f.value, ast.Name) and f.value.id == "time"
    return isinstance(f, ast.Name) and f.id == "sleep"


def _has_pow(node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.BinOp) and isinstance(n.op, ast.Pow) for n in ast.walk(node)
    )


class AdHocBackoffRule(Rule):
    rule_id = "ad-hoc-backoff"
    description = (
        "hand-rolled exponential-backoff sleep outside storage/retry.py "
        "(no jitter: a worker fleet retries in lockstep)"
    )

    def check(self, ctx: RuleContext) -> list[Finding]:
        rel = ctx.rel_path.replace("\\", "/")
        if rel.endswith("storage/retry.py") or rel.startswith("tests/"):
            return []
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and _is_sleep_call(node)):
                continue
            if any(_has_pow(a) for a in node.args):
                findings.append(
                    Finding(
                        ctx.rel_path, node.lineno, self.rule_id,
                        "hand-rolled exponential backoff retries in lockstep "
                        "across a fleet; use "
                        "cosmos_curate_tpu.storage.retry.sleep_backoff "
                        "(full jitter, shared schedule)",
                    )
                )
        return findings
