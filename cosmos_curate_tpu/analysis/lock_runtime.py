"""Runtime lock sanitizer — the dynamic twin of ``concurrency_check.py``.

Opt-in (``CURATE_LOCKCHECK=1`` before the first ``import
cosmos_curate_tpu``) because it proxies every ``threading.Lock`` /
``threading.RLock`` the repo creates. When enabled it records, per thread:

- the set of proxied locks currently held (an ordered stack);
- every observed acquisition-order edge ``held -> newly-acquired``;
- **order inversions**: acquiring B while holding A after some thread has
  already acquired A while holding B — the live counterpart of the static
  checker's ``lock-order`` cycles;
- **blocking under lock**: ``time.sleep`` / ``os.fsync`` executed while
  any proxied lock is held (the live counterpart of ``lock-blocking``);
- per-lock max hold time, acquisition count, and peak waiters.

Locks are named by their creation site (repo-relative ``file:line``),
which joins onto the static pass through
``LockRegistry.by_site()`` — see :func:`cross_validate`. Locks created
outside the repo tree (stdlib ``queue.Queue`` internals, third-party
code) get real locks, not proxies: the sanitizer watches *our* locks
only, so overhead stays proportional to repo lock traffic.

The proxies implement ``_release_save`` / ``_acquire_restore`` /
``_is_owned``, so a ``threading.Condition`` built on a proxied lock
(``Condition(self._lock)``, or a bare ``Condition()`` whose implicit
RLock resolves through the patched constructor) keeps the held-set
consistent across ``wait()``.

Knobs:

- ``CURATE_LOCKCHECK=1``         — install at package import.
- ``CURATE_LOCKCHECK_REPORT=p``  — dump a JSON report to ``p`` at exit
  (default ``lockcheck_report.json`` in the CWD). When ``p`` is an
  existing directory, each process writes ``lockcheck-<pid>.json``
  inside it — the soak scripts point every spawned process at one
  directory and sweep it afterwards.

Report schema (``lockcheck_report.json``)::

    {"clean": bool,                  # no inversions and no blocking events
     "locks": {name: {"acquisitions": n, "max_hold_s": s, "reentrant": b}},
     "edges": [[src, dst], ...],     # observed order edges (site names)
     "inversions": [{"held": a, "acquiring": b, "prior_edge": [b, a],
                     "thread": t, "stack": [...]}],
     "blocking": [{"call": c, "held": [...], "thread": t, "stack": [...]}]}

Programmatic use (tests, soaks)::

    rec = install()           # idempotent; returns the active recorder
    ... exercise code ...
    report = rec.report()
    uninstall()               # restore the real constructors
"""

from __future__ import annotations

import atexit
import functools
import json
import os
import sys
import threading
import time
import traceback
from pathlib import Path
from typing import Any

ENV_FLAG = "CURATE_LOCKCHECK"
ENV_REPORT = "CURATE_LOCKCHECK_REPORT"
DEFAULT_REPORT = "lockcheck_report.json"

# Bound the evidence lists so a pathological soak can't balloon the report:
# the first occurrences carry all the diagnostic value.
_MAX_EVENTS = 200
_STACK_DEPTH = 6

# Real constructors, captured at import so proxies and the recorder itself
# never recurse through the patch.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_SLEEP = time.sleep
_REAL_FSYNC = os.fsync


def _repo_root() -> Path:
    from cosmos_curate_tpu.analysis.common import find_pyproject

    pyproject = find_pyproject()
    return pyproject.parent if pyproject else Path.cwd()


def _short_stack() -> list[str]:
    """Innermost repo frames as ``file:line fn`` — enough to find the site
    without shipping whole tracebacks into the report."""
    out = []
    for fr in traceback.extract_stack()[:-2][-_STACK_DEPTH:]:
        out.append(f"{fr.filename}:{fr.lineno} {fr.name}")
    return out


class LockOrderError(AssertionError):
    """Raised on inversion when the recorder runs in strict mode (tests)."""


class _Recorder:
    """Process-global observation store. All mutation happens under a real
    (unproxied) lock; the per-thread held stack is thread-local so reads on
    the acquire hot path are lock-free."""

    def __init__(self, repo_root: Path, strict: bool = False) -> None:
        self.repo_root = repo_root.resolve()
        self.strict = strict
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        # name -> {"acquisitions", "max_hold_s", "reentrant"}
        self.locks: dict[str, dict[str, Any]] = {}
        # observed order edges (src site, dst site) -> first-seen stack
        self.edges: dict[tuple[str, str], list[str]] = {}
        self.inversions: list[dict[str, Any]] = []
        self.blocking: list[dict[str, Any]] = []

    # -- per-thread held stack ---------------------------------------------

    def held(self) -> list["_ProxyBase"]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def held_names(self) -> list[str]:
        seen: list[str] = []
        for p in self.held():
            if p.name not in seen:
                seen.append(p.name)
        return seen

    # -- event recording ----------------------------------------------------

    def register(self, proxy: "_ProxyBase") -> None:
        with self._mu:
            self.locks.setdefault(
                proxy.name,
                {"acquisitions": 0, "max_hold_s": 0.0, "reentrant": proxy.reentrant},
            )

    def note_acquired(self, proxy: "_ProxyBase", held: list["_ProxyBase"]) -> None:
        """Called after a successful non-reentrant acquire, with ``held``
        the stack *before* this acquisition."""
        inversion = None
        with self._mu:
            stats = self.locks.setdefault(
                proxy.name,
                {"acquisitions": 0, "max_hold_s": 0.0, "reentrant": proxy.reentrant},
            )
            stats["acquisitions"] += 1
            for h in held:
                if h.name == proxy.name:
                    continue
                edge = (h.name, proxy.name)
                if edge not in self.edges:
                    self.edges[edge] = _short_stack()
                if (proxy.name, h.name) in self.edges and len(
                    self.inversions
                ) < _MAX_EVENTS:
                    inversion = {
                        "held": h.name,
                        "acquiring": proxy.name,
                        "prior_edge": [proxy.name, h.name],
                        "thread": threading.current_thread().name,
                        "stack": _short_stack(),
                    }
                    self.inversions.append(inversion)
        if inversion is not None and self.strict:
            raise LockOrderError(
                f"lock-order inversion: acquiring {proxy.name} while holding "
                f"{inversion['held']} — the opposite order was already observed"
            )

    def note_released(self, proxy: "_ProxyBase", held_s: float) -> None:
        with self._mu:
            stats = self.locks.get(proxy.name)
            if stats is not None and held_s > stats["max_hold_s"]:
                stats["max_hold_s"] = held_s

    def note_blocking(self, call: str) -> None:
        names = self.held_names()
        if not names:
            return
        with self._mu:
            if len(self.blocking) < _MAX_EVENTS:
                self.blocking.append(
                    {
                        "call": call,
                        "held": names,
                        "thread": threading.current_thread().name,
                        "stack": _short_stack(),
                    }
                )

    # -- reporting ----------------------------------------------------------

    def report(self) -> dict[str, Any]:
        with self._mu:
            return {
                "clean": not self.inversions and not self.blocking,
                "locks": {k: dict(v) for k, v in self.locks.items()},
                "edges": sorted([src, dst] for src, dst in self.edges),
                "inversions": list(self.inversions),
                "blocking": list(self.blocking),
            }

    def dump(self, path: str | Path | None = None) -> Path:
        out = Path(path or os.environ.get(ENV_REPORT, DEFAULT_REPORT))
        if out.is_dir():
            # directory target: per-process file, so a soak's driver and
            # worker processes (which inherit ENV_REPORT) don't clobber
            # each other's reports
            out = out / f"lockcheck-{os.getpid()}.json"
        tmp = out.with_name(out.name + f".{os.getpid()}.tmp")
        tmp.write_text(json.dumps(self.report(), indent=2, sort_keys=True))
        tmp.replace(out)
        return out


# ---------------------------------------------------------------------------
# lock proxies


class _ProxyBase:
    """Shared acquire/release bookkeeping. Subclasses bind the inner lock
    kind; the recorder only ever sees ``name`` / ``reentrant``."""

    reentrant = False

    def __init__(self, inner: Any, name: str, rec: _Recorder) -> None:
        self._inner = inner
        self.name = name
        self._rec = rec
        self._t0 = 0.0
        rec.register(self)

    # Depth of *this* lock on the current thread's stack (RLock re-entry).
    def _depth(self) -> int:
        return sum(1 for p in self._rec.held() if p is self)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = self._rec.held()
        first = self._depth() == 0
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if first:
                self._rec.note_acquired(self, list(held))
                self._t0 = time.monotonic()
            held.append(self)
        return ok

    def release(self) -> None:
        self._inner.release()
        held = self._rec.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        if self._depth() == 0 and self._t0:
            self._rec.note_released(self, time.monotonic() - self._t0)
            self._t0 = 0.0

    __enter__ = acquire

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{type(self).__name__} {self.name} wrapping {self._inner!r}>"


class _LockProxy(_ProxyBase):
    reentrant = False


class _RLockProxy(_ProxyBase):
    reentrant = True

    # Condition integration: threading.Condition grabs these three methods
    # off its lock when present. Delegating while keeping the held stack
    # consistent is what lets ``cv.wait()`` hand the lock to another thread
    # without the sanitizer thinking it is still held here.

    def _release_save(self) -> Any:
        depth = self._depth()
        state = self._inner._release_save()
        held = self._rec.held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
        if self._t0:
            self._rec.note_released(self, time.monotonic() - self._t0)
            self._t0 = 0.0
        return (state, depth)

    def _acquire_restore(self, saved: Any) -> None:
        state, depth = saved
        self._inner._acquire_restore(state)
        held = self._rec.held()
        self._rec.note_acquired(self, list(held))
        self._t0 = time.monotonic()
        held.extend([self] * depth)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def locked(self) -> bool:  # RLock on some versions lacks .locked()
        try:
            return self._inner.locked()
        except AttributeError:  # pragma: no cover - py<3.12
            return self._inner._is_owned()


# ---------------------------------------------------------------------------
# installation


_active: _Recorder | None = None


def _creation_site(rec: _Recorder) -> tuple[str, int] | None:
    """Repo-relative (file, line) of the frame calling ``Lock()`` —
    skipping threading.py itself so ``Condition()``'s implicit RLock is
    attributed to the Condition call site. None -> non-repo code."""
    threading_file = threading.__file__
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename == threading_file:
        f = f.f_back
    if f is None:
        return None
    fname = f.f_code.co_filename
    try:
        rel = Path(fname).resolve().relative_to(rec.repo_root).as_posix()
    except ValueError:
        return None
    return rel, f.f_lineno


def _make_factory(real_ctor: Any, proxy_cls: type, rec: _Recorder) -> Any:
    @functools.wraps(real_ctor)
    def factory(*args: Any, **kwargs: Any) -> Any:
        inner = real_ctor(*args, **kwargs)
        site = _creation_site(rec)
        if site is None:
            return inner  # non-repo lock: stay out of the way
        return proxy_cls(inner, f"{site[0]}:{site[1]}", rec)

    return factory


def _patched_sleep(rec: _Recorder, secs: float) -> None:
    rec.note_blocking("time.sleep")
    _REAL_SLEEP(secs)


def _patched_fsync(rec: _Recorder, fd: int) -> None:
    rec.note_blocking("os.fsync")
    _REAL_FSYNC(fd)


def install(strict: bool = False, repo_root: Path | None = None) -> _Recorder:
    """Patch the lock constructors and blocking syscall wrappers.
    Idempotent: a second call returns the active recorder unchanged."""
    global _active
    if _active is not None:
        return _active
    rec = _Recorder(repo_root or _repo_root(), strict=strict)
    threading.Lock = _make_factory(_REAL_LOCK, _LockProxy, rec)
    threading.RLock = _make_factory(_REAL_RLOCK, _RLockProxy, rec)
    time.sleep = functools.partial(_patched_sleep, rec)
    os.fsync = functools.partial(_patched_fsync, rec)
    _active = rec
    return rec


def uninstall() -> _Recorder | None:
    """Restore the real constructors; returns the recorder (with all its
    observations) for inspection, or None if nothing was installed."""
    global _active
    rec, _active = _active, None
    if rec is not None:
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        time.sleep = _REAL_SLEEP
        os.fsync = _REAL_FSYNC
    return rec


def active() -> _Recorder | None:
    return _active


def maybe_install_from_env() -> _Recorder | None:
    """The ``cosmos_curate_tpu/__init__`` hook: install + register the
    exit-time report dump iff ``CURATE_LOCKCHECK=1``."""
    if os.environ.get(ENV_FLAG, "") not in ("1", "true", "yes"):
        return None
    rec = install()

    @atexit.register
    def _dump() -> None:  # pragma: no cover - exercised by soaks
        try:
            rec.dump()
        except OSError:
            pass

    return rec


# ---------------------------------------------------------------------------
# static/dynamic cross-validation


def cross_validate(report: dict[str, Any], analysis: Any) -> list[str]:
    """Compare a runtime report against a static ``RepoAnalysis``.

    Returns human-readable gap notes: an *observed* order edge whose both
    endpoints are statically-registered locks but which the static graph
    lacks means the AST pass missed a real nesting (e.g. through a code
    path it cannot follow) — worth a look, not necessarily a bug.
    """
    by_site = analysis.registry.by_site()

    def to_key(name: str) -> str | None:
        file, _, line = name.rpartition(":")
        try:
            return by_site.get((file, int(line)))
        except ValueError:
            return None

    static_edges = {
        (analysis.registry.root(a), analysis.registry.root(b))
        for a, b in analysis.edge_set()
    }
    gaps: list[str] = []
    for src, dst in report.get("edges", []):
        ks, kd = to_key(src), to_key(dst)
        if ks is None or kd is None:
            continue
        ks, kd = analysis.registry.root(ks), analysis.registry.root(kd)
        if ks != kd and (ks, kd) not in static_edges:
            gaps.append(
                f"observed order edge {ks} -> {kd} (runtime {src} -> {dst}) "
                "is missing from the static graph"
            )
    return gaps
