"""Pipeline-graph linter: semantic validation of a ``PipelineSpec``.

A mis-wired pipeline — mismatched stage task types, an over-subscribed
STREAMING TPU budget, a duplicate stage name — should be rejected before a
single worker spawns, not hours into a petabyte-scale run. ``run_pipeline``
calls :func:`validate_pipeline_spec` as an on-by-default pre-flight
(``skip_validation=True`` is the escape hatch); ``cosmos-curate-tpu lint``
exposes the same checks for ad-hoc use.

Checks:

- **type-flow**: via ``typing.get_type_hints`` on each stage's
  ``process_data`` — every task type stage *k* emits must be accepted by
  stage *k+1* (and the input tasks by stage 0). Untyped stages (e.g. the
  observability wrappers' dynamic subclasses) are skipped, not failed.
- **duplicate-stage**: two stages sharing a name would collide in metrics,
  artifacts and the autoscaler's per-stage state.
- **infeasible-streaming**: STREAMING keeps every pool live at once, so the
  summed minimum TPU demand must fit the declared cluster shape
  (``PipelineConfig.num_tpu_chips``); see ``ExecutionMode`` docs in
  core/pipeline.py. Checked only when the shape is declared — discovery
  happens at run time otherwise.
- **nonsense-spec**: contradictory resource requests (``tpus > 0`` with
  ``entire_tpu_host``, TPU stages with ``num_workers_per_node`` packing)
  and out-of-range scheduling knobs.
- **mesh-divisibility**: a stage-declared ``Stage.mesh_spec`` whose
  ``MeshSpec`` cannot tile ``ClusterShape.num_tpu_chips`` (shared
  arithmetic with the shardcheck pass, analysis/shard_check.py).
"""

from __future__ import annotations

import math
import types
import typing
from typing import TYPE_CHECKING, Any

from cosmos_curate_tpu.analysis.common import Finding, Severity
from cosmos_curate_tpu.utils.logging import get_logger

if TYPE_CHECKING:
    from cosmos_curate_tpu.core.pipeline import PipelineSpec
    from cosmos_curate_tpu.core.stage import StageSpec

logger = get_logger(__name__)

_SPEC_FILE = "<pipeline-spec>"


class PipelineValidationError(ValueError):
    """Raised by the ``run_pipeline`` pre-flight; carries all findings so a
    mis-wired spec surfaces every problem at once, not one per run."""

    def __init__(self, findings: list[Finding]) -> None:
        self.findings = findings
        lines = "\n".join(f"  - {f.render()}" for f in findings)
        super().__init__(
            f"pipeline spec failed pre-flight validation "
            f"({len(findings)} error(s); pass skip_validation=True to bypass):\n{lines}"
        )


# -- type-flow --------------------------------------------------------------


def _element_types(hint: Any) -> tuple[type, ...] | None:
    """``list[X]`` / ``list[X] | None`` / ``Optional[list[X | Y]]`` -> the
    element classes, or None when nothing checkable can be extracted
    (missing hint, TypeVar, Any, unparameterized list)."""
    if hint is None:
        return None
    origin = typing.get_origin(hint)
    if origin is typing.Union or origin is types.UnionType:
        for arm in typing.get_args(hint):
            if arm is type(None):
                continue
            got = _element_types(arm)
            if got is not None:
                return got
        return None
    if origin not in (list, typing.List):
        return None
    args = typing.get_args(hint)
    if not args:
        return None
    elems: list[type] = []
    for a in args:
        a_origin = typing.get_origin(a)
        if a_origin is typing.Union or a_origin is types.UnionType:
            members = [m for m in typing.get_args(a) if m is not type(None)]
        else:
            members = [a]
        for m in members:
            if not isinstance(m, type):  # TypeVar, Any, forward ref left over
                return None
            elems.append(m)
    return tuple(elems) or None


def _process_data_hints(stage: Any) -> tuple[tuple[type, ...] | None, tuple[type, ...] | None]:
    """-> (accepted element types, emitted element types) for a stage's
    ``process_data``, each None when unannotated/unresolvable."""
    fn = getattr(type(stage), "process_data", None)
    if fn is None:
        return None, None
    try:
        hints = typing.get_type_hints(fn)
    except Exception:  # unresolvable forward refs in user code: skip, don't fail
        return None, None
    params = [k for k in hints if k != "return"]
    accepts = _element_types(hints[params[0]]) if params else None
    emits = _element_types(hints.get("return"))
    return accepts, emits


def _compatible(emitted: tuple[type, ...], accepted: tuple[type, ...]) -> bool:
    return all(any(issubclass(e, a) for a in accepted) for e in emitted)


def _names(types_: tuple[type, ...]) -> str:
    return " | ".join(t.__name__ for t in types_)


def _check_type_flow(spec: "PipelineSpec", findings: list[Finding]) -> None:
    stages = spec.stages
    flows: list[tuple[str, tuple[type, ...] | None, tuple[type, ...] | None]] = [
        (s.name, *_process_data_hints(s.stage)) for s in stages
    ]
    # input tasks -> first stage
    if stages and spec.input_data:
        accepts = flows[0][1]
        if accepts is not None:
            bad = {type(t) for t in spec.input_data if not isinstance(t, accepts)}
            for t in sorted(bad, key=lambda c: c.__name__):
                findings.append(
                    Finding(
                        _SPEC_FILE, 0, "type-flow",
                        f"input tasks of type {t.__name__} are not accepted by first "
                        f"stage '{flows[0][0]}' (accepts {_names(accepts)})",
                    )
                )
    # stage k -> stage k+1
    for (up_name, _, emits), (down_name, accepts, _) in zip(flows, flows[1:]):
        if emits is None or accepts is None:
            continue  # untyped end: nothing checkable
        if not _compatible(emits, accepts):
            findings.append(
                Finding(
                    _SPEC_FILE, 0, "type-flow",
                    f"stage '{up_name}' emits {_names(emits)} but the next stage "
                    f"'{down_name}' accepts {_names(accepts)}",
                )
            )


# -- names ------------------------------------------------------------------


def _check_duplicate_names(spec: "PipelineSpec", findings: list[Finding]) -> None:
    seen: dict[str, int] = {}
    for idx, s in enumerate(spec.stages):
        if s.name in seen:
            # the engine runs duplicate-named stages (pools key on index),
            # but their metrics/artifacts/timings merge under one name —
            # surface it without rejecting a functional spec
            findings.append(
                Finding(
                    _SPEC_FILE, 0, "duplicate-stage",
                    f"stage name '{s.name}' used by both stage {seen[s.name]} and "
                    f"stage {idx}; their metrics, artifacts and autoscaler state "
                    "will merge under one name",
                    severity=Severity.WARNING,
                )
            )
        else:
            seen[s.name] = idx


# -- resources --------------------------------------------------------------


def _min_workers(s: "StageSpec") -> int:
    if s.num_workers is not None:
        return max(1, s.num_workers)
    return max(1, s.min_workers)


def _min_chip_demand(s: "StageSpec", host_chips: int) -> float:
    res = s.stage.resources
    if res.entire_tpu_host:
        return float(host_chips) * _min_workers(s)
    if res.tpus > 0:
        return res.tpus * _min_workers(s)
    return 0.0


def _check_resources(spec: "PipelineSpec", findings: list[Finding]) -> None:
    from cosmos_curate_tpu.core.pipeline import ExecutionMode

    cfg = spec.config
    for s in spec.stages:
        res = s.stage.resources
        if res.tpus > 0 and res.entire_tpu_host:
            findings.append(
                Finding(
                    _SPEC_FILE, 0, "nonsense-spec",
                    f"stage '{s.name}' requests both tpus={res.tpus} and "
                    "entire_tpu_host=True; an entire-host claim already owns every "
                    "local chip — drop one of the two",
                )
            )
        if res.uses_tpu and s.num_workers_per_node is not None:
            findings.append(
                Finding(
                    _SPEC_FILE, 0, "nonsense-spec",
                    f"stage '{s.name}' is a TPU stage but sets "
                    f"num_workers_per_node={s.num_workers_per_node}; per-node packing "
                    "only applies to CPU stages (chips bind to one worker per host)",
                )
            )
        if s.min_workers < 0:
            findings.append(
                Finding(
                    _SPEC_FILE, 0, "nonsense-spec",
                    f"stage '{s.name}' has min_workers={s.min_workers} < 0",
                )
            )
        if s.max_workers is not None and s.max_workers < max(1, s.min_workers):
            findings.append(
                Finding(
                    _SPEC_FILE, 0, "nonsense-spec",
                    f"stage '{s.name}' has max_workers={s.max_workers} below "
                    f"min_workers={s.min_workers}",
                )
            )
        if s.num_run_attempts < 1:
            findings.append(
                Finding(
                    _SPEC_FILE, 0, "nonsense-spec",
                    f"stage '{s.name}' has num_run_attempts={s.num_run_attempts}; "
                    "at least one attempt is required",
                )
            )
        if not 0.0 <= s.stage_save_sample_rate <= 1.0:
            findings.append(
                Finding(
                    _SPEC_FILE, 0, "nonsense-spec",
                    f"stage '{s.name}' has stage_save_sample_rate="
                    f"{s.stage_save_sample_rate} outside [0, 1]",
                )
            )

    # Feasibility against a *declared* cluster shape only; an undeclared
    # shape is discovered at run time (engine runner._discover_tpus).
    cluster = cfg.cluster_shape
    chips = cluster.num_tpu_chips
    if chips is not None:
        # mesh-divisibility: a TPU stage's declared MeshSpec must tile the
        # cluster. A mesh larger than the cluster cannot run at all; a
        # non-dividing one technically runs on a device subset but strands
        # the declared remainder (sp_size=3 on a 4-chip host silently idles
        # a chip you paid for) — both are spec bugs to fix before any
        # worker spawns, with skip_validation as the escape hatch.
        from cosmos_curate_tpu.analysis.shard_check import mesh_tiling_errors

        for s in spec.stages:
            mesh_spec = getattr(s.stage, "mesh_spec", None)
            if mesh_spec is None:
                continue
            for msg in mesh_tiling_errors(mesh_spec, chips):
                findings.append(
                    Finding(
                        _SPEC_FILE, 0, "mesh-divisibility",
                        f"stage '{s.name}' declares a device mesh that does "
                        f"not tile the declared cluster: {msg}",
                    )
                )
        demands = [(s, _min_chip_demand(s, chips)) for s in spec.stages]
        for s, d in demands:
            if d > chips:
                findings.append(
                    Finding(
                        _SPEC_FILE, 0, "infeasible-streaming",
                        f"stage '{s.name}' alone needs {_fmt(d)} TPU chip(s) at its "
                        f"minimum worker count but the declared cluster has {chips}",
                    )
                )
        if cfg.execution_mode is ExecutionMode.STREAMING:
            total = sum(d for _, d in demands)
            if total > chips and not any(d > chips for _, d in demands):
                tpu_stages = ", ".join(
                    f"'{s.name}'={_fmt(d)}" for s, d in demands if d > 0
                )
                findings.append(
                    Finding(
                        _SPEC_FILE, 0, "infeasible-streaming",
                        f"STREAMING keeps every pool live simultaneously but the "
                        f"summed minimum TPU demand {_fmt(total)} exceeds the declared "
                        f"{chips} chip(s) ({tpu_stages}); use BATCH mode, shrink "
                        "min_workers, or declare a larger cluster",
                    )
                )
    if cluster.num_cpus is not None and cfg.execution_mode is ExecutionMode.STREAMING:
        total_cpus = sum(
            s.stage.resources.cpus * _min_workers(s) for s in spec.stages
        )
        if total_cpus > cluster.num_cpus:
            findings.append(
                Finding(
                    _SPEC_FILE, 0, "infeasible-streaming",
                    f"summed minimum CPU demand {_fmt(total_cpus)} exceeds the "
                    f"declared {_fmt(cluster.num_cpus)} CPUs; the autoscaler cannot "
                    "shrink below per-stage minimums",
                    severity=Severity.WARNING,
                )
            )


def _fmt(x: float) -> str:
    return str(int(x)) if float(x).is_integer() and not math.isinf(x) else f"{x:g}"


# -- entry points -----------------------------------------------------------


def lint_pipeline_spec(spec: "PipelineSpec") -> list[Finding]:
    """All findings (errors and warnings) for a pipeline spec."""
    findings: list[Finding] = []
    _check_duplicate_names(spec, findings)
    _check_type_flow(spec, findings)
    _check_resources(spec, findings)
    return findings


def validate_pipeline_spec(spec: "PipelineSpec") -> None:
    """The ``run_pipeline`` pre-flight: raise on errors, log warnings."""
    findings = lint_pipeline_spec(spec)
    errors = [f for f in findings if f.severity is Severity.ERROR]
    for f in findings:
        if f.severity is not Severity.ERROR:
            logger.warning("pipeline pre-flight: %s", f.render())
    if errors:
        raise PipelineValidationError(errors)
