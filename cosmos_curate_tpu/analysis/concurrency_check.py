"""Concurrency verifier: whole-repo lock-order graph + blocking-under-lock
+ unguarded shared state.

The fourth curate-lint pillar (after the AST rules, the graph linter and
shardcheck), run as ``cosmos-curate-tpu lint --concurrency``. Unlike the
per-file AST rules this is a *whole-repo* pass: lock identity and
acquisition order only mean something across files, so the checker first
builds a registry of every ``threading.Lock``/``RLock``/``Condition``
attribute in the tree and then analyzes every function against it.

Three rule ids, all suppressible with the usual
``# curate-lint: disable=<rule>`` comments:

``lock-order``
    Cycles in the acquisition-order graph (potential deadlock), and
    re-acquisition of a held non-reentrant ``Lock`` (certain deadlock).
    Edges come from nested ``with`` statements and, interprocedurally,
    from same-class methods called while a lock is held (bounded depth).
    A ``Condition(self._lock)`` aliases the lock it wraps — ``with
    self._work_cv:`` IS ``with self._lock:`` for ordering purposes.

``lock-blocking``
    A blocking call made while a registered lock is held: ``os.fsync``,
    ``time.sleep``, ``subprocess.*``, socket ``accept/recv*/sendall``,
    blocking ``queue.put/get``, thread/process ``.join()``, ``.wait()``
    on a *different* lock's condition/event, and jit-dispatch calls
    (reusing the sync-readback rule's jit-name tracking). Every thread
    queued behind the lock stalls for the full duration of the call.

``unguarded-shared``
    Shared attributes with inconsistent guarding, in classes that start
    threads. ``# guarded-by: <lock>`` on the attribute's initialization
    declares intent: every mutation outside ``__init__`` must then hold
    that lock. Without an annotation a majority heuristic applies: an
    attribute mutated both from a thread-target context and elsewhere,
    where most mutation sites hold a lock but some do not, flags the
    unguarded sites. (Files under ``engine/`` keep the stricter
    ``lock-discipline`` rule for the heuristic half; the declared
    ``guarded-by`` contract is enforced everywhere.)

Library entry points: :func:`run_concurrency_check` (the CLI path),
:func:`analyze` (returns the full :class:`RepoAnalysis` — registry, order
edges, findings — used by the runtime sanitizer's cross-validation and by
tests).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Sequence

from cosmos_curate_tpu.analysis.common import (
    Finding,
    LintConfig,
    Severity,
    is_suppressed,
    load_config,
    parse_suppressions,
)

RULE_ORDER = "lock-order"
RULE_BLOCKING = "lock-blocking"
RULE_UNGUARDED = "unguarded-shared"

# Interprocedural expansion depth: a() -> b() -> c() is followed this many
# call hops when propagating acquired-lock sets and blocking calls.
MAX_CALL_DEPTH = 3

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*(?P<lock>[\w.]+)")
# ``# holds-lock: _lock, _prefix_lock`` on (or above) a ``def`` declares the
# caller-must-hold contract (clang REQUIRES()): the body is analyzed with
# those locks held, and every same-class call site is checked to hold them.
_HOLDS_LOCK_RE = re.compile(r"#\s*holds-lock:\s*(?P<locks>[\w.,\s]+)")

_LOCK_CTORS = {"Lock": False, "RLock": True, "Condition": True, "Semaphore": False,
               "BoundedSemaphore": False}

# receiver-name hints for blocking queue.put/.get (a bare ``.get`` is every
# dict in the repo; require the receiver to look like a queue)
_QUEUEISH = re.compile(r"(^q$|queue$|_q$)", re.IGNORECASE)
_JOINABLE = re.compile(r"(thread|proc|worker|agent)", re.IGNORECASE)

_SOCKET_BLOCKERS = {"accept", "recv", "recvfrom", "recv_into", "sendall"}

# Construction-phase methods: mutations here happen-before any worker
# thread exists (the same exemption lock-discipline gives __init__).
_INIT_PHASE_METHODS = {"__init__", "__post_init__", "setup", "build"}
_SUBPROCESS_BLOCKERS = {"run", "Popen", "call", "check_call", "check_output",
                        "communicate"}


# ---------------------------------------------------------------------------
# registry


@dataclass(frozen=True)
class LockDecl:
    """One registered lock. ``key`` is ``ClassName._attr`` for instance /
    class attributes and ``<module_stem>._NAME`` for module globals."""

    key: str
    file: str
    line: int
    ctor: str  # Lock | RLock | Condition | ...
    reentrant: bool
    alias_of: str | None = None  # Condition(self._lock) aliases that key


class LockRegistry:
    def __init__(self) -> None:
        self.decls: dict[str, LockDecl] = {}

    def add(self, decl: LockDecl) -> None:
        # first declaration wins (a lock re-created in a reset() method is
        # still the same logical lock)
        self.decls.setdefault(decl.key, decl)

    def root(self, key: str) -> str:
        """Follow Condition-aliasing to the underlying lock's key."""
        seen = set()
        while key in self.decls and self.decls[key].alias_of and key not in seen:
            seen.add(key)
            key = self.decls[key].alias_of  # type: ignore[assignment]
        return key

    def reentrant(self, key: str) -> bool:
        root = self.root(key)
        decl = self.decls.get(root)
        return decl.reentrant if decl else True

    def by_site(self) -> dict[tuple[str, int], str]:
        """(file, line) of the constructor call -> key; joins the runtime
        sanitizer's creation-site lock names back onto static keys."""
        return {(d.file, d.line): d.key for d in self.decls.values()}


# ---------------------------------------------------------------------------
# per-function facts


@dataclass
class _Acquire:
    key: str
    held: tuple[str, ...]  # root keys held at this acquisition, in order
    line: int


@dataclass
class _Blocking:
    desc: str
    held: tuple[str, ...]
    line: int


@dataclass
class _Call:
    callee: str  # bare self-method / module-function name
    held: tuple[str, ...]
    line: int


@dataclass
class _Mutation:
    attr: str
    method: str
    line: int
    held: tuple[str, ...]
    kind: str  # "store" | "mutator"


@dataclass
class FuncFacts:
    qualname: str  # "Class.method" or "function"
    acquires: list[_Acquire] = field(default_factory=list)
    blocking: list[_Blocking] = field(default_factory=list)
    calls: list[_Call] = field(default_factory=list)
    # holds-lock contract: root keys the caller must hold (analysis seeds
    # the held set with these; call sites are verified)
    requires: tuple[str, ...] = ()
    def_line: int = 0


@dataclass
class ClassFacts:
    name: str
    file: str
    methods: dict[str, FuncFacts] = field(default_factory=dict)
    mutations: list[_Mutation] = field(default_factory=list)
    # attr -> (lock key, decl line) from ``# guarded-by:`` comments
    guarded_by: dict[str, tuple[str, int]] = field(default_factory=dict)
    starts_threads: bool = False
    thread_targets: set[str] = field(default_factory=set)
    safe_attrs: set[str] = field(default_factory=set)
    call_graph: dict[str, set[str]] = field(default_factory=dict)


@dataclass
class ModuleFacts:
    rel_path: str
    stem: str
    classes: dict[str, ClassFacts] = field(default_factory=dict)
    functions: dict[str, FuncFacts] = field(default_factory=dict)


@dataclass
class OrderEdge:
    src: str
    dst: str
    file: str
    line: int
    via: str  # "" for a direct nested with, else the call chain


@dataclass
class RepoAnalysis:
    registry: LockRegistry
    edges: list[OrderEdge]
    findings: list[Finding]

    def edge_set(self) -> set[tuple[str, str]]:
        return {(e.src, e.dst) for e in self.edges}


# ---------------------------------------------------------------------------
# AST helpers (shared vocabulary with rules/lock_discipline.py, kept local
# so the whole-repo pass has no per-file-rule dependencies)

_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "setdefault", "add", "discard", "appendleft", "popleft",
}

_THREAD_SAFE_TYPES = {
    "Event", "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Barrier", "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue",
    "local",
}


def _self_attr(node: ast.expr) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in ("self", "cls")
    ):
        return node.attr
    return None


def _self_rooted_base(node: ast.expr) -> str | None:
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        direct = _self_attr(node)
        if direct is not None:
            return direct
        node = node.value
    return None


def _dotted_final(node: ast.expr) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _receiver_name(func: ast.expr) -> str | None:
    """``self.x.put`` -> 'x', ``q.put`` -> 'q'."""
    if not isinstance(func, ast.Attribute):
        return None
    base = func.value
    direct = _self_attr(base)
    if direct is not None:
        return direct
    if isinstance(base, ast.Name):
        return base.id
    return _dotted_final(base)


def _lock_ctor(call: ast.expr) -> tuple[str, ast.Call] | None:
    """``threading.Lock()`` / bare ``Lock()`` -> (ctor name, call node)."""
    if not isinstance(call, ast.Call):
        return None
    name = _dotted_final(call.func)
    if name in _LOCK_CTORS:
        return name, call
    return None


def _collect_jit_names(tree: ast.Module) -> set[str]:
    from cosmos_curate_tpu.analysis.rules import sync_readback

    return sync_readback._collect_jit_names(tree)


def _is_unbounded_queue_ctor(value: ast.expr) -> bool:
    """``queue.Queue()`` / ``mp.Queue()`` with no maxsize (or 0/negative):
    ``put()`` on the instance never blocks."""
    if not isinstance(value, ast.Call) or _dotted_final(value.func) not in (
        "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "deque",
    ):
        return False
    size: ast.expr | None = value.args[0] if value.args else None
    for kw in value.keywords:
        if kw.arg == "maxsize":
            size = kw.value
    if size is None:
        return True
    return isinstance(size, ast.Constant) and isinstance(size.value, int) and size.value <= 0


def _timeout_is_zero(node: ast.Call) -> bool:
    t: ast.expr | None = node.args[0] if node.args else None
    for kw in node.keywords:
        if kw.arg == "timeout":
            t = kw.value
    return isinstance(t, ast.Constant) and t.value == 0


def _parse_holds_lock(
    def_line: int, source_lines: list[str], cls_name: str | None, reg: LockRegistry
) -> tuple[str, ...]:
    """The holds-lock contract on the ``def`` line or the line above it,
    resolved to registered root keys (unknown names kept verbatim so the
    checker can flag the typo)."""
    for line_no in (def_line, def_line - 1):
        if not (1 <= line_no <= len(source_lines)):
            continue
        m = _HOLDS_LOCK_RE.search(source_lines[line_no - 1])
        if not m:
            continue
        out = []
        for name in (n.strip() for n in m.group("locks").split(",")):
            if not name:
                continue
            key = name if "." in name else (f"{cls_name}.{name}" if cls_name else name)
            out.append(reg.root(key))
        return tuple(out)
    return ()


_JIT_HOLDER_CONVENTION = re.compile(r"^_(jitted\w*|apply|sample)$")


# ---------------------------------------------------------------------------
# registry construction


def _register_module_locks(mod: ModuleFacts, tree: ast.Module, reg: LockRegistry) -> None:
    for node in tree.body:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        ctor = _lock_ctor(value) if value is not None else None
        if ctor is None:
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                reg.add(
                    LockDecl(
                        key=f"{mod.stem}.{t.id}",
                        file=mod.rel_path,
                        line=value.lineno,
                        ctor=ctor[0],
                        reentrant=_LOCK_CTORS[ctor[0]],
                    )
                )


def _register_class_locks(
    mod: ModuleFacts, cls: ast.ClassDef, reg: LockRegistry
) -> None:
    def add(attr: str, ctor: str, call: ast.Call) -> None:
        alias = None
        if ctor == "Condition" and call.args:
            aliased = _self_attr(call.args[0])
            if aliased is not None:
                alias = f"{cls.name}.{aliased}"
        reg.add(
            LockDecl(
                key=f"{cls.name}.{attr}",
                file=mod.rel_path,
                line=call.lineno,
                ctor=ctor,
                reentrant=_LOCK_CTORS[ctor],
                alias_of=alias,
            )
        )

    # class-level attributes (shared_engine's registry-wide class lock)
    for item in cls.body:
        if isinstance(item, (ast.Assign, ast.AnnAssign)):
            value = item.value
            ctor = _lock_ctor(value) if value is not None else None
            if ctor is None:
                continue
            targets = item.targets if isinstance(item, ast.Assign) else [item.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    add(t.id, ctor[0], ctor[1])
    # instance attributes assigned in any method (usually __init__)
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(item):
            if not isinstance(node, ast.Assign):
                continue
            ctor = _lock_ctor(node.value)
            if ctor is None:
                continue
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    add(attr, ctor[0], ctor[1])


# ---------------------------------------------------------------------------
# function-body analysis


class _FuncScanner:
    """Walk one function body tracking the ordered set of held locks."""

    def __init__(
        self,
        facts: FuncFacts,
        reg: LockRegistry,
        mod: ModuleFacts,
        cls_name: str | None,
        jit_names: set[str],
        unbounded_queues: set[str] | None = None,
    ) -> None:
        self.facts = facts
        self.reg = reg
        self.mod = mod
        self.cls_name = cls_name
        self.jit_names = jit_names
        # attribute names known to be unbounded queue.Queue / mp.Queue
        # instances (put() on them never blocks); locals join during scan
        self.unbounded = set(unbounded_queues or ())

    # -- lock-expression resolution
    def resolve(self, expr: ast.expr) -> str | None:
        """Map a with-item / receiver expression to a registered lock key
        (pre-aliasing), or None."""
        if isinstance(expr, ast.Call):
            expr = expr.func  # with self._lock.acquire_timeout(...) style
        attr = _self_attr(expr)
        if attr is not None and self.cls_name:
            key = f"{self.cls_name}.{attr}"
            if key in self.reg.decls:
                return key
        if isinstance(expr, ast.Name):
            key = f"{self.mod.stem}.{expr.id}"
            if key in self.reg.decls:
                return key
        if isinstance(expr, ast.Attribute):
            # ClassName._lock (class attribute referenced by name)
            base = expr.value
            if isinstance(base, ast.Name):
                key = f"{base.id}.{expr.attr}"
                if key in self.reg.decls:
                    return key
        return None

    # -- entry
    def scan(self, body: Iterable[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt, held=self.facts.requires)

    def _stmt(self, node: ast.AST, held: tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs are analyzed on their own (closures: best effort)
        if isinstance(node, ast.Assign) and _is_unbounded_queue_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.unbounded.add(t.id)
        if isinstance(node, ast.With):
            inner = held
            for item in node.items:
                key = self.resolve(item.context_expr)
                if key is not None:
                    root = self.reg.root(key)
                    self.facts.acquires.append(_Acquire(root, inner, item.context_expr.lineno))
                    if root not in inner:
                        inner = inner + (root,)
                else:
                    self._expr(item.context_expr, held=inner)
            for stmt in node.body:
                self._stmt(stmt, held=inner)
            return
        for child in ast.iter_child_nodes(node):
            self._expr_or_stmt(child, held)

    def _expr_or_stmt(self, node: ast.AST, held: tuple[str, ...]) -> None:
        if isinstance(node, ast.Call):
            self._call(node, held)
        self._stmt(node, held)

    def _expr(self, node: ast.AST, held: tuple[str, ...]) -> None:
        for child in ast.walk(node):
            if isinstance(child, ast.Call):
                self._call(child, held)

    # -- calls
    def _call(self, node: ast.Call, held: tuple[str, ...]) -> None:
        func = node.func
        attr = func.attr if isinstance(func, ast.Attribute) else None
        recv = _receiver_name(func)

        # explicit .acquire(): an order observation (no scope tracking)
        if attr == "acquire" and isinstance(func, ast.Attribute):
            key = self.resolve(func.value)
            if key is not None:
                self.facts.acquires.append(
                    _Acquire(self.reg.root(key), held, node.lineno)
                )
                return

        # self-call graph edge (interprocedural order + blocking)
        callee = _self_attr(func)
        if callee is not None and self.cls_name:
            self.facts.calls.append(_Call(callee, held, node.lineno))
        elif isinstance(func, ast.Name) and func.id in self.mod.functions:
            self.facts.calls.append(_Call(func.id, held, node.lineno))

        desc = self._blocking_desc(node, func, attr, recv, held)
        if desc is not None:
            self.facts.blocking.append(_Blocking(desc, held, node.lineno))

    def _blocking_desc(
        self,
        node: ast.Call,
        func: ast.expr,
        attr: str | None,
        recv: str | None,
        held: tuple[str, ...],
    ) -> str | None:
        # recorded even with nothing held locally: a caller may hold a lock
        # across a call into this function (the interprocedural report)
        if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
            owner = func.value.id
            if owner == "os" and attr in ("fsync", "fdatasync"):
                return f"os.{attr}()"
            if owner == "time" and attr == "sleep":
                return "time.sleep()"
            if owner == "subprocess" and attr in _SUBPROCESS_BLOCKERS:
                return f"subprocess.{attr}()"
            if owner == "shutil" and attr in ("copy", "copy2", "copytree", "move"):
                return f"shutil.{attr}()"
        if attr in _SOCKET_BLOCKERS:
            return f".{attr}() (socket)"
        if attr in ("put", "get") and recv and _QUEUEISH.search(recv):
            if attr == "put" and recv in self.unbounded:
                return None  # unbounded queue: put() cannot block
            if not any(
                kw.arg == "block" and isinstance(kw.value, ast.Constant)
                and kw.value.value is False
                for kw in node.keywords
            ):
                return f"blocking {recv}.{attr}()"
        if attr == "join" and recv and _JOINABLE.search(recv):
            if _timeout_is_zero(node):
                return None  # join(timeout=0) is a non-blocking reap
            return f"{recv}.join()"
        if attr == "wait" and isinstance(func, ast.Attribute):
            # waiting on a cv/event while holding an UNRELATED lock: the cv
            # releases only its own lock, anything else stays held for the
            # whole wait. (Held-gated here: without local context we cannot
            # tell a cv's own lock from a stranger's, so this one is not
            # propagated interprocedurally.)
            key = self.resolve(func.value)
            own_root = self.reg.root(key) if key else None
            others = [h for h in held if h != own_root]
            if others and (key is not None or (recv or "").endswith(("_cv", "_event", "_evt"))):
                return f"{recv}.wait() while holding {', '.join(others)}"
            return None
        # jit dispatch under a lock serializes every waiter behind device
        # compute (sync-readback's jit-name tracking, same convention)
        name = _dotted_final(func)
        if name and (name in self.jit_names or _JIT_HOLDER_CONVENTION.match(name)):
            return f"jit dispatch {name}(...)"
        return None


# ---------------------------------------------------------------------------
# class-body analysis (mutations for unguarded-shared)


class _MutationScanner:
    def __init__(
        self,
        cls_facts: ClassFacts,
        scanner: _FuncScanner,
        method: str,
    ) -> None:
        self.cf = cls_facts
        self.scanner = scanner
        self.method = method

    def scan(self, body: Iterable[ast.stmt], held: tuple[str, ...] = ()) -> None:
        for stmt in body:
            self._stmt(stmt, held=held)

    def _stmt(self, node: ast.AST, held: tuple[str, ...]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return
        if isinstance(node, ast.With):
            inner = held
            for item in node.items:
                key = self.scanner.resolve(item.context_expr)
                if key is not None:
                    root = self.scanner.reg.root(key)
                    if root not in inner:
                        inner = inner + (root,)
            for stmt in node.body:
                self._stmt(stmt, held=inner)
            return
        self._record(node, held)
        for child in ast.iter_child_nodes(node):
            self._stmt(child, held)

    def _record(self, node: ast.AST, held: tuple[str, ...]) -> None:
        targets: list[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        elif isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATORS:
                base = _self_rooted_base(node.func.value)
                if base is not None:
                    self.cf.mutations.append(
                        _Mutation(base, self.method, node.lineno, held, "mutator")
                    )
            return
        for t in targets:
            for el in t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]:
                attr = _self_rooted_base(el)
                if attr is not None:
                    self.cf.mutations.append(
                        _Mutation(attr, self.method, getattr(node, "lineno", 0), held, "store")
                    )


def _scan_class(
    mod: ModuleFacts,
    cls: ast.ClassDef,
    reg: LockRegistry,
    jit_names: set[str],
    source_lines: list[str],
) -> ClassFacts:
    cf = ClassFacts(cls.name, mod.rel_path)
    unbounded: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        if node.value is None or not _is_unbounded_queue_ctor(node.value):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            attr = _self_attr(t)
            if attr is not None:
                unbounded.add(attr)
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        ff = FuncFacts(
            f"{cls.name}.{item.name}",
            requires=_parse_holds_lock(item.lineno, source_lines, cls.name, reg),
            def_line=item.lineno,
        )
        scanner = _FuncScanner(ff, reg, mod, cls.name, jit_names, unbounded)
        scanner.scan(item.body)
        cf.methods[item.name] = ff
        cf.call_graph[item.name] = {c.callee for c in ff.calls}
        _MutationScanner(cf, scanner, item.name).scan(item.body, held=ff.requires)
        for node in ast.walk(item):
            if isinstance(node, ast.Call) and _dotted_final(node.func) == "Thread":
                cf.starts_threads = True
                for kw in node.keywords:
                    if kw.arg == "target":
                        target = _self_attr(kw.value)
                        if target is not None:
                            cf.thread_targets.add(target)
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                ctor = _dotted_final(node.value.func)
                if ctor in _THREAD_SAFE_TYPES:
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            cf.safe_attrs.add(attr)
    # guarded-by annotations: the comment sits on the line of an attribute
    # assignment anywhere in the class body
    for item in ast.walk(cls):
        if not isinstance(item, (ast.Assign, ast.AnnAssign)):
            continue
        line_no = getattr(item, "lineno", 0)
        if not (1 <= line_no <= len(source_lines)):
            continue
        m = _GUARDED_BY_RE.search(source_lines[line_no - 1])
        if not m:
            continue
        lock_name = m.group("lock")
        key = lock_name if "." in lock_name else f"{cls.name}.{lock_name}"
        targets = item.targets if isinstance(item, ast.Assign) else [item.target]
        for t in targets:
            attr = _self_attr(t)
            if attr is not None:
                cf.guarded_by[attr] = (key, line_no)
    return cf


def _scan_module(path: Path, rel: str, reg_only: bool, reg: LockRegistry) -> tuple[ModuleFacts | None, ast.Module | None, str]:
    try:
        source = path.read_text(encoding="utf-8")
        tree = ast.parse(source, filename=str(path))
    except (OSError, UnicodeDecodeError, SyntaxError):
        return None, None, ""
    mod = ModuleFacts(rel_path=rel, stem=path.stem)
    _register_module_locks(mod, tree, reg)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            _register_class_locks(mod, node, reg)
    return mod, tree, source


# ---------------------------------------------------------------------------
# interprocedural expansion


def _transitive(
    start: str,
    call_graph: dict[str, set[str]],
    per_method: dict[str, set],
    depth: int = MAX_CALL_DEPTH,
) -> set:
    """Union ``per_method`` values over calls reachable from ``start``
    within ``depth`` hops (including start itself)."""
    out: set = set(per_method.get(start, ()))
    frontier = {start}
    seen = {start}
    for _ in range(depth):
        nxt: set[str] = set()
        for m in frontier:
            for callee in call_graph.get(m, ()):
                if callee not in seen:
                    seen.add(callee)
                    nxt.add(callee)
                    out |= per_method.get(callee, set())
        if not nxt:
            break
        frontier = nxt
    return out


# ---------------------------------------------------------------------------
# the pass


def _iter_files(paths: Sequence[str | Path], exclude: Sequence[str]) -> list[Path]:
    from cosmos_curate_tpu.analysis.ast_lint import iter_python_files

    return iter_python_files(paths, exclude)


def analyze(
    paths: Sequence[str | Path],
    config: LintConfig | None = None,
) -> RepoAnalysis:
    config = config or load_config()
    from cosmos_curate_tpu.analysis.ast_lint import _repo_root, _rel

    root = _repo_root()
    files = _iter_files(paths, config.exclude)

    reg = LockRegistry()
    parsed: list[tuple[ModuleFacts, ast.Module, str]] = []
    # pass 1: registry over every file (order edges in file A may involve
    # locks declared in file B)
    for f in files:
        rel = _rel(f, root)
        mod, tree, source = _scan_module(f, rel, reg_only=True, reg=reg)
        if mod is not None and tree is not None:
            parsed.append((mod, tree, source))

    # pass 2: per-function facts against the complete registry
    edges: list[OrderEdge] = []
    findings: list[Finding] = []
    suppressions: dict[str, tuple[dict[int, set[str]], set[str]]] = {}
    for mod, tree, source in parsed:
        lines = source.splitlines()
        jit_names = _collect_jit_names(tree)
        # module-level functions first (so _FuncScanner sees them as callees)
        fn_nodes = [
            n for n in tree.body if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for n in fn_nodes:
            mod.functions[n.name] = FuncFacts(
                n.name,
                requires=_parse_holds_lock(n.lineno, lines, None, reg),
                def_line=n.lineno,
            )
        for n in fn_nodes:
            scanner = _FuncScanner(mod.functions[n.name], reg, mod, None, jit_names)
            scanner.scan(n.body)
        for n in tree.body:
            if isinstance(n, ast.ClassDef):
                mod.classes[n.name] = _scan_class(mod, n, reg, jit_names, lines)

        mod_findings = _module_findings(mod, reg, edges)
        per_line, file_wide = parse_suppressions(source)
        suppressions[mod.rel_path] = (per_line, file_wide)
        findings.extend(
            f for f in mod_findings if not is_suppressed(f, per_line, file_wide)
        )

    for f in _cycle_findings(edges, reg):
        per_line, file_wide = suppressions.get(f.file, ({}, set()))
        if not is_suppressed(f, per_line, file_wide):
            findings.append(f)
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return RepoAnalysis(registry=reg, edges=edges, findings=findings)


def _module_findings(
    mod: ModuleFacts, reg: LockRegistry, edges: list[OrderEdge]
) -> list[Finding]:
    findings: list[Finding] = []

    def group_facts(
        funcs: dict[str, FuncFacts], call_graph: dict[str, set[str]]
    ) -> None:
        acq_sets = {
            name: {a.key for a in ff.acquires} for name, ff in funcs.items()
        }
        blocking_sets = {
            name: {(b.desc, b.line) for b in ff.blocking} for name, ff in funcs.items()
        }
        for name, ff in funcs.items():
            # direct order edges + non-reentrant re-acquire
            for a in ff.acquires:
                for h in a.held:
                    if h == a.key:
                        continue
                    edges.append(OrderEdge(h, a.key, mod.rel_path, a.line, ""))
                if a.key in a.held and not reg.reentrant(a.key):
                    findings.append(
                        Finding(
                            mod.rel_path, a.line, RULE_ORDER,
                            f"re-acquiring non-reentrant lock {a.key} while "
                            "already held: guaranteed self-deadlock",
                        )
                    )
            # direct blocking-under-lock (held-gated; lock-free blocking
            # calls are only reported through a lock-holding caller below)
            for b in ff.blocking:
                if not b.held:
                    continue
                findings.append(
                    Finding(
                        mod.rel_path, b.line, RULE_BLOCKING,
                        f"{b.desc} while holding {', '.join(b.held)}: every "
                        "thread queued on the lock stalls for the call's "
                        "full duration",
                    )
                )
            # holds-lock contract verification: a same-group call into a
            # method that declares requirements must already hold them
            for c in ff.calls:
                callee_ff = funcs.get(c.callee)
                if callee_ff is None:
                    continue
                for req in callee_ff.requires:
                    if req not in c.held:
                        findings.append(
                            Finding(
                                mod.rel_path, c.line, RULE_UNGUARDED,
                                f"call to {c.callee}() (holds-lock: {req} at "
                                f"line {callee_ff.def_line}) without holding "
                                f"{req}",
                            )
                        )
            # interprocedural: locks/blocking reachable through calls made
            # while something is held
            for c in ff.calls:
                if not c.held:
                    continue
                reached = _transitive(c.callee, call_graph, acq_sets)
                for lock in sorted(reached):
                    if lock in c.held:
                        if not reg.reentrant(lock):
                            findings.append(
                                Finding(
                                    mod.rel_path, c.line, RULE_ORDER,
                                    f"call to {c.callee}() re-acquires "
                                    f"non-reentrant {lock} already held here: "
                                    "guaranteed self-deadlock",
                                )
                            )
                        continue
                    for h in c.held:
                        edges.append(
                            OrderEdge(h, lock, mod.rel_path, c.line, f"via {c.callee}()")
                        )
                reached_blocking = _transitive(c.callee, call_graph, blocking_sets)
                for desc, _bline in sorted(reached_blocking):
                    findings.append(
                        Finding(
                            mod.rel_path, c.line, RULE_BLOCKING,
                            f"call to {c.callee}() reaches {desc} while "
                            f"holding {', '.join(c.held)}",
                        )
                    )

    mod_call_graph = {
        name: {c.callee for c in ff.calls} for name, ff in mod.functions.items()
    }
    group_facts(mod.functions, mod_call_graph)
    for cls in mod.classes.values():
        group_facts(cls.methods, cls.call_graph)
        findings.extend(_unguarded_findings(mod, cls, reg))
    return findings


def _unguarded_findings(
    mod: ModuleFacts, cls: ClassFacts, reg: LockRegistry
) -> list[Finding]:
    findings: list[Finding] = []
    by_attr: dict[str, list[_Mutation]] = {}
    for m in cls.mutations:
        if m.method in _INIT_PHASE_METHODS or m.attr in cls.safe_attrs:
            continue
        by_attr.setdefault(m.attr, []).append(m)

    # declared contracts are enforced everywhere
    for attr, (lock_key, decl_line) in cls.guarded_by.items():
        root = reg.root(lock_key)
        if lock_key not in reg.decls:
            findings.append(
                Finding(
                    mod.rel_path, decl_line, RULE_UNGUARDED,
                    f"guarded-by names unknown lock '{lock_key}' "
                    f"(registered: class locks of {cls.name})",
                )
            )
            continue
        for m in by_attr.get(attr, []):
            if root not in m.held:
                findings.append(
                    Finding(
                        mod.rel_path, m.line, RULE_UNGUARDED,
                        f"self.{attr} is declared guarded-by {lock_key} but "
                        f"{cls.name}.{m.method} mutates it without holding it",
                    )
                )

    # heuristic half only for thread-starting classes, and not under
    # engine/ where the stricter lock-discipline rule owns the territory
    if not cls.starts_threads or "engine/" in mod.rel_path.replace("\\", "/"):
        return findings
    thread_reach = _thread_reachable(cls)
    for attr, muts in sorted(by_attr.items()):
        if attr in cls.guarded_by:
            continue
        guarded = [m for m in muts if m.held]
        unguarded = [m for m in muts if not m.held]
        if not guarded or not unguarded:
            continue
        in_thread = any(m.method in thread_reach for m in muts)
        outside = any(m.method not in thread_reach for m in muts)
        if not (in_thread and outside):
            continue
        if len(guarded) <= len(unguarded):
            continue  # majority must be guarded for intent to be inferable
        locks = {h for m in guarded for h in m.held}
        hint = sorted(locks)[0] if locks else "?"
        for m in unguarded:
            findings.append(
                Finding(
                    mod.rel_path, m.line, RULE_UNGUARDED,
                    f"self.{attr} is mutated under {hint} at "
                    f"{len(guarded)} site(s) but {cls.name}.{m.method} "
                    "mutates it lock-free; guard it or declare intent with "
                    f"'# guarded-by: {hint.split('.', 1)[-1]}' on its init",
                )
            )
    return findings


def _thread_reachable(cls: ClassFacts) -> set[str]:
    seen: set[str] = set()
    stack = list(cls.thread_targets)
    while stack:
        m = stack.pop()
        if m in seen:
            continue
        seen.add(m)
        stack.extend(cls.call_graph.get(m, ()))
    return seen


# ---------------------------------------------------------------------------
# cycles


def _cycle_findings(edges: list[OrderEdge], reg: LockRegistry) -> list[Finding]:
    graph: dict[str, set[str]] = {}
    example: dict[tuple[str, str], OrderEdge] = {}
    for e in edges:
        if e.src == e.dst:
            continue
        graph.setdefault(e.src, set()).add(e.dst)
        graph.setdefault(e.dst, set())
        example.setdefault((e.src, e.dst), e)

    sccs = _tarjan(graph)
    findings: list[Finding] = []
    for comp in sccs:
        if len(comp) < 2:
            continue
        nodes = sorted(comp)
        sites = []
        for a, b in sorted(example):
            if a in comp and b in comp:
                e = example[(a, b)]
                via = f" {e.via}" if e.via else ""
                sites.append(f"{a}->{b} at {e.file}:{e.line}{via}")
        anchor = min(
            (example[(a, b)] for a, b in example if a in comp and b in comp),
            key=lambda e: (e.file, e.line),
        )
        findings.append(
            Finding(
                anchor.file, anchor.line, RULE_ORDER,
                "lock acquisition-order cycle (potential deadlock) between "
                f"{', '.join(nodes)}: {'; '.join(sites)} — pick one canonical "
                "order and document it at the lock declarations",
            )
        )
    return findings


def _tarjan(graph: dict[str, set[str]]) -> list[set[str]]:
    """Iterative Tarjan SCC (the repo is small but recursion limits are
    not worth tripping in a linter)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set[str] = set()
    stack: list[str] = []
    sccs: list[set[str]] = []
    counter = [0]

    for start in graph:
        if start in index:
            continue
        work: list[tuple[str, Iterable[str]]] = [(start, iter(graph[start]))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            node, it = work[-1]
            advanced = False
            for nbr in it:
                if nbr not in index:
                    index[nbr] = low[nbr] = counter[0]
                    counter[0] += 1
                    stack.append(nbr)
                    on_stack.add(nbr)
                    work.append((nbr, iter(graph[nbr])))
                    advanced = True
                    break
                if nbr in on_stack:
                    low[node] = min(low[node], index[nbr])
            if advanced:
                continue
            work.pop()
            if low[node] == index[node]:
                comp: set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.add(w)
                    if w == node:
                        break
                sccs.append(comp)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
    return sccs


# ---------------------------------------------------------------------------
# entry point


def run_concurrency_check(
    paths: Sequence[str | Path],
    config: LintConfig | None = None,
) -> list[Finding]:
    """The ``lint --concurrency`` pass: returns surviving findings."""
    return analyze(paths, config).findings
