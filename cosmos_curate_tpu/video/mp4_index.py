"""SDK-free MP4 sample-table parser: exact per-frame timestamps.

Equivalent capability of the reference's packet-timestamp probe
(cosmos_curate/pipelines/video/utils/decoder_utils.py:230
``get_video_timestamps`` via PyAV packet PTS): cv2 exposes no reliable
per-packet PTS, so variable-frame-rate videos got constant-rate
approximations. This module reads the container's own sample tables
(ISO/IEC 14496-12 boxes) with the stdlib only:

  moov/trak/mdia/hdlr('vide')     find the video track
  mdia/mdhd                       timescale (v0 32-bit / v1 64-bit)
  stbl/stts                       decode deltas -> DTS
  stbl/ctts                       composition offsets -> PTS = DTS + offset
  stbl/stss                       sync samples (keyframes; absent = all)

Exact for CFR *and* VFR mp4/mov files; videos in other containers (mkv,
webm) fall back to the caller's constant-rate path.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Iterator

import numpy as np

# containers worth descending into
_CONTAINER_BOXES = {b"moov", b"trak", b"mdia", b"minf", b"stbl"}


@dataclass(frozen=True)
class Mp4VideoIndex:
    timescale: int
    pts_s: np.ndarray  # float64 [N], presentation order (sorted ascending)
    keyframes: np.ndarray  # bool [N], in presentation order
    frame_count: int

    @property
    def duration_s(self) -> float:
        if self.frame_count == 0:
            return 0.0
        # last PTS + median delta approximates the tail frame's duration
        deltas = np.diff(self.pts_s)
        tail = float(np.median(deltas)) if len(deltas) else 0.0
        return float(self.pts_s[-1]) + tail


class Mp4ParseError(ValueError):
    pass


def _iter_boxes(data: memoryview, start: int, end: int) -> Iterator[tuple[bytes, int, int]]:
    """Yield (type, payload_start, payload_end) for boxes in [start, end)."""
    pos = start
    while pos + 8 <= end:
        size = struct.unpack_from(">I", data, pos)[0]
        btype = bytes(data[pos + 4 : pos + 8])
        header = 8
        if size == 1:  # 64-bit largesize
            if pos + 16 > end:
                raise Mp4ParseError("truncated largesize box")
            size = struct.unpack_from(">Q", data, pos + 8)[0]
            header = 16
        elif size == 0:  # box extends to end of enclosing scope
            size = end - pos
        if size < header or pos + size > end:
            raise Mp4ParseError(f"bad box size {size} for {btype!r}")
        yield btype, pos + header, pos + size
        pos += size


def _find_box(data: memoryview, start: int, end: int, path: list[bytes]) -> tuple[int, int] | None:
    if not path:
        return start, end
    for btype, a, b in _iter_boxes(data, start, end):
        if btype == path[0]:
            found = _find_box(data, a, b, path[1:])
            if found is not None:
                return found
    return None


def _full_box(data: memoryview, start: int) -> tuple[int, int]:
    """(version, payload offset after version/flags)."""
    version = data[start]
    return version, start + 4


def _video_trak(data: memoryview, moov: tuple[int, int]) -> tuple[int, int] | None:
    for btype, a, b in _iter_boxes(data, *moov):
        if btype != b"trak":
            continue
        hdlr = _find_box(data, a, b, [b"mdia", b"hdlr"])
        if hdlr is None:
            continue
        handler = bytes(data[hdlr[0] + 8 : hdlr[0] + 12])
        if handler == b"vide":
            return a, b
    return None


def _read_moov_from_file(path: str) -> bytes:
    """Stream the top-level box headers and read ONLY the moov box — a
    multi-GB source must not be slurped to parse a few-KB sample table."""
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                raise Mp4ParseError("no moov box (not ISO-BMFF or fragmented)")
            size = struct.unpack(">I", header[:4])[0]
            btype = header[4:8]
            hlen = 8
            if size == 1:
                big = f.read(8)
                if len(big) < 8:
                    raise Mp4ParseError("truncated largesize box")
                size = struct.unpack(">Q", big)[0]
                hlen = 16
            elif size == 0:
                # box to EOF; only useful if it IS the moov
                if btype == b"moov":
                    return header + f.read()
                raise Mp4ParseError("no moov box before to-EOF box")
            if size < hlen:
                raise Mp4ParseError(f"bad box size {size} for {btype!r}")
            if btype == b"moov":
                body = f.read(size - hlen)
                if len(body) < size - hlen:
                    raise Mp4ParseError("truncated moov box")
                return header + (b"" if hlen == 8 else big) + body
            f.seek(size - hlen, 1)


def parse_mp4_video_index(source: bytes | str) -> Mp4VideoIndex:
    """Parse an mp4/mov's video sample tables into per-frame PTS.

    PTS are normalized so the first presented frame is at 0 — this absorbs
    the B-frame decoder-delay offset that muxers compensate with an edit
    list (the common single-entry elst case), without parsing elst itself.

    Raises Mp4ParseError when the data is not ISO-BMFF, has no video
    track, or has corrupt sample tables — callers fall back to
    constant-rate timestamps."""
    try:
        return _parse_impl(source)
    except Mp4ParseError:
        raise
    except (struct.error, IndexError, ValueError, OverflowError, MemoryError) as e:
        # corrupt/truncated tables must degrade to the fallback, not crash
        raise Mp4ParseError(f"corrupt sample tables: {e}") from e


def _parse_impl(source: bytes | str) -> Mp4VideoIndex:
    if isinstance(source, str):
        raw = _read_moov_from_file(source)
    else:
        raw = source
    data = memoryview(raw)
    moov = _find_box(data, 0, len(data), [b"moov"])
    if moov is None:
        raise Mp4ParseError("no moov box (not ISO-BMFF or fragmented)")
    trak = _video_trak(data, moov)
    if trak is None:
        raise Mp4ParseError("no video track")

    mdhd = _find_box(data, *trak, [b"mdia", b"mdhd"])
    if mdhd is None:
        raise Mp4ParseError("no mdhd")
    version, p = _full_box(data, mdhd[0])
    if version == 1:
        timescale = struct.unpack_from(">I", data, p + 16)[0]
    else:
        timescale = struct.unpack_from(">I", data, p + 8)[0]
    if timescale <= 0:
        raise Mp4ParseError(f"bad timescale {timescale}")

    stbl = _find_box(data, *trak, [b"mdia", b"minf", b"stbl"])
    if stbl is None:
        raise Mp4ParseError("no stbl")

    stts = _find_box(data, *stbl, [b"stts"])
    if stts is None:
        raise Mp4ParseError("no stts")
    _, p = _full_box(data, stts[0])
    (n_entries,) = struct.unpack_from(">I", data, p)
    counts = np.empty(n_entries, np.int64)
    deltas = np.empty(n_entries, np.int64)
    for i in range(n_entries):
        c, d = struct.unpack_from(">II", data, p + 4 + 8 * i)
        counts[i], deltas[i] = c, d
    durations = np.repeat(deltas, counts)
    n = int(counts.sum())
    dts = np.concatenate([[0], np.cumsum(durations[:-1])]) if n else np.zeros(0, np.int64)

    pts = dts.astype(np.int64)
    ctts = _find_box(data, *stbl, [b"ctts"])
    if ctts is not None:
        version, p = _full_box(data, ctts[0])
        (n_entries,) = struct.unpack_from(">I", data, p)
        counts_c = np.empty(n_entries, np.int64)
        offsets = np.empty(n_entries, np.int64)
        for i in range(n_entries):
            c = struct.unpack_from(">I", data, p + 4 + 8 * i)[0]
            # v1 offsets are signed; v0 unsigned (but commonly signed in
            # the wild — parse as signed either way, negative offsets are
            # real in v1 files)
            o = struct.unpack_from(">i" if version == 1 else ">I", data, p + 8 + 8 * i)[0]
            if version == 0 and o >= 2**31:
                o -= 2**32
            counts_c[i], offsets[i] = c, o
        full_offsets = np.repeat(offsets, counts_c)
        if len(full_offsets) < n:
            full_offsets = np.pad(full_offsets, (0, n - len(full_offsets)))
        pts = dts + full_offsets[:n]

    keyframes = np.ones(n, bool)
    stss = _find_box(data, *stbl, [b"stss"])
    if stss is not None:
        _, p = _full_box(data, stss[0])
        (n_sync,) = struct.unpack_from(">I", data, p)
        keyframes = np.zeros(n, bool)
        for i in range(n_sync):
            idx = struct.unpack_from(">I", data, p + 4 + 4 * i)[0] - 1  # 1-based
            if 0 <= idx < n:
                keyframes[idx] = True

    # present in presentation order, anchored at 0 (see docstring)
    order = np.argsort(pts, kind="stable")
    pts = pts[order]
    if n:
        pts = pts - pts[0]
    return Mp4VideoIndex(
        timescale=timescale,
        pts_s=pts.astype(np.float64) / timescale,
        keyframes=keyframes[order],
        frame_count=n,
    )
