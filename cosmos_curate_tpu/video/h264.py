"""cv2.VideoWriter-compatible wrapper over the native H264 encoder.

The reference guarantees H264 clip output (clip_extraction_stages.py:167);
cv2 in this image has no H264 encoder, so ``video/encode.py`` prefers this
writer (libx264 through the system ffmpeg libraries, bound in
cosmos_curate_tpu/native/h264_encoder.c) and only then negotiates down.
"""

from __future__ import annotations

import ctypes

import numpy as np

from cosmos_curate_tpu.native import load_h264

_probe_result: bool | None = None


class NativeH264Writer:
    """Same call surface as cv2.VideoWriter (isOpened/write/release);
    ``write`` takes BGR uint8 frames like cv2."""

    def __init__(
        self,
        path: str,
        fps: float,
        size_wh: tuple[int, int],
        *,
        crf: int = 23,
        preset: str = "veryfast",
    ) -> None:
        self._lib = load_h264()
        self._ctx = None
        self._w, self._h = size_wh
        if self._lib is not None:
            self._ctx = self._lib.curate_h264_open(
                path.encode(), self._w, self._h, float(fps), crf, preset.encode()
            )

    def isOpened(self) -> bool:
        return self._ctx is not None

    def write(self, frame_bgr: np.ndarray) -> None:
        if self._ctx is None:
            raise RuntimeError("writer not open")
        if frame_bgr.shape[:2] != (self._h, self._w) or frame_bgr.dtype != np.uint8:
            raise ValueError(
                f"expected uint8 [{self._h}, {self._w}, 3], got "
                f"{frame_bgr.dtype} {frame_bgr.shape}"
            )
        frame = np.ascontiguousarray(frame_bgr)
        rc = self._lib.curate_h264_write(self._ctx, frame.ctypes.data_as(ctypes.c_void_p))
        if rc != 0:
            raise RuntimeError(f"H264 encode failed rc={rc}")

    def release(self) -> None:
        if self._ctx is not None:
            self._lib.curate_h264_close(self._ctx)
            self._ctx = None

    def __del__(self) -> None:
        self.release()


def h264_available() -> bool:
    """One-time probe: can the native encoder actually open a file here?"""
    global _probe_result
    if _probe_result is None:
        import os
        import tempfile

        ok = False
        if load_h264() is not None:
            fd, path = tempfile.mkstemp(suffix=".mp4")
            os.close(fd)
            try:
                w = NativeH264Writer(path, 24.0, (32, 32))
                ok = w.isOpened()
                if ok:
                    w.write(np.zeros((32, 32, 3), np.uint8))
                w.release()
            except Exception:
                ok = False
            finally:
                os.unlink(path)
        _probe_result = ok
    return _probe_result
