"""Codec motion-vector motion scores.

Equivalent capability of the reference's motion-vector backend
(cosmos_curate/pipelines/video/filtering/motion/motion_vector_backend.py —
decoder-exported motion vectors -> global-mean and per-patch-min scores):
the native binding (native/mv_extract.c, libavcodec ``export_mvs``)
aggregates each inter frame's vectors into a ``grid x grid`` field of mean
|mv| in pixels; this module normalizes the field into the two filter
scores. Works for whatever codec the clip carries (mpeg4 from the cv2
fallback, h264 from the native encoder) — a decode without any MV side
data (all-intra stream, missing ffmpeg) reports ``None`` so the filter can
fall back to the frame-diff estimator.

Score scale: per-frame mean |mv| in PIXELS divided by frame height —
resolution-independent fraction of the frame the content moves per frame.
A static encode's skip blocks carry no vectors, so static clips score
exactly 0 (same property the frame-diff estimator's calibration notes).
"""

from __future__ import annotations

import ctypes
import os
import tempfile
from dataclasses import dataclass

import numpy as np

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

MV_PATCH_GRID = 8
_MAX_FRAMES = 2048


@dataclass
class MVField:
    """Per-frame mean-|mv| grids (pixels) for one clip's inter frames."""

    field: np.ndarray  # float32 [T, grid, grid]
    has_mv: np.ndarray  # bool [T]
    width: int
    height: int


def extract_mv_field(
    video_bytes: bytes, *, grid: int = MV_PATCH_GRID, max_frames: int = _MAX_FRAMES
) -> MVField | None:
    """Decode ``video_bytes`` and return the per-frame MV field, or None
    when the native binding is unavailable or the stream yields no frames."""
    from cosmos_curate_tpu.native import load_mv

    lib = load_mv()
    if lib is None:
        return None
    field = np.zeros((max_frames, grid, grid), np.float32)
    has = np.zeros(max_frames, np.uint8)
    w = ctypes.c_int(0)
    h = ctypes.c_int(0)
    # libavformat wants a path; /dev/shm keeps the copy in RAM
    fd, path = tempfile.mkstemp(suffix=".mp4", dir="/dev/shm")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(video_bytes)
        n = lib.curate_mv_field(
            path.encode(),
            grid,
            field.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            has.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
            max_frames,
            ctypes.byref(w),
            ctypes.byref(h),
        )
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass
    if n <= 0 or w.value <= 0 or h.value <= 0:
        return None
    return MVField(
        field=field[:n], has_mv=has[:n].astype(bool), width=w.value, height=h.value
    )


def mv_motion_scores(mv: MVField) -> tuple[float, float] | None:
    """(global_score, per_patch_min) from the MV field, or None when the
    clip has no inter frames to score (single-frame / all-intra stream).

    global: mean over inter frames of the frame's mean cell |mv| / height.
    per_patch_min: min over grid cells of that cell's time-mean |mv| /
    height — a clip where one region never moves scores ~0 here even if
    something else moves (the reference's patch-min semantics)."""
    inter = mv.field[mv.has_mv]
    if inter.shape[0] == 0:
        return None
    norm = float(mv.height)
    global_score = float(inter.mean()) / norm
    per_patch = inter.mean(axis=0) / norm  # [grid, grid] time-mean per cell
    return global_score, float(per_patch.min())
