"""CPU video decode: metadata probing and frame extraction.

Equivalent capability of the reference's decoder layer
(cosmos_curate/pipelines/video/utils/decoder_utils.py:
``extract_video_metadata``:120, ``decode_video_cpu``:505,
``extract_frames``:611) built on OpenCV's FFmpeg backend instead of PyAV
(not in this image). Decode is deliberately CPU-side — there is no TPU video
engine (SURVEY.md §2.7), so throughput comes from many decode workers feeding
batched device stages.

All entry points accept either a path or encoded ``bytes`` (served through a
memfd so nothing touches disk).
"""

from __future__ import annotations

import contextlib
from typing import Iterator

import cv2
import numpy as np

from cosmos_curate_tpu.data.model import FrameExtractionSignature, VideoMetadata
from cosmos_curate_tpu.utils.memfd import buffer_as_path


@contextlib.contextmanager
def _open_capture(source: str | bytes) -> Iterator[cv2.VideoCapture]:
    with contextlib.ExitStack() as stack:
        if isinstance(source, (bytes, bytearray, memoryview)):
            path = stack.enter_context(buffer_as_path(bytes(source)))
        else:
            path = str(source)
        cap = cv2.VideoCapture(path)
        try:
            if not cap.isOpened():
                raise ValueError(f"could not open video source ({len(source) if isinstance(source, (bytes, bytearray)) else path})")
            yield cap
        finally:
            cap.release()


def extract_video_metadata(source: str | bytes) -> VideoMetadata:
    """Probe width/height/fps/frame-count/duration."""
    size = len(source) if isinstance(source, (bytes, bytearray)) else 0
    with _open_capture(source) as cap:
        fps = float(cap.get(cv2.CAP_PROP_FPS)) or 0.0
        n = int(cap.get(cv2.CAP_PROP_FRAME_COUNT))
        fourcc = int(cap.get(cv2.CAP_PROP_FOURCC))
        codec = "".join(chr((fourcc >> (8 * i)) & 0xFF) for i in range(4)).strip("\x00 ")
        return VideoMetadata(
            width=int(cap.get(cv2.CAP_PROP_FRAME_WIDTH)),
            height=int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT)),
            fps=fps,
            num_frames=n,
            duration_s=(n / fps) if fps > 0 else 0.0,
            codec=codec,
            size_bytes=size,
        )


def decode_frames(
    source: str | bytes,
    *,
    start_frame: int = 0,
    num_frames: int | None = None,
    stride: int = 1,
    resize_hw: tuple[int, int] | None = None,
) -> np.ndarray:
    """Decode frames to RGB uint8 ``[T, H, W, 3]``.

    Sequential read with frame skipping (seek via CAP_PROP_POS_FRAMES is
    unreliable across codecs, so we always roll forward).
    """
    frames: list[np.ndarray] = []
    with _open_capture(source) as cap:
        idx = 0
        wanted = start_frame
        while True:
            ok = cap.grab()
            if not ok:
                break
            if idx == wanted:
                ok, bgr = cap.retrieve()
                if not ok:
                    break
                if resize_hw is not None:
                    bgr = cv2.resize(bgr, (resize_hw[1], resize_hw[0]), interpolation=cv2.INTER_AREA)
                frames.append(cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB))
                if num_frames is not None and len(frames) >= num_frames:
                    break
                wanted += stride
            idx += 1
    if not frames:
        return np.zeros((0, 0, 0, 3), np.uint8)
    return np.stack(frames)


def decode_frame_ids(
    source: str | bytes,
    frame_ids: list[int],
    *,
    resize_hw: tuple[int, int] | None = None,
) -> np.ndarray:
    """Decode an explicit sorted list of frame indices (reference
    ``decode_video_cpu_frame_ids``:389)."""
    targets = sorted(set(frame_ids))
    out: dict[int, np.ndarray] = {}
    with _open_capture(source) as cap:
        idx = 0
        ti = 0
        while ti < len(targets):
            ok = cap.grab()
            if not ok:
                break
            if idx == targets[ti]:
                ok, bgr = cap.retrieve()
                if not ok:
                    break
                if resize_hw is not None:
                    bgr = cv2.resize(bgr, (resize_hw[1], resize_hw[0]), interpolation=cv2.INTER_AREA)
                out[idx] = cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB)
                ti += 1
            idx += 1
    if not out:
        return np.zeros((0, 0, 0, 3), np.uint8)
    return np.stack([out[i] for i in targets if i in out])


def extract_frames_multi(
    source: str | bytes,
    signatures: tuple[FrameExtractionSignature, ...] | list[FrameExtractionSignature],
    *,
    resize_hw: tuple[int, int] | None = None,
) -> dict[str, np.ndarray]:
    """Serve every ``FrameExtractionSignature`` from ONE decode pass.

    The per-signature path re-opens the container (a fresh memfd copy for
    byte sources) and rolls the decoder forward once per signature — k
    signatures cost k full decodes of the same bytes. Here the capture opens
    once, every source frame is decoded at most once, and resize + BGR→RGB
    conversion run once per retrieved frame, shared by every signature that
    samples it. Returns ``{sig.key(): [T, H, W, 3] uint8}`` — an empty
    ``(0, 0, 0, 3)`` array for signatures nothing decoded for (the same
    convention as the single-signature path). Duplicate keys collapse.
    """
    sigs = list(signatures)
    empty = np.zeros((0, 0, 0, 3), np.uint8)
    if not sigs:
        return {}
    frames: dict[str, list[np.ndarray]] = {}
    for s in sigs:
        frames.setdefault(s.key(), [])
    try:
        with _open_capture(source) as cap:
            fps = float(cap.get(cv2.CAP_PROP_FPS))
            if fps <= 0:
                return {k: empty for k in frames}
            stride = {s.key(): max(1, round(fps / s.target_fps)) for s in sigs}
            wanted = {k: 0 for k in frames}
            idx = 0
            while True:
                ok = cap.grab()
                if not ok:
                    break
                takers = [k for k, w in wanted.items() if w == idx]
                if takers:
                    ok, bgr = cap.retrieve()
                    if not ok:
                        break
                    if resize_hw is not None:
                        bgr = cv2.resize(bgr, (resize_hw[1], resize_hw[0]), interpolation=cv2.INTER_AREA)
                    rgb = cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB)
                    for k in takers:
                        frames[k].append(rgb)
                        wanted[k] += stride[k]
                idx += 1
    except ValueError:
        return {k: empty for k in frames}
    return {k: (np.stack(v) if v else empty) for k, v in frames.items()}


def extract_frames_at_fps(
    source: str | bytes,
    *,
    target_fps: float = 1.0,
    resize_hw: tuple[int, int] | None = None,
) -> np.ndarray:
    """Uniformly sample frames at ``target_fps`` (the frame-extraction stage's
    core op, clip_frame_extraction_stages.py:43 in the reference).

    Single decoder open: the source fps is read off the already-open capture
    (a second probe open would double the memfd copies on the hot CPU path).
    Thin wrapper over the multi-signature pass so the two never diverge.
    """
    sig = FrameExtractionSignature("fps", target_fps)
    return extract_frames_multi(source, (sig,), resize_hw=resize_hw)[sig.key()]


def get_frame_timestamps(source: str | bytes) -> np.ndarray:
    """Per-frame presentation timestamps in seconds (reference
    ``get_video_timestamps``:230, PyAV packet PTS).

    Exact for mp4/mov — the container's sample tables are parsed directly
    (video/mp4_index.py), correct for VFR too. Other containers fall back
    to a constant-rate assumption from probed fps."""
    from cosmos_curate_tpu.video.mp4_index import Mp4ParseError, parse_mp4_video_index

    try:
        return parse_mp4_video_index(source).pts_s
    except (Mp4ParseError, OSError):
        pass
    meta = extract_video_metadata(source)
    if meta.fps <= 0:
        return np.zeros(0, np.float64)
    return np.arange(meta.num_frames, dtype=np.float64) / meta.fps
