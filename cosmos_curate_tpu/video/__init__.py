import os as _os

# Silence FFmpeg's logger before cv2 loads it (AV_LOG_QUIET=-8); the encoder
# preference probe intentionally trips unavailable codecs.
_os.environ.setdefault("OPENCV_FFMPEG_LOGLEVEL", "-8")

from cosmos_curate_tpu.video.decode import (
    decode_frames,
    extract_frames_at_fps,
    extract_video_metadata,
)
from cosmos_curate_tpu.video.encode import encode_frames, transcode_clip
from cosmos_curate_tpu.video.splitter import fixed_stride_spans
from cosmos_curate_tpu.video.windowing import compute_windows

__all__ = [
    "compute_windows",
    "decode_frames",
    "encode_frames",
    "extract_frames_at_fps",
    "extract_video_metadata",
    "fixed_stride_spans",
    "transcode_clip",
]
