"""Span math for clip extraction.

Equivalent capability of the reference's ``FixedStrideExtractorStage`` span
logic (cosmos_curate/pipelines/video/clipping/clip_extraction_stages.py:664
and :554 uuid chains) plus the scene-span filtering/cropping applied after
shot detection (transnetv2_extraction_stages.py:264-365).
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.data.model import Clip, deterministic_id


def fixed_stride_spans(
    duration_s: float,
    *,
    clip_len_s: float = 10.0,
    stride_s: float | None = None,
    min_clip_len_s: float = 2.0,
) -> list[tuple[float, float]]:
    """Fixed-duration spans over ``[0, duration_s)``; the last partial span is
    kept only if at least ``min_clip_len_s`` long."""
    if duration_s <= 0 or clip_len_s <= 0:
        return []
    stride = stride_s if stride_s is not None else clip_len_s
    if stride <= 0:
        raise ValueError("stride must be positive")
    spans = []
    t = 0.0
    while t < duration_s:
        end = min(t + clip_len_s, duration_s)
        if end - t >= min_clip_len_s:
            spans.append((t, end))
        t += stride
    return spans


def scene_spans_from_predictions(
    predictions: np.ndarray,
    fps: float,
    *,
    threshold: float = 0.4,
    min_scene_len_s: float = 2.0,
    max_scene_len_s: float = 60.0,
    crop_s: float = 0.0,
    timestamps_s: np.ndarray | None = None,
) -> list[tuple[float, float]]:
    """Turn per-frame shot-transition probabilities into scene spans.

    - frames with probability ≥ threshold are cut points;
    - scenes shorter than ``min_scene_len_s`` are dropped;
    - scenes longer than ``max_scene_len_s`` are split into max-length pieces;
    - ``crop_s`` is trimmed off both ends (transition blur guard).
    Mirrors the reference's post-processing semantics
    (transnetv2_extraction_stages.py:264-365).

    ``timestamps_s`` (per-frame PTS, len == len(predictions)) makes the
    frame→time mapping exact on variable-frame-rate sources; without it
    the constant-rate ``fps`` mapping is used.
    """
    if predictions.size == 0:
        return []
    n = int(predictions.size)
    if timestamps_s is not None and len(timestamps_s) == n:
        tail = float(np.median(np.diff(timestamps_s))) if n > 1 else 1.0 / max(fps, 1.0)
        frame_time = np.append(np.asarray(timestamps_s, np.float64), timestamps_s[-1] + tail)
    elif fps > 0:
        frame_time = np.arange(n + 1, dtype=np.float64) / fps
    else:
        return []
    cuts = np.flatnonzero(predictions >= threshold)
    boundaries = [0, *(int(c) + 1 for c in cuts), n]
    spans: list[tuple[float, float]] = []
    for a, b in zip(boundaries[:-1], boundaries[1:]):
        if b <= a:
            continue
        start, end = float(frame_time[a]) + crop_s, float(frame_time[b]) - crop_s
        if end - start < min_scene_len_s:
            continue
        while end - start > max_scene_len_s:
            spans.append((start, start + max_scene_len_s))
            start += max_scene_len_s
        if end - start >= min_scene_len_s:
            spans.append((start, end))
    return spans


def make_clips(source_video: str, spans: list[tuple[float, float]]) -> list[Clip]:
    """Build ``Clip`` objects with deterministic uuid5 ids so re-runs and
    resume produce identical identities."""
    return [
        Clip(
            uuid=deterministic_id(source_video, f"{s:.6f}-{e:.6f}"),
            source_video=source_video,
            span=(s, e),
        )
        for s, e in spans
    ]
