"""CPU video encode / per-clip transcode.

Equivalent capability of the reference's ``ClipTranscodingStage`` encode core
(cosmos_curate/pipelines/video/clipping/clip_extraction_stages.py:167):
extract a clip's span from the source and re-encode it as a standalone mp4.
Uses cv2's FFmpeg writer; codec is negotiated from a preference list because
encoder availability differs per image (h264 is absent here; mp4v works).
"""

from __future__ import annotations

import os
import tempfile

import cv2
import numpy as np

from cosmos_curate_tpu.video.decode import _open_capture

_CODEC_PREFERENCE = ("avc1", "mp4v")
_negotiated: str | None = None


def _pick_codec() -> str:
    global _negotiated
    if _negotiated is not None:
        return _negotiated
    try:
        prev = cv2.utils.logging.getLogLevel()
        cv2.utils.logging.setLogLevel(cv2.utils.logging.LOG_LEVEL_SILENT)
    except AttributeError:
        prev = None
    try:
        with tempfile.NamedTemporaryFile(suffix=".mp4") as f:
            for cc in _CODEC_PREFERENCE:
                w = cv2.VideoWriter(f.name, cv2.VideoWriter_fourcc(*cc), 24.0, (16, 16))
                ok = w.isOpened()
                w.release()
                if ok:
                    _negotiated = cc
                    return cc
    finally:
        if prev is not None:
            cv2.utils.logging.setLogLevel(prev)
    raise RuntimeError("no usable mp4 encoder in cv2 build")


def negotiated_codec() -> str:
    """The codec clips will actually be written with: native H264 when the
    binding is live (reference guarantees H264 output,
    clip_extraction_stages.py:167), else cv2's negotiated fallback."""
    from cosmos_curate_tpu.video.h264 import h264_available

    return "avc1" if h264_available() else _pick_codec()


def make_writer(path: str, fps: float, w: int, h: int):
    """(writer, codec) — writer has the cv2.VideoWriter call surface."""
    from cosmos_curate_tpu.video.h264 import NativeH264Writer, h264_available

    if h264_available():
        writer = NativeH264Writer(path, fps, (w, h))
        if writer.isOpened():
            return writer, "avc1"
    codec = _pick_codec()
    writer = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*codec), fps, (w, h))
    return writer, codec


def encode_frames(frames: np.ndarray, fps: float) -> bytes:
    """Encode RGB uint8 ``[T, H, W, 3]`` frames into an mp4 container."""
    if frames.ndim != 4 or frames.shape[-1] != 3:
        raise ValueError(f"expected [T,H,W,3] RGB frames, got {frames.shape}")
    t, h, w, _ = frames.shape
    # the writers require a real file path (no memfd: re-opened by name).
    fd, path = tempfile.mkstemp(suffix=".mp4")
    os.close(fd)
    try:
        writer, codec = make_writer(path, fps, w, h)
        if not writer.isOpened():
            raise RuntimeError(f"encoder {codec} failed to open for {w}x{h}@{fps}")
        for i in range(t):
            writer.write(cv2.cvtColor(frames[i], cv2.COLOR_RGB2BGR))
        writer.release()
        with open(path, "rb") as f:
            return f.read()
    finally:
        os.unlink(path)


def transcode_clip(
    source: str | bytes,
    span_s: tuple[float, float],
    *,
    resize_hw: tuple[int, int] | None = None,
) -> tuple[bytes, str]:
    """Cut one ``span_s`` (seconds) out of ``source``; see
    ``transcode_clips`` for the multi-span single-pass API."""
    results = transcode_clips(source, [span_s], resize_hw=resize_hw)
    return results[0]


class _ClipWriter:
    """One open encoder + temp file for a span being cut."""

    def __init__(self, start_f: int, end_f: int):
        self.start_f = start_f
        self.end_f = end_f
        self.path: str | None = None
        self.writer: cv2.VideoWriter | None = None

    def open(self, fps: float, w: int, h: int) -> str:
        fd, self.path = tempfile.mkstemp(suffix=".mp4")
        os.close(fd)
        self.writer, codec = make_writer(self.path, fps, w, h)
        if not self.writer.isOpened():
            raise RuntimeError(f"encoder {codec} failed to open for {w}x{h}@{fps}")
        return codec

    def finish(self) -> bytes:
        data = b""
        if self.writer is not None:
            self.writer.release()
            self.writer = None
        if self.path is not None:
            with open(self.path, "rb") as f:
                data = f.read()
            os.unlink(self.path)
            self.path = None
        return data

    def abort(self) -> None:
        if self.writer is not None:
            self.writer.release()
            self.writer = None
        if self.path is not None:
            os.unlink(self.path)
            self.path = None


def transcode_clips(
    source: str | bytes,
    spans_s: list[tuple[float, float]],
    *,
    resize_hw: tuple[int, int] | None = None,
    timestamps_s=None,
) -> list[tuple[bytes, str]]:
    """Cut every span of ``source`` in ONE sequential decode pass.

    The naive per-clip approach decodes frames 0..end for each clip —
    quadratic in clip count for a long video (360 clips of a 1-hour video =
    ~180x redundant decode). Here the source is opened once, each frame is
    decoded once, and every encoder whose span covers it receives it
    (overlapping spans supported). Returns (mp4_bytes, codec) per span, in
    input order; spans past end-of-stream yield empty bytes.
    """
    codec = negotiated_codec()
    if not spans_s:
        return []
    with _open_capture(source) as cap:
        fps = float(cap.get(cv2.CAP_PROP_FPS)) or 24.0
        if timestamps_s is not None and len(timestamps_s) > 0:
            # exact PTS mapping — must mirror the span computation
            # (splitter.scene_spans_from_predictions with timestamps_s),
            # or VFR clips cut at the wrong frames
            import numpy as np

            ts = np.asarray(timestamps_s, np.float64)
            clips = [
                _ClipWriter(
                    int(np.searchsorted(ts, a, side="left")),
                    max(
                        int(np.searchsorted(ts, a, side="left")) + 1,
                        int(np.searchsorted(ts, b, side="left")),
                    ),
                )
                for a, b in spans_s
            ]
        else:
            clips = [_ClipWriter(int(a * fps), int(b * fps)) for a, b in spans_s]
        # sorted view by start frame for an O(1) active set sweep
        pending = sorted(range(len(clips)), key=lambda i: clips[i].start_f)
        active: list[int] = []
        results: list[bytes] = [b""] * len(clips)
        max_end = max(c.end_f for c in clips)
        p = 0
        idx = 0
        try:
            while idx < max_end:
                ok = cap.grab()
                if not ok:
                    break
                while p < len(pending) and clips[pending[p]].start_f <= idx:
                    active.append(pending[p])
                    p += 1
                done = [i for i in active if clips[i].end_f <= idx]
                for i in done:
                    results[i] = clips[i].finish()
                    active.remove(i)
                if active:
                    ok, bgr = cap.retrieve()
                    if not ok:
                        break
                    if resize_hw is not None:
                        bgr = cv2.resize(
                            bgr, (resize_hw[1], resize_hw[0]), interpolation=cv2.INTER_AREA
                        )
                    h, w = bgr.shape[:2]
                    for i in active:
                        c = clips[i]
                        if c.writer is None:
                            codec = c.open(fps, w, h)
                        c.writer.write(bgr)
                idx += 1
            for i in active:
                results[i] = clips[i].finish()
        finally:
            for c in clips:
                c.abort()
        return [(r, codec) for r in results]
