"""CPU video encode / per-clip transcode.

Equivalent capability of the reference's ``ClipTranscodingStage`` encode core
(cosmos_curate/pipelines/video/clipping/clip_extraction_stages.py:167):
extract a clip's span from the source and re-encode it as a standalone mp4.
Uses cv2's FFmpeg writer; codec is negotiated from a preference list because
encoder availability differs per image (h264 is absent here; mp4v works).
"""

from __future__ import annotations

import os
import tempfile

import cv2
import numpy as np

from cosmos_curate_tpu.video.decode import _open_capture

_CODEC_PREFERENCE = ("avc1", "mp4v")
_negotiated: str | None = None


def _pick_codec() -> str:
    global _negotiated
    if _negotiated is not None:
        return _negotiated
    try:
        prev = cv2.utils.logging.getLogLevel()
        cv2.utils.logging.setLogLevel(cv2.utils.logging.LOG_LEVEL_SILENT)
    except AttributeError:
        prev = None
    try:
        with tempfile.NamedTemporaryFile(suffix=".mp4") as f:
            for cc in _CODEC_PREFERENCE:
                w = cv2.VideoWriter(f.name, cv2.VideoWriter_fourcc(*cc), 24.0, (16, 16))
                ok = w.isOpened()
                w.release()
                if ok:
                    _negotiated = cc
                    return cc
    finally:
        if prev is not None:
            cv2.utils.logging.setLogLevel(prev)
    raise RuntimeError("no usable mp4 encoder in cv2 build")


def encode_frames(frames: np.ndarray, fps: float) -> bytes:
    """Encode RGB uint8 ``[T, H, W, 3]`` frames into an mp4 container."""
    if frames.ndim != 4 or frames.shape[-1] != 3:
        raise ValueError(f"expected [T,H,W,3] RGB frames, got {frames.shape}")
    codec = _pick_codec()
    t, h, w, _ = frames.shape
    # cv2's writer requires a real file path (no memfd: it re-opens by name).
    fd, path = tempfile.mkstemp(suffix=".mp4")
    os.close(fd)
    try:
        writer = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*codec), fps, (w, h))
        if not writer.isOpened():
            raise RuntimeError(f"encoder {codec} failed to open for {w}x{h}@{fps}")
        for i in range(t):
            writer.write(cv2.cvtColor(frames[i], cv2.COLOR_RGB2BGR))
        writer.release()
        with open(path, "rb") as f:
            return f.read()
    finally:
        os.unlink(path)


def transcode_clip(
    source: str | bytes,
    span_s: tuple[float, float],
    *,
    resize_hw: tuple[int, int] | None = None,
) -> tuple[bytes, str]:
    """Cut ``span_s`` (seconds) out of ``source`` and re-encode standalone.

    Returns (mp4 bytes, codec fourcc). Decode and encode stream frame-by-
    frame so a 5-hour source never fully materializes.
    """
    codec = _pick_codec()
    with _open_capture(source) as cap:
        fps = float(cap.get(cv2.CAP_PROP_FPS)) or 24.0
        start_f = int(span_s[0] * fps)
        end_f = int(span_s[1] * fps)
        fd, path = tempfile.mkstemp(suffix=".mp4")
        os.close(fd)
        writer = None
        try:
            idx = 0
            while idx < end_f:
                ok = cap.grab()
                if not ok:
                    break
                if idx >= start_f:
                    ok, bgr = cap.retrieve()
                    if not ok:
                        break
                    if resize_hw is not None:
                        bgr = cv2.resize(bgr, (resize_hw[1], resize_hw[0]), interpolation=cv2.INTER_AREA)
                    if writer is None:
                        h, w = bgr.shape[:2]
                        writer = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*codec), fps, (w, h))
                        if not writer.isOpened():
                            raise RuntimeError(f"encoder {codec} failed to open")
                    writer.write(bgr)
                idx += 1
            if writer is None:
                return b"", codec
            writer.release()
            writer = None
            with open(path, "rb") as f:
                return f.read(), codec
        finally:
            if writer is not None:
                writer.release()
            os.unlink(path)
