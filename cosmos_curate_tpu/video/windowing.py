"""Caption-window math: the data-layer answer to long context.

Equivalent capability of the reference's ``compute_windows``
(cosmos_curate/pipelines/video/utils/windowing_utils.py:53-89): a clip's
frames are cut into fixed windows (default 256 frames); a trailing remainder
shorter than ``remainder_threshold`` merges into the previous window instead
of forming a runt. This is how the system scales sequence length without
in-model attention sharding (SURVEY.md §5); in-model long context is handled
separately by ring attention (parallel/ring_attention.py).
"""

from __future__ import annotations


def compute_windows(
    num_frames: int,
    *,
    window_len: int = 256,
    remainder_threshold: int = 128,
) -> list[tuple[int, int]]:
    """Return [start, end) frame windows covering ``num_frames``.

    The final window absorbs a short remainder (< threshold); a remainder
    ≥ threshold becomes its own window.
    """
    if num_frames <= 0 or window_len <= 0:
        return []
    if remainder_threshold > window_len:
        raise ValueError("remainder_threshold must be <= window_len")
    windows = []
    start = 0
    while start + window_len <= num_frames:
        windows.append((start, start + window_len))
        start += window_len
    rem = num_frames - start
    if rem > 0:
        if windows and rem < remainder_threshold:
            windows[-1] = (windows[-1][0], num_frames)
        else:
            windows.append((start, num_frames))
    return windows


def overlapping_windows(
    num_frames: int,
    *,
    window_len: int = 128,
    overlap: int = 64,
) -> list[tuple[int, int]]:
    """Overlapped windows for super-resolution-style blending (reference
    SR path: 128-frame windows, 64-frame overlap,
    inference_seedvr2_window.py:483-530)."""
    if num_frames <= 0:
        return []
    if overlap >= window_len:
        raise ValueError("overlap must be < window_len")
    step = window_len - overlap
    windows = []
    start = 0
    while True:
        end = min(start + window_len, num_frames)
        windows.append((start, end))
        if end >= num_frames:
            break
        start += step
    return windows
