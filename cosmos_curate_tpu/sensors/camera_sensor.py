"""Camera sensor: sampled batched frame access over a video + timestamps.

Equivalent capability of the reference's CameraSensor
(cosmos_curate/core/sensors/sensors/camera_sensor.py:46-265 — a camera whose
``sample(spec)`` yields one CameraData batch per sampling window, decoding
each selected frame once and repeating it per the grid-match counts; MCAP
variant mcap_camera_sensor.py). Built over our cv2 decode plane and the
JSONL session reader (sensors/data.py) — an MCAP parser slots in behind the
same constructor (no mcap package in this image).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Generator, Sequence

import numpy as np

from cosmos_curate_tpu.sensors.data import (
    CameraExtrinsics,
    CameraFrameRef,
    CameraIntrinsics,
    SensorSession,
)
from cosmos_curate_tpu.sensors.sampling import NS, SamplingSpec, sample_window_indices


@dataclass
class CameraData:
    """One sampling window's worth of frames from one camera."""

    align_timestamps_ns: np.ndarray  # the window's grid points
    sensor_timestamps_ns: np.ndarray  # chosen frame timestamps (repeated)
    frame_indices: np.ndarray  # source frame index per sample (repeated)
    frames: np.ndarray  # uint8 [N, H, W, 3] RGB (repeated per counts)
    camera: str = ""
    intrinsics: CameraIntrinsics | None = None
    extrinsics: CameraExtrinsics | None = None

    def __len__(self) -> int:
        return len(self.sensor_timestamps_ns)


class CameraSensor:
    """One camera of a capture session, sampled on nanosecond grids."""

    def __init__(
        self,
        camera: str,
        frames: Sequence[CameraFrameRef],
        *,
        intrinsics: CameraIntrinsics | None = None,
        extrinsics: CameraExtrinsics | None = None,
        resize_hw: tuple[int, int] | None = None,
    ) -> None:
        if not frames:
            raise ValueError(f"camera {camera!r} has no frames")
        self.camera = camera
        self.frames = sorted(frames, key=lambda f: f.timestamp_s)
        self.intrinsics = intrinsics
        self.extrinsics = extrinsics
        self.resize_hw = resize_hw
        from cosmos_curate_tpu.sensors.validation import strictly_increasing_int64

        # fail-loud on duplicate/backward timestamps at construction
        # (reference utils/validation.py) — not as a misalignment later
        self._ts_ns = strictly_increasing_int64(
            f"camera {camera!r} timestamps",
            [round(f.timestamp_s * NS) for f in self.frames],
        )

    @classmethod
    def from_session(
        cls, session: SensorSession, camera: str, **kw
    ) -> "CameraSensor":
        return cls(
            camera,
            session.cameras.get(camera, []),
            intrinsics=session.intrinsics.get(camera),
            extrinsics=session.extrinsics.get(camera),
            **kw,
        )

    # -- index properties (reference camera_sensor.py:107-156) ------------
    @property
    def timestamps_ns(self) -> np.ndarray:
        return self._ts_ns

    @property
    def start_ns(self) -> int:
        return int(self._ts_ns[0])

    @property
    def end_ns(self) -> int:
        return int(self._ts_ns[-1])

    @property
    def max_gap_ns(self) -> int:
        if len(self._ts_ns) < 2:
            return 0
        return int(np.diff(self._ts_ns).max())

    # -- sampling ----------------------------------------------------------
    def sample(self, spec: SamplingSpec) -> Generator[CameraData, None, None]:
        """One CameraData per sampling window (empty windows yield empty
        batches so batch i always corresponds to window i). Each selected
        source frame is decoded once and repeated per its match count."""
        from cosmos_curate_tpu.video.decode import decode_frame_ids

        for window in spec.grid:
            idx, counts = sample_window_indices(self._ts_ns, window, policy=spec.policy)
            if len(idx) == 0:
                yield CameraData(
                    align_timestamps_ns=window.timestamps_ns,
                    sensor_timestamps_ns=np.zeros(0, np.int64),
                    frame_indices=np.zeros(0, np.int64),
                    frames=np.zeros((0, 0, 0, 3), np.uint8),
                    camera=self.camera,
                    intrinsics=self.intrinsics,
                    extrinsics=self.extrinsics,
                )
                continue
            # group by source video (a camera may span several files)
            refs = [self.frames[i] for i in idx]
            decoded: dict[int, np.ndarray] = {}
            by_video: dict[str, list[int]] = {}
            for j, r in enumerate(refs):
                by_video.setdefault(r.video_path, []).append(j)
            for video, positions in by_video.items():
                # decode_frame_ids returns frames in sorted-id order
                positions = sorted(positions, key=lambda j: refs[j].frame_index)
                frame_ids = [refs[j].frame_index for j in positions]
                frames = decode_frame_ids(video, frame_ids, resize_hw=self.resize_hw)
                for j, fr in zip(positions, frames):
                    decoded[j] = fr
            stacked = np.stack([decoded[j] for j in range(len(refs))])
            rep = np.repeat(np.arange(len(refs)), counts)
            yield CameraData(
                align_timestamps_ns=window.timestamps_ns,
                sensor_timestamps_ns=np.repeat(self._ts_ns[idx], counts),
                frame_indices=np.repeat(
                    np.asarray([r.frame_index for r in refs], np.int64), counts
                ),
                frames=stacked[rep],
                camera=self.camera,
                intrinsics=self.intrinsics,
                extrinsics=self.extrinsics,
            )
