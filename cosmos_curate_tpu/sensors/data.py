"""Multi-sensor data model for AV capture sessions.

Equivalent capability of the reference's sensor library data layer
(cosmos_curate/core/sensors/data/ — camera/gps/imu samples, camera
intrinsics/extrinsics, aligned frames; design docs
docs/curator/design/SENSOR_LIBRARY*.md). MCAP container parsing is gated
(no mcap package in this image); the JSONL session log reader below covers
the same record shapes for local data.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path

import numpy as np


@dataclass(frozen=True)
class CameraIntrinsics:
    fx: float
    fy: float
    cx: float
    cy: float
    width: int
    height: int
    distortion: tuple[float, ...] = ()

    def matrix(self) -> np.ndarray:
        return np.array(
            [[self.fx, 0, self.cx], [0, self.fy, self.cy], [0, 0, 1]], np.float64
        )


@dataclass(frozen=True)
class CameraExtrinsics:
    """Sensor-to-vehicle transform."""

    rotation: tuple[float, float, float, float] = (1.0, 0.0, 0.0, 0.0)  # wxyz quat
    translation: tuple[float, float, float] = (0.0, 0.0, 0.0)

    def matrix(self) -> np.ndarray:
        w, x, y, z = self.rotation
        R = np.array(
            [
                [1 - 2 * (y * y + z * z), 2 * (x * y - w * z), 2 * (x * z + w * y)],
                [2 * (x * y + w * z), 1 - 2 * (x * x + z * z), 2 * (y * z - w * x)],
                [2 * (x * z - w * y), 2 * (y * z + w * x), 1 - 2 * (x * x + y * y)],
            ]
        )
        T = np.eye(4)
        T[:3, :3] = R
        T[:3, 3] = self.translation
        return T


@dataclass(frozen=True)
class CameraFrameRef:
    """Reference to one camera frame: video + index + timestamp."""

    camera: str
    video_path: str
    frame_index: int
    timestamp_s: float


@dataclass(frozen=True)
class GpsSample:
    timestamp_s: float
    latitude: float
    longitude: float
    altitude_m: float = 0.0
    speed_mps: float = 0.0


@dataclass(frozen=True)
class ImuSample:
    timestamp_s: float
    accel: tuple[float, float, float]
    gyro: tuple[float, float, float]


@dataclass
class AlignedFrame:
    """One time-aligned multi-sensor snapshot."""

    timestamp_s: float
    cameras: dict[str, CameraFrameRef] = field(default_factory=dict)
    gps: GpsSample | None = None
    imu: ImuSample | None = None


@dataclass
class SensorSession:
    session_id: str
    cameras: dict[str, list[CameraFrameRef]] = field(default_factory=dict)
    gps: list[GpsSample] = field(default_factory=list)
    imu: list[ImuSample] = field(default_factory=list)
    intrinsics: dict[str, CameraIntrinsics] = field(default_factory=dict)
    extrinsics: dict[str, CameraExtrinsics] = field(default_factory=dict)


def load_session_jsonl(path: str | Path) -> SensorSession:
    """Read a session log: one JSON record per line with a ``type`` field
    (camera_frame | gps | imu | intrinsics | extrinsics)."""
    session = SensorSession(session_id=Path(path).stem)
    for line in Path(path).read_text().splitlines():
        if not line.strip():
            continue
        rec = json.loads(line)
        kind = rec.pop("type")
        if kind == "camera_frame":
            session.cameras.setdefault(rec["camera"], []).append(CameraFrameRef(**rec))
        elif kind == "gps":
            session.gps.append(GpsSample(**rec))
        elif kind == "imu":
            session.imu.append(
                ImuSample(rec["timestamp_s"], tuple(rec["accel"]), tuple(rec["gyro"]))
            )
        elif kind == "intrinsics":
            cam = rec.pop("camera")
            session.intrinsics[cam] = CameraIntrinsics(**rec)
        elif kind == "extrinsics":
            cam = rec.pop("camera")
            session.extrinsics[cam] = CameraExtrinsics(
                tuple(rec["rotation"]), tuple(rec["translation"])
            )
    return session
