"""Pure-Python MCAP reader/writer (no SDK; implements the public MCAP spec).

The reference's sensor library reads robotics captures through the ``mcap``
package (cosmos_curate/core/sensors/utils/mcap.py:21-158,
sensors/mcap_camera_sensor.py:76). That SDK is absent from this image, so
this module implements the container format directly from the open spec
(mcap.dev/spec): little-endian records, prefixed strings/maps, chunked and
unchunked data sections, zstd/no-compression chunks, metadata records, the
summary section, and time/topic-filtered message iteration that skips
non-overlapping chunks via chunk indexes.

Reader API mirrors what the reference code needs: ``summary`` (schemas,
channels, statistics, chunk indexes), ``iter_messages(topics, start_time,
end_time, log_time_order)``, ``iter_metadata()``. The writer produces
spec-valid files (verified round-trip in tests) and powers the
make-mcap-from-video tooling (reference scripts/make_mcap_from_mp4.py).
"""

from __future__ import annotations

import io
import struct
import zlib
from dataclasses import dataclass, field
from typing import BinaryIO, Iterator

MAGIC = b"\x89MCAP0\r\n"

OP_HEADER = 0x01
OP_FOOTER = 0x02
OP_SCHEMA = 0x03
OP_CHANNEL = 0x04
OP_MESSAGE = 0x05
OP_CHUNK = 0x06
OP_MESSAGE_INDEX = 0x07
OP_CHUNK_INDEX = 0x08
OP_ATTACHMENT = 0x09
OP_ATTACHMENT_INDEX = 0x0A
OP_STATISTICS = 0x0B
OP_METADATA = 0x0C
OP_METADATA_INDEX = 0x0D
OP_SUMMARY_OFFSET = 0x0E
OP_DATA_END = 0x0F


class McapError(ValueError):
    pass


# ---------------------------------------------------------------------------
# primitive encode/decode


def _u16(v: int) -> bytes:
    return struct.pack("<H", v)


def _u32(v: int) -> bytes:
    return struct.pack("<I", v)


def _u64(v: int) -> bytes:
    return struct.pack("<Q", v)


def _string(s: str) -> bytes:
    b = s.encode()
    return _u32(len(b)) + b


def _str_map(m: dict[str, str]) -> bytes:
    body = b"".join(_string(k) + _string(v) for k, v in m.items())
    return _u32(len(body)) + body


class _Cursor:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0) -> None:
        self.buf = buf
        self.pos = pos

    def u8(self) -> int:
        v = self.buf[self.pos]
        self.pos += 1
        return v

    def u16(self) -> int:
        (v,) = struct.unpack_from("<H", self.buf, self.pos)
        self.pos += 2
        return v

    def u32(self) -> int:
        (v,) = struct.unpack_from("<I", self.buf, self.pos)
        self.pos += 4
        return v

    def u64(self) -> int:
        (v,) = struct.unpack_from("<Q", self.buf, self.pos)
        self.pos += 8
        return v

    def raw(self, n: int) -> bytes:
        v = self.buf[self.pos : self.pos + n]
        if len(v) != n:
            raise McapError("truncated record")
        self.pos += n
        return v

    def string(self) -> str:
        return self.raw(self.u32()).decode()

    def str_map(self) -> dict[str, str]:
        end = self.u32() + self.pos
        out: dict[str, str] = {}
        while self.pos < end:
            k = self.string()
            out[k] = self.string()
        return out


# ---------------------------------------------------------------------------
# records


@dataclass(frozen=True)
class Schema:
    id: int
    name: str
    encoding: str
    data: bytes


@dataclass(frozen=True)
class Channel:
    id: int
    schema_id: int
    topic: str
    message_encoding: str
    metadata: dict[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class Message:
    channel_id: int
    sequence: int
    log_time: int
    publish_time: int
    data: bytes


@dataclass(frozen=True)
class ChunkIndex:
    message_start_time: int
    message_end_time: int
    chunk_start_offset: int
    chunk_length: int
    compression: str
    compressed_size: int
    uncompressed_size: int


@dataclass(frozen=True)
class Statistics:
    message_count: int
    schema_count: int
    channel_count: int
    attachment_count: int
    metadata_count: int
    chunk_count: int
    message_start_time: int
    message_end_time: int
    channel_message_counts: dict[int, int]


@dataclass(frozen=True)
class MetadataRecord:
    name: str
    metadata: dict[str, str]


@dataclass
class Summary:
    schemas: dict[int, Schema] = field(default_factory=dict)
    channels: dict[int, Channel] = field(default_factory=dict)
    chunk_indexes: list[ChunkIndex] = field(default_factory=list)
    statistics: Statistics | None = None


def _decompress(compression: str, data: bytes, uncompressed_size: int) -> bytes:
    if compression in ("", "none"):
        return data
    if compression == "zstd":
        import zstandard

        return zstandard.ZstdDecompressor().decompress(
            data, max_output_size=uncompressed_size or -1
        )
    if compression == "lz4":
        try:
            import lz4.frame
        except ImportError as e:
            raise McapError("lz4-compressed MCAP chunk but lz4 is not installed") from e
        return lz4.frame.decompress(data)
    raise McapError(f"unknown MCAP chunk compression {compression!r}")


def _compress(compression: str, data: bytes) -> bytes:
    if compression in ("", "none"):
        return data
    if compression == "zstd":
        import zstandard

        return zstandard.ZstdCompressor().compress(data)
    raise McapError(f"unsupported writer compression {compression!r}")


# ---------------------------------------------------------------------------
# reader


class McapReader:
    """Random-access MCAP reader over a seekable binary stream."""

    def __init__(self, stream: BinaryIO) -> None:
        self._f = stream
        self._f.seek(0)
        if self._f.read(len(MAGIC)) != MAGIC:
            raise McapError("not an MCAP file (bad leading magic)")
        self._summary: Summary | None = None

    # -- low-level record walk --------------------------------------------

    def _iter_records(
        self, start: int, end: int | None = None
    ) -> Iterator[tuple[int, bytes, int]]:
        """Yield (opcode, content, record_start_offset) from the file."""
        f = self._f
        f.seek(start)
        while True:
            offset = f.tell()
            if end is not None and offset >= end:
                return
            head = f.read(9)
            if len(head) < 9:
                return
            op = head[0]
            (length,) = struct.unpack("<Q", head[1:])
            content = f.read(length)
            if len(content) != length:
                raise McapError(f"truncated record op=0x{op:02x} at {offset}")
            yield op, content, offset
            if op == OP_FOOTER:
                return

    @staticmethod
    def _iter_chunk_records(chunk_content: bytes) -> Iterator[tuple[int, bytes]]:
        cur = _Cursor(chunk_content)
        start_time = cur.u64()  # noqa: F841 — spec fields, kept for clarity
        end_time = cur.u64()  # noqa: F841
        uncompressed_size = cur.u64()
        uncompressed_crc = cur.u32()
        compression = cur.string()
        records = cur.raw(cur.u64())
        data = _decompress(compression, records, uncompressed_size)
        if uncompressed_crc and zlib.crc32(data) != uncompressed_crc:
            raise McapError("MCAP chunk CRC mismatch")
        inner = _Cursor(data)
        while inner.pos < len(data):
            op = inner.u8()
            length = inner.u64()
            yield op, inner.raw(length)

    # -- record parsers ----------------------------------------------------

    @staticmethod
    def _parse_schema(content: bytes) -> Schema:
        cur = _Cursor(content)
        return Schema(cur.u16(), cur.string(), cur.string(), cur.raw(cur.u32()))

    @staticmethod
    def _parse_channel(content: bytes) -> Channel:
        cur = _Cursor(content)
        return Channel(cur.u16(), cur.u16(), cur.string(), cur.string(), cur.str_map())

    @staticmethod
    def _parse_message(content: bytes) -> Message:
        cur = _Cursor(content)
        return Message(cur.u16(), cur.u32(), cur.u64(), cur.u64(), content[cur.pos :])

    @staticmethod
    def _parse_chunk_index(content: bytes) -> ChunkIndex:
        cur = _Cursor(content)
        start, end = cur.u64(), cur.u64()
        chunk_start, chunk_len = cur.u64(), cur.u64()
        cur.raw(cur.u32())  # message_index_offsets
        cur.u64()  # message_index_length
        compression = cur.string()
        return ChunkIndex(start, end, chunk_start, chunk_len, compression, cur.u64(), cur.u64())

    @staticmethod
    def _parse_statistics(content: bytes) -> Statistics:
        cur = _Cursor(content)
        msg_count = cur.u64()
        schema_count, channel_count = cur.u16(), cur.u32()
        attach_count, meta_count, chunk_count = cur.u32(), cur.u32(), cur.u32()
        start, end = cur.u64(), cur.u64()
        counts: dict[int, int] = {}
        map_end = cur.u32() + cur.pos
        while cur.pos < map_end:
            cid = cur.u16()
            counts[cid] = cur.u64()
        return Statistics(
            msg_count, schema_count, channel_count, attach_count, meta_count,
            chunk_count, start, end, counts,
        )

    # -- summary -----------------------------------------------------------

    def get_summary(self) -> Summary:
        """Parse the summary section (via the footer); falls back to a full
        data-section scan for files written without one."""
        if self._summary is not None:
            return self._summary
        f = self._f
        f.seek(0, io.SEEK_END)
        file_end = f.tell()
        footer_start = file_end - len(MAGIC) - (9 + 8 + 8 + 4)
        f.seek(footer_start)
        head = f.read(9)
        summary = Summary()
        if len(head) == 9 and head[0] == OP_FOOTER:
            cur = _Cursor(f.read(20))
            summary_start = cur.u64()
            if f.read(len(MAGIC)) != MAGIC:
                raise McapError("bad trailing magic")
            if summary_start:
                for op, content, _ in self._iter_records(summary_start, file_end):
                    if op == OP_SCHEMA:
                        s = self._parse_schema(content)
                        summary.schemas[s.id] = s
                    elif op == OP_CHANNEL:
                        c = self._parse_channel(content)
                        summary.channels[c.id] = c
                    elif op == OP_CHUNK_INDEX:
                        summary.chunk_indexes.append(self._parse_chunk_index(content))
                    elif op == OP_STATISTICS:
                        summary.statistics = self._parse_statistics(content)
                self._summary = summary
                return summary
        # no summary section: scan the data section
        for op, content, _ in self._iter_records(len(MAGIC)):
            if op == OP_SCHEMA:
                s = self._parse_schema(content)
                summary.schemas[s.id] = s
            elif op == OP_CHANNEL:
                c = self._parse_channel(content)
                summary.channels[c.id] = c
            elif op == OP_CHUNK:
                for iop, icontent in self._iter_chunk_records(content):
                    if iop == OP_SCHEMA:
                        s = self._parse_schema(icontent)
                        summary.schemas[s.id] = s
                    elif iop == OP_CHANNEL:
                        c = self._parse_channel(icontent)
                        summary.channels[c.id] = c
            elif op in (OP_DATA_END, OP_FOOTER):
                break
        self._summary = summary
        return summary

    # -- public iteration --------------------------------------------------

    def iter_metadata(self) -> Iterator[MetadataRecord]:
        for op, content, _ in self._iter_records(len(MAGIC)):
            if op == OP_METADATA:
                cur = _Cursor(content)
                yield MetadataRecord(cur.string(), cur.str_map())
            elif op in (OP_DATA_END, OP_FOOTER):
                return

    def iter_message_times(self, topics: str | list[str] | None = None) -> Iterator[int]:
        """Yield message log_times (file order, payloads discarded as they
        stream) — the memory-safe way to build a timeline."""
        if isinstance(topics, str):
            topics = [topics]
        summary = self.get_summary()
        want = (
            None
            if topics is None
            else {c.id for c in summary.channels.values() if c.topic in topics}
        )
        channels: dict[int, Channel] = dict(summary.channels)
        for op, content, _ in self._iter_records(len(MAGIC)):
            if op == OP_CHANNEL:
                c = self._parse_channel(content)
                channels[c.id] = c
            elif op == OP_MESSAGE:
                m = self._parse_message(content)
                if want is None or m.channel_id in want:
                    yield m.log_time
            elif op == OP_CHUNK:
                for iop, icontent in self._iter_chunk_records(content):
                    if iop == OP_CHANNEL:
                        c = self._parse_channel(icontent)
                        channels[c.id] = c
                    elif iop == OP_MESSAGE:
                        cur = _Cursor(icontent)
                        cid = cur.u16()
                        if want is None or cid in want:
                            cur.u32()  # sequence
                            yield cur.u64()  # log_time (payload never sliced)
            elif op in (OP_DATA_END, OP_FOOTER):
                return

    def iter_messages(
        self,
        topics: str | list[str] | None = None,
        start_time: int | None = None,
        end_time: int | None = None,
        *,
        log_time_order: bool = True,
        reverse: bool = False,
    ) -> Iterator[tuple[Schema | None, Channel, Message]]:
        """Yield ``(schema, channel, message)`` with ``start_time <= log_time <
        end_time`` on the given topic(s). Chunk indexes (when present) are
        used to skip chunks entirely outside the window."""
        if isinstance(topics, str):
            topics = [topics]
        summary = self.get_summary()
        want = (
            None
            if topics is None
            else {c.id for c in summary.channels.values() if c.topic in topics}
        )

        skip_ranges: list[tuple[int, int]] = []
        if summary.chunk_indexes and (start_time is not None or end_time is not None):
            for ci in summary.chunk_indexes:
                if (end_time is not None and ci.message_start_time >= end_time) or (
                    start_time is not None and ci.message_end_time < start_time
                ):
                    skip_ranges.append((ci.chunk_start_offset, ci.chunk_length))
        skip = {off for off, _ in skip_ranges}

        channels: dict[int, Channel] = dict(summary.channels)
        schemas: dict[int, Schema] = dict(summary.schemas)
        out: list[Message] = []

        def consider(m: Message) -> None:
            if want is not None and m.channel_id not in want:
                return
            if start_time is not None and m.log_time < start_time:
                return
            if end_time is not None and m.log_time >= end_time:
                return
            out.append(m)

        for op, content, offset in self._iter_records(len(MAGIC)):
            if op == OP_SCHEMA:
                s = self._parse_schema(content)
                schemas[s.id] = s
            elif op == OP_CHANNEL:
                c = self._parse_channel(content)
                channels[c.id] = c
            elif op == OP_MESSAGE:
                consider(self._parse_message(content))
            elif op == OP_CHUNK:
                if offset in skip:
                    continue
                for iop, icontent in self._iter_chunk_records(content):
                    if iop == OP_SCHEMA:
                        s = self._parse_schema(icontent)
                        schemas[s.id] = s
                    elif iop == OP_CHANNEL:
                        c = self._parse_channel(icontent)
                        channels[c.id] = c
                    elif iop == OP_MESSAGE:
                        consider(self._parse_message(icontent))
            elif op in (OP_DATA_END, OP_FOOTER):
                break

        if log_time_order:
            out.sort(key=lambda m: m.log_time, reverse=reverse)
        elif reverse:
            out.reverse()
        for m in out:
            ch = channels[m.channel_id]
            yield schemas.get(ch.schema_id), ch, m


def make_reader(stream: BinaryIO) -> McapReader:
    return McapReader(stream)


# ---------------------------------------------------------------------------
# writer


class McapWriter:
    """Writes spec-valid MCAP: one chunk per ``flush`` (or unchunked),
    metadata records, and a summary section with chunk indexes/statistics."""

    def __init__(
        self,
        stream: BinaryIO,
        *,
        profile: str = "",
        library: str = "cosmos-curate-tpu",
        compression: str = "zstd",
        chunk_size: int = 4 * 1024 * 1024,
    ) -> None:
        self._f = stream
        self._compression = compression
        self._chunk_size = chunk_size
        self._schemas: dict[int, Schema] = {}
        self._channels: dict[int, Channel] = {}
        self._chunk_buf = bytearray()
        self._chunk_start_time: int | None = None
        self._chunk_end_time: int | None = None
        self._chunk_indexes: list[ChunkIndex] = []
        self._metadata_count = 0
        self._message_count = 0
        self._msg_start: int | None = None
        self._msg_end: int | None = None
        self._channel_counts: dict[int, int] = {}
        self._finished = False
        self._f.write(MAGIC)
        self._record(OP_HEADER, _string(profile) + _string(library))

    def _record(self, op: int, content: bytes) -> None:
        self._f.write(bytes([op]) + _u64(len(content)) + content)

    @staticmethod
    def _encode(op: int, content: bytes) -> bytes:
        return bytes([op]) + _u64(len(content)) + content

    def register_schema(self, name: str, encoding: str, data: bytes) -> int:
        sid = len(self._schemas) + 1
        self._schemas[sid] = Schema(sid, name, encoding, data)
        self._chunk_buf += self._encode(
            OP_SCHEMA, _u16(sid) + _string(name) + _string(encoding) + _u32(len(data)) + data
        )
        return sid

    def register_channel(
        self,
        topic: str,
        message_encoding: str,
        schema_id: int = 0,
        metadata: dict[str, str] | None = None,
    ) -> int:
        cid = len(self._channels)
        self._channels[cid] = Channel(cid, schema_id, topic, message_encoding, metadata or {})
        self._chunk_buf += self._encode(
            OP_CHANNEL,
            _u16(cid)
            + _u16(schema_id)
            + _string(topic)
            + _string(message_encoding)
            + _str_map(metadata or {}),
        )
        return cid

    def add_message(
        self, channel_id: int, log_time: int, data: bytes, *, publish_time: int | None = None,
        sequence: int = 0,
    ) -> None:
        if channel_id not in self._channels:
            raise McapError(f"unknown channel id {channel_id}")
        pub = log_time if publish_time is None else publish_time
        self._chunk_buf += self._encode(
            OP_MESSAGE, _u16(channel_id) + _u32(sequence) + _u64(log_time) + _u64(pub) + data
        )
        self._message_count += 1
        self._channel_counts[channel_id] = self._channel_counts.get(channel_id, 0) + 1
        self._msg_start = log_time if self._msg_start is None else min(self._msg_start, log_time)
        self._msg_end = log_time if self._msg_end is None else max(self._msg_end, log_time)
        if self._chunk_start_time is None or log_time < self._chunk_start_time:
            self._chunk_start_time = log_time
        if self._chunk_end_time is None or log_time > self._chunk_end_time:
            self._chunk_end_time = log_time
        if len(self._chunk_buf) >= self._chunk_size:
            self.flush_chunk()

    def add_metadata(self, name: str, metadata: dict[str, str]) -> None:
        self.flush_chunk()
        self._record(OP_METADATA, _string(name) + _str_map(metadata))
        self._metadata_count += 1

    def flush_chunk(self) -> None:
        if not self._chunk_buf:
            return
        data = bytes(self._chunk_buf)
        self._chunk_buf = bytearray()
        compressed = _compress(self._compression, data)
        start = self._chunk_start_time or 0
        end = self._chunk_end_time or 0
        self._chunk_start_time = self._chunk_end_time = None
        content = (
            _u64(start)
            + _u64(end)
            + _u64(len(data))
            + _u32(zlib.crc32(data))
            + _string(self._compression)
            + _u64(len(compressed))
            + compressed
        )
        chunk_offset = self._f.tell()
        self._record(OP_CHUNK, content)
        chunk_length = self._f.tell() - chunk_offset
        self._chunk_indexes.append(
            ChunkIndex(start, end, chunk_offset, chunk_length, self._compression,
                       len(compressed), len(data))
        )

    def finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        self.flush_chunk()
        self._record(OP_DATA_END, _u32(0))
        summary_start = self._f.tell()
        for s in self._schemas.values():
            self._record(
                OP_SCHEMA,
                _u16(s.id) + _string(s.name) + _string(s.encoding) + _u32(len(s.data)) + s.data,
            )
        for c in self._channels.values():
            self._record(
                OP_CHANNEL,
                _u16(c.id) + _u16(c.schema_id) + _string(c.topic)
                + _string(c.message_encoding) + _str_map(c.metadata),
            )
        for ci in self._chunk_indexes:
            self._record(
                OP_CHUNK_INDEX,
                _u64(ci.message_start_time) + _u64(ci.message_end_time)
                + _u64(ci.chunk_start_offset) + _u64(ci.chunk_length)
                + _u32(0)  # empty message_index_offsets map
                + _u64(0)  # message_index_length
                + _string(ci.compression)
                + _u64(ci.compressed_size) + _u64(ci.uncompressed_size),
            )
        counts = b"".join(_u16(cid) + _u64(n) for cid, n in self._channel_counts.items())
        self._record(
            OP_STATISTICS,
            _u64(self._message_count) + _u16(len(self._schemas)) + _u32(len(self._channels))
            + _u32(0) + _u32(self._metadata_count) + _u32(len(self._chunk_indexes))
            + _u64(self._msg_start or 0) + _u64(self._msg_end or 0)
            + _u32(len(counts)) + counts,
        )
        self._record(OP_FOOTER, _u64(summary_start) + _u64(0) + _u32(0))
        self._f.write(MAGIC)

    def __enter__(self) -> "McapWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.finish()


# ---------------------------------------------------------------------------
# reference-API helpers (cosmos_curate/core/sensors/utils/mcap.py)

VIDEO_METADATA_RECORD_NAME = "cosmos_curate.video_metadata.v1"


def channel_for_topic(summary: Summary, topic: str) -> Channel | None:
    matches = [ch for ch in summary.channels.values() if ch.topic == topic]
    if not matches:
        return None
    if len(matches) != 1:
        raise McapError(f"expected exactly one MCAP channel for topic {topic!r}")
    return matches[0]


def get_metadata_record(reader: McapReader, name: str) -> dict[str, str]:
    matches = [r.metadata for r in reader.iter_metadata() if r.name == name]
    if not matches:
        raise McapError(f"required MCAP metadata record {name!r} not found")
    if len(matches) != 1:
        raise McapError(f"expected exactly one MCAP metadata record {name!r}")
    return matches[0]


def load_timeline(reader: McapReader, topic: str):
    import numpy as np

    # payload-free scan: a multi-GB capture must not be resident just to
    # read its timestamps
    times = sorted(reader.iter_message_times(topics=topic))
    if not times:
        raise McapError(f"no MCAP messages on topic {topic!r}")
    arr = np.array(times, dtype=np.int64)
    arr.flags.writeable = False
    return arr


def load_start_end_ns(reader: McapReader, topic: str) -> tuple[int, int]:
    timeline = load_timeline(reader, topic)
    return int(timeline[0]), int(timeline[-1])
