"""MCAP-backed camera sensor + video→MCAP capture tooling.

Equivalent capability of the reference's McapCameraSensor
(cosmos_curate/core/sensors/sensors/mcap_camera_sensor.py:76-314) and its
capture script (core/sensors/scripts/make_mcap_from_mp4.py), built on the
SDK-free MCAP implementation in sensors/mcap.py. Contract shared with the
reference: raw ``rgb8`` frames on one topic with ``width``/``height`` channel
metadata, nanosecond ``log_time`` timestamps, and a
``cosmos_curate.video_metadata.v1`` metadata record describing the source
video.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from cosmos_curate_tpu.sensors.camera_sensor import CameraData
from cosmos_curate_tpu.sensors.mcap import (
    VIDEO_METADATA_RECORD_NAME,
    McapError,
    McapReader,
    McapWriter,
    channel_for_topic,
    get_metadata_record,
    load_timeline,
    make_reader,
)
from cosmos_curate_tpu.sensors.sampling import NS, SamplingSpec, sample_window_indices

DEFAULT_TOPIC = "/camera/rgb"


def _rgb8_dims(channel) -> tuple[int, int]:
    if channel.message_encoding != "rgb8":
        raise McapError(
            f"expected rgb8 channel, got message_encoding={channel.message_encoding!r}"
        )
    try:
        width = int(channel.metadata["width"])
        height = int(channel.metadata["height"])
    except (KeyError, ValueError) as e:
        raise McapError(
            f"channel metadata must carry integer width/height: {channel.metadata!r}"
        ) from e
    if width <= 0 or height <= 0:
        raise McapError(f"invalid rgb8 dimensions {width}x{height}")
    return width, height


class McapCameraSensor:
    """One camera topic of an MCAP capture, sampled on nanosecond grids.

    Same ``sample(spec) -> CameraData per window`` surface as CameraSensor;
    timestamps come from message ``log_time`` (ns), frames from raw rgb8
    payloads.
    """

    def __init__(self, path: str | Path, topic: str = DEFAULT_TOPIC) -> None:
        self.path = Path(path)
        self.topic = topic
        # seekable file handle, NOT read_bytes: a 10 GB capture must not be
        # resident for the sensor's lifetime
        self._reader = make_reader(open(self.path, "rb"))
        channel = channel_for_topic(self._reader.get_summary(), topic)
        if channel is None:
            raise McapError(f"MCAP file {path} has no channel for topic {topic!r}")
        self._channel = channel
        self.width, self.height = _rgb8_dims(channel)
        self._ts_ns = load_timeline(self._reader, topic)

    def close(self) -> None:
        self._reader._f.close()

    def __enter__(self) -> "McapCameraSensor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def video_metadata(self) -> dict[str, str]:
        return get_metadata_record(self._reader, VIDEO_METADATA_RECORD_NAME)

    @property
    def timestamps_ns(self) -> np.ndarray:
        return self._ts_ns

    @property
    def start_ns(self) -> int:
        return int(self._ts_ns[0])

    @property
    def end_ns(self) -> int:
        return int(self._ts_ns[-1])

    def _frames_for_window(self, start_ns: int, end_ns_exclusive: int) -> tuple[np.ndarray, list[bytes]]:
        times, payloads = [], []
        for _, _, msg in self._reader.iter_messages(
            topics=self.topic, start_time=start_ns, end_time=end_ns_exclusive
        ):
            times.append(msg.log_time)
            payloads.append(msg.data)
        return np.asarray(times, np.int64), payloads

    def sample(self, spec: SamplingSpec):
        """One CameraData per sampling window (empty windows yield empty
        batches), decoding each selected payload once and repeating per
        grid-match counts — the reference sampler's decode-once plan."""
        shape = (self.height, self.width, 3)
        for window in spec.grid:
            if len(window) == 0:
                sel = np.zeros(0, np.int64)
            else:
                idx, counts = sample_window_indices(
                    self._ts_ns, window, policy=spec.policy
                )
                sel = idx
            if len(sel) == 0:
                yield CameraData(
                    align_timestamps_ns=window.timestamps_ns,
                    sensor_timestamps_ns=np.zeros(0, np.int64),
                    frame_indices=np.zeros(0, np.int64),
                    frames=np.zeros((0, 0, 0, 3), np.uint8),
                    camera=self.topic,
                )
                continue
            lo = int(self._ts_ns[sel[0]])
            hi = int(self._ts_ns[sel[-1]]) + 1
            times, payloads = self._frames_for_window(lo, hi)
            # map timeline positions, not log_times: messages sharing one
            # timestamp must keep their distinct payloads (fetch order and
            # the timeline are both stable log_time sorts of file order)
            first_pos = int(np.searchsorted(self._ts_ns, lo, side="left"))
            if len(payloads) != int(np.searchsorted(self._ts_ns, hi, side="left")) - first_pos:
                raise McapError(
                    f"window fetch returned {len(payloads)} frames, timeline expects "
                    f"{int(np.searchsorted(self._ts_ns, hi, side='left')) - first_pos}"
                )
            frames = np.stack(
                [
                    np.frombuffer(payloads[i - first_pos], np.uint8).reshape(shape)
                    for i in sel
                ]
            )
            rep = np.repeat(np.arange(len(sel)), counts)
            yield CameraData(
                align_timestamps_ns=window.timestamps_ns,
                sensor_timestamps_ns=np.repeat(self._ts_ns[sel], counts),
                frame_indices=np.repeat(sel, counts),
                frames=frames[rep],
                camera=self.topic,
            )


def make_mcap_from_video(
    video_path: str | Path,
    mcap_path: str | Path,
    *,
    topic: str = DEFAULT_TOPIC,
    start_ns: int = 0,
    compression: str = "zstd",
    resize_hw: tuple[int, int] | None = None,
) -> int:
    """Convert a video file into the rgb8 MCAP capture contract; returns the
    frame count (reference scripts/make_mcap_from_mp4.py capability)."""
    import cv2

    cap = cv2.VideoCapture(str(video_path))
    if not cap.isOpened():
        raise ValueError(f"cannot open video {video_path}")
    fps = cap.get(cv2.CAP_PROP_FPS) or 24.0
    n = 0
    with open(mcap_path, "wb") as f, McapWriter(f, compression=compression) as w:
        cid = None
        while True:
            ok, frame = cap.read()
            if not ok:
                break
            if resize_hw is not None:
                frame = cv2.resize(frame, (resize_hw[1], resize_hw[0]))
            rgb = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
            if cid is None:
                h, width = rgb.shape[:2]
                cid = w.register_channel(
                    topic, "rgb8", metadata={"width": str(width), "height": str(h)}
                )
            log_time = start_ns + round(n / fps * NS)
            w.add_message(cid, log_time, rgb.tobytes())
            n += 1
        cap.release()
        if cid is None:
            raise ValueError(f"video {video_path} has no frames")
        w.add_metadata(
            VIDEO_METADATA_RECORD_NAME,
            {
                "source": str(video_path),
                "fps": f"{fps:.6f}",
                "num_frames": str(n),
                "width": str(rgb.shape[1]),
                "height": str(rgb.shape[0]),
            },
        )
    return n
