"""Image sensor: sampled access over timestamped still images.

Equivalent capability of the reference's ImageSensor
(cosmos_curate/core/sensors/sensors/image_sensor.py:51-160): a directory (or
explicit list) of image files with per-image timestamps, exposing the same
``start_ns``/``end_ns``/``sample(spec)`` surface as CameraSensor so it
drops into a SensorGroup. Timestamps come from an explicit list or are
parsed from filenames (``<anything>_<ns>.<ext>`` or a bare integer stem).
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path
from typing import Generator, Sequence

import numpy as np

from cosmos_curate_tpu.sensors.sampling import SamplingSpec, sample_window_indices
from cosmos_curate_tpu.sensors.validation import require_strictly_increasing

_IMAGE_SUFFIXES = (".jpg", ".jpeg", ".png", ".webp", ".bmp")


def timestamp_from_name(path: Path) -> int:
    """``frame_0001700000000.jpg`` / ``1700000000.png`` -> ns int."""
    stem = path.stem
    tail = stem.rsplit("_", 1)[-1]
    if not tail.isdigit():
        raise ValueError(f"cannot parse a timestamp from image name {path.name!r}")
    return int(tail)


@dataclass
class ImageData:
    """One sampling window's worth of images."""

    align_timestamps_ns: np.ndarray
    sensor_timestamps_ns: np.ndarray
    paths: list[str]
    frames: np.ndarray  # uint8 [N, H, W, 3] RGB

    def __len__(self) -> int:
        return len(self.sensor_timestamps_ns)


class ImageSensor:
    def __init__(
        self,
        paths: Sequence[str | Path],
        timestamps_ns: Sequence[int] | None = None,
        *,
        resize_hw: tuple[int, int] | None = None,
    ) -> None:
        if not paths:
            raise ValueError("image sensor needs at least one image")
        if timestamps_ns is None:
            timestamps_ns = [timestamp_from_name(Path(p)) for p in paths]
        if len(timestamps_ns) != len(paths):
            raise ValueError(
                f"{len(timestamps_ns)} timestamps for {len(paths)} images"
            )
        order = np.argsort(np.asarray(timestamps_ns, np.int64), kind="stable")
        self._paths = [str(paths[i]) for i in order]
        self._ts_ns = np.asarray(timestamps_ns, np.int64)[order]
        require_strictly_increasing("image timestamps", self._ts_ns)
        self.resize_hw = resize_hw

    @classmethod
    def from_dir(cls, directory: str | Path, **kw) -> "ImageSensor":
        paths = sorted(
            p for p in Path(directory).iterdir() if p.suffix.lower() in _IMAGE_SUFFIXES
        )
        return cls(paths, **kw)

    @property
    def timestamps_ns(self) -> np.ndarray:
        return self._ts_ns

    @property
    def start_ns(self) -> int:
        return int(self._ts_ns[0])

    @property
    def end_ns(self) -> int:
        return int(self._ts_ns[-1])

    def _load(self, idx: int) -> np.ndarray:
        import cv2

        img = cv2.imread(self._paths[idx], cv2.IMREAD_COLOR)
        if img is None:
            raise FileNotFoundError(f"unreadable image {self._paths[idx]}")
        if self.resize_hw is not None:
            h, w = self.resize_hw
            img = cv2.resize(img, (w, h), interpolation=cv2.INTER_AREA)
        return cv2.cvtColor(img, cv2.COLOR_BGR2RGB)

    def sample(self, spec: SamplingSpec) -> Generator[ImageData, None, None]:
        """One ImageData per window; each selected image is loaded once and
        repeated per its grid-match count (CameraSensor semantics)."""
        for window in spec.grid:
            idx, counts = sample_window_indices(self._ts_ns, window, policy=spec.policy)
            if len(idx) == 0:
                yield ImageData(
                    align_timestamps_ns=window.timestamps_ns,
                    sensor_timestamps_ns=np.zeros(0, np.int64),
                    paths=[],
                    frames=np.zeros((0, 0, 0, 3), np.uint8),
                )
                continue
            unique = np.stack([self._load(int(i)) for i in idx])
            rep = np.repeat(np.arange(len(idx)), counts)
            yield ImageData(
                align_timestamps_ns=window.timestamps_ns,
                sensor_timestamps_ns=np.repeat(self._ts_ns[idx], counts),
                paths=[self._paths[int(idx[j])] for j in rep],
                frames=unique[rep],
            )
