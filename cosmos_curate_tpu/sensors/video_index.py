"""Video frame index: per-frame timestamps for sensor construction.

Equivalent capability of the reference's video index utils
(cosmos_curate/core/sensors/utils/video.py — decode-plan/time-base mapping
used by camera sensors): derive a nanosecond timestamp per frame of an mp4
so a bare video becomes a CameraSensor without a sidecar log. cv2 exposes
no reliable per-packet PTS, so the index is constant-frame-rate (fps from
the container), anchored at a caller-supplied capture start time — exact
for the CFR captures AV rigs produce.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from cosmos_curate_tpu.sensors.sampling import NS


@dataclass(frozen=True)
class VideoIndex:
    path: str
    fps: float
    frame_count: int
    timestamps_ns: np.ndarray  # int64 [frame_count], anchored at t0_ns

    @property
    def duration_s(self) -> float:
        return self.frame_count / self.fps if self.fps > 0 else 0.0


def index_video(path: str, *, t0_ns: int = 0) -> VideoIndex:
    # exact per-frame PTS from the container's sample tables when the file
    # is ISO-BMFF (correct for VFR too); constant-rate fallback otherwise
    from cosmos_curate_tpu.video.mp4_index import Mp4ParseError, parse_mp4_video_index

    try:
        idx = parse_mp4_video_index(path)
    except (Mp4ParseError, OSError):
        idx = None
    if idx is not None and idx.frame_count > 0:
        ts = t0_ns + np.round(idx.pts_s * NS).astype(np.int64)
        fps = idx.frame_count / idx.duration_s if idx.duration_s > 0 else 0.0
        return VideoIndex(
            path=path, fps=float(fps), frame_count=idx.frame_count, timestamps_ns=ts
        )
    import cv2

    cap = cv2.VideoCapture(path)
    try:
        if not cap.isOpened():
            raise FileNotFoundError(f"unreadable video {path}")
        fps = cap.get(cv2.CAP_PROP_FPS) or 0.0
        count = int(cap.get(cv2.CAP_PROP_FRAME_COUNT) or 0)
    finally:
        cap.release()
    if fps <= 0 or count <= 0:
        raise ValueError(f"video {path} has no usable fps/frame count ({fps}, {count})")
    ts = t0_ns + (np.arange(count, dtype=np.int64) * round(NS / fps)).astype(np.int64)
    return VideoIndex(path=path, fps=float(fps), frame_count=count, timestamps_ns=ts)


def camera_frame_refs(camera: str, path: str, *, t0_ns: int = 0) -> list:
    """CameraFrameRef list for a bare mp4 — feed straight to CameraSensor."""
    from cosmos_curate_tpu.sensors.data import CameraFrameRef

    index = index_video(path, t0_ns=t0_ns)
    return [
        CameraFrameRef(
            camera=camera,
            video_path=path,
            frame_index=i,
            timestamp_s=float(index.timestamps_ns[i]) / NS,
        )
        for i in range(index.frame_count)
    ]
