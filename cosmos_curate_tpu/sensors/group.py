"""Sensor group: lockstep aligned sampling across named sensors.

Equivalent capability of the reference's SensorGroup
(cosmos_curate/core/sensors/sensors/group.py:48-125): a named collection of
sensors (cameras, image sensors, signal sensors) driven through one
``sample(spec)`` entry point — all sensor generators advance in lockstep,
one step per grid window, yielding a per-window frame whose ``sensor_data``
mapping includes only sensors with data for that window. A sensor with no
coverage for a window is simply absent; a window nobody covers yields an
empty mapping (callers decide whether that's an error).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Generator, Protocol, runtime_checkable

import numpy as np

from cosmos_curate_tpu.sensors.sampling import SamplingSpec


@runtime_checkable
class Sensor(Protocol):
    """Anything samplable on a nanosecond grid (CameraSensor, ImageSensor,
    SignalSensor, MCAP variants)."""

    @property
    def start_ns(self) -> int: ...

    @property
    def end_ns(self) -> int: ...

    def sample(self, spec: SamplingSpec) -> Generator: ...


@dataclass
class GroupFrame:
    """One grid window's aligned snapshot across the group."""

    align_timestamps_ns: np.ndarray
    sensor_data: dict[str, object] = field(default_factory=dict)


class SensorGroup:
    def __init__(self, sensors: dict[str, Sensor]) -> None:
        if not sensors:
            raise ValueError("sensors must be non-empty")
        self._sensors = dict(sensors)

    @property
    def sensors(self) -> dict[str, Sensor]:
        return dict(self._sensors)

    @property
    def start_ns(self) -> int:
        return min(s.start_ns for s in self._sensors.values())

    @property
    def end_ns(self) -> int:
        return max(s.end_ns for s in self._sensors.values())

    def sample(self, spec: SamplingSpec) -> Generator[GroupFrame, None, None]:
        """One GroupFrame per window in ``spec.grid``; every sensor receives
        the same spec (including its policy — tolerance violations propagate
        unchanged from whichever sensor raises)."""
        generators = {name: s.sample(spec) for name, s in self._sensors.items()}
        for window in spec.grid:
            data: dict[str, object] = {}
            for name, gen in generators.items():
                batch = next(gen)
                if len(batch) > 0:
                    data[name] = batch
            yield GroupFrame(align_timestamps_ns=window.timestamps_ns, sensor_data=data)
