"""Array validation helpers for sensor data.

Equivalent capability of the reference's validation utils
(cosmos_curate/core/sensors/utils/validation.py:29-113): fail-loud dtype /
shape / monotonicity / finiteness checks applied at sensor-construction
time, so malformed capture data surfaces as a clear ValueError at load —
not as a silent misalignment three stages later.
"""

from __future__ import annotations

import numpy as np


def require_1d(name: str, values: np.ndarray, dtype: type | None = None) -> np.ndarray:
    arr = np.asarray(values)
    if arr.ndim != 1:
        raise ValueError(f"{name} must be 1-D, got shape {arr.shape}")
    if dtype is not None and arr.dtype != np.dtype(dtype):
        raise ValueError(f"{name} must have dtype {np.dtype(dtype)}, got {arr.dtype}")
    return arr


def require_finite(name: str, values: np.ndarray) -> np.ndarray:
    arr = np.asarray(values)
    if not np.isfinite(arr).all():
        bad = int(np.size(arr) - np.isfinite(arr).sum())
        raise ValueError(f"{name} contains {bad} non-finite values")
    return arr


def require_strictly_increasing(name: str, values: np.ndarray) -> np.ndarray:
    arr = np.asarray(values)
    if len(arr) > 1 and not (np.diff(arr) > 0).all():
        i = int(np.argmin(np.diff(arr)))
        raise ValueError(
            f"{name} must be strictly increasing; violation at index {i}: "
            f"{arr[i]} -> {arr[i + 1]}"
        )
    return arr


def require_nondecreasing(name: str, values: np.ndarray) -> np.ndarray:
    arr = np.asarray(values)
    if len(arr) > 1 and not (np.diff(arr) >= 0).all():
        i = int(np.argmin(np.diff(arr)))
        raise ValueError(
            f"{name} must be non-decreasing; violation at index {i}: "
            f"{arr[i]} -> {arr[i + 1]}"
        )
    return arr


def strictly_increasing_int64(name: str, values) -> np.ndarray:
    """Canonical timestamp-array constructor: 1-D int64, strictly increasing."""
    arr = np.asarray(values, np.int64)
    require_1d(name, arr, np.int64)
    require_strictly_increasing(name, arr)
    return arr
