"""Multi-sensor temporal alignment and sampling.

Equivalent capability of the reference's sensor sampling/alignment layer
(cosmos_curate/core/sensors/sampling/ — grid/policy/sampler/spec; aligned
frame assembly). Alignment is nearest-timestamp within a tolerance; the
sampling grid picks target times at a fixed rate over the overlapping span
of all requested cameras.
"""

from __future__ import annotations

import bisect
from typing import Sequence

from cosmos_curate_tpu.sensors.data import AlignedFrame, SensorSession


def nearest(sorted_ts: Sequence[float], target: float) -> int:
    """Index of the element of ``sorted_ts`` closest to ``target``."""
    i = bisect.bisect_left(sorted_ts, target)
    if i == 0:
        return 0
    if i >= len(sorted_ts):
        return len(sorted_ts) - 1
    return i if sorted_ts[i] - target < target - sorted_ts[i - 1] else i - 1


def sampling_grid(session: SensorSession, *, rate_hz: float, cameras: list[str] | None = None):
    """Target timestamps at ``rate_hz`` over the span covered by ALL cameras."""
    cams = cameras or sorted(session.cameras)
    if not cams or any(not session.cameras.get(c) for c in cams):
        return []
    start = max(session.cameras[c][0].timestamp_s for c in cams)
    end = min(session.cameras[c][-1].timestamp_s for c in cams)
    if end < start or rate_hz <= 0:
        return []
    step = 1.0 / rate_hz
    out = []
    t = start
    while t <= end + 1e-9:
        out.append(round(t, 9))
        t += step
    return out


def align(
    session: SensorSession,
    *,
    rate_hz: float = 2.0,
    cameras: list[str] | None = None,
    tolerance_s: float = 0.1,
) -> list[AlignedFrame]:
    """Assemble aligned multi-camera (+gps/imu) frames on the sampling grid;
    grid points where any camera misses the tolerance are dropped."""
    cams = cameras or sorted(session.cameras)
    if any(not session.cameras.get(c) for c in cams):
        return []  # a requested camera has no frames (matches sampling_grid)
    cam_ts = {c: [f.timestamp_s for f in session.cameras[c]] for c in cams}
    gps_ts = [g.timestamp_s for g in session.gps]
    imu_ts = [s.timestamp_s for s in session.imu]
    frames: list[AlignedFrame] = []
    for t in sampling_grid(session, rate_hz=rate_hz, cameras=cams):
        aligned = AlignedFrame(timestamp_s=t)
        ok = True
        for c in cams:
            idx = nearest(cam_ts[c], t)
            ref = session.cameras[c][idx]
            if abs(ref.timestamp_s - t) > tolerance_s:
                ok = False
                break
            aligned.cameras[c] = ref
        if not ok:
            continue
        if gps_ts:
            g = session.gps[nearest(gps_ts, t)]
            if abs(g.timestamp_s - t) <= tolerance_s:
                aligned.gps = g
        if imu_ts:
            s = session.imu[nearest(imu_ts, t)]
            if abs(s.timestamp_s - t) <= tolerance_s:
                aligned.imu = s
        frames.append(aligned)
    return frames
