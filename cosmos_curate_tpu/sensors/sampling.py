"""Timestamp sampling: ns grids, windows, policies.

Equivalent capability of the reference's sampling layer
(cosmos_curate/core/sensors/sampling/{grid,policy,spec,sampler}.py): build a
strictly-ascending int64 nanosecond grid at a sample rate, iterate it as
half-open windows, and match each grid point to the nearest canonical
sensor timestamp under a tolerance policy. Own implementation of the same
contracts (half-open ``[start, exclusive_end)`` windows; the grid always
includes ``start_ns``; an inclusive ``end_ns`` stays reachable by retaining
one sample past it as the exclusive boundary marker).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Iterator

import numpy as np

NS = 1_000_000_000


def make_ts_grid(
    start_ns: int,
    end_ns: int | None = None,
    sample_rate_hz: float | None = None,
    *,
    exclusive_end_ns: int | None = None,
) -> tuple[int, int, np.ndarray]:
    """-> (start_ns, exclusive_end_ns, timestamps_ns[int64, read-only]).

    Exactly one of ``end_ns`` (inclusive) / ``exclusive_end_ns`` (half-open)
    must be given; see module docstring for the boundary semantics."""
    if sample_rate_hz is None or sample_rate_hz <= 0:
        raise ValueError(f"sample_rate_hz must be > 0, got {sample_rate_hz}")
    if (end_ns is None) == (exclusive_end_ns is None):
        raise ValueError("exactly one of end_ns / exclusive_end_ns required")
    if exclusive_end_ns is not None:
        if exclusive_end_ns <= start_ns:
            raise ValueError(f"exclusive_end_ns {exclusive_end_ns} <= start_ns {start_ns}")
        bound_ns = exclusive_end_ns - 1
    else:
        if end_ns < start_ns:
            raise ValueError(f"end_ns {end_ns} < start_ns {start_ns}")
        bound_ns = end_ns

    step_s = 1.0 / sample_rate_hz
    span_steps = (bound_ns - start_ns) / NS / step_s
    # enough samples that the last one lands strictly past the bound (it
    # becomes the exclusive-end marker), robust to float roundoff at exact
    # multiples
    n = max(2, math.floor(np.nextafter(span_steps, np.inf)) + 2)
    ts = np.round((start_ns / NS + np.arange(n) * step_s) * NS).astype(np.int64)
    if np.any(np.diff(ts) <= 0):
        raise ValueError(
            f"sample_rate_hz={sample_rate_hz} rounds to a non-increasing ns grid"
        )
    grid = ts[:-1]
    grid.flags.writeable = False
    out_excl = exclusive_end_ns if exclusive_end_ns is not None else int(ts[-1])
    return int(ts[0]), out_excl, grid


@dataclass(frozen=True)
class SamplingWindow:
    """One half-open batch of grid timestamps: every reference timestamp
    strictly below ``exclusive_end_ns`` belongs to this window."""

    timestamps_ns: np.ndarray
    exclusive_end_ns: int

    def __len__(self) -> int:
        return len(self.timestamps_ns)


@dataclass(frozen=True)
class SamplingGrid:
    """A ts grid chunked into fixed-size windows for batched decoding."""

    start_ns: int
    exclusive_end_ns: int
    timestamps_ns: np.ndarray
    window_size: int = 64

    @classmethod
    def from_rate(
        cls,
        start_ns: int,
        *,
        sample_rate_hz: float,
        end_ns: int | None = None,
        exclusive_end_ns: int | None = None,
        window_size: int = 64,
    ) -> "SamplingGrid":
        s, e, ts = make_ts_grid(
            start_ns, end_ns, sample_rate_hz, exclusive_end_ns=exclusive_end_ns
        )
        return cls(s, e, ts, window_size)

    def __iter__(self) -> Iterator[SamplingWindow]:
        n = len(self.timestamps_ns)
        for i in range(0, max(n, 1), self.window_size):
            chunk = self.timestamps_ns[i : i + self.window_size]
            if i + self.window_size >= n:
                end = self.exclusive_end_ns
            else:
                end = int(self.timestamps_ns[i + self.window_size])
            yield SamplingWindow(chunk, end)

    def __len__(self) -> int:
        n = len(self.timestamps_ns)
        return max(1, -(-n // self.window_size))


@dataclass(frozen=True)
class SamplingPolicy:
    """tolerance_ns = max |grid point - chosen canonical timestamp|; grid
    points with no canonical sample inside the tolerance are dropped."""

    tolerance_ns: int = 0

    def __post_init__(self) -> None:
        if self.tolerance_ns < 0:
            raise ValueError("tolerance_ns must be >= 0")


@dataclass(frozen=True)
class SamplingSpec:
    grid: SamplingGrid
    policy: SamplingPolicy | None = None


def find_closest_indices(canonical: np.ndarray, grid: np.ndarray) -> np.ndarray:
    """For each grid timestamp, the index of the nearest canonical
    timestamp (canonical must be sorted ascending)."""
    canonical = np.asarray(canonical, np.int64)
    grid = np.asarray(grid, np.int64)
    pos = np.searchsorted(canonical, grid)
    pos = np.clip(pos, 1, len(canonical) - 1) if len(canonical) > 1 else np.zeros_like(pos)
    left = canonical[pos - 1] if len(canonical) > 1 else canonical[pos]
    right = canonical[pos]
    choose_left = (grid - left) <= (right - grid)
    return np.where(choose_left, pos - 1, pos) if len(canonical) > 1 else pos


def sample_window_indices(
    canonical: np.ndarray,
    window: SamplingWindow,
    *,
    policy: SamplingPolicy | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """-> (unique canonical indices, per-index repeat counts) for a window.

    A canonical frame matched by several grid points is decoded once and
    repeated (counts), matching the reference sampler's decode-once plan
    (sampling/sampler.py:75)."""
    if len(window) == 0 or len(canonical) == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    idx = find_closest_indices(canonical, window.timestamps_ns)
    if policy is not None:
        delta = np.abs(np.asarray(canonical, np.int64)[idx] - window.timestamps_ns)
        idx = idx[delta <= policy.tolerance_ns]  # 0 = exact matches only
    if len(idx) == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    uniq, counts = np.unique(idx, return_counts=True)
    return uniq.astype(np.int64), counts.astype(np.int64)
