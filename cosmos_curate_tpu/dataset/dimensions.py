"""Dataset dimension bucketing.

Equivalent capability of the reference's dimensions module
(cosmos_curate/core/utils/dataset/dimensions.py — 514 LoC bucketing by
resolution / aspect ratio / frame window for webdataset sharding). Clips are
grouped into buckets so every sample in a shard has compatible tensor
shapes for training.
"""

from __future__ import annotations

from dataclasses import dataclass

_ASPECT_BUCKETS: list[tuple[str, float]] = [
    ("16-9", 16 / 9),
    ("4-3", 4 / 3),
    ("1-1", 1.0),
    ("3-4", 3 / 4),
    ("9-16", 9 / 16),
]

_RES_BUCKETS: list[tuple[str, int]] = [  # by min(height, width)
    ("2160p", 2160),
    ("1080p", 1080),
    ("720p", 720),
    ("480p", 480),
    ("360p", 360),
    ("0p", 0),
]

_FRAME_WINDOWS: list[int] = [256, 128, 64, 32, 16, 0]


@dataclass(frozen=True)
class DimensionBucket:
    aspect: str
    resolution: str
    frame_window: int

    @property
    def key(self) -> str:
        return f"{self.aspect}_{self.resolution}_w{self.frame_window}"


def bucket_for(width: int, height: int, num_frames: int) -> DimensionBucket:
    if width <= 0 or height <= 0:
        return DimensionBucket("1-1", "0p", 0)
    ratio = width / height
    aspect = min(_ASPECT_BUCKETS, key=lambda b: abs(b[1] - ratio))[0]
    short = min(width, height)
    resolution = next(name for name, px in _RES_BUCKETS if short >= px)
    window = next(w for w in _FRAME_WINDOWS if num_frames >= w)
    return DimensionBucket(aspect, resolution, window)
