"""Dataset dimension bucketing and path codecs.

Equivalent capability of the reference's dimensions module
(cosmos_curate/core/utils/dataset/dimensions.py — even-rounded resize math,
aspect/resolution/duration range bins with contiguity validation, and
bucket <-> dataset-path string codecs used to lay out webdataset shards).
Own design: one generic contiguous ``RangeBins`` primitive instead of three
hand-rolled bin-spec classes, and a dataclass bucket whose ``key``/``path``
round-trip through a single regex.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Generic, Sequence, TypeVar

T = TypeVar("T")


def round_to_even(n: float) -> int:
    """Nearest even integer (video codecs require even dimensions). Ties
    round UP, matching the reference (_round_to_nearest_even keeps the floor
    only when strictly closer)."""
    base = int(n) // 2 * 2
    return base if n - base < (base + 2) - n else base + 2


@dataclass(frozen=True)
class Dimensions:
    """Width/height pair with the resize math model stages share."""

    width: int
    height: int

    @property
    def w_by_h(self) -> float:
        return self.width / self.height

    def resize_by_shortest_side(self, short: int) -> "Dimensions":
        """Scale so min(w, h) == short, the long side rounded to even."""
        if short % 2:
            raise ValueError(f"target short side must be even, got {short}")
        if self.height <= self.width:
            return Dimensions(round_to_even(short / self.height * self.width), short)
        return Dimensions(short, round_to_even(short / self.width * self.height))


class RangeBins(Generic[T]):
    """Contiguous half-open value ranges mapping to labels.

    The single primitive behind aspect-ratio, resolution, and duration
    binning; construction validates contiguity so dataset layouts can't
    silently develop gaps. ``closed="right"`` means ``(lo, hi]`` (the
    reference's aspect-bin convention); ``closed="left"`` means
    ``[lo, hi)`` (floor semantics — a 400px-short video is 360p-class)."""

    def __init__(self, edges: Sequence[float], labels: Sequence[T], *, closed: str = "right"):
        if len(edges) != len(labels) + 1:
            raise ValueError(f"{len(labels)} bins need {len(labels) + 1} edges")
        for a, b in zip(edges, edges[1:]):
            if not a < b:
                raise ValueError(f"bin edges must increase: {a} !< {b}")
        if closed not in ("left", "right"):
            raise ValueError(f"closed must be 'left' or 'right', got {closed!r}")
        self.edges = list(edges)
        self.labels = list(labels)
        self.closed = closed

    def find(self, value: float) -> T | None:
        for lo, hi, label in zip(self.edges, self.edges[1:], self.labels):
            hit = lo <= value < hi if self.closed == "left" else lo < value <= hi
            if hit:
                return label
        return None


# Standard bins: the dataset layouts the reference's standard image/video
# datasets use (dimensions.py:212-318,390-470).
ASPECT_BINS: RangeBins[tuple[int, int]] = RangeBins(
    [0.0, 0.65, 0.88, 1.16, 1.55, 10.0],
    [(9, 16), (3, 4), (1, 1), (4, 3), (16, 9)],
)
RESOLUTION_BINS: RangeBins[str] = RangeBins(
    [0, 360, 480, 720, 1080, 2160, float("inf")],
    ["0p", "360p", "480p", "720p", "1080p", "2160p"],
    closed="left",
)
DURATION_BINS: RangeBins[str] = RangeBins(
    [0.0, 2.0, 5.0, 10.0, 30.0, 60.0, float("inf")],
    ["0-2s", "2-5s", "5-10s", "10-30s", "30-60s", "60s-"],
)
FRAME_WINDOWS: list[int] = [256, 128, 64, 32, 16, 0]


@dataclass(frozen=True)
class DimensionBucket:
    """One shard-compatible group: aspect x resolution x frame window,
    optionally a duration band."""

    aspect: str  # "16-9"
    resolution: str  # "720p"
    frame_window: int
    duration: str | None = None

    @property
    def key(self) -> str:
        base = f"{self.aspect}_{self.resolution}_w{self.frame_window}"
        return f"{base}_d{self.duration}" if self.duration else base

    # -- dataset path codec (reference to_path_string/from_path_string) ---
    @property
    def path(self) -> str:
        parts = [
            f"resolution_{self.resolution}",
            f"aspect_ratio_{self.aspect.replace('-', '_')}",
            f"frames_{self.frame_window}",
        ]
        if self.duration:
            parts.append(f"duration_{self.duration}")
        return "/".join(parts)

    _PATH_RE = re.compile(
        r"resolution_(?P<res>[0-9]+p)/aspect_ratio_(?P<aw>\d+)_(?P<ah>\d+)"
        r"/frames_(?P<fw>\d+)(?:/duration_(?P<dur>[^/]+))?"
    )

    @classmethod
    def from_path(cls, path: str) -> "DimensionBucket":
        m = cls._PATH_RE.search(path)
        if m is None:
            raise ValueError(f"not a dimension path: {path!r}")
        return cls(
            aspect=f"{m.group('aw')}-{m.group('ah')}",
            resolution=m.group("res"),
            frame_window=int(m.group("fw")),
            duration=m.group("dur"),
        )


def bucket_for(
    width: int,
    height: int,
    num_frames: int,
    *,
    duration_s: float | None = None,
) -> DimensionBucket:
    """Classify a clip into its shard bucket. Out-of-range (degenerate)
    inputs land in the smallest bucket rather than raising — a single bad
    probe must not kill a sharding run."""
    if width <= 0 or height <= 0:
        return DimensionBucket("1-1", "0p", 0)
    ar = ASPECT_BINS.find(width / height) or (16, 9)
    aspect = f"{ar[0]}-{ar[1]}"
    resolution = RESOLUTION_BINS.find(min(width, height)) or "0p"
    window = next(w for w in FRAME_WINDOWS if num_frames >= w)
    duration = DURATION_BINS.find(duration_s) if duration_s is not None else None
    return DimensionBucket(aspect, resolution, window, duration)
