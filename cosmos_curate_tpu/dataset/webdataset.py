"""Webdataset tar shard writing.

Equivalent capability of the reference's webdataset utils
(cosmos_curate/core/utils/dataset/webdataset_utils.py): samples are groups
of same-basename files inside sequentially numbered tars
(``<bucket>/shard-00000.tar`` with ``<uuid>.mp4``, ``<uuid>.json``,
``<uuid>.npy`` members), the format the webdataset training loaders read.
Pure stdlib tarfile — no webdataset package needed to *write*.
"""

from __future__ import annotations

import io
import json
import tarfile
import time
from typing import Any, Iterator

import numpy as np

from cosmos_curate_tpu.storage.client import write_bytes


class ShardWriter:
    """Accumulates samples into size-capped tar shards."""

    def __init__(
        self,
        output_prefix: str,
        *,
        max_bytes_per_shard: int = 256 << 20,
        max_samples_per_shard: int = 512,
    ) -> None:
        self.output_prefix = output_prefix.rstrip("/")
        self.max_bytes = max_bytes_per_shard
        self.max_samples = max_samples_per_shard
        self.shard_index = 0
        self.shard_paths: list[str] = []
        self._buf: io.BytesIO | None = None
        self._tar: tarfile.TarFile | None = None
        self._samples = 0

    def _ensure_open(self) -> None:
        if self._tar is None:
            self._buf = io.BytesIO()
            self._tar = tarfile.open(fileobj=self._buf, mode="w")
            self._samples = 0

    def add_sample(self, key: str, parts: dict[str, bytes]) -> None:
        """parts: extension (e.g. "mp4", "json", "npy") -> bytes."""
        self._ensure_open()
        for ext, data in parts.items():
            info = tarfile.TarInfo(name=f"{key}.{ext}")
            info.size = len(data)
            info.mtime = int(time.time())
            self._tar.addfile(info, io.BytesIO(data))
        self._samples += 1
        if self._samples >= self.max_samples or self._buf.tell() >= self.max_bytes:
            self._flush()

    def _flush(self) -> None:
        if self._tar is None or self._samples == 0:
            return
        self._tar.close()
        path = f"{self.output_prefix}/shard-{self.shard_index:05d}.tar"
        write_bytes(path, self._buf.getvalue())
        self.shard_paths.append(path)
        self.shard_index += 1
        self._tar = None
        self._buf = None

    def close(self) -> list[str]:
        self._flush()
        return self.shard_paths


def encode_sample_parts(
    *,
    mp4: bytes | None = None,
    meta: dict[str, Any] | None = None,
    arrays: dict[str, np.ndarray] | None = None,
    text: str | None = None,
) -> dict[str, bytes]:
    parts: dict[str, bytes] = {}
    if mp4 is not None:
        parts["mp4"] = mp4
    if meta is not None:
        parts["json"] = json.dumps(meta).encode()
    if text is not None:
        parts["txt"] = text.encode()
    for name, arr in (arrays or {}).items():
        sink = io.BytesIO()
        np.save(sink, arr)
        parts[f"{name}.npy"] = sink.getvalue()
    return parts


def iter_tar_samples(data: bytes) -> Iterator[tuple[str, dict[str, bytes]]]:
    """Read back samples grouped by basename (for tests/verification)."""
    groups: dict[str, dict[str, bytes]] = {}
    order: list[str] = []
    with tarfile.open(fileobj=io.BytesIO(data)) as tar:
        for member in tar.getmembers():
            key, _, ext = member.name.partition(".")
            if key not in groups:
                groups[key] = {}
                order.append(key)
            groups[key][ext] = tar.extractfile(member).read()
    for key in order:
        yield key, groups[key]
