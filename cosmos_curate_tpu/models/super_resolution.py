"""Video super-resolution model (windowed, overlap-blended).

Equivalent capability of the reference's SeedVR2 integration
(cosmos_curate/models/seedvr2.py:145 + pipelines/video/super_resolution/ —
diffusion SR over 128-frame windows with 64-frame overlap and blending,
sequence parallelism via ``sp_size``). Our own compact Flax model: residual
conv trunk + depth-to-space 2x upsampler, applied window-batched. The
sequence-parallel hook shards the frame axis of a window across the mesh
(``shard_map`` over 'seq') — the TPU translation of the reference's
torch.distributed ``sp_size`` padding (inference_seedvr2_window.py:510-522).
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.models import registry
from cosmos_curate_tpu.parallel import axes
from cosmos_curate_tpu.parallel.mesh import seq_mesh
from cosmos_curate_tpu.parallel.sharding import shard_map


@dataclass(frozen=True)
class SRConfig:
    channels: int = 64
    blocks: int = 6
    scale: int = 2  # depth-to-space factor


SR_BASE = SRConfig()
SR_TINY_TEST = SRConfig(channels=8, blocks=1)

registry.register_model("super-resolution-tpu", "windowed conv video SR (Flax)")


class ResBlock(nn.Module):
    channels: int

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.channels, (3, 3), dtype=jnp.bfloat16, param_dtype=jnp.float32)(x)
        h = nn.relu(h)
        h = nn.Conv(self.channels, (3, 3), dtype=jnp.bfloat16, param_dtype=jnp.float32)(h)
        return x + h


class SRNet(nn.Module):
    cfg: SRConfig

    @nn.compact
    def __call__(self, frames_u8, *, float_out: bool = False):
        """uint8 [T, H, W, 3] -> uint8 [T, H*scale, W*scale, 3].

        ``float_out=True`` returns the pre-quantization float image in
        [0, 1] — required for training (the uint8 cast has zero gradient)."""
        cfg = self.cfg
        x = frames_u8.astype(jnp.bfloat16) / 255.0
        x = nn.Conv(cfg.channels, (3, 3), dtype=jnp.bfloat16, param_dtype=jnp.float32)(x)
        for _ in range(cfg.blocks):
            x = ResBlock(cfg.channels)(x)
        x = nn.Conv(3 * cfg.scale * cfg.scale, (3, 3), dtype=jnp.bfloat16, param_dtype=jnp.float32)(x)
        t, h, w, c = x.shape
        s = cfg.scale
        x = x.reshape(t, h, w, s, s, 3).transpose(0, 1, 3, 2, 4, 5).reshape(t, h * s, w * s, 3)
        # residual bilinear base so random weights still upscale sanely
        base = jax.image.resize(
            frames_u8.astype(jnp.float32) / 255.0, (t, h * s, w * s, 3), "bilinear"
        )
        out = jnp.clip(base + x.astype(jnp.float32), 0.0, 1.0)
        if float_out:
            return out
        return (out * 255.0).astype(jnp.uint8)


class SuperResolutionModel(ModelInterface):
    MODEL_ID = "super-resolution-tpu"

    def __init__(self, cfg: SRConfig = SR_BASE, *, sp_size: int = 1) -> None:
        self.cfg = cfg
        self.sp_size = sp_size  # frames sharded over 'seq' when > 1
        self._apply = None
        self._params = None
        self._pipeline = None

    @property
    def model_id_names(self) -> list[str]:
        return [self.MODEL_ID]

    def setup(self) -> None:
        model = SRNet(self.cfg)

        def init(seed: int):
            return model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 16, 16, 3), jnp.uint8))

        self._params = registry.load_params(self.MODEL_ID, init)
        if self.sp_size > 1:
            from jax.sharding import PartitionSpec as P

            mesh = seq_mesh(self.sp_size)

            def fwd(params, frames):
                return model.apply(params, frames)

            self._apply = jax.jit(
                shard_map(
                    fwd,
                    mesh=mesh,
                    in_specs=(P(), P(axes.SEQ, None, None, None)),
                    out_specs=P(axes.SEQ, None, None, None),
                    check_vma=False,
                )
            )
        else:
            from cosmos_curate_tpu.models.device_pipeline import donate_kwargs

            self._apply = jax.jit(model.apply, **donate_kwargs(1))
        from cosmos_curate_tpu.models.device_pipeline import DevicePipeline

        self._pipeline = DevicePipeline("sr/srnet", self._apply)

    def submit_window(self, frames: np.ndarray) -> None:
        """Queue one window for upscaling; results resolve in submission
        order at drain_windows(). The SR stage submits every window of a
        clip before reading any back, so H2D, compute, and D2H pipeline
        across the window loop."""
        if self._pipeline is None:
            raise RuntimeError("call setup() first")
        t = frames.shape[0]
        if self.sp_size > 1:  # pad frame count to the sp shard multiple
            from cosmos_curate_tpu.models.batching import pad_to

            frames = pad_to(frames, t + (-t) % self.sp_size)
        self._pipeline.submit(self._params, frames, n_valid=t)

    def drain_windows(self) -> list[np.ndarray]:
        return self._pipeline.drain()

    def upscale_window(self, frames: np.ndarray) -> np.ndarray:
        """Synchronous single-window path (tests, ad-hoc callers)."""
        self.submit_window(frames)
        return self.drain_windows()[0]
