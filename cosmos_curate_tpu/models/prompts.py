"""Captioning / filtering prompt variants.

Equivalent capability of the reference's prompt library
(cosmos_curate/models/prompts.py, pipelines/common/filter_prompts.py):
named prompt variants for captioning, refinement, and semantic filtering.
Text is our own.
"""

from __future__ import annotations

CAPTION_PROMPTS: dict[str, str] = {
    "default": (
        "Describe this video clip in detail: the subjects, their actions, "
        "the setting, camera motion, and lighting."
    ),
    "av": (
        "Describe this driving scene: road layout, vehicles, pedestrians, "
        "traffic signals, weather, and the ego vehicle's maneuver."
    ),
    "short": "Write a one-sentence description of this video clip.",
    "factual": (
        "List only directly observable facts about this video clip, "
        "without speculation."
    ),
}

REFINEMENT_PROMPT = (
    "Rewrite the following video description to be clearer and more "
    "specific, keeping every stated fact: "
)

ENHANCE_PROMPT = (
    "Improve this caption's fluency and detail without inventing facts: "
)

SEMANTIC_FILTER_PROMPTS: dict[str, str] = {
    "default": (
        "Does this video clip contain clear, well-lit, non-synthetic "
        "real-world footage? Answer yes or no."
    ),
    "overlay-text": (
        "Does this video clip contain burned-in overlay text, subtitles, "
        "or watermarks? Answer yes or no."
    ),
    "image-default": (
        "Is this a clear, well-lit, non-synthetic real-world photograph? "
        "Answer yes or no."
    ),
}


def get_caption_prompt(variant: str) -> str:
    try:
        return CAPTION_PROMPTS[variant]
    except KeyError:
        raise KeyError(
            f"unknown caption prompt variant {variant!r}; have {sorted(CAPTION_PROMPTS)}"
        ) from None
