"""CLIP text tower: causal transformer + EOT pooling + projection.

Equivalent capability of the reference's CLIP text encoding path
(cosmos_curate/models/clip.py drives HF transformers CLIP; the text tower
embeds queries/prompts into the shared image-text space). Our own Flax
implementation over the shared ``TransformerBlock``; weights convert from
HF ``CLIPTextModelWithProjection`` via ``models/convert_hf.convert_clip_text``
with an exact parity test (tests/models/test_convert_hf.py).

TPU-first: token + position embedding and the causal stack run in one jit;
batches pad to power-of-two lengths (static shapes).
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.models import registry
from cosmos_curate_tpu.models.layers import TransformerBlock, dense


@dataclass(frozen=True)
class CLIPTextConfig:
    vocab: int = 49408
    width: int = 512
    layers: int = 12
    heads: int = 8
    max_len: int = 77
    projection_dim: int = 512
    act: str = "quick_gelu"
    ln_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.width // self.heads


CLIP_TEXT_B = CLIPTextConfig()
CLIP_TEXT_L = CLIPTextConfig(width=768, layers=12, heads=12, projection_dim=768)
CLIP_TEXT_TINY_TEST = CLIPTextConfig(
    vocab=64, width=32, layers=2, heads=2, max_len=16, projection_dim=16
)


class CLIPTextEncoder(nn.Module):
    """ids [B, T] -> (pooled [B, P], tokens [B, T, W]).

    Pooling follows CLIP: the feature at the EOT position, taken as
    ``ids.argmax(-1)`` — the EOT token has the highest id in CLIP's BPE
    vocab, so callers must append it (HF uses the same argmax rule)."""

    cfg: CLIPTextConfig
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, ids):
        cfg = self.cfg
        x = nn.Embed(
            cfg.vocab, cfg.width, param_dtype=jnp.float32, dtype=self.dtype, name="tok_embed"
        )(ids)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.01), (1, cfg.max_len, cfg.width), jnp.float32
        )
        x = x + pos[:, : ids.shape[1]].astype(self.dtype)
        for i in range(cfg.layers):
            x = TransformerBlock(
                cfg.heads,
                cfg.head_dim,
                dtype=self.dtype,
                causal=True,
                act=cfg.act,
                ln_eps=cfg.ln_eps,
                name=f"block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, epsilon=cfg.ln_eps, name="ln_final")(x)
        eot = jnp.argmax(ids, axis=-1)
        pooled = jnp.take_along_axis(x, eot[:, None, None], axis=1)[:, 0]
        pooled = dense(
            cfg.projection_dim, None, name="proj", use_bias=False, dtype=self.dtype
        )(pooled)
        return pooled.astype(jnp.float32), x


class CLIPTextEmbeddings(ModelInterface):
    """Batched token ids -> L2-normalized text embeddings."""

    _CONFIGS = {
        "clip-text-b-tpu": CLIP_TEXT_B,
        "clip-text-l-tpu": CLIP_TEXT_L,
        "clip-text-tiny-test": CLIP_TEXT_TINY_TEST,
    }

    def __init__(self, variant: str = "clip-text-b-tpu") -> None:
        if variant not in self._CONFIGS:
            raise ValueError(f"unknown variant {variant!r}; have {sorted(self._CONFIGS)}")
        self.variant = variant
        self.cfg = self._CONFIGS[variant]
        self._apply = None
        self._params = None
        self._pipeline = None

    @property
    def model_id_names(self) -> list[str]:
        return [self.variant]

    @property
    def embedding_dim(self) -> int:
        return self.cfg.projection_dim

    def setup(self) -> None:
        model = CLIPTextEncoder(self.cfg)

        def init(seed: int):
            return model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 4), jnp.int32))

        self._params = registry.load_params(self.variant, init)

        from cosmos_curate_tpu.models.device_pipeline import DevicePipeline, donate_kwargs

        def embed(params, ids):
            pooled, _ = model.apply(params, ids)
            return pooled / jnp.linalg.norm(pooled, axis=-1, keepdims=True)

        self._apply = jax.jit(embed, **donate_kwargs(1))
        self._pipeline = DevicePipeline(f"clip-text/{self.variant}", self._apply)

    def encode_ids(self, ids: np.ndarray) -> np.ndarray:
        """int32 [N, T] (EOT appended, pad after) -> float32 [N, P].
        Dispatched through the shared DevicePipeline."""
        if self._pipeline is None:
            raise RuntimeError("call setup() first")
        return self._pipeline.run(self._params, np.asarray(ids, np.int32))


registry.register_model("clip-text-b-tpu", "CLIP text tower, ViT-B width (Flax)")
registry.register_model("clip-text-l-tpu", "CLIP text tower, ViT-L width (Flax)")
