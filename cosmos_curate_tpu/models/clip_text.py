"""CLIP text tower: causal transformer + EOT pooling + projection.

Equivalent capability of the reference's CLIP text encoding path
(cosmos_curate/models/clip.py drives HF transformers CLIP; the text tower
embeds queries/prompts into the shared image-text space). Our own Flax
implementation over the shared ``TransformerBlock``; weights convert from
HF ``CLIPTextModelWithProjection`` via ``models/convert_hf.convert_clip_text``
with an exact parity test (tests/models/test_convert_hf.py).

TPU-first: token + position embedding and the causal stack run in one jit;
batches pad to power-of-two lengths (static shapes).
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.models import registry
from cosmos_curate_tpu.models.layers import TransformerBlock, dense


@dataclass(frozen=True)
class CLIPTextConfig:
    vocab: int = 49408
    width: int = 512
    layers: int = 12
    heads: int = 8
    max_len: int = 77
    projection_dim: int = 512
    act: str = "quick_gelu"
    ln_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.width // self.heads


CLIP_TEXT_B = CLIPTextConfig()
CLIP_TEXT_L = CLIPTextConfig(width=768, layers=12, heads=12, projection_dim=768)
CLIP_TEXT_TINY_TEST = CLIPTextConfig(
    vocab=64, width=32, layers=2, heads=2, max_len=16, projection_dim=16
)


class CLIPTextEncoder(nn.Module):
    """ids [B, T] -> (pooled [B, P], tokens [B, T, W]).

    Pooling follows CLIP: the feature at the EOT position, taken as
    ``ids.argmax(-1)`` — the EOT token has the highest id in CLIP's BPE
    vocab, so callers must append it (HF uses the same argmax rule)."""

    cfg: CLIPTextConfig
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, ids):
        cfg = self.cfg
        x = nn.Embed(
            cfg.vocab, cfg.width, param_dtype=jnp.float32, dtype=self.dtype, name="tok_embed"
        )(ids)
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.01), (1, cfg.max_len, cfg.width), jnp.float32
        )
        x = x + pos[:, : ids.shape[1]].astype(self.dtype)
        for i in range(cfg.layers):
            x = TransformerBlock(
                cfg.heads,
                cfg.head_dim,
                dtype=self.dtype,
                causal=True,
                act=cfg.act,
                ln_eps=cfg.ln_eps,
                name=f"block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, epsilon=cfg.ln_eps, name="ln_final")(x)
        eot = jnp.argmax(ids, axis=-1)
        pooled = jnp.take_along_axis(x, eot[:, None, None], axis=1)[:, 0]
        pooled = dense(
            cfg.projection_dim, None, name="proj", use_bias=False, dtype=self.dtype
        )(pooled)
        return pooled.astype(jnp.float32), x


class CLIPTextEmbeddings(ModelInterface):
    """Batched token ids -> L2-normalized text embeddings."""

    _CONFIGS = {
        "clip-text-b-tpu": CLIP_TEXT_B,
        "clip-text-l-tpu": CLIP_TEXT_L,
        "clip-text-tiny-test": CLIP_TEXT_TINY_TEST,
    }

    def __init__(self, variant: str = "clip-text-b-tpu") -> None:
        if variant not in self._CONFIGS:
            raise ValueError(f"unknown variant {variant!r}; have {sorted(self._CONFIGS)}")
        self.variant = variant
        self.cfg = self._CONFIGS[variant]
        self._apply = None
        self._params = None
        self._pipeline = None
        self._tokenizer = None

    @property
    def model_id_names(self) -> list[str]:
        return [self.variant]

    @property
    def embedding_dim(self) -> int:
        return self.cfg.projection_dim

    def setup(self) -> None:
        model = CLIPTextEncoder(self.cfg)

        def init(seed: int):
            return model.init(jax.random.PRNGKey(seed), jnp.zeros((1, 4), jnp.int32))

        self._params = registry.load_params(self.variant, init)

        from cosmos_curate_tpu.models.device_pipeline import DevicePipeline, donate_kwargs

        def embed(params, ids):
            pooled, _ = model.apply(params, ids)
            return pooled / jnp.linalg.norm(pooled, axis=-1, keepdims=True)

        self._apply = jax.jit(embed, **donate_kwargs(1))
        self._pipeline = DevicePipeline(f"clip-text/{self.variant}", self._apply)

    def encode_ids(self, ids: np.ndarray) -> np.ndarray:
        """int32 [N, T] (EOT appended, pad after) -> float32 [N, P].
        Dispatched through the shared DevicePipeline."""
        if self._pipeline is None:
            raise RuntimeError("call setup() first")
        return self._pipeline.run(self._params, np.asarray(ids, np.int32))

    @property
    def provenance(self) -> str:
        """Weights provenance of this tower RIGHT NOW (``"random"`` until a
        converted checkpoint is staged) — the gate the index server checks
        before serving text-to-clip queries."""
        return registry.weights_provenance(self.variant)

    def encode_texts(self, texts: list[str]) -> np.ndarray:
        """Tokenized query path (text-to-clip search): strings -> L2-
        normalized float32 [N, P]. Tokenizes with the staged CLIP BPE when
        the checkpoint ships ``vocab.json``/``merges.txt``, else the
        hermetic fallback (see :func:`clip_text_tokenizer`); sequences pad
        to a shared pow2 length ≤ ``max_len`` so the compiled-shape
        universe stays bounded."""
        from cosmos_curate_tpu.models.batching import next_pow2

        if not texts:
            return np.zeros((0, self.cfg.projection_dim), np.float32)
        if self._tokenizer is None:
            self._tokenizer = clip_text_tokenizer(self.variant, self.cfg)
        rows = [self._tokenizer.encode(t, max_len=self.cfg.max_len) for t in texts]
        width = min(self.cfg.max_len, next_pow2(max(len(r) for r in rows)))
        ids = np.zeros((len(rows), width), np.int32)  # pad id 0 < EOT: argmax pooling safe
        for i, r in enumerate(rows):
            ids[i, : len(r)] = r[:width]
        return self.encode_ids(ids)


# ---------------------------------------------------------------------------
# query tokenization (text-to-clip search)


class CLIPTokenizer:
    """CLIP's BPE (the HF ``vocab.json`` + ``merges.txt`` format the text
    checkpoints ship): lowercased input, GPT-2 byte alphabet, ``</w>``
    end-of-word marker, ``<|startoftext|>``/``<|endoftext|>`` wrapping. The
    EOT id is the vocabulary maximum, which is what makes the encoder's
    ``argmax`` pooling (CLIPTextEncoder) find it."""

    def __init__(
        self,
        vocab: dict[str, int],
        merges: list[tuple[str, str]],
        *,
        sot: str = "<|startoftext|>",
        eot: str = "<|endoftext|>",
    ) -> None:
        import regex

        self.vocab = vocab
        self.ranks = {m: i for i, m in enumerate(merges)}
        self.sot_id = vocab[sot]
        self.eot_id = vocab[eot]
        from cosmos_curate_tpu.models.tokenizer import _gpt2_byte_encoder

        self._byte_enc = _gpt2_byte_encoder()
        # CLIP's pre-tokenizer split (open_clip simple_tokenizer), \p classes
        # need the `regex` module (already a repo dependency)
        self._splitter = regex.compile(
            r"'s|'t|'re|'ve|'m|'ll|'d|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+",
            regex.IGNORECASE,
        )
        self._cache: dict[str, list[int]] = {}

    @classmethod
    def from_files(cls, vocab_json, merges_txt) -> "CLIPTokenizer":
        import json as _json
        from pathlib import Path

        vocab = _json.loads(Path(vocab_json).read_text())
        merges: list[tuple[str, str]] = []
        for line in Path(merges_txt).read_text().splitlines():
            if not line or line.startswith("#version"):
                continue
            left, _, right = line.partition(" ")
            merges.append((left, right))
        return cls(vocab, merges)

    def _bpe(self, word: str) -> list[int]:
        cached = self._cache.get(word)
        if cached is not None:
            return cached
        chars = "".join(self._byte_enc[b] for b in word.encode("utf-8"))
        if not chars:
            return []
        parts = list(chars[:-1]) + [chars[-1] + "</w>"]
        while len(parts) > 1:
            best, best_rank = -1, None
            for i in range(len(parts) - 1):
                r = self.ranks.get((parts[i], parts[i + 1]))
                if r is not None and (best_rank is None or r < best_rank):
                    best, best_rank = i, r
            if best_rank is None:
                break
            parts[best: best + 2] = [parts[best] + parts[best + 1]]
        out = [self.vocab[p] for p in parts if p in self.vocab]
        if len(self._cache) < 16384:
            self._cache[word] = out
        return out

    def encode(self, text: str, *, max_len: int = 77) -> list[int]:
        """[SOT] + BPE tokens + [EOT], truncated so EOT always survives
        (the pooled feature is read at the EOT position)."""
        ids = [self.sot_id]
        for piece in self._splitter.findall(" ".join(text.lower().split())):
            ids.extend(self._bpe(piece))
        ids = ids[: max_len - 1]
        ids.append(self.eot_id)
        return ids


class FallbackClipTokenizer:
    """Hermetic stand-in when no tokenizer files are staged (tiny-test
    configs, architecture-only runs): bytes fold into the body id range,
    SOT/EOT take the two top ids so EOT stays the sequence argmax. Stable
    and reversible enough for shape/latency tests — NOT a semantic
    tokenizer, which is why text search is provenance-gated anyway."""

    def __init__(self, vocab_size: int) -> None:
        if vocab_size < 4:
            raise ValueError("vocab too small for SOT/EOT + body ids")
        self.sot_id = vocab_size - 2
        self.eot_id = vocab_size - 1
        self._body = vocab_size - 3  # ids 1..vocab-3; 0 stays the pad id

    def encode(self, text: str, *, max_len: int = 77) -> list[int]:
        ids = [self.sot_id]
        ids.extend(1 + (b % self._body) for b in text.lower().encode("utf-8"))
        ids = ids[: max_len - 1]
        ids.append(self.eot_id)
        return ids


def clip_text_tokenizer(variant: str, cfg: CLIPTextConfig):
    """The query tokenizer for ``variant``: the checkpoint's staged CLIP
    BPE (``vocab.json`` + ``merges.txt``, pulled alongside the weights)
    when present, else the hermetic fallback sized to the config vocab."""
    try:
        registry.maybe_pull_tokenizer_files(variant)
    except Exception:  # offline/unstaged: the fallback below covers it
        pass
    vocab = registry.find_model_file(variant, "vocab.json")
    merges = registry.find_model_file(variant, "merges.txt")
    if vocab is not None and merges is not None:
        return CLIPTokenizer.from_files(vocab, merges)
    return FallbackClipTokenizer(cfg.vocab)


registry.register_model("clip-text-b-tpu", "CLIP text tower, ViT-B width (Flax)")
registry.register_model("clip-text-l-tpu", "CLIP text tower, ViT-L width (Flax)")
