"""HF checkpoint converters: real pretrained weights → our Flax layouts.

Equivalent capability of the reference's weight flow (HF → cloud cache →
local dir, cosmos_curate/core/utils/model/model_utils.py:596-700): where the
reference loads HF checkpoints directly into torch modules, our models are
independent Flax architectures, so conversion is an explicit weight-layout
mapping. ``convert_clip_vision`` covers CLIP-family vision towers
(openai/clip-vit-*-patch*); converted checkpoints are staged via
``models/registry.py::save_params`` and the matching ``ViTConfig`` must use
``act="quick_gelu", ln_eps=1e-5``.

Architecture parity is proven by test (tests/models/test_convert_hf.py): a
randomly initialized HF CLIP vision model and our ViT with converted
weights produce matching embeddings.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.models.vit import ViTConfig
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def clip_vision_config(hf_config) -> ViTConfig:
    """ViTConfig matching an HF CLIPVisionConfig; fails fast on shapes or
    activations our ViT cannot represent (silent mismatch would surface as
    a confusing flax shape error — or worse, wrong numerics — at load)."""
    if hf_config.intermediate_size != 4 * hf_config.hidden_size:
        raise ValueError(
            f"unsupported MLP ratio: intermediate {hf_config.intermediate_size} "
            f"!= 4 x hidden {hf_config.hidden_size}"
        )
    if hf_config.hidden_act not in ("gelu", "quick_gelu"):
        raise ValueError(f"unsupported activation {hf_config.hidden_act!r}")
    return ViTConfig(
        image_size=hf_config.image_size,
        patch_size=hf_config.patch_size,
        width=hf_config.hidden_size,
        layers=hf_config.num_hidden_layers,
        heads=hf_config.num_attention_heads,
        projection_dim=hf_config.projection_dim,
        act=hf_config.hidden_act,
        ln_eps=hf_config.layer_norm_eps,
    )


def _t(w) -> np.ndarray:
    return np.asarray(w.detach().cpu().numpy() if hasattr(w, "detach") else w)


def convert_clip_vision(hf_model) -> dict:
    """transformers CLIPVisionModelWithProjection → our ViT params tree."""
    sd = {k: _t(v) for k, v in hf_model.state_dict().items()}
    v = "vision_model."
    params: dict = {}
    # patchify conv: torch [out, in, kh, kw] -> flax [kh, kw, in, out]
    params["patch_embed"] = {
        "kernel": sd[f"{v}embeddings.patch_embedding.weight"].transpose(2, 3, 1, 0)
    }
    params["cls"] = sd[f"{v}embeddings.class_embedding"][None, None, :]
    params["pos_embed"] = sd[f"{v}embeddings.position_embedding.weight"][None]
    params["ln_pre"] = {
        "scale": sd[f"{v}pre_layrnorm.weight"],  # (sic — HF's own key name)
        "bias": sd[f"{v}pre_layrnorm.bias"],
    }
    params["ln_post"] = {
        "scale": sd[f"{v}post_layernorm.weight"],
        "bias": sd[f"{v}post_layernorm.bias"],
    }
    n_layers = hf_model.config.num_hidden_layers
    for i in range(n_layers):
        e = f"{v}encoder.layers.{i}."

        def lin(name):  # torch Linear [out, in] -> flax kernel [in, out]
            return {
                "kernel": sd[f"{e}{name}.weight"].T,
                "bias": sd[f"{e}{name}.bias"],
            }

        params[f"block_{i}"] = {
            "ln1": {"scale": sd[f"{e}layer_norm1.weight"], "bias": sd[f"{e}layer_norm1.bias"]},
            "ln2": {"scale": sd[f"{e}layer_norm2.weight"], "bias": sd[f"{e}layer_norm2.bias"]},
            "attn": {
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "out": lin("self_attn.out_proj"),
            },
            "mlp": {"up": lin("mlp.fc1"), "down": lin("mlp.fc2")},
        }
    params["proj"] = {"kernel": sd["visual_projection.weight"].T}
    logger.info("converted CLIP vision tower: %d layers", n_layers)
    return {"params": params}


def clip_text_config(hf_config):
    """CLIPTextConfig matching an HF CLIPTextConfig (fails fast like
    ``clip_vision_config``)."""
    from cosmos_curate_tpu.models.clip_text import CLIPTextConfig

    if hf_config.intermediate_size != 4 * hf_config.hidden_size:
        raise ValueError(
            f"unsupported MLP ratio: intermediate {hf_config.intermediate_size} "
            f"!= 4 x hidden {hf_config.hidden_size}"
        )
    if hf_config.hidden_act not in ("gelu", "quick_gelu"):
        raise ValueError(f"unsupported activation {hf_config.hidden_act!r}")
    return CLIPTextConfig(
        vocab=hf_config.vocab_size,
        width=hf_config.hidden_size,
        layers=hf_config.num_hidden_layers,
        heads=hf_config.num_attention_heads,
        max_len=hf_config.max_position_embeddings,
        projection_dim=hf_config.projection_dim,
        act=hf_config.hidden_act,
        ln_eps=hf_config.layer_norm_eps,
    )


def convert_clip_text(hf_model) -> dict:
    """transformers CLIPTextModelWithProjection → our CLIPTextEncoder params."""
    sd = {k: _t(v) for k, v in hf_model.state_dict().items()}
    t = "text_model."
    params: dict = {
        "tok_embed": {"embedding": sd[f"{t}embeddings.token_embedding.weight"]},
        "pos_embed": sd[f"{t}embeddings.position_embedding.weight"][None],
        "ln_final": {
            "scale": sd[f"{t}final_layer_norm.weight"],
            "bias": sd[f"{t}final_layer_norm.bias"],
        },
    }
    n_layers = hf_model.config.num_hidden_layers
    for i in range(n_layers):
        e = f"{t}encoder.layers.{i}."

        def lin(name):
            return {
                "kernel": sd[f"{e}{name}.weight"].T,
                "bias": sd[f"{e}{name}.bias"],
            }

        params[f"block_{i}"] = {
            "ln1": {"scale": sd[f"{e}layer_norm1.weight"], "bias": sd[f"{e}layer_norm1.bias"]},
            "ln2": {"scale": sd[f"{e}layer_norm2.weight"], "bias": sd[f"{e}layer_norm2.bias"]},
            "attn": {
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "out": lin("self_attn.out_proj"),
            },
            "mlp": {"up": lin("mlp.fc1"), "down": lin("mlp.fc2")},
        }
    params["proj"] = {"kernel": sd["text_projection.weight"].T}
    logger.info("converted CLIP text tower: %d layers", n_layers)
    return {"params": params}


def convert_aesthetic_head(state_dict) -> dict:
    """ttj/sac-logos-ava1-l14-linearMSE MLP state dict → AestheticMLP params.

    The published checkpoint (reference models/aesthetics.py:44-53) is an
    ``nn.Sequential``: Linear(768,1024) @0, Dropout, Linear(1024,128) @2,
    Dropout, Linear(128,64) @4, Dropout, Linear(64,16) @6, Linear(16,1) @7.
    Accepts keys both as ``layers.N.weight`` and bare ``N.weight``.
    """
    sd = {k: _t(v) for k, v in state_dict.items()}

    def get(idx: int) -> dict:
        for prefix in ("layers.", ""):
            wk = f"{prefix}{idx}.weight"
            if wk in sd:
                return {"kernel": sd[wk].T, "bias": sd[f"{prefix}{idx}.bias"]}
        raise KeyError(f"no Linear at sequential index {idx} in state dict")

    params = {f"fc{j}": get(idx) for j, idx in enumerate((0, 2, 4, 6))}
    params["out"] = get(7)
    logger.info("converted aesthetic head: %d linear layers", 5)
    return {"params": params}


def t5_encoder_config(hf_config):
    """Our T5Config from an HF T5Config."""
    from cosmos_curate_tpu.models.t5 import T5Config

    act = "gated-gelu" if getattr(hf_config, "is_gated_act", False) else "relu"
    return T5Config(
        vocab=hf_config.vocab_size,
        dim=hf_config.d_model,
        d_kv=hf_config.d_kv,
        d_ff=hf_config.d_ff,
        layers=hf_config.num_layers,
        heads=hf_config.num_heads,
        num_buckets=hf_config.relative_attention_num_buckets,
        max_distance=getattr(hf_config, "relative_attention_max_distance", 128),
        act=act,
        ln_eps=hf_config.layer_norm_epsilon,
    )


def convert_t5_encoder(hf_model) -> dict:
    """transformers T5EncoderModel → our T5Encoder params."""
    sd = {k: _t(v) for k, v in hf_model.state_dict().items()}
    cfg = hf_model.config
    params: dict = {
        "shared": {"embedding": sd["shared.weight"]},
        "rel_bias": {
            "embedding": sd[
                "encoder.block.0.layer.0.SelfAttention.relative_attention_bias.weight"
            ]
        },
        "ln_final": {"weight": sd["encoder.final_layer_norm.weight"]},
    }
    gated = getattr(cfg, "is_gated_act", False)
    for i in range(cfg.num_layers):
        e = f"encoder.block.{i}."

        def lin(name):
            return {"kernel": sd[f"{e}{name}.weight"].T}

        mlp = (
            {
                "wi_0": lin("layer.1.DenseReluDense.wi_0"),
                "wi_1": lin("layer.1.DenseReluDense.wi_1"),
                "wo": lin("layer.1.DenseReluDense.wo"),
            }
            if gated
            else {
                "wi": lin("layer.1.DenseReluDense.wi"),
                "wo": lin("layer.1.DenseReluDense.wo"),
            }
        )
        params[f"block_{i}"] = {
            "ln1": {"weight": sd[f"{e}layer.0.layer_norm.weight"]},
            "ln2": {"weight": sd[f"{e}layer.1.layer_norm.weight"]},
            "attn": {
                "q": lin("layer.0.SelfAttention.q"),
                "k": lin("layer.0.SelfAttention.k"),
                "v": lin("layer.0.SelfAttention.v"),
                "o": lin("layer.0.SelfAttention.o"),
            },
            "mlp": mlp,
        }
    logger.info("converted T5 encoder: %d layers", cfg.num_layers)
    return {"params": params}
