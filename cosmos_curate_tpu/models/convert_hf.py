"""HF checkpoint converters: real pretrained weights → our Flax layouts.

Equivalent capability of the reference's weight flow (HF → cloud cache →
local dir, cosmos_curate/core/utils/model/model_utils.py:596-700): where the
reference loads HF checkpoints directly into torch modules, our models are
independent Flax architectures, so conversion is an explicit weight-layout
mapping. ``convert_clip_vision`` covers CLIP-family vision towers
(openai/clip-vit-*-patch*); converted checkpoints are staged via
``models/registry.py::save_params`` and the matching ``ViTConfig`` must use
``act="quick_gelu", ln_eps=1e-5``.

Architecture parity is proven by test (tests/models/test_convert_hf.py): a
randomly initialized HF CLIP vision model and our ViT with converted
weights produce matching embeddings.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.models.vit import ViTConfig
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def clip_vision_config(hf_config) -> ViTConfig:
    """ViTConfig matching an HF CLIPVisionConfig; fails fast on shapes or
    activations our ViT cannot represent (silent mismatch would surface as
    a confusing flax shape error — or worse, wrong numerics — at load)."""
    if hf_config.intermediate_size != 4 * hf_config.hidden_size:
        raise ValueError(
            f"unsupported MLP ratio: intermediate {hf_config.intermediate_size} "
            f"!= 4 x hidden {hf_config.hidden_size}"
        )
    if hf_config.hidden_act not in ("gelu", "quick_gelu"):
        raise ValueError(f"unsupported activation {hf_config.hidden_act!r}")
    return ViTConfig(
        image_size=hf_config.image_size,
        patch_size=hf_config.patch_size,
        width=hf_config.hidden_size,
        layers=hf_config.num_hidden_layers,
        heads=hf_config.num_attention_heads,
        projection_dim=hf_config.projection_dim,
        act=hf_config.hidden_act,
        ln_eps=hf_config.layer_norm_eps,
    )


def _t(w) -> np.ndarray:
    return np.asarray(w.detach().cpu().numpy() if hasattr(w, "detach") else w)


def convert_clip_vision(hf_model) -> dict:
    """transformers CLIPVisionModelWithProjection → our ViT params tree."""
    sd = {k: _t(v) for k, v in hf_model.state_dict().items()}
    v = "vision_model."
    params: dict = {}
    # patchify conv: torch [out, in, kh, kw] -> flax [kh, kw, in, out]
    params["patch_embed"] = {
        "kernel": sd[f"{v}embeddings.patch_embedding.weight"].transpose(2, 3, 1, 0)
    }
    params["cls"] = sd[f"{v}embeddings.class_embedding"][None, None, :]
    params["pos_embed"] = sd[f"{v}embeddings.position_embedding.weight"][None]
    params["ln_pre"] = {
        "scale": sd[f"{v}pre_layrnorm.weight"],  # (sic — HF's own key name)
        "bias": sd[f"{v}pre_layrnorm.bias"],
    }
    params["ln_post"] = {
        "scale": sd[f"{v}post_layernorm.weight"],
        "bias": sd[f"{v}post_layernorm.bias"],
    }
    n_layers = hf_model.config.num_hidden_layers
    for i in range(n_layers):
        e = f"{v}encoder.layers.{i}."

        def lin(name):  # torch Linear [out, in] -> flax kernel [in, out]
            return {
                "kernel": sd[f"{e}{name}.weight"].T,
                "bias": sd[f"{e}{name}.bias"],
            }

        params[f"block_{i}"] = {
            "ln1": {"scale": sd[f"{e}layer_norm1.weight"], "bias": sd[f"{e}layer_norm1.bias"]},
            "ln2": {"scale": sd[f"{e}layer_norm2.weight"], "bias": sd[f"{e}layer_norm2.bias"]},
            "attn": {
                "q": lin("self_attn.q_proj"),
                "k": lin("self_attn.k_proj"),
                "v": lin("self_attn.v_proj"),
                "out": lin("self_attn.out_proj"),
            },
            "mlp": {"up": lin("mlp.fc1"), "down": lin("mlp.fc2")},
        }
    params["proj"] = {"kernel": sd["visual_projection.weight"].T}
    logger.info("converted CLIP vision tower: %d layers", n_layers)
    return {"params": params}
