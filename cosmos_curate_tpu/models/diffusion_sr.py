"""Diffusion-class video super-resolution (windowed, conditional).

Equivalent capability CLASS of the reference's SeedVR2 integration
(cosmos_curate/models/seedvr2.py:145 — a diffusion transformer denoises
video windows conditioned on the low-res input, sequence-parallel over
frames, inference_seedvr2_window.py:483-530). This is our own compact
Flax design, sized to be trainable in a single TPU window on synthetic
degradations (this image has no egress for the 3B SeedVR2 checkpoint; see
PARITY.md for the honest quality note):

- **residual diffusion**: the model denoises the HR RESIDUAL over the
  bilinear-upsampled input — the conditioning carries all low-frequency
  content, so a small denoiser only has to synthesize detail;
- **denoiser** = small conv UNet (stride-2 down / depth-to-space up,
  GroupNorm + SiLU, FiLM timestep modulation) with temporal
  self-attention at the bottleneck, so frames inside a window agree on
  the synthesized detail (the video-consistency property that separates
  diffusion SR from per-frame conv SR);
- **v-prediction** on a cosine schedule; deterministic DDIM sampling in
  ``sample_steps`` steps with a per-window fixed seed (same input →
  same output, the pipeline's reproducibility contract);
- **windowed inference**: frames process in fixed ``window``-frame chunks
  (one compiled program), chunks batched; the ``sp_size`` hook shards the
  chunk batch over the 'seq' mesh axis (chunks are independent, so this
  is exact — the TPU translation of the reference's sp_size frame
  sharding, at window granularity).
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.models import registry
from cosmos_curate_tpu.parallel import axes
from cosmos_curate_tpu.parallel.mesh import seq_mesh
from cosmos_curate_tpu.parallel.sharding import shard_map


@dataclass(frozen=True)
class DiffusionSRConfig:
    scale: int = 2
    channels: int = 48
    levels: int = 2  # stride-2 UNet levels
    blocks: int = 2  # res blocks per level
    temporal_heads: int = 4
    window: int = 4  # frames denoised together
    timesteps: int = 1000  # training schedule resolution
    sample_steps: int = 8  # DDIM steps at inference


DIFF_SR_BASE = DiffusionSRConfig()
DIFF_SR_TINY_TEST = DiffusionSRConfig(
    channels=8, levels=1, blocks=1, temporal_heads=2, window=2, sample_steps=2
)

registry.register_model("diffusion-sr-tpu", "windowed conditional diffusion video SR (Flax)")


def cosine_alpha_sigma(t: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Continuous cosine schedule: t in [0, 1] -> (alpha, sigma) with
    alpha^2 + sigma^2 = 1 (public formulation, Nichol & Dhariwal)."""
    angle = t * (jnp.pi / 2)
    return jnp.cos(angle), jnp.sin(angle)


def _timestep_embedding(t: jnp.ndarray, dim: int) -> jnp.ndarray:
    """Sinusoidal embedding of continuous t in [0, 1] -> [..., dim]."""
    half = dim // 2
    freqs = jnp.exp(jnp.linspace(0.0, 6.0, half))
    ang = t[..., None] * freqs
    return jnp.concatenate([jnp.sin(ang), jnp.cos(ang)], axis=-1)


class _FiLMResBlock(nn.Module):
    channels: int

    @nn.compact
    def __call__(self, x, temb):
        h = nn.GroupNorm(num_groups=min(8, self.channels))(x)
        h = nn.silu(h)
        h = nn.Conv(self.channels, (3, 3), dtype=jnp.bfloat16, param_dtype=jnp.float32)(h)
        # FiLM: timestep scales/shifts the normalized features
        mod = nn.Dense(2 * self.channels, param_dtype=jnp.float32)(temb)
        scale, shift = jnp.split(mod, 2, axis=-1)
        h = h * (1 + scale[:, None, None, :]) + shift[:, None, None, :]
        h = nn.silu(h)
        h = nn.Conv(self.channels, (3, 3), dtype=jnp.bfloat16, param_dtype=jnp.float32)(h)
        if x.shape[-1] != self.channels:
            x = nn.Conv(self.channels, (1, 1), dtype=jnp.bfloat16, param_dtype=jnp.float32)(x)
        return x + h


class _TemporalAttention(nn.Module):
    """Self-attention ACROSS the frame axis at every spatial position —
    the cross-frame consistency mechanism (frames agree on synthesized
    detail). Tokens are frames: cost O(T^2 · HW · C), tiny for window
    sizes."""

    heads: int

    @nn.compact
    def __call__(self, x):  # [T, H, W, C]
        t, h, w, c = x.shape
        d = c // self.heads
        y = nn.GroupNorm(num_groups=min(8, c))(x)
        y = y.reshape(t, h * w, c)
        q = nn.Dense(c, param_dtype=jnp.float32, name="q")(y)
        k = nn.Dense(c, param_dtype=jnp.float32, name="k")(y)
        v = nn.Dense(c, param_dtype=jnp.float32, name="v")(y)
        q = q.reshape(t, h * w, self.heads, d)
        k = k.reshape(t, h * w, self.heads, d)
        v = v.reshape(t, h * w, self.heads, d)
        # attend over the FRAME axis per (position, head)
        logits = jnp.einsum("tphd,sphd->phts", q, k) * (d**-0.5)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("phts,sphd->tphd", probs, v).reshape(t, h * w, c)
        out = nn.Dense(c, param_dtype=jnp.float32, name="out")(out)
        return x + out.reshape(t, h, w, c)


class DenoiserUNet(nn.Module):
    """v-prediction denoiser over the HR residual, conditioned on the
    upsampled LR frames (channel-concat) and the timestep (FiLM)."""

    cfg: DiffusionSRConfig

    @nn.compact
    def __call__(self, z, cond, t):
        """z: [T, H, W, 3] noisy residual; cond: [T, H, W, 3] bilinear-up
        LR in [0,1]; t: scalar in [0,1]. Returns v prediction [T, H, W, 3]."""
        cfg = self.cfg
        temb = _timestep_embedding(jnp.full((z.shape[0],), t), 4 * cfg.channels)
        temb = nn.Dense(4 * cfg.channels, param_dtype=jnp.float32)(temb)
        temb = nn.silu(temb)
        x = jnp.concatenate([z, cond], axis=-1).astype(jnp.bfloat16)
        x = nn.Conv(cfg.channels, (3, 3), dtype=jnp.bfloat16, param_dtype=jnp.float32)(x)
        skips = []
        ch = cfg.channels
        for lvl in range(cfg.levels):
            for _ in range(cfg.blocks):
                x = _FiLMResBlock(ch)(x, temb)
            skips.append(x)
            ch *= 2
            x = nn.Conv(
                ch, (3, 3), strides=(2, 2), dtype=jnp.bfloat16, param_dtype=jnp.float32
            )(x)
        for _ in range(cfg.blocks):
            x = _FiLMResBlock(ch)(x, temb)
        x = _TemporalAttention(cfg.temporal_heads)(x.astype(jnp.float32)).astype(jnp.bfloat16)
        for _ in range(cfg.blocks):
            x = _FiLMResBlock(ch)(x, temb)
        for lvl in reversed(range(cfg.levels)):
            ch //= 2
            t_, h_, w_, c_ = x.shape
            x = nn.Conv(4 * ch, (3, 3), dtype=jnp.bfloat16, param_dtype=jnp.float32)(x)
            x = x.reshape(t_, h_, w_, 2, 2, ch).transpose(0, 1, 3, 2, 4, 5).reshape(
                t_, h_ * 2, w_ * 2, ch
            )
            x = jnp.concatenate([x, skips[lvl].astype(jnp.bfloat16)], axis=-1)
            for _ in range(cfg.blocks):
                x = _FiLMResBlock(ch)(x, temb)
        x = nn.GroupNorm(num_groups=min(8, ch))(x)
        x = nn.silu(x)
        return nn.Conv(
            3, (3, 3), dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=nn.initializers.zeros,
        )(x.astype(jnp.float32))


def ddim_sample(model, params, cond, cfg: DiffusionSRConfig, rng_key) -> jnp.ndarray:
    """Deterministic DDIM over ``sample_steps``: returns the denoised
    residual x0 for one window. v-param identities: x0 = a·z − s·v,
    eps = s·z + a·v; update z ← a'·x0 + s'·eps."""
    z = jax.random.normal(rng_key, cond.shape, jnp.float32)
    ts = jnp.linspace(1.0, 0.0, cfg.sample_steps + 1)

    def body(z, i):
        t_now, t_next = ts[i], ts[i + 1]
        a, s = cosine_alpha_sigma(t_now)
        v = model.apply(params, z, cond, t_now)
        x0 = a * z - s * v
        eps = s * z + a * v
        a2, s2 = cosine_alpha_sigma(t_next)
        return a2 * x0 + s2 * eps, None

    z, _ = jax.lax.scan(body, z, jnp.arange(cfg.sample_steps))
    # t=0: alpha=1, sigma=0 -> z IS x0
    return z


class DiffusionSRModel(ModelInterface):
    MODEL_ID = "diffusion-sr-tpu"

    def __init__(self, cfg: DiffusionSRConfig = DIFF_SR_BASE, *, sp_size: int = 1) -> None:
        self.cfg = cfg
        self.sp_size = sp_size  # window chunks sharded over 'seq' when > 1
        self._sample = None
        self._params = None
        self._pipeline = None

    @property
    def model_id_names(self) -> list[str]:
        return [self.MODEL_ID]

    def setup(self) -> None:
        cfg = self.cfg
        model = DenoiserUNet(cfg)

        def init(seed: int):
            s = 16 * cfg.scale
            dummy = jnp.zeros((cfg.window, s, s, 3), jnp.float32)
            return model.init(jax.random.PRNGKey(seed), dummy, dummy, jnp.float32(0.5))

        self._params = registry.load_params(self.MODEL_ID, init)

        def sample_chunks(params, conds, keys):
            # conds: [N, window, H, W, 3] independent chunks
            return jax.vmap(lambda c, k: ddim_sample(model, params, c, cfg, k))(
                conds, keys
            )

        if self.sp_size > 1:
            from jax.sharding import PartitionSpec as P

            mesh = seq_mesh(self.sp_size)
            inner = shard_map(
                sample_chunks,
                mesh=mesh,
                in_specs=(P(), P(axes.SEQ), P(axes.SEQ)),
                out_specs=P(axes.SEQ),
                check_vma=False,
            )
        else:
            inner = sample_chunks

        def upscale(params, frames_u8, seeds):
            """The whole window path under ONE jit — bilinear base, chunked
            DDIM sampling, residual combine, uint8 quantize — so a window
            is a single async dispatch through the DevicePipeline instead
            of eager device ops bracketing a jitted core."""
            t_pad, h, w = frames_u8.shape[:3]
            base = jax.image.resize(
                frames_u8.astype(jnp.float32) / 255.0,
                (t_pad, h * cfg.scale, w * cfg.scale, 3),
                "bilinear",
            )
            n_chunk = t_pad // cfg.window
            conds = base.reshape(n_chunk, cfg.window, h * cfg.scale, w * cfg.scale, 3)
            keys = jax.vmap(jax.random.PRNGKey)(seeds)
            residual = inner(params, conds, keys)
            out = jnp.clip(conds + residual, 0.0, 1.0)
            out = out.reshape(t_pad, h * cfg.scale, w * cfg.scale, 3)
            return (out * 255.0).astype(jnp.uint8)

        self._sample = jax.jit(upscale)
        from cosmos_curate_tpu.models.device_pipeline import DevicePipeline

        self._pipeline = DevicePipeline("sr/diffusion", self._sample)

    def submit_window(self, frames: np.ndarray) -> None:
        """Queue one window; results resolve in order at drain_windows()."""
        if self._pipeline is None:
            raise RuntimeError("call setup() first")
        cfg = self.cfg
        t = frames.shape[0]
        # fixed-shape chunking: pad the frame axis to a window multiple
        # (and to the sp shard multiple), one compiled program per shape
        n_chunk = -(-t // cfg.window)
        if self.sp_size > 1:
            n_chunk += (-n_chunk) % self.sp_size
        t_pad = n_chunk * cfg.window
        if t_pad != t:
            from cosmos_curate_tpu.models.batching import pad_to

            frames = pad_to(frames, t_pad)
        # per-chunk FIXED seeds: identical input -> identical output
        seeds = np.arange(n_chunk, dtype=np.uint32)
        self._pipeline.submit(self._params, frames, seeds, n_valid=t)

    def drain_windows(self) -> list[np.ndarray]:
        return self._pipeline.drain()

    def upscale_window(self, frames: np.ndarray) -> np.ndarray:
        """uint8 [T, H, W, 3] -> uint8 [T, H*scale, W*scale, 3]
        (synchronous single-window path)."""
        self.submit_window(frames)
        return self.drain_windows()[0]
