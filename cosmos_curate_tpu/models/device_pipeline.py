"""Shared async device pipeline for all model stages.

Every model stage used to run the same synchronous loop: build a host
batch, ``jax.device_put`` (implicit), compute under jit, and immediately
block on ``np.asarray`` readback. That serializes four engines that can
run concurrently — host batch prep, the H2D transfer engine, the MXU, and
D2H readback — and bench rounds showed the embed stage at ~97% of
end-to-end wall time as a result.

``DevicePipeline`` is the one sanctioned dispatch point (the sync-readback
lint rule keeps inline ``np.asarray(jit_fn(...))`` from creeping back):

- **micro-batching**: a shape-grouped host batch is split into fixed
  power-of-two bucket micro-batches (``plan_micro_batches``, reusing the
  ``batching`` pow2 discipline), so one logical batch becomes several
  dispatches that can overlap instead of one monolithic call;
- **double buffering**: JAX dispatch is asynchronous, so submitting
  micro-batch k+1 starts its H2D transfer while k computes. A bounded
  in-flight window (default 2) applies backpressure by settling the
  oldest dispatch — the host-level analogue of the kernel-level DMA
  double buffering in the Pallas guide;
- **deferred readback**: readback is decoupled from dispatch — a result
  is read back when its dispatch settles (compute done; pure D2H that
  overlaps the compute of later batches) and handed out in submission
  order at drain. Device memory stays bounded at the in-flight window —
  settled results live on the host, not in HBM;
- **donation**: on backends with buffer donation (TPU/GPU) the data
  arguments are donated to cut HBM churn; on CPU the knob degrades to a
  no-op (``donate_kwargs`` returns nothing);
- **compile cache**: constructing a pipeline enables the persistent XLA
  compilation cache (``CURATE_COMPILE_CACHE`` knob, utils/jax_cache.py),
  so bucket-shape compiles are paid once per machine, not per process.

Per-dispatch H2D/compute/readback/gap timings flow through
``observability.stage_timer.record_dispatch`` so the overlap is measurable
(bench.py asserts dispatch-gap < 20% of embed-stage wall), not asserted.
"""

from __future__ import annotations

import os
import time
import warnings
from dataclasses import dataclass
from typing import Any, Callable

import jax
import numpy as np

from cosmos_curate_tpu.models.batching import next_pow2, pad_to
from cosmos_curate_tpu.observability.stage_timer import DispatchRecord, record_dispatch

MICRO_BATCH_ENV = "CURATE_MICRO_BATCH"
DEFAULT_MICRO_BATCH = 32
DEFAULT_IN_FLIGHT = 2

_DONATABLE_BACKENDS = ("tpu", "gpu", "cuda", "rocm")


def donation_supported() -> bool:
    """Buffer donation is implemented on TPU/GPU; on CPU jax ignores it
    with a per-compile warning, so we gate instead of spamming."""
    try:
        return jax.default_backend() in _DONATABLE_BACKENDS
    except Exception:
        return False


_DONATION_WARNING_FILTERED = False


def donate_kwargs(*argnums: int) -> dict:
    """``jax.jit`` kwargs donating ``argnums`` on supported backends, {}
    on CPU (the donation fallback path). Most stage inputs (uint8 frames)
    cannot alias their f32 outputs, so XLA may still decline the alias —
    donation then only releases the input buffer early; the 'not usable'
    warning for that case is noise and is filtered once per process."""
    global _DONATION_WARNING_FILTERED
    if not donation_supported():
        return {}
    if not _DONATION_WARNING_FILTERED:
        # Process-global by necessity: the warning fires at compile time
        # deep inside jax, so there is no call site of ours to scope a
        # catch_warnings around. The message-exact match keeps every other
        # donation diagnostic (wrong argnums, aliasing bugs) audible.
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        _DONATION_WARNING_FILTERED = True
    return {"donate_argnums": argnums}


def micro_batch_cap(override: int | None = None) -> int:
    """Micro-batch bucket cap: pow2, env-tunable via CURATE_MICRO_BATCH.
    A non-pow2 value rounds DOWN — the cap is an operator-set ceiling on
    per-dispatch device memory, which rounding up would exceed."""
    if override is not None:
        cap = override
    else:
        cap = int(os.environ.get(MICRO_BATCH_ENV, DEFAULT_MICRO_BATCH))
    if cap < 1:
        raise ValueError(f"micro-batch cap must be >= 1, got {cap}")
    return cap if cap & (cap - 1) == 0 else 1 << (cap.bit_length() - 1)


def plan_micro_batches(n: int, cap: int) -> list[tuple[int, int, int]]:
    """Split a batch of ``n`` rows into (start, stop, padded_size) bucket
    micro-batches: full ``cap``-sized chunks, then one remainder padded to
    its next power of two. A batch at or under the cap produces exactly
    the single pow2 bucket the old ``pad_batch`` path compiled, so the
    compiled-shape set (and any warmup that used it) carries over."""
    if n <= 0:
        return []
    plan: list[tuple[int, int, int]] = []
    start = 0
    while n - start > cap:
        plan.append((start, start + cap, cap))
        start += cap
    rest = n - start
    plan.append((start, n, min(next_pow2(rest), cap)))
    return plan


@dataclass
class _InFlight:
    result: Any  # device array or pytree of device arrays; None once read back
    n_valid: int | None
    rows: int
    padded_rows: int
    h2d_s: float
    dispatch_t: float
    postprocess: Callable[[Any], Any] | None = None
    done_t: float | None = None  # set when compute completion is observed
    host: Any = None  # host (numpy) result once read back
    d2h_s: float = 0.0


class DevicePipeline:
    """Micro-batched asynchronous dispatcher over one jitted callable.

    ``fn`` is called as ``fn(*args)`` — typically ``(params, batch)`` but
    any mix of array and non-array leading arguments works (np.ndarray
    args are explicitly ``device_put``; everything else, e.g. an already
    device-resident param pytree or a static int, passes through).

    Not thread-safe: each stage worker owns its own instance (the jitted
    ``fn`` itself is shared across instances by the models' lru-cached
    constructors, so compiles are still paid once).
    """

    def __init__(
        self,
        name: str,
        fn: Callable[..., Any],
        *,
        micro_batch: int | None = None,
        in_flight: int = DEFAULT_IN_FLIGHT,
    ) -> None:
        self.name = name
        self._fn = fn
        self._cap = micro_batch_cap(micro_batch)
        self._depth = max(1, in_flight)
        self._pending: list[_InFlight] = []
        self._settled: list[_InFlight] = []
        # first touch of any model path: make the compile-cache knob real
        from cosmos_curate_tpu.utils.jax_cache import enable_persistent_cache

        enable_persistent_cache()

    # -- core ---------------------------------------------------------------

    def submit(
        self,
        *args: Any,
        n_valid: int | None = None,
        rows: int | None = None,
        postprocess: Callable[[Any], Any] | None = None,
    ) -> None:
        """Dispatch one pre-shaped micro-batch; returns immediately.

        ``n_valid`` trims array results to their first n rows at drain
        (None = no trim — e.g. scalar outputs). ``postprocess`` runs on
        the host arrays at drain, in submission order.

        ANY failure (transfer, backpressure settle, dispatch) aborts the
        whole pipeline before propagating: earlier submissions' results are
        lost, but a caller that catches the error and keeps going can never
        pair leftover results with the wrong later submissions."""
        try:
            t0 = time.monotonic()
            dev = [
                jax.device_put(a) if isinstance(a, np.ndarray) else a for a in args
            ]
            t1 = time.monotonic()
            # backpressure: bounded in-flight window — wait on the oldest
            # dispatch's COMPUTE (block_until_ready holds no readback),
            # keeping at most `depth` micro-batches of activations on device
            while len(self._pending) >= self._depth:
                self._settle_oldest()
            result = self._fn(*dev)
        except Exception:
            self.abort()
            raise
        dispatch_t = time.monotonic()
        padded = 0
        for a in args:
            if isinstance(a, np.ndarray) and a.ndim >= 1:
                padded = int(a.shape[0])
                break
        self._pending.append(
            _InFlight(
                result=result,
                n_valid=n_valid,
                rows=rows if rows is not None else (n_valid or padded),
                padded_rows=padded,
                h2d_s=t1 - t0,
                dispatch_t=dispatch_t,
                postprocess=postprocess,
            )
        )

    def abort(self) -> None:
        """Drop ALL in-flight and settled work. Called internally on any
        settle/readback failure so a caller that catches the error resumes
        with an empty pipeline — losing that burst's results is recoverable
        (the stages mark the affected clips errored); silently pairing the
        survivors with the WRONG submissions on the next drain is not."""
        self._pending.clear()
        self._settled.clear()

    def _settle_oldest(self) -> None:
        """Wait for the oldest dispatch's compute, then read it back.

        The readback happens HERE, not at drain: a settled-but-unread
        result would pin its device buffers until the drain, so a long
        submit burst (the SR window loop) would hold every output in HBM
        at once. Reading back a finished result is pure D2H — it overlaps
        the compute of the still-pending dispatches, and device memory
        stays bounded at the in-flight window."""
        inf = self._pending.pop(0)
        try:
            jax.block_until_ready(inf.result)
            inf.done_t = time.monotonic()
            inf.host = jax.tree_util.tree_map(np.asarray, inf.result)
        except Exception:
            self.abort()
            raise
        inf.d2h_s = time.monotonic() - inf.done_t
        inf.result = None  # release the device buffers
        self._settled.append(inf)

    def drain(self) -> list[Any]:
        """Resolve everything submitted since the last drain, in submission
        order, as host (numpy) values — trimmed to ``n_valid`` and passed
        through ``postprocess`` when given. Settle and readback interleave:
        the D2H of batch k runs while batches k+1.. still compute. Records
        per-dispatch timings. On ANY failure the pipeline aborts (state
        fully cleared) before the exception propagates."""
        from cosmos_curate_tpu.observability.tracing import traced_span

        # take ownership up front: a failure partway must not leave stale
        # results behind to misalign the NEXT drain's zip
        burst = self._settled + self._pending
        self._settled, self._pending = [], []
        out: list[Any] = []
        if not burst:
            return out
        with traced_span(
            f"device.{self.name}.drain",
            dispatches=len(burst),
            rows=sum(inf.rows for inf in burst),
        ):
            out = self._drain_burst(burst)
        return out

    def _drain_burst(self, burst: list) -> list[Any]:
        # gap accounting is local to this submit..drain burst: carrying it
        # across drains would book unrelated stage work (decode, IO between
        # process_data calls) as device idle
        out: list[Any] = []
        last_done: float | None = None
        try:
            for inf in burst:
                if inf.done_t is None:
                    jax.block_until_ready(inf.result)
                    inf.done_t = time.monotonic()
                gap = 0.0
                if last_done is not None:
                    # device idle = it finished the previous batch before
                    # this one was even dispatched; 0 when the next dispatch
                    # was already queued (the overlap working as intended)
                    gap = max(0.0, inf.dispatch_t - last_done)
                compute_start = (
                    inf.dispatch_t if last_done is None else max(inf.dispatch_t, last_done)
                )
                compute_s = max(0.0, inf.done_t - compute_start)
                last_done = inf.done_t
                if inf.host is not None:
                    host, d2h_s = inf.host, inf.d2h_s  # read back at settle
                else:
                    t0 = time.monotonic()
                    host = jax.tree_util.tree_map(np.asarray, inf.result)
                    d2h_s = time.monotonic() - t0
                if inf.n_valid is not None:
                    host = jax.tree_util.tree_map(
                        lambda a, n=inf.n_valid: a[:n] if getattr(a, "ndim", 0) >= 1 else a,
                        host,
                    )
                if inf.postprocess is not None:
                    host = inf.postprocess(host)
                record_dispatch(
                    self.name,
                    DispatchRecord(
                        h2d_s=inf.h2d_s,
                        compute_s=compute_s,
                        d2h_s=d2h_s,
                        gap_s=gap,
                        rows=inf.rows,
                        padded_rows=inf.padded_rows,
                    ),
                )
                out.append(host)
        except Exception:
            self.abort()
            raise
        return out

    @property
    def pending(self) -> int:
        return len(self._pending) + len(self._settled)

    # -- convenience --------------------------------------------------------

    def track(self) -> "SubmissionTracker":
        return SubmissionTracker(self)

    def run(self, params: Any, *arrays: np.ndarray) -> np.ndarray:
        """The full pipelined replacement for ``np.asarray(fn(params,
        padded))[:n]``: split ``arrays`` (shared leading dim) into bucket
        micro-batches, pad each to its bucket, dispatch all, drain, and
        concatenate the valid rows back in order.

        Must not be interleaved with in-flight ``submit`` work on the same
        pipeline (drain resolves everything)."""
        if self.pending:
            raise RuntimeError("run() with submissions in flight; drain() first")
        n = int(arrays[0].shape[0])
        for a in arrays[1:]:
            if a.shape[0] != n:
                # a shorter array would silently pad with repeated rows —
                # plausible-looking wrong results (same hardening class as
                # parallel.sharding.shard_batch)
                raise ValueError(
                    f"run() arrays disagree on leading dim: {n} vs {a.shape[0]}"
                )
        if n == 0:
            # preserve the sync path's empty-batch contract (shape/dtype
            # from an actual zero-row dispatch)
            return np.asarray(self._fn(params, *arrays))
        for start, stop, target in plan_micro_batches(n, self._cap):
            chunk = [pad_to(a[start:stop], target) for a in arrays]
            self.submit(params, *chunk, n_valid=stop - start)
        outs = self.drain()
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)


class SubmissionTracker:
    """Pairs in-flight submissions with the caller's items (clips, spans).

    The filter stages submit one dispatch per clip and zip the drained
    results back at the end of process_data. This helper owns that
    pending list so the pairing and the abort bookkeeping live in ONE
    place: when a failure aborts the pipeline, the items whose results
    were dropped with it are handed back (``lost_to_abort``) so the stage
    can record per-item errors instead of silently skipping them.
    """

    def __init__(self, pipeline: DevicePipeline) -> None:
        self.pipeline = pipeline
        self._items: list[Any] = []

    def submit(self, item: Any, *args: Any, **kwargs: Any) -> None:
        self.pipeline.submit(*args, **kwargs)
        self._items.append(item)

    def lost_to_abort(self) -> list[Any]:
        """Call from an except handler: if the pipeline aborted (all
        in-flight work cleared), returns the items whose results are gone
        and forgets them — pairing survivors with the wrong results is the
        failure mode this prevents. Returns [] when nothing was lost."""
        if self._items and self.pipeline.pending == 0:
            lost, self._items = self._items, []
            return lost
        return []

    def drain(self) -> list[tuple[Any, Any]]:
        """-> [(item, result)] in submission order. On failure the items
        are kept so the caller's except path can claim them via
        ``lost_to_abort`` and record per-item errors."""
        items, self._items = self._items, []
        try:
            results = self.pipeline.drain()
        except Exception:
            self._items = items
            raise
        return list(zip(items, results))

    def __len__(self) -> int:
        return len(self._items)
