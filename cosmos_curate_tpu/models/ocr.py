"""Learned overlay-text detection + recognition (OCR), TPU-first.

Replaces the reference's PaddleOCR pairing
(cosmos_curate/models/paddle_ocr.py:317-554 — a DB-style text detector and a
CTC recognizer driving the artificial-text filter) with our own Flax models:

- ``TextDetector`` — a small FCN over RGB frames producing a text-probability
  heatmap at 1/4 resolution (DB-style shrunken-region target). Whole-batch
  one-jit inference; boxes come from connected components on host.
- ``TextRecognizer`` — a CRNN: conv feature pyramid collapsing height,
  width-preserving sequence features, and per-timestep charset logits
  decoded with greedy CTC.

Both are trained on synthetically rendered text (models/ocr_train.py) since
the image has zero egress; the checkpoint ships under ``weights/`` via the
registry. Detection drives the artificial-text filter stage; recognition is
exposed for OCR consumers (reference PaddleOCRModel.recognize parity).
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# CTC charset: blank=0, then printable chars OCR must distinguish
CHARSET = " ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789.,:!?-'&%$#@()/"
BLANK_ID = 0


def char_to_id(c: str) -> int:
    i = CHARSET.find(c)
    return i + 1 if i >= 0 else CHARSET.find("?") + 1


def encode_text(text: str) -> list[int]:
    return [char_to_id(c) for c in text]


def decode_ids(ids: list[int]) -> str:
    return "".join(CHARSET[i - 1] for i in ids if 1 <= i <= len(CHARSET))


@dataclass(frozen=True)
class DetectorConfig:
    height: int = 128
    width: int = 224
    base_filters: int = 16


class TextDetector(nn.Module):
    """FCN heatmap detector: uint8 [B, H, W, 3] -> logits [B, H/4, W/4]."""

    cfg: DetectorConfig = DetectorConfig()

    @nn.compact
    def __call__(self, frames_u8: jax.Array) -> jax.Array:
        f = self.cfg.base_filters
        x = frames_u8.astype(jnp.float32) / 127.5 - 1.0
        x = nn.Conv(f, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = nn.Conv(2 * f, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        # dilated context without further downsampling (text strokes are
        # thin; receptive field matters more than depth)
        x = nn.Conv(2 * f, (3, 3), kernel_dilation=(2, 2))(x)
        x = nn.relu(x)
        x = nn.Conv(2 * f, (3, 3), kernel_dilation=(4, 4))(x)
        x = nn.relu(x)
        x = nn.Conv(1, (1, 1))(x)
        return x[..., 0]


@dataclass(frozen=True)
class RecognizerConfig:
    height: int = 32
    max_width: int = 160
    base_filters: int = 24
    hidden: int = 96

    @property
    def num_classes(self) -> int:
        return len(CHARSET) + 1  # + blank

    @property
    def seq_len(self) -> int:
        return self.max_width // 4


class TextRecognizer(nn.Module):
    """CRNN: uint8 crops [B, 32, W, 3] -> logits [B, W/4, num_classes]."""

    cfg: RecognizerConfig = RecognizerConfig()

    @nn.compact
    def __call__(self, crops_u8: jax.Array) -> jax.Array:
        f = self.cfg.base_filters
        x = crops_u8.astype(jnp.float32) / 127.5 - 1.0
        x = nn.Conv(f, (3, 3), strides=(2, 2))(x)  # H/2, W/2
        x = nn.relu(x)
        x = nn.Conv(2 * f, (3, 3), strides=(2, 2))(x)  # H/4, W/4
        x = nn.relu(x)
        x = nn.Conv(2 * f, (3, 3))(x)
        x = nn.relu(x)
        # collapse height into channels -> width-major sequence
        b, h, w, c = x.shape
        seq = x.transpose(0, 2, 1, 3).reshape(b, w, h * c)
        seq = nn.Dense(self.cfg.hidden)(seq)
        seq = nn.relu(seq)
        # bidirectional context via two causal conv passes (cheap BiLSTM
        # stand-in that stays a single fused program on the MXU)
        fwd = nn.Conv(self.cfg.hidden, (5,), padding="SAME")(seq)
        seq = nn.relu(fwd) + seq
        return nn.Dense(self.cfg.num_classes)(seq)


def greedy_ctc_decode(logits: np.ndarray) -> list[str]:
    """[B, T, K] -> best-path decoded strings (collapse repeats, drop blank)."""
    out = []
    ids = np.asarray(logits).argmax(axis=-1)
    for row in ids:
        collapsed = []
        prev = -1
        for i in row:
            if i != prev and i != BLANK_ID:
                collapsed.append(int(i))
            prev = i
        out.append(decode_ids(collapsed))
    return out


@dataclass
class TextBox:
    x0: int
    y0: int
    x1: int
    y1: int
    score: float


def heatmap_to_boxes(
    prob: np.ndarray, *, threshold: float = 0.5, scale: int = 4, min_area: int = 6
) -> list[TextBox]:
    """Connected components over a thresholded heatmap -> frame-space boxes
    (host-side; the heatmap is tiny). ``scale`` maps heatmap px -> frame px."""
    import cv2

    mask = (prob > threshold).astype(np.uint8)
    n, labels, stats, _ = cv2.connectedComponentsWithStats(mask, connectivity=8)
    boxes = []
    for i in range(1, n):
        x, y, w, h, area = stats[i]
        if area < min_area:
            continue
        comp_scores = prob[labels == i]
        boxes.append(
            TextBox(
                int(x * scale),
                int(y * scale),
                int((x + w) * scale),
                int((y + h) * scale),
                float(comp_scores.mean()),
            )
        )
    return boxes


class OcrModel:
    """Detector + recognizer behind one interface (reference PaddleOCRModel
    capability: detect boxes, recognize text, score overlay coverage)."""

    def __init__(
        self,
        det_cfg: DetectorConfig = DetectorConfig(),
        rec_cfg: RecognizerConfig = RecognizerConfig(),
    ) -> None:
        self.det_cfg = det_cfg
        self.rec_cfg = rec_cfg
        self.detector = TextDetector(det_cfg)
        self.recognizer = TextRecognizer(rec_cfg)
        self._det_params = None
        self._rec_params = None
        self._det_apply = None
        self._rec_apply = None

    def setup(self, *, require_weights: bool = False) -> None:
        """``require_weights=True`` raises when trained checkpoints are
        missing/mismatched — callers that would fail open on random logits
        (the text filter) must use it."""
        from cosmos_curate_tpu.models import registry

        self._det_params = registry.load_params(
            "ocr-detector-tpu",
            lambda seed: self.detector.init(
                jax.random.PRNGKey(seed),
                jnp.zeros((1, self.det_cfg.height, self.det_cfg.width, 3), jnp.uint8),
            ),
            require=require_weights,
        )
        self._rec_params = registry.load_params(
            "ocr-recognizer-tpu",
            lambda seed: self.recognizer.init(
                jax.random.PRNGKey(seed),
                jnp.zeros((1, self.rec_cfg.height, self.rec_cfg.max_width, 3), jnp.uint8),
            ),
            require=require_weights,
        )
        self._det_apply = jax.jit(self.detector.apply)
        self._rec_apply = jax.jit(self.recognizer.apply)

    def _resize(self, frames, hw: tuple[int, int]) -> np.ndarray:
        """Accepts an array batch OR a list of differently-sized frames."""
        import cv2

        h, w = hw
        return np.stack([cv2.resize(np.asarray(f), (w, h)) for f in frames])

    def detect(self, frames: np.ndarray, *, threshold: float = 0.5) -> list[list[TextBox]]:
        """uint8 [B, H, W, 3] -> per-frame text boxes in model input space."""
        x = self._resize(frames, (self.det_cfg.height, self.det_cfg.width))
        prob = jax.nn.sigmoid(self._det_apply(self._det_params, jnp.asarray(x)))
        prob = np.asarray(prob)
        return [heatmap_to_boxes(p, threshold=threshold) for p in prob]

    def text_coverage(self, frames: np.ndarray, *, threshold: float = 0.5) -> float:
        """Max fraction of frame area covered by detected text — the filter
        stage's decision signal (reference uses box-area heuristics)."""
        x = self._resize(frames, (self.det_cfg.height, self.det_cfg.width))
        prob = jax.nn.sigmoid(self._det_apply(self._det_params, jnp.asarray(x)))
        cover = (prob > threshold).mean(axis=(1, 2))
        return float(np.asarray(cover).max())

    def recognize(self, crops: np.ndarray) -> list[str]:
        """uint8 [B, h, w, 3] text crops -> decoded strings."""
        x = self._resize(crops, (self.rec_cfg.height, self.rec_cfg.max_width))
        logits = self._rec_apply(self._rec_params, jnp.asarray(x))
        return greedy_ctc_decode(np.asarray(logits))

    def read(self, frame: np.ndarray, *, threshold: float = 0.5) -> list[tuple[TextBox, str]]:
        """Full OCR on one frame: detect boxes, recognize each crop."""
        (boxes,) = self.detect(frame[None], threshold=threshold)
        if not boxes:
            return []
        # boxes are in detector input space; map back to the frame
        fh, fw = frame.shape[:2]
        sy = fh / self.det_cfg.height
        sx = fw / self.det_cfg.width
        crops = []
        mapped = []
        for b in boxes:
            x0, y0 = max(0, int(b.x0 * sx)), max(0, int(b.y0 * sy))
            x1, y1 = min(fw, int(b.x1 * sx)), min(fh, int(b.y1 * sy))
            if x1 - x0 < 4 or y1 - y0 < 4:
                continue
            crops.append(frame[y0:y1, x0:x1])
            mapped.append(TextBox(x0, y0, x1, y1, b.score))
        if not crops:
            return []
        texts = self.recognize(crops)
        return list(zip(mapped, texts))
