"""SDK-free Hugging Face Hub file download for weight bootstrap.

Equivalent capability of the reference's hub pull
(cosmos_curate/core/utils/model_utils.py:596-700 — deployments outside a
pre-baked image bootstrap model weights from the hub): plain HTTPS GETs
against the hub's ``/{repo}/resolve/{revision}/{file}`` layout with

- streaming download + Range RESUME (a killed multi-GB pull continues
  instead of restarting),
- per-destination file lock + atomic rename (concurrent workers on one
  node pay the download once; readers never see a partial file),
- integrity: an explicit ``expected_sha256`` wins; otherwise the hub's
  ``X-Linked-ETag`` (the LFS sha256) is verified when present,
- ``HF_TOKEN`` bearer auth for gated repos,
- endpoint override via ``CURATE_HF_ENDPOINT``/``HF_ENDPOINT`` (tests run
  against a local fake; air-gapped mirrors work the same way).

The downloaded artifacts are the HF-native files (safetensors +
tokenizer); converting them into this framework's checkpoint format is
the converters' job (models/convert_*.py), wired through
``cli: models pull-hf``.
"""

from __future__ import annotations

import hashlib
import os
import urllib.error
import urllib.request
from pathlib import Path

from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_CHUNK = 8 * 1024 * 1024


class HubDownloadError(RuntimeError):
    pass


def hub_endpoint() -> str:
    return (
        os.environ.get("CURATE_HF_ENDPOINT")
        or os.environ.get("HF_ENDPOINT")
        or "https://huggingface.co"
    ).rstrip("/")


def hub_url(repo_id: str, filename: str, revision: str = "main") -> str:
    return f"{hub_endpoint()}/{repo_id}/resolve/{revision}/{filename}"


def _request(url: str, *, headers: dict[str, str]) -> urllib.request.Request:
    h = dict(headers)
    token = os.environ.get("HF_TOKEN", "")
    if token:
        h["Authorization"] = f"Bearer {token}"
    return urllib.request.Request(url, headers=h)


def download_file(
    repo_id: str,
    filename: str,
    dest: str | Path,
    *,
    revision: str = "main",
    expected_sha256: str = "",
    timeout: float = 60.0,
) -> Path:
    """Download one repo file to ``dest`` (resumable, locked, verified).
    Returns ``dest``; raises HubDownloadError on HTTP failure or an
    integrity mismatch (the partial file is kept for resume only when the
    bytes were sound)."""
    from cosmos_curate_tpu.utils.file_lock import file_lock

    dest = Path(dest)
    if dest.exists():
        # an existing file short-circuits the download but NOT an explicit
        # integrity request: re-running with --sha256 must actually verify
        if expected_sha256:
            _verify_file(dest, expected_sha256, label=str(dest))
        return dest
    dest.parent.mkdir(parents=True, exist_ok=True)
    url = hub_url(repo_id, filename, revision)
    tmp = dest.with_name(dest.name + ".part")
    with file_lock(dest.parent / f".{dest.name}.lock"):
        if dest.exists():  # another worker won while we waited
            return dest
        offset = tmp.stat().st_size if tmp.exists() else 0
        headers = {"Range": f"bytes={offset}-"} if offset else {}
        try:
            resp = urllib.request.urlopen(_request(url, headers=headers), timeout=timeout)
        except urllib.error.HTTPError as e:
            if e.code == 416 and offset:  # already fully downloaded
                resp = None
            else:
                raise HubDownloadError(
                    f"hub download failed for {url}: HTTP {e.code}"
                ) from e
        except urllib.error.URLError as e:
            raise HubDownloadError(f"hub unreachable for {url}: {e}") from e
        if resp is not None:
            with resp:
                if offset and resp.status != 206:
                    # server ignored the Range header: restart from zero
                    logger.info("resume unsupported for %s; restarting", url)
                    offset = 0
                mode = "ab" if offset else "wb"
                with tmp.open(mode) as fh:
                    while True:
                        chunk = resp.read(_CHUNK)
                        if not chunk:
                            break
                        fh.write(chunk)
                want = expected_sha256 or _linked_sha(resp.headers)
        else:
            want = expected_sha256
        if want:
            try:
                _verify_file(tmp, want, label=url)
            except HubDownloadError:
                tmp.unlink(missing_ok=True)  # corrupt: resume would keep it
                raise
        tmp.rename(dest)
    logger.info("pulled %s/%s@%s -> %s", repo_id, filename, revision, dest)
    return dest


def _verify_file(path: Path, want: str, *, label: str) -> None:
    digest = hashlib.sha256()
    with path.open("rb") as fh:
        while True:
            chunk = fh.read(_CHUNK)
            if not chunk:
                break
            digest.update(chunk)
    if digest.hexdigest() != want.lower():
        raise HubDownloadError(
            f"integrity check failed for {label}: "
            f"sha256 {digest.hexdigest()} != {want}"
        )


def _linked_sha(headers) -> str:
    """The hub serves LFS files with X-Linked-ETag: \"<sha256>\"."""
    etag = headers.get("X-Linked-ETag", "") or ""
    etag = etag.strip('"')
    return etag if len(etag) == 64 and all(c in "0123456789abcdef" for c in etag.lower()) else ""


def pull_repo_files(
    repo_id: str,
    filenames: list[str],
    dest_dir: str | Path,
    *,
    revision: str = "main",
    expected_sha256: dict[str, str] | None = None,
) -> list[Path]:
    """Download several files of one repo into ``dest_dir``, PRESERVING
    repo subpaths ('text_encoder/config.json' keeps its directory — two
    files sharing a basename must not collide)."""
    shas = expected_sha256 or {}
    return [
        download_file(
            repo_id, name, Path(dest_dir) / name, revision=revision,
            expected_sha256=shas.get(name, ""),
        )
        for name in filenames
    ]
