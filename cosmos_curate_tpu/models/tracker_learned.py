"""Learned single-object tracker (siamese appearance embedding), TPU-first.

The learned upgrade over models/tracker.py's NCC baseline, closing the
reference's SAM3-class tracking capability gap (cosmos_curate/models/sam3.py:41):
a small conv net embeds the prompted template and each frame's search
window; their cross-correlation (one conv on the MXU) yields a response map
whose peak is the object displacement — the classic fully-convolutional
siamese formulation (public SiamFC family). The WHOLE clip still runs as
one jitted ``lax.scan``: the embedder is inside the scan body, so there is
no per-frame Python and compile count stays O(template buckets).

Trained on synthetic moving-object clips with distractors and appearance
jitter (models/tracker_train.py); checkpoint ships under
``weights/tracker-siamese-tpu/``.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

STRIDE = 4


@dataclass(frozen=True)
class SiameseConfig:
    template_size: int = 32
    search_size: int = 64
    features: int = 32
    work_size: int = 128
    ema: float = 0.05  # template-embedding update rate


class EmbedNet(nn.Module):
    """Shared embedding tower: uint8-scaled [B, S, S, 3] -> [B, S/4, S/4, F]."""

    features: int = 32

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        f = self.features
        x = nn.Conv(f // 2, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = nn.Conv(f, (3, 3), strides=(2, 2))(x)
        x = nn.relu(x)
        x = nn.Conv(f, (3, 3))(x)
        # zero-mean per channel so correlation scores are shift-robust
        return x - x.mean(axis=(1, 2), keepdims=True)


def _prep(frames_u8) -> jax.Array:
    return frames_u8.astype(jnp.float32) / 127.5 - 1.0


@functools.partial(jax.jit, static_argnames=("cfg",))
def _siamese_scan(params, frames_u8, box0, cfg: SiameseConfig):
    """frames_u8 [T, S, S, 3] work-size clip; box0 [4] (cx, cy, w, h) in work
    coords. Returns (centers [T, 2], scores [T])."""
    net = EmbedNet(cfg.features)
    s = frames_u8.shape[1]
    ts, ss = cfg.template_size, cfg.search_size

    def crop(img, cx, cy, size):
        x0 = jnp.clip(cx - size // 2, 0, s - size).astype(jnp.int32)
        y0 = jnp.clip(cy - size // 2, 0, s - size).astype(jnp.int32)
        return jax.lax.dynamic_slice(img, (y0, x0, 0), (size, size, 3)), x0, y0

    cx0 = box0[0].astype(jnp.int32)
    cy0 = box0[1].astype(jnp.int32)
    patch0, tx0, ty0 = crop(frames_u8[0], cx0, cy0, ts)
    delta = jnp.stack(
        [cx0 - (tx0 + ts // 2), cy0 - (ty0 + ts // 2)]
    ).astype(jnp.float32)
    tfeat0 = net.apply(params, _prep(patch0)[None])[0]  # [ts/4, ts/4, F]

    def step(carry, frame):
        tfeat, cx, cy = carry
        window, wx0, wy0 = crop(frame, cx, cy, ss)
        sfeat = net.apply(params, _prep(window)[None])[0]  # [ss/4, ss/4, F]
        resp = jax.lax.conv_general_dilated(
            sfeat.transpose(2, 0, 1)[None],
            tfeat.transpose(2, 0, 1)[None].transpose(1, 0, 2, 3),
            window_strides=(1, 1),
            padding="VALID",
            feature_group_count=cfg.features,
        ).sum(axis=1)[0]
        idx = jnp.argmax(resp)
        dy, dx = jnp.unravel_index(idx, resp.shape)
        score = resp.reshape(-1)[idx] / (tfeat.shape[0] * tfeat.shape[1] * cfg.features)
        # feature-map peak -> window pixel -> frame pixel
        ncx = wx0 + (dx + tfeat.shape[1] // 2) * STRIDE + STRIDE // 2
        ncy = wy0 + (dy + tfeat.shape[0] // 2) * STRIDE + STRIDE // 2
        new_patch, _, _ = crop(frame, ncx, ncy, ts)
        nfeat = net.apply(params, _prep(new_patch)[None])[0]
        tfeat = (1.0 - cfg.ema) * tfeat + cfg.ema * nfeat
        return (tfeat, ncx, ncy), (jnp.stack([ncx, ncy]), score)

    (_, _, _), (centers, scores) = jax.lax.scan(step, (tfeat0, cx0, cy0), frames_u8)
    return centers.astype(jnp.float32) + delta[None, :], scores


class SiameseTracker:
    """Learned drop-in for TemplateTracker (same track() surface)."""

    def __init__(self, cfg: SiameseConfig = SiameseConfig()) -> None:
        self.cfg = cfg
        self.net = EmbedNet(cfg.features)
        self._params = None

    def setup(self, *, require_weights: bool = False) -> None:
        from cosmos_curate_tpu.models import registry

        self._params = registry.load_params(
            "tracker-siamese-tpu",
            lambda seed: self.net.init(
                jax.random.PRNGKey(seed),
                jnp.zeros((1, self.cfg.template_size, self.cfg.template_size, 3)),
            ),
            require=require_weights,
        )

    def track(
        self, frames: np.ndarray, box_xywh: tuple[float, float, float, float]
    ) -> tuple[np.ndarray, np.ndarray]:
        """frames uint8 [T, H, W, 3]; box (x, y, w, h) on frame 0. Returns
        (boxes [T, 4] xywh original coords, scores [T])."""
        from cosmos_curate_tpu.models.tracker import host_track

        if self._params is None:
            self.setup()

        def scan(padded, box0):
            return _siamese_scan(self._params, padded, jnp.asarray(box0), self.cfg)

        return host_track(frames, box_xywh, self.cfg.work_size, scan)
