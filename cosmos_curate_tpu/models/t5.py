"""Text encoder for caption embeddings.

Equivalent capability of the reference's T5 encoder
(cosmos_curate/models/t5_encoder.py:80 — google-t5/t5-11b encodes captions
into per-token embeddings packaged as ``EncodedSample`` for webdataset /
cosmos-predict training). Our own Flax encoder-only transformer (byte-level
tokens, learned positions); the interface — captions in, padded per-token
embeddings + mask out — matches what the dataset writers consume.
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.models import registry
from cosmos_curate_tpu.models.batching import pad_batch
from cosmos_curate_tpu.models.layers import TransformerBlock
from cosmos_curate_tpu.models.tokenizer import ByteTokenizer


@dataclass(frozen=True)
class T5Config:
    vocab: int = 512
    dim: int = 512
    layers: int = 8
    heads: int = 8
    max_len: int = 512


T5_BASE = T5Config()
T5_TINY_TEST = T5Config(dim=32, layers=1, heads=2, max_len=64)


@dataclass
class EncodedSample:
    """Per-caption encoding (reference t5_encoder.py:56)."""

    text: str
    tokens: np.ndarray  # int32 [T]
    embedding: np.ndarray  # float32 [T, dim]
    mask: np.ndarray  # bool [T]


class TextEncoder(nn.Module):
    cfg: T5Config

    @nn.compact
    def __call__(self, ids, mask):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab, cfg.dim, param_dtype=jnp.float32, dtype=jnp.bfloat16)(ids)
        pos = self.param("pos", nn.initializers.normal(0.02), (1, cfg.max_len, cfg.dim), jnp.float32)
        x = x + pos[:, : ids.shape[1]].astype(x.dtype)
        attn_mask = (mask[:, None, None, :] & mask[:, None, :, None])
        for i in range(cfg.layers):
            x = TransformerBlock(cfg.heads, cfg.dim // cfg.heads, name=f"b{i}")(x, attn_mask)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


class T5EncoderTPU(ModelInterface):
    MODEL_ID = "t5-encoder-tpu"

    def __init__(self, cfg: T5Config = T5_BASE) -> None:
        self.cfg = cfg
        self.tokenizer = ByteTokenizer()
        self._apply = None
        self._params = None

    @property
    def model_id_names(self) -> list[str]:
        return [self.MODEL_ID]

    def setup(self) -> None:
        model = TextEncoder(self.cfg)

        def init(seed: int):
            ids = jnp.zeros((1, 8), jnp.int32)
            return model.init(jax.random.PRNGKey(seed), ids, jnp.ones((1, 8), bool))

        self._params = registry.load_params(self.MODEL_ID, init)
        self._apply = jax.jit(model.apply)

    def encode(self, texts: list[str]) -> list[EncodedSample]:
        if self._apply is None:
            raise RuntimeError("call setup() first")
        if not texts:
            return []
        tok = self.tokenizer
        encoded = [tok.encode(t)[: self.cfg.max_len] for t in texts]
        max_t = max(len(e) for e in encoded)
        # pad T to pow2 and B to pow2 — static shapes for XLA
        from cosmos_curate_tpu.models.batching import next_pow2

        t_pad = min(next_pow2(max_t), self.cfg.max_len)
        ids = np.full((len(texts), t_pad), tok.pad_id, np.int32)
        mask = np.zeros((len(texts), t_pad), bool)
        for i, e in enumerate(encoded):
            ids[i, : len(e)] = e
            mask[i, : len(e)] = True
        ids_p, n = pad_batch(ids)
        mask_p, _ = pad_batch(mask)
        emb = np.asarray(self._apply(self._params, ids_p, mask_p))[:n]
        return [
            EncodedSample(
                text=texts[i],
                tokens=ids[i][mask[i]],
                embedding=emb[i][mask[i]],
                mask=mask[i],
            )
            for i in range(n)
        ]
