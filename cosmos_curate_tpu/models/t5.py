"""T5 text encoder for caption embeddings.

Equivalent capability of the reference's T5 encoder
(cosmos_curate/models/t5_encoder.py:80 — google-t5/t5-11b encodes captions
into per-token embeddings packaged as ``EncodedSample`` for webdataset /
cosmos-predict training). This is a faithful T5 encoder stack (public
architecture: RMS layer norm, relative-position-bucket attention bias shared
across layers, unscaled attention, bias-free projections), so real HF T5
checkpoints convert exactly — ``models/convert_hf.convert_t5_encoder`` with
a parity test (tests/models/test_convert_hf.py).

TPU-first: one jitted forward over power-of-two padded batches; weight
matrices carry Megatron TP annotations via ``models/layers.dense``.
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.models import registry
from cosmos_curate_tpu.models.layers import dense


@dataclass(frozen=True)
class T5Config:
    vocab: int = 512  # byte-level default; converted checkpoints use 32128
    dim: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    layers: int = 8
    heads: int = 8
    max_len: int = 512
    num_buckets: int = 32
    max_distance: int = 128
    act: str = "relu"  # "relu" (t5 v1.0) | "gated-gelu" (v1.1 / flan)
    ln_eps: float = 1e-6


T5_BASE = T5Config()
# Real HF checkpoint shapes (google-t5/t5-small). To serve a converted
# checkpoint, construct ``T5EncoderTPU(T5_SMALL, tokenizer=...)`` with a
# SentencePiece-compatible tokenizer — the default ByteTokenizer's ids do
# NOT correspond to T5's vocabulary, and the default T5_BASE tree will not
# structure-match a converted t5-small msgpack.
T5_SMALL = T5Config(vocab=32128, dim=512, d_kv=64, d_ff=2048, layers=6, heads=8)
T5_TINY_TEST = T5Config(vocab=512, dim=32, d_kv=16, d_ff=64, layers=1, heads=2, max_len=64)


@dataclass
class EncodedSample:
    """Per-caption encoding (reference t5_encoder.py:56)."""

    text: str
    tokens: np.ndarray  # int32 [T]
    embedding: np.ndarray  # float32 [T, dim]
    mask: np.ndarray  # bool [T]


class T5LayerNorm(nn.Module):
    """RMS norm, weight-only, computed in f32 (T5 convention)."""

    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        w = self.param("weight", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        return (w * x.astype(jnp.float32) * jax.lax.rsqrt(var + self.eps)).astype(x.dtype)


def t5_relative_position_bucket(
    relative_position, *, num_buckets: int = 32, max_distance: int = 128
):
    """Bidirectional T5 bucketing of (key_pos - query_pos) distances
    (public algorithm, T5 paper / HF modeling_t5)."""
    nb = num_buckets // 2
    buckets = jnp.where(relative_position > 0, nb, 0)
    rel = jnp.abs(relative_position)
    max_exact = nb // 2
    is_small = rel < max_exact
    large = max_exact + (
        jnp.log(jnp.maximum(rel, 1).astype(jnp.float32) / max_exact)
        / float(np.log(max_distance / max_exact))
        * (nb - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, nb - 1)
    return buckets + jnp.where(is_small, rel, large)


class T5RelativeBias(nn.Module):
    cfg: T5Config

    @nn.compact
    def __call__(self, q_len: int, k_len: int):
        """-> [1, heads, q_len, k_len] attention bias."""
        table = self.param(
            "embedding",
            nn.initializers.normal(0.02),
            (self.cfg.num_buckets, self.cfg.heads),
            jnp.float32,
        )
        ctx = jnp.arange(q_len)[:, None]
        mem = jnp.arange(k_len)[None, :]
        buckets = t5_relative_position_bucket(
            mem - ctx,
            num_buckets=self.cfg.num_buckets,
            max_distance=self.cfg.max_distance,
        )
        return table[buckets].transpose(2, 0, 1)[None]


class T5Attention(nn.Module):
    """T5 self-attention: no QK scaling, no biases, additive position bias."""

    cfg: T5Config
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, bias):
        cfg = self.cfg
        inner = cfg.heads * cfg.d_kv
        b, s, _ = x.shape
        q = dense(inner, "out", name="q", use_bias=False, dtype=self.dtype)(x)
        k = dense(inner, "out", name="k", use_bias=False, dtype=self.dtype)(x)
        v = dense(inner, "out", name="v", use_bias=False, dtype=self.dtype)(x)
        q = q.reshape(b, s, cfg.heads, cfg.d_kv)
        k = k.reshape(b, s, cfg.heads, cfg.d_kv)
        v = v.reshape(b, s, cfg.heads, cfg.d_kv)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) + bias
        probs = jax.nn.softmax(logits, axis=-1).astype(self.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, s, inner)
        return dense(cfg.dim, "in", name="o", use_bias=False, dtype=self.dtype)(out)


class T5FF(nn.Module):
    cfg: T5Config
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        if cfg.act == "gated-gelu":
            g = nn.gelu(
                dense(cfg.d_ff, "out", name="wi_0", use_bias=False, dtype=self.dtype)(x),
                approximate=False,
            )
            h = g * dense(cfg.d_ff, "out", name="wi_1", use_bias=False, dtype=self.dtype)(x)
        else:
            h = nn.relu(
                dense(cfg.d_ff, "out", name="wi", use_bias=False, dtype=self.dtype)(x)
            )
        return dense(cfg.dim, "in", name="wo", use_bias=False, dtype=self.dtype)(h)


class T5EncoderBlock(nn.Module):
    cfg: T5Config
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, bias):
        y = T5LayerNorm(eps=self.cfg.ln_eps, name="ln1")(x)
        x = x + T5Attention(self.cfg, dtype=self.dtype, name="attn")(y, bias)
        y = T5LayerNorm(eps=self.cfg.ln_eps, name="ln2")(x)
        x = x + T5FF(self.cfg, dtype=self.dtype, name="mlp")(y)
        return x


class T5Encoder(nn.Module):
    """ids [B, T], mask [B, T] bool -> per-token embeddings [B, T, dim]."""

    cfg: T5Config
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, ids, mask):
        cfg = self.cfg
        x = nn.Embed(
            cfg.vocab, cfg.dim, param_dtype=jnp.float32, dtype=self.dtype, name="shared"
        )(ids)
        s = ids.shape[1]
        bias = T5RelativeBias(cfg, name="rel_bias")(s, s)
        # key-side padding mask, additive (HF's extended attention mask)
        bias = bias + jnp.where(mask[:, None, None, :], 0.0, -1e9)
        for i in range(cfg.layers):
            x = T5EncoderBlock(cfg, dtype=self.dtype, name=f"block_{i}")(x, bias)
        x = T5LayerNorm(eps=cfg.ln_eps, name="ln_final")(x)
        return x.astype(jnp.float32)


# Backwards-compatible alias (the pre-T5-parity encoder class name).
TextEncoder = T5Encoder


class T5EncoderTPU(ModelInterface):
    MODEL_ID = "t5-encoder-tpu"

    def __init__(self, cfg: T5Config = T5_BASE, *, tokenizer=None) -> None:
        self.cfg = cfg
        # resolution happens in setup(): remote weight/tokenizer staging
        # runs there, and the guards need to see the staged state
        self.tokenizer = tokenizer
        self._apply = None
        self._params = None
        self._pipeline = None

    def _resolve_tokenizer(self):
        """Staged ``tokenizer.json`` (exact T5 ids) wins; the byte fallback
        serves random-init testing ONLY. Guards (mirroring the hf_chat
        flavors' rule that wrong ids must fail loudly, not embed garbage):

        - a staged checkpoint WITHOUT its tokenizer.json refuses to serve
          (the embedding table is indexed by sentencepiece ids; pass
          ``tokenizer=ByteTokenizer()`` explicitly to override);
        - a tokenizer whose ids exceed ``cfg.vocab`` refuses (XLA's
          out-of-bounds gather clamps silently)."""
        from cosmos_curate_tpu.models import registry as _registry
        from cosmos_curate_tpu.models.tokenizer import ByteTokenizer, t5_tokenizer

        # pull the remote checkpoint FIRST: the staged-checkpoint guard
        # below must see the same state load_params will (a fresh node
        # would otherwise accept the byte fallback, then pull the real
        # checkpoint and serve wrong ids); the sidecar pull happens only
        # when a converted checkpoint is actually in play, so repo-native
        # deployments never pay doomed GETs
        try:
            _registry.maybe_pull_remote_weights(self.MODEL_ID)
        except _registry.WeightsIntegrityError:
            raise
        except Exception:
            pass  # load_params retries and reports; resolution uses local state
        if _registry.find_checkpoint(self.MODEL_ID):
            _registry.maybe_pull_tokenizer_files(self.MODEL_ID)
        tok = t5_tokenizer(self.MODEL_ID)
        if isinstance(tok, ByteTokenizer) and _registry.find_checkpoint(self.MODEL_ID):
            raise FileNotFoundError(
                f"{self.MODEL_ID} has a staged checkpoint but no "
                f"tokenizer.json — byte-level ids would address wrong "
                f"embedding rows; stage the checkpoint's tokenizer.json "
                f"(or pass tokenizer= explicitly for a byte-trained model)"
            )
        if tok.vocab_size > self.cfg.vocab:
            raise ValueError(
                f"staged tokenizer has {tok.vocab_size} ids but the config "
                f"embeds only {self.cfg.vocab} — use the matching T5Config "
                f"(e.g. T5_SMALL for converted checkpoints)"
            )
        return tok

    @property
    def model_id_names(self) -> list[str]:
        return [self.MODEL_ID]

    def setup(self) -> None:
        if self.tokenizer is None:
            self.tokenizer = self._resolve_tokenizer()
        model = T5Encoder(self.cfg)

        def init(seed: int):
            ids = jnp.zeros((1, 8), jnp.int32)
            return model.init(jax.random.PRNGKey(seed), ids, jnp.ones((1, 8), bool))

        self._params = registry.load_params(self.MODEL_ID, init)
        from cosmos_curate_tpu.models.device_pipeline import DevicePipeline, donate_kwargs

        self._apply = jax.jit(model.apply, **donate_kwargs(1, 2))
        self._pipeline = DevicePipeline("t5-encode", self._apply)

    def encode(self, texts: list[str]) -> list[EncodedSample]:
        if self._apply is None:
            raise RuntimeError("call setup() first")
        if not texts:
            return []
        tok = self.tokenizer
        def _truncate(ids: list[int]) -> list[int]:
            if len(ids) <= self.cfg.max_len:
                return ids
            out = ids[: self.cfg.max_len]
            # HF fast tokenizers truncate BEFORE post-processing, so the
            # final special token (</s>) survives; preserve that here
            if ids[-1] == tok.eos_id and out[-1] != tok.eos_id:
                out[-1] = tok.eos_id
            return out

        encoded = [_truncate(tok.encode(t)) for t in texts]
        max_t = max(len(e) for e in encoded)
        # pad T to pow2 and B to pow2 — static shapes for XLA
        from cosmos_curate_tpu.models.batching import next_pow2

        t_pad = min(next_pow2(max_t), self.cfg.max_len)
        ids = np.full((len(texts), t_pad), tok.pad_id, np.int32)
        mask = np.zeros((len(texts), t_pad), bool)
        for i, e in enumerate(encoded):
            ids[i, : len(e)] = e
            mask[i, : len(e)] = True
        n = len(texts)
        # batch axis bucketing + async dispatch via the shared pipeline
        # (ids and mask pad together along axis 0)
        emb = self._pipeline.run(self._params, ids, mask)
        return [
            EncodedSample(
                text=texts[i],
                tokens=ids[i][mask[i]],
                embedding=emb[i][mask[i]],
                mask=mask[i],
            )
            for i in range(n)
        ]
