"""Synthetic-data training for the super-resolution network.

The reference ships SeedVR2's pretrained diffusion SR
(cosmos_curate/pipelines/video/super_resolution/); this image has no
network egress, so a functional (non-random) SR checkpoint comes from
training our residual SRNet (models/super_resolution.py) on synthesized
LR→HR pairs: crisp procedural textures (edges, text-like glyphs, gradients,
checkers) downsampled with the same bilinear kernel the model's residual
base uses — the net learns exactly the detail the base loses. The trained
checkpoint is staged through the registry (commit under
``weights/super-resolution-tpu/``); staging a converted real checkpoint
under $CURATE_MODEL_WEIGHTS_DIR still wins.

TPU-first: one jitted L1-loss train step (conv-heavy → MXU); synthesis on
host numpy.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.models.super_resolution import SR_BASE, SRConfig, SRNet
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _texture(rng: np.random.Generator, h: int, w: int) -> np.ndarray:
    """One crisp HR frame [h, w, 3] float32 in [0, 1] with high-frequency
    content worth recovering."""
    kind = rng.integers(0, 4)
    img = np.zeros((h, w, 3), np.float32)
    if kind == 0:  # random oriented edges
        img += rng.uniform(0.1, 0.9, 3)
        for _ in range(6):
            x0, y0 = rng.integers(0, w), rng.integers(0, h)
            angle = rng.uniform(0, np.pi)
            yy, xx = np.mgrid[0:h, 0:w]
            side = (xx - x0) * np.cos(angle) + (yy - y0) * np.sin(angle) > 0
            img[side] = rng.uniform(0, 1, 3)
    elif kind == 1:  # checkerboard at random phase/scale
        s = int(rng.integers(2, 6))
        yy, xx = np.mgrid[0:h, 0:w]
        mask = ((xx // s) + (yy // s)) % 2 == 0
        a, b = rng.uniform(0, 1, (2, 3))
        img[mask] = a
        img[~mask] = b
    elif kind == 2:  # text-like glyph strokes
        img += rng.uniform(0.6, 1.0, 3)
        ink = rng.uniform(0.0, 0.3, 3)
        for _ in range(10):
            x0, y0 = rng.integers(0, w - 6), rng.integers(0, h - 6)
            lw = int(rng.integers(1, 3))
            if rng.random() < 0.5:
                img[y0 : y0 + 6, x0 : x0 + lw] = ink
            else:
                img[y0 : y0 + lw, x0 : x0 + 6] = ink
    else:  # smooth gradient + sharp dots
        yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
        img += (xx / w)[..., None] * rng.uniform(0.3, 1.0, 3)
        for _ in range(12):
            x0, y0 = rng.integers(1, w - 1), rng.integers(1, h - 1)
            img[y0, x0] = rng.uniform(0, 1, 3)
    return np.clip(img, 0.0, 1.0)


def synthesize_batch(
    rng: np.random.Generator, batch: int, hr: int, scale: int
) -> tuple[np.ndarray, np.ndarray]:
    """(lr_u8 [B, hr/scale, hr/scale, 3], hr_u8 [B, hr, hr, 3])."""
    import cv2

    lr_size = hr // scale
    hrs = np.empty((batch, hr, hr, 3), np.uint8)
    lrs = np.empty((batch, lr_size, lr_size, 3), np.uint8)
    for i in range(batch):
        img = _texture(rng, hr, hr)
        hrs[i] = (img * 255).astype(np.uint8)
        lrs[i] = (
            cv2.resize(img, (lr_size, lr_size), interpolation=cv2.INTER_LINEAR) * 255
        ).astype(np.uint8)
    return lrs, hrs


def train(
    cfg: SRConfig = SR_BASE,
    *,
    steps: int = 500,
    batch: int = 16,
    hr_size: int = 64,
    lr: float = 2e-4,
    seed: int = 0,
    log_every: int = 100,
):
    import jax
    import jax.numpy as jnp
    import optax

    model = SRNet(cfg)
    rng = np.random.default_rng(seed)
    lrs0, _ = synthesize_batch(rng, batch, hr_size, cfg.scale)
    params = model.init(jax.random.PRNGKey(seed), jnp.asarray(lrs0[:1]))
    opt = optax.adam(lr)
    opt_state = opt.init(params)

    def loss_fn(p, lr_u8, hr_u8):
        # vmap over the batch: the model is written per-clip [T, H, W, 3].
        # float_out: gradients through the uint8 output cast are zero.
        out = jax.vmap(lambda x: model.apply(p, x[None], float_out=True)[0])(lr_u8)
        return jnp.abs(out - hr_u8.astype(jnp.float32) / 255.0).mean()

    @jax.jit
    def step(params, opt_state, lr_u8, hr_u8):
        loss, grads = jax.value_and_grad(loss_fn)(params, lr_u8, hr_u8)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    loss = None
    for i in range(steps):
        lrs, hrs = synthesize_batch(rng, batch, hr_size, cfg.scale)
        params, opt_state, loss = step(params, opt_state, jnp.asarray(lrs), jnp.asarray(hrs))
        if log_every and (i + 1) % log_every == 0:
            logger.info("sr train step %d/%d loss %.5f", i + 1, steps, float(loss))
    return params, float(loss) if loss is not None else float("nan")


def train_and_stage(
    cfg: SRConfig = SR_BASE,
    *,
    model_id: str = "super-resolution-tpu",
    out_dir: str | None = None,
    **train_kw,
):
    from cosmos_curate_tpu.models import registry

    params, loss = train(cfg, **train_kw)
    ckpt = registry.save_params(model_id, params, root=out_dir)
    logger.info("staged %s (final loss %.5f) at %s", model_id, loss, ckpt)
    return ckpt, loss


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description="Train SRNet on synthetic LR/HR pairs")
    ap.add_argument("--steps", type=int, default=500)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--hr-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out-dir", default=None, help="e.g. <repo>/weights to commit")
    a = ap.parse_args()
    train_and_stage(
        steps=a.steps, batch=a.batch, hr_size=a.hr_size, lr=a.lr, seed=a.seed,
        out_dir=a.out_dir,
    )
