"""CLIP-style image embedder + aesthetic head.

Equivalent capability of the reference's CLIP / aesthetics models
(cosmos_curate/models/clip.py:36-118 — openai/clip-vit-large-patch14
normalized image embeddings; models/aesthetics.py:30-155 — linear MLP over
CLIP embeddings). Our own Flax ViT backbone (models/vit.py) with L2-
normalized projection; the aesthetic scorer composes the two exactly like
the reference's ``CLIPAestheticScorer`` (models/clip_aesthetics.py:27).

TPU-first: preprocessing (resize + normalize) runs on-device inside the same
jit as the forward pass, so the host→device transfer is raw uint8 frames.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.models import registry
from cosmos_curate_tpu.models.vit import VIT_B_16, VIT_L_14, VIT_TINY_TEST, ViT, ViTConfig, preprocess_frames

import dataclasses

# The clip-vit-* registry slots hold OpenAI-CLIP-converted checkpoints
# (models/convert_hf.py), so their configs MUST carry CLIP's activation and
# layer-norm eps — a staged real checkpoint under plain gelu/1e-6 would run
# silently wrong.
_CONFIGS: dict[str, ViTConfig] = {
    "clip-vit-l14-tpu": dataclasses.replace(
        VIT_L_14, act="quick_gelu", ln_eps=1e-5, preprocess="clip"
    ),
    "clip-vit-b16-tpu": dataclasses.replace(
        VIT_B_16, act="quick_gelu", ln_eps=1e-5, preprocess="clip"
    ),
    "clip-vit-tiny-test": VIT_TINY_TEST,
}


class AestheticMLP(nn.Module):
    """Score head over image embeddings (reference: ttj/sac-logos-ava1-l14-
    linearMSE, models/aesthetics.py:44-53). The checkpoint is a pure Linear
    stack — Linear(768,1024)->...->Linear(16,1) with Dropout between (a
    no-op at inference) and NO activations; adding ReLUs would make staged
    real weights score incorrectly."""

    hidden: tuple[int, ...] = (1024, 128, 64, 16)

    @nn.compact
    def __call__(self, emb):
        x = emb.astype(jnp.float32)
        for i, h in enumerate(self.hidden):
            x = nn.Dense(h, name=f"fc{i}")(x)
        return nn.Dense(1, name="out")(x)[..., 0]


@functools.lru_cache(maxsize=8)
def _jitted_embed(cfg: ViTConfig):
    """Compiled embed shared across instances (see embedder._jitted_apply).
    Frames (arg 1) donated on TPU/GPU — no result alias, just HBM churn."""
    from cosmos_curate_tpu.models.device_pipeline import donate_kwargs

    model = ViT(cfg)
    size = cfg.image_size

    def embed(params, frames_u8):
        pixels = preprocess_frames(frames_u8, image_size=size, mode=cfg.preprocess)
        pooled, _ = model.apply(params, pixels)
        pooled = pooled.astype(jnp.float32)
        return pooled / jnp.linalg.norm(pooled, axis=-1, keepdims=True)

    return jax.jit(embed, **donate_kwargs(1))


class CLIPImageEmbeddings(ModelInterface):
    """Batched image -> normalized embedding on the local device/mesh."""

    def __init__(self, variant: str = "clip-vit-b16-tpu") -> None:
        if variant not in _CONFIGS:
            raise ValueError(f"unknown CLIP variant {variant!r}; have {sorted(_CONFIGS)}")
        self.variant = variant
        self.cfg = _CONFIGS[variant]
        self._apply = None
        self._params = None
        self._pipeline = None

    @property
    def model_id_names(self) -> list[str]:
        return [self.variant]

    @property
    def embedding_dim(self) -> int:
        return self.cfg.projection_dim

    def setup(self) -> None:
        model = ViT(self.cfg)
        size = self.cfg.image_size

        def init(seed: int):
            dummy = jnp.zeros((1, size, size, 3), jnp.uint8)
            return model.init(
                jax.random.PRNGKey(seed),
                preprocess_frames(dummy, image_size=size, mode=self.cfg.preprocess),
            )

        self._params = registry.load_params(self.variant, init)
        self._apply = _jitted_embed(self.cfg)
        from cosmos_curate_tpu.models.device_pipeline import DevicePipeline

        self._pipeline = DevicePipeline(f"clip/{self.variant}", self._apply)

    def encode_frames(self, frames_u8: np.ndarray) -> np.ndarray:
        """uint8 [N, H, W, 3] -> float32 [N, P] L2-normalized.

        Dispatched through the shared DevicePipeline: pow2 bucket
        micro-batches overlap H2D transfer, compute, and readback."""
        if self._pipeline is None:
            raise RuntimeError("call setup() first")
        return self._pipeline.run(self._params, frames_u8)


class AestheticScorer(ModelInterface):
    """Embeddings -> scalar score (compose with CLIPImageEmbeddings)."""

    MODEL_ID = "aesthetics-mlp-tpu"

    def __init__(self, embedding_dim: int = 512) -> None:
        self.embedding_dim = embedding_dim
        self._apply = None
        self._params = None
        self._pipeline = None

    @property
    def model_id_names(self) -> list[str]:
        return [self.MODEL_ID]

    def setup(self) -> None:
        model = AestheticMLP()

        def init(seed: int):
            return model.init(jax.random.PRNGKey(seed), jnp.zeros((1, self.embedding_dim)))

        self._params = registry.load_params(self.MODEL_ID, init)
        from cosmos_curate_tpu.models.device_pipeline import DevicePipeline, donate_kwargs

        self._apply = jax.jit(model.apply, **donate_kwargs(1))
        self._pipeline = DevicePipeline("aesthetic-mlp", self._apply)

    def score(self, embeddings: np.ndarray) -> np.ndarray:
        if self._pipeline is None:
            raise RuntimeError("call setup() first")
        return self._pipeline.run(self._params, embeddings)


class CLIPAestheticScorer(ModelInterface):
    """Fused frames -> aesthetic score (reference clip_aesthetics.py:27).

    Defaults to the L/14 tower: the reference aesthetic head is trained on
    768-d CLIP-L embeddings (models/aesthetics.py:69)."""

    def __init__(self, variant: str = "clip-vit-l14-tpu") -> None:
        self.clip = CLIPImageEmbeddings(variant)
        self.head = AestheticScorer(self.clip.embedding_dim)

    @property
    def model_id_names(self) -> list[str]:
        return self.clip.model_id_names + self.head.model_id_names

    def setup(self) -> None:
        self.clip.setup()
        self.head.setup()

    def score_frames(self, frames_u8: np.ndarray) -> np.ndarray:
        emb = self.clip.encode_frames(frames_u8)
        return self.head.score(emb)
