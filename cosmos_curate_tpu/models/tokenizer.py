"""Tokenizer for the caption engine.

No pretrained tokenizer assets exist in this image (zero egress), so the
default is a byte-level tokenizer (ids 0-255 = raw bytes + special tokens) —
hermetic, reversible, and vocab-compatible with the bundled VLM configs.
Real deployments plug an HF tokenizer through the same interface (the
engine only calls ``encode``/``decode``/special-token properties).
"""

from __future__ import annotations


class ByteTokenizer:
    PAD = 256
    BOS = 257
    EOS = 258
    IMAGE = 259  # placeholder id marking where vision tokens splice in

    vocab_size = 512  # padded to an MXU-friendly size

    def encode(self, text: str, *, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.BOS] if add_bos else []) + ids

    def decode(self, ids: list[int]) -> str:
        data = bytes(i for i in ids if i < 256)
        return data.decode("utf-8", errors="replace")

    @property
    def eos_id(self) -> int:
        return self.EOS

    @property
    def pad_id(self) -> int:
        return self.PAD
