"""Tokenizers for the caption engine.

Two implementations behind one interface (the engine only calls
``encode``/``decode``/``eos_id``/``pad_id``/``vocab_size``):

- ``ByteTokenizer``: ids 0-255 = raw bytes + special tokens — hermetic,
  reversible, always available (no assets).
- ``BPETokenizer``: self-contained byte-level BPE (reference capability:
  the caption models' BPE tokenizers loaded via HF processors,
  cosmos_curate/models/vllm_plugin.py:47). Train it on a corpus, save/load
  its own JSON, or load pretrained GPT-2-format ``vocab.json``+``merges.txt``
  (the file format Qwen2/GPT-2-family checkpoints ship) — no ``tokenizers``
  library needed, so real checkpoints' tokenizers work in this image.
"""

from __future__ import annotations

import json
import re
from pathlib import Path


class ByteTokenizer:
    PAD = 256
    BOS = 257
    EOS = 258
    IMAGE = 259  # placeholder id marking where vision tokens splice in

    vocab_size = 512  # padded to an MXU-friendly size

    def encode(self, text: str, *, add_bos: bool = True) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.BOS] if add_bos else []) + ids

    def decode_bytes(self, ids: list[int]) -> bytes:
        return bytes(i for i in ids if i < 256)

    def decode(self, ids: list[int]) -> str:
        return self.decode_bytes(ids).decode("utf-8", errors="replace")

    @property
    def eos_id(self) -> int:
        return self.EOS

    @property
    def pad_id(self) -> int:
        return self.PAD


# GPT-2's printable-unicode byte encoding (public algorithm): every byte maps
# to a visible character so vocab/merges files stay text. Needed to read
# pretrained GPT-2-format tokenizer files.
def _gpt2_byte_encoder() -> dict[int, str]:
    bs = list(range(ord("!"), ord("~") + 1)) + list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


# Simplified GPT-2-style pretokenizer: contractions, letter runs, digit
# runs, other-symbol runs, whitespace runs (kept with the following word).
_PRETOKEN_RE = re.compile(
    r"'(?:[sdmt]|ll|ve|re)| ?[^\W\d_]+| ?\d+| ?[^\s\w]+|\s+(?!\S)|\s+"
)


class BPETokenizer:
    """Byte-level BPE over the shared special-token layout.

    ids 0-255 are raw bytes (so any input is encodable), specials sit at
    256-259 (same slots as ``ByteTokenizer`` — engine configs need no
    change), merged tokens start at 260.
    """

    PAD = 256
    BOS = 257
    EOS = 258
    IMAGE = 259
    _FIRST_MERGE = 260

    def __init__(self, merges: list[tuple[int, int]] | None = None, vocab_size: int | None = None):
        self.merges: list[tuple[int, int]] = list(merges or [])
        self._ranks: dict[tuple[int, int], int] = {m: i for i, m in enumerate(self.merges)}
        self._token_bytes: list[bytes] = [bytes([i]) for i in range(256)] + [b""] * 4
        for a, b in self.merges:
            self._token_bytes.append(self._token_bytes[a] + self._token_bytes[b])
        self.vocab_size = vocab_size or max(512, self._FIRST_MERGE + len(self.merges))

    # -- core -----------------------------------------------------------
    def _apply_merges(self, ids: list[int]) -> list[int]:
        """Greedy lowest-rank-first merging (standard BPE apply)."""
        if len(ids) < 2:
            return ids
        while True:
            best_rank = None
            best_i = -1
            for i in range(len(ids) - 1):
                r = self._ranks.get((ids[i], ids[i + 1]))
                if r is not None and (best_rank is None or r < best_rank):
                    best_rank, best_i = r, i
            if best_rank is None:
                return ids
            ids = ids[:best_i] + [self._FIRST_MERGE + best_rank] + ids[best_i + 2 :]

    def encode(self, text: str, *, add_bos: bool = True) -> list[int]:
        out = [self.BOS] if add_bos else []
        for piece in _PRETOKEN_RE.findall(text):
            out.extend(self._apply_merges(list(piece.encode("utf-8"))))
        return out

    def decode_bytes(self, ids: list[int]) -> bytes:
        return b"".join(
            self._token_bytes[i] for i in ids if i < len(self._token_bytes) and i not in (
                self.PAD, self.BOS, self.EOS, self.IMAGE
            )
        )

    def decode(self, ids: list[int]) -> str:
        return self.decode_bytes(ids).decode("utf-8", errors="replace")

    @property
    def eos_id(self) -> int:
        return self.EOS

    @property
    def pad_id(self) -> int:
        return self.PAD

    # -- training -------------------------------------------------------
    @classmethod
    def train(cls, corpus: list[str], vocab_size: int = 512) -> "BPETokenizer":
        """Classic BPE: repeatedly merge the most frequent adjacent pair
        within pretokenized pieces until ``vocab_size`` is reached."""
        from collections import Counter

        pieces: Counter[tuple[int, ...]] = Counter()
        for text in corpus:
            for piece in _PRETOKEN_RE.findall(text):
                pieces[tuple(piece.encode("utf-8"))] += 1
        merges: list[tuple[int, int]] = []
        next_id = cls._FIRST_MERGE
        words = dict(pieces)
        while next_id < vocab_size:
            pair_counts: Counter[tuple[int, int]] = Counter()
            for word, freq in words.items():
                for i in range(len(word) - 1):
                    pair_counts[(word[i], word[i + 1])] += freq
            if not pair_counts:
                break
            (a, b), freq = pair_counts.most_common(1)[0]
            if freq < 2:
                break
            merges.append((a, b))
            new_words = {}
            for word, f in words.items():
                out = []
                i = 0
                while i < len(word):
                    if i + 1 < len(word) and word[i] == a and word[i + 1] == b:
                        out.append(next_id)
                        i += 2
                    else:
                        out.append(word[i])
                        i += 1
                new_words[tuple(out)] = new_words.get(tuple(out), 0) + f
            words = new_words
            next_id += 1
        return cls(merges, vocab_size=vocab_size)

    # -- persistence ----------------------------------------------------
    def save(self, path: str | Path) -> None:
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(
            json.dumps({"version": 1, "vocab_size": self.vocab_size, "merges": self.merges})
        )

    @classmethod
    def load(cls, path: str | Path) -> "BPETokenizer":
        data = json.loads(Path(path).read_text())
        return cls([tuple(m) for m in data["merges"]], vocab_size=data["vocab_size"])

    @classmethod
    def from_gpt2_files(cls, vocab_json: str | Path, merges_txt: str | Path) -> "BPETokenizer":
        """Load a pretrained GPT-2-format tokenizer (Qwen2/GPT-2 family ship
        ``vocab.json`` + ``merges.txt``). Token ids are remapped into our
        layout: the base alphabet collapses to raw bytes 0-255; each merge
        becomes one new id in file order, so text round-trips exactly (ids
        differ from HF's — for converted-checkpoint inference use
        HFVocabTokenizer, which preserves the HF ids the embedding rows
        are indexed by)."""
        vocab, pairs = _parse_gpt2_files(vocab_json, merges_txt)
        bytes_to_id: dict[bytes, int] = {bytes([i]): i for i in range(256)}
        merges: list[tuple[int, int]] = []
        next_id = cls._FIRST_MERGE
        for lb, rb in pairs:
            if lb not in bytes_to_id or rb not in bytes_to_id:
                continue  # merge over a token we never formed (defensive)
            merges.append((bytes_to_id[lb], bytes_to_id[rb]))
            bytes_to_id[lb + rb] = next_id
            next_id += 1
        return cls(merges, vocab_size=max(len(vocab) + 4, next_id))



def _parse_gpt2_files(vocab_json: str | Path, merges_txt: str | Path):
    """Shared GPT-2-format loader: (vocab as bytes->HF id, merge byte
    pairs in file order). Both tokenizer loaders build on this so the file
    parsing cannot drift between them."""
    decoder = {v: k for k, v in _gpt2_byte_encoder().items()}

    def to_bytes(token: str) -> bytes:
        return bytes(decoder[ch] for ch in token)

    raw = json.loads(Path(vocab_json).read_text())
    vocab = {to_bytes(tok): tid for tok, tid in raw.items()}
    pairs: list[tuple[bytes, bytes]] = []
    for line in Path(merges_txt).read_text().splitlines():
        if not line or line.startswith("#version"):
            continue
        left, _, right = line.partition(" ")
        pairs.append((to_bytes(left), to_bytes(right)))
    return vocab, pairs


class HFVocabTokenizer:
    """GPT-2-format BPE tokenizer that preserves the checkpoint's EXACT
    token ids — required when the LM weights are converted from HF (the
    embedding table is indexed by HF ids; `from_gpt2_files`' remapped ids
    would address the wrong rows).

    Byte-level BPE with HF's merge ranks and pre-tokenizer regex (Qwen2's
    cl100k-style split), plus the checkpoint's special tokens. Satisfies
    the CaptionEngine tokenizer protocol (encode/decode/decode_bytes/
    eos_id/pad_id/vocab_size).
    """

    # Qwen2/Qwen2.5 pre-tokenizer split (tokenizer.json pretokenizer)
    _PRETOK = (
        r"(?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}|"
        r" ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+"
    )

    def __init__(
        self,
        vocab: dict[bytes, int],
        merge_ranks: dict[tuple[bytes, bytes], int],
        *,
        specials: dict[str, int] | None = None,
        eos_token: str = "<|im_end|>",
        pad_token: str = "<|endoftext|>",
    ) -> None:
        import regex

        self._vocab = vocab
        self._ranks = merge_ranks
        self._id_to_bytes = {i: b for b, i in vocab.items()}
        self.specials = dict(specials or {})
        for name, sid in self.specials.items():
            self._id_to_bytes.setdefault(sid, b"")  # specials decode to ''
        self._eos = self.specials.get(eos_token)
        self._pad = self.specials.get(pad_token)
        if self._eos is None or self._pad is None:
            raise ValueError(
                f"specials must define {eos_token!r} and {pad_token!r}"
            )
        self._splitter = regex.compile(self._PRETOK)
        self.vocab_size = max(
            max(vocab.values()), *self.specials.values(), 0
        ) + 1

    @classmethod
    def from_gpt2_files(
        cls,
        vocab_json: str | Path,
        merges_txt: str | Path,
        *,
        specials: dict[str, int] | None = None,
        **kw,
    ) -> "HFVocabTokenizer":
        vocab, pairs = _parse_gpt2_files(vocab_json, merges_txt)
        ranks = {pair: rank for rank, pair in enumerate(pairs)}
        if specials is None:
            specials = QWEN2_SPECIAL_TOKENS
        return cls(vocab, ranks, specials=specials, **kw)

    def _bpe(self, chunk: bytes) -> list[int]:
        parts = [bytes([b]) for b in chunk]
        while len(parts) > 1:
            best, best_rank = -1, None
            for i in range(len(parts) - 1):
                r = self._ranks.get((parts[i], parts[i + 1]))
                if r is not None and (best_rank is None or r < best_rank):
                    best, best_rank = i, r
            if best_rank is None:
                break
            parts[best : best + 2] = [parts[best] + parts[best + 1]]
        out = []
        for p in parts:
            tid = self._vocab.get(p)
            if tid is None:
                # unmergeable byte outside the vocab (shouldn't happen for
                # byte-level vocabs, defensive)
                out.extend(self._vocab.get(bytes([b]), 0) for b in p)
            else:
                out.append(tid)
        return out

    def encode(self, text: str, *, add_bos: bool = False) -> list[int]:  # noqa: ARG002
        import unicodedata

        # HF's Qwen2 tokenizer NFC-normalizes before pre-tokenization
        # (prepare_for_tokenization) — required for the exact-id guarantee
        # on decomposed input (e.g. macOS-originated 'café')
        text = unicodedata.normalize("NFC", text)
        ids: list[int] = []
        for piece in self._splitter.findall(text):
            ids.extend(self._bpe(piece.encode("utf-8")))
        return ids

    def decode_bytes(self, ids: list[int]) -> bytes:
        return b"".join(self._id_to_bytes.get(i, b"") for i in ids)

    def decode(self, ids: list[int]) -> str:
        return self.decode_bytes(ids).decode("utf-8", errors="replace")

    @property
    def eos_id(self) -> int:
        return self._eos

    @property
    def pad_id(self) -> int:
        return self._pad


class HFJsonTokenizer:
    """Any HF checkpoint's EXACT tokenization via its ``tokenizer.json``,
    loaded through the ``tokenizers`` library (present in this image —
    unlike ``sentencepiece``, so T5/unigram checkpoints are servable too).
    Satisfies the engine tokenizer protocol (encode/decode/decode_bytes/
    eos_id/pad_id/vocab_size)."""

    def __init__(
        self,
        path: str | Path,
        *,
        eos_token: str = "</s>",
        pad_token: str = "<pad>",
        add_special_tokens: bool = True,
    ) -> None:
        from tokenizers import Tokenizer

        self._tok = Tokenizer.from_file(str(path))
        self._add_special = add_special_tokens
        eos = self._tok.token_to_id(eos_token)
        pad = self._tok.token_to_id(pad_token)
        if eos is None or pad is None:
            raise ValueError(
                f"tokenizer at {path} lacks {eos_token!r}/{pad_token!r}"
            )
        self._eos = eos
        self._pad = pad
        # max id + 1, NOT the token count: a tokenizer.json with sparse
        # added-token ids above the count would pass the T5 'tokenizer
        # exceeds cfg.vocab' guard yet emit out-of-range ids that XLA's
        # gather silently clamps — the exact failure that guard exists
        # to prevent
        vocab = self._tok.get_vocab(with_added_tokens=True)
        self.vocab_size = (max(vocab.values()) + 1) if vocab else 0

    def encode(self, text: str, *, add_bos: bool = False) -> list[int]:  # noqa: ARG002
        return self._tok.encode(
            text, add_special_tokens=self._add_special
        ).ids

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def decode_bytes(self, ids: list[int]) -> bytes:
        return self.decode(ids).encode("utf-8")

    @property
    def eos_id(self) -> int:
        return self._eos

    @property
    def pad_id(self) -> int:
        return self._pad


def t5_tokenizer(model_id: str = "t5-encoder-tpu"):
    """The T5 serving tokenizer: the checkpoint's staged ``tokenizer.json``
    when present (exact T5 sentencepiece ids — the embedding table is
    indexed by them), else the hermetic byte tokenizer with its documented
    random-init-only caveat."""
    from cosmos_curate_tpu.models.registry import find_model_file

    p = find_model_file(model_id, "tokenizer.json")
    if p is not None:
        return HFJsonTokenizer(p)
    return ByteTokenizer()


# Qwen2/Qwen2.5(-VL) special-token ids (tokenizer_config.json)
QWEN2_SPECIAL_TOKENS = {
    "<|endoftext|>": 151643,
    "<|im_start|>": 151644,
    "<|im_end|>": 151645,
    "<|vision_start|>": 151652,
    "<|vision_end|>": 151653,
    "<|vision_pad|>": 151654,
    "<|image_pad|>": 151655,
    "<|video_pad|>": 151656,
}


def default_caption_tokenizer():
    """The tokenizer caption-family stages use: a staged/committed trained
    BPE when present (word-level tokens, ~3-4x fewer decode steps), else the
    hermetic byte tokenizer. Both share the special-token layout, so the
    bundled VLM configs (vocab 512) serve either."""
    from cosmos_curate_tpu.models.registry import REPO_WEIGHTS_DIR, weights_root

    for root in (weights_root(), REPO_WEIGHTS_DIR):
        p = root / "caption-tokenizer" / "bpe.json"
        if p.exists():
            return BPETokenizer.load(p)
    return ByteTokenizer()


def train_caption_tokenizer(out_path: str | Path, *, vocab_size: int = 512) -> "BPETokenizer":
    """Train the caption BPE on the prompt library + a caption-style corpus
    (the text distribution the engine actually decodes)."""
    from cosmos_curate_tpu.models import prompts

    corpus = list(prompts.CAPTION_PROMPTS.values())
    corpus.extend(prompts.SEMANTIC_FILTER_PROMPTS.values())
    corpus.extend([prompts.REFINEMENT_PROMPT, prompts.ENHANCE_PROMPT])
    subjects = ["car", "person", "dog", "truck", "cyclist", "bus", "crowd", "robot arm"]
    scenes = ["a city street", "a highway", "a warehouse", "a park", "an intersection",
              "a parking lot", "a kitchen", "a factory floor"]
    actions = ["driving", "walking", "turning left", "stopping", "accelerating",
               "crossing", "picking up an object", "waiting"]
    for s in subjects:
        for sc in scenes:
            for a in actions:
                corpus.append(f"The video shows a {s} {a} in {sc}.")
    tok = BPETokenizer.train(corpus, vocab_size=vocab_size)
    tok.save(out_path)
    return tok


if __name__ == "__main__":
    import sys

    out = sys.argv[1] if len(sys.argv) > 1 else "weights/caption-tokenizer/bpe.json"
    t = train_caption_tokenizer(out)
    sample = "The video shows a red car driving in a city street."
    print(f"trained BPE: {len(t.merges)} merges -> {out}")
    print(f"sample: {len(t.encode(sample))} tokens vs {len(sample)+1} byte tokens")
