"""InternVideo2 video embedder — the reference's flagship embedding model.

Equivalent capability of the reference's InternVideo2 stage-2 video tower
(cosmos_curate/models/internvideo2_mm.py:334 `get_vid_feat` over the
vendored `PretrainInternVideo2`,
models/internvideo2_multi_modality/internvideo2/internvideo2.py:390): a
deep ViT over 3D tubelet patches with RMSNorm blocks, QK-normalization and
LayerScale, an attentive-pooling projector, and the multimodal
`vision_proj` head producing the l2-normalized 512-d contrastive embedding
the splitting pipeline stores per clip (dedup + shard consume it).

TPU-first re-design of the same architecture:

- the Conv3d patchify is a single dense matmul over host-reshaped tubelet
  patches (MXU-shaped, no conv lowering),
- attention stays head-grouped with fp32 softmax; the whole stack runs in
  a configurable compute dtype (bf16 on chip),
- the 3D sincos position table is bound as a parameter, so a converted
  checkpoint's (possibly temporally-interpolated) table loads verbatim,
- inference = `jit`ted pure function over a static [B, T, S, S, 3] shape;
  one compiled program per clip-batch bucket.

The training-only branches of the reference tower (masked-token path,
CLIP-teacher decoders `clip_decoder`/`final_clip_decoder`, the separate
`clip_pos_embed` table that only feeds those decoders) are deliberately
absent: `get_vid_feat` never uses them at inference. The converter
(convert_iv2.py) maps a real stage-2 checkpoint's remaining tensors 1:1.
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from cosmos_curate_tpu.core.model import ModelInterface
from cosmos_curate_tpu.models.layers import dense


@dataclass(frozen=True)
class IV2Config:
    img_size: int = 224
    patch_size: int = 14
    tubelet_size: int = 1
    num_frames: int = 8
    embed_dim: int = 1408
    depth: int = 40
    num_heads: int = 16
    mlp_ratio: float = 48 / 11
    qkv_bias: bool = False
    qk_normalization: bool = True
    # LayerScale init (checkpoint values load over it)
    init_values: float = 1e-5
    attn_pool_num_heads: int = 16
    clip_embed_dim: int = 768
    # the multimodal head's contrastive dim (internvideo2_mm "embed_dim")
    proj_dim: int = 512
    rms_eps: float = 1e-6
    ln_eps: float = 1e-5

    @property
    def grid(self) -> tuple[int, int, int]:
        hw = self.img_size // self.patch_size
        return (self.num_frames // self.tubelet_size, hw, hw)

    @property
    def num_patches(self) -> int:
        gt, gh, gw = self.grid
        return gt * gh * gw

    @property
    def patch_dim(self) -> int:
        return 3 * self.tubelet_size * self.patch_size * self.patch_size

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads


# InternVideo2-1B stage2 (internvideo2.py:696 pretrain_internvideo2_1b_
# patch14_224 + internvideo2_mm_config_model.json: clip_embed_dim 768,
# mm embed_dim 512)
IV2_1B = IV2Config()
IV2_TINY_TEST = IV2Config(
    img_size=28,
    patch_size=14,
    num_frames=2,
    embed_dim=32,
    depth=2,
    num_heads=4,
    mlp_ratio=2.0,
    attn_pool_num_heads=4,
    clip_embed_dim=16,
    proj_dim=8,
)

# ImageNet normalization (internvideo2_mm.py:378)
IV2_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IV2_STD = np.array([0.229, 0.224, 0.225], np.float32)


def sincos_1d(dim: int, positions: np.ndarray) -> np.ndarray:
    """Standard 1D sincos table (even dim): [len(positions), dim]."""
    omega = 1.0 / (10000 ** (np.arange(dim // 2, dtype=np.float64) / (dim / 2.0)))
    out = np.einsum("p,d->pd", positions.astype(np.float64), omega)
    return np.concatenate([np.sin(out), np.cos(out)], axis=1)


def sincos_3d_pos_embed(dim: int, grid: tuple[int, int, int]) -> np.ndarray:
    """3D sincos position table with cls row, matching the reference's
    `get_3d_sincos_pos_embed` split (pos_embed.py): dim//4 temporal +
    3*dim//4 spatial (2D sincos over h/w halves)."""
    gt, gh, gw = grid
    dim_t = dim // 4
    dim_s = dim - dim_t  # 2D part
    # 2D sincos, h-major row order. Reference quirk (pos_embed.py:40
    # "here w goes first"): the FIRST spatial half encodes the w
    # coordinate, the second the h coordinate.
    hh, ww = np.meshgrid(np.arange(gh), np.arange(gw), indexing="ij")
    emb_w = sincos_1d(dim_s // 2, ww.reshape(-1))
    emb_h = sincos_1d(dim_s // 2, hh.reshape(-1))
    spatial = np.concatenate([emb_w, emb_h], axis=1)  # [gh*gw, dim_s]
    temporal = sincos_1d(dim_t, np.arange(gt))  # [gt, dim_t]
    spatial = np.tile(spatial[None], (gt, 1, 1)).reshape(gt * gh * gw, dim_s)
    temporal = np.repeat(temporal, gh * gw, axis=0)
    table = np.concatenate([temporal, spatial], axis=1)
    return np.concatenate([np.zeros((1, dim)), table], axis=0).astype(np.float32)


def frames_to_tubelets(frames: jnp.ndarray, cfg: IV2Config) -> jnp.ndarray:
    """uint8/float [B, T, H, W, 3] -> [B, num_patches, patch_dim] tubelet
    vectors in (c, kt, kh, kw) element order — the flatten order of the
    reference Conv3d's weight, so the converter's kernel reshape is exact.
    Grid order is (t, h, w) row-major, matching the tower's
    `flatten(3).permute` token order."""
    b = frames.shape[0]
    gt, gh, gw = cfg.grid
    tub, p = cfg.tubelet_size, cfg.patch_size
    x = frames.astype(jnp.float32) / 255.0
    x = (x - IV2_MEAN) / IV2_STD
    x = x.reshape(b, gt, tub, gh, p, gw, p, 3)
    x = x.transpose(0, 1, 3, 5, 7, 2, 4, 6)  # [B, gt, gh, gw, c, tub, ph, pw]
    return x.reshape(b, cfg.num_patches, cfg.patch_dim)


class IV2RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (normed * scale).astype(x.dtype)


class IV2Block(nn.Module):
    cfg: IV2Config
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, n, c = x.shape
        h, d = cfg.num_heads, cfg.head_dim

        y = IV2RMSNorm(eps=cfg.rms_eps, name="ln1")(x)
        qkv = dense(3 * c, "out", name="qkv", use_bias=cfg.qkv_bias, dtype=self.dtype)(y)
        qkv = qkv.reshape(b, n, 3, h, d)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        if cfg.qk_normalization:
            # the reference normalizes q/k over the FULL flattened head dim
            # (internvideo2.py:219), not per head
            q = IV2RMSNorm(eps=cfg.rms_eps, name="q_norm")(q.reshape(b, n, c)).reshape(b, n, h, d)
            k = IV2RMSNorm(eps=cfg.rms_eps, name="k_norm")(k.reshape(b, n, c)).reshape(b, n, h, d)
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) * (d**-0.5)
        probs = jax.nn.softmax(logits, axis=-1).astype(self.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, n, c)
        attn = dense(c, "in", name="attn_out", use_bias=True, dtype=self.dtype)(attn)
        ls1 = self.param(
            "ls1", nn.initializers.constant(cfg.init_values), (c,), jnp.float32
        )
        x = x + (attn.astype(jnp.float32) * ls1).astype(x.dtype)

        y = IV2RMSNorm(eps=cfg.rms_eps, name="ln2")(x)
        hidden = int(c * cfg.mlp_ratio)
        y = dense(hidden, "out", name="fc1", use_bias=True, dtype=self.dtype)(y)
        y = nn.gelu(y, approximate=False)  # torch nn.GELU default: exact erf
        y = dense(c, "in", name="fc2", use_bias=True, dtype=self.dtype)(y)
        ls2 = self.param(
            "ls2", nn.initializers.constant(cfg.init_values), (c,), jnp.float32
        )
        return x + (y.astype(jnp.float32) * ls2).astype(x.dtype)


class IV2AttentionPool(nn.Module):
    """The reference `AttentionPoolingBlock` (internvideo2.py:146): mean
    query cross-attends the token sequence; output projected to
    clip_embed_dim."""

    cfg: IV2Config
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, n, c = x.shape
        h = cfg.attn_pool_num_heads
        d = c // h
        q_in = nn.LayerNorm(epsilon=cfg.ln_eps, name="ln_q", dtype=jnp.float32)(
            x.mean(axis=1, keepdims=True).astype(jnp.float32)
        )
        k_in = nn.LayerNorm(epsilon=cfg.ln_eps, name="ln_k", dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
        v_in = nn.LayerNorm(epsilon=cfg.ln_eps, name="ln_v", dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
        q = dense(c, None, name="q", use_bias=True, dtype=self.dtype)(q_in.astype(self.dtype))
        k = dense(c, None, name="k", use_bias=True, dtype=self.dtype)(k_in.astype(self.dtype))
        v = dense(c, None, name="v", use_bias=True, dtype=self.dtype)(v_in.astype(self.dtype))
        q = q.reshape(b, 1, h, d)
        k = k.reshape(b, n, h, d)
        v = v.reshape(b, n, h, d)
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) * (d**-0.5)
        probs = jax.nn.softmax(logits, axis=-1).astype(self.dtype)
        pooled = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(b, 1, c)
        out = dense(cfg.clip_embed_dim, None, name="out", use_bias=True, dtype=self.dtype)(pooled)
        return out[:, 0]


class InternVideo2Tower(nn.Module):
    """Frames -> l2-normalized [B, proj_dim] contrastive video embedding
    (the `get_vid_feat` path: tower -> attentive pool -> vision_proj ->
    normalize)."""

    cfg: IV2Config
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, frames_u8):
        cfg = self.cfg
        patches = frames_to_tubelets(frames_u8, cfg)
        x = dense(
            cfg.embed_dim, None, name="patch_proj", use_bias=True, dtype=self.dtype
        )(patches.astype(self.dtype))
        b = x.shape[0]
        cls = self.param(
            "cls", nn.initializers.normal(0.02), (1, 1, cfg.embed_dim), jnp.float32
        )
        pos = self.param(
            "pos_embed",
            lambda _rng: jnp.asarray(
                sincos_3d_pos_embed(cfg.embed_dim, cfg.grid)[None]
            ),
        )
        x = jnp.concatenate([jnp.broadcast_to(cls, (b, 1, cfg.embed_dim)).astype(self.dtype), x], axis=1)
        x = (x.astype(jnp.float32) + pos).astype(self.dtype)
        for i in range(cfg.depth):
            x = IV2Block(cfg, dtype=self.dtype, name=f"block_{i}")(x)
        pooled = IV2AttentionPool(cfg, dtype=self.dtype, name="pool")(x)
        proj = dense(cfg.proj_dim, None, name="vision_proj", use_bias=True, dtype=jnp.float32)(
            pooled.astype(jnp.float32)
        )
        return proj / (jnp.linalg.norm(proj, axis=-1, keepdims=True) + 1e-12)


# Embedding-stage variants: name -> (config, weight-registry id,
# require staged weights). The 1B flavor refuses random-init (a user
# asking for InternVideo2 embeddings must not silently get noise).
IV2_VARIANTS: dict[str, tuple[IV2Config, str, bool]] = {
    "iv2": (IV2_1B, "internvideo2-1b-tpu", True),
    "iv2-tiny-test": (IV2_TINY_TEST, "internvideo2-tiny-test", False),
}

_APPLY_CACHE: dict[tuple, object] = {}


def _jitted_apply(cfg: IV2Config, dtype):
    """One compiled apply per (config, dtype) — shared across stage
    instances so warmup survives stage construction. The clip batch
    (arg 1) is donated on TPU/GPU."""
    key = (cfg, str(dtype))
    fn = _APPLY_CACHE.get(key)
    if fn is None:
        from cosmos_curate_tpu.models.device_pipeline import donate_kwargs

        model = InternVideo2Tower(cfg, dtype=dtype)
        fn = jax.jit(model.apply, **donate_kwargs(1))
        _APPLY_CACHE[key] = fn
    return fn


class IV2Embedder(ModelInterface):
    """ModelInterface wrapper serving the embedding stage
    (same surface as VideoEmbedder: sample_frame_indices / encode_clips /
    embedding_dim). Mirrors the reference's inference flow
    (internvideo2_mm.py:396 `_construct_frames`: stride-sample num_frames,
    cv2-resize to img_size, normalize, one batched forward)."""

    MODEL_ID = "internvideo2-1b-tpu"

    def __init__(self, cfg: IV2Config = IV2_1B, *, model_id: str | None = None,
                 require_weights: bool = False, dtype=jnp.bfloat16) -> None:
        self.cfg = cfg
        self.model_id = model_id or self.MODEL_ID
        self.require_weights = require_weights
        self.dtype = dtype
        self._apply = None
        self._params = None
        self._pipeline = None

    @property
    def model_id_names(self) -> list[str]:
        return [self.model_id]

    @property
    def embedding_dim(self) -> int:
        return self.cfg.proj_dim

    def setup(self) -> None:
        from cosmos_curate_tpu.models import registry

        model = InternVideo2Tower(self.cfg, dtype=self.dtype)

        def init(seed: int):
            s = self.cfg.img_size
            dummy = jnp.zeros((1, self.cfg.num_frames, s, s, 3), jnp.uint8)
            return model.init(jax.random.PRNGKey(seed), dummy)

        self._params = registry.load_params(
            self.model_id, init, require=self.require_weights
        )
        self._apply = _jitted_apply(self.cfg, self.dtype)
        from cosmos_curate_tpu.models.device_pipeline import DevicePipeline

        self._pipeline = DevicePipeline(f"iv2/{self.model_id}", self._apply)

    def sample_frame_indices(self, total: int) -> np.ndarray:
        """Uniform temporal sampling to cfg.num_frames (the reference
        strides then truncates; linspace covers the same span without
        dropping the tail on non-divisible counts)."""
        n = self.cfg.num_frames
        if total <= 0:
            return np.zeros(0, np.int64)
        return np.linspace(0, max(total - 1, 0), n).round().astype(np.int64)

    def _resize(self, clips: np.ndarray) -> np.ndarray:
        s = self.cfg.img_size
        if clips.shape[2] == s and clips.shape[3] == s:
            return clips
        import cv2

        b, t = clips.shape[:2]
        out = np.empty((b, t, s, s, 3), np.uint8)
        for i in range(b):
            for j in range(t):
                out[i, j] = cv2.resize(clips[i, j], (s, s), interpolation=cv2.INTER_AREA)
        return out

    def encode_clips(self, clips_frames: np.ndarray) -> np.ndarray:
        """uint8 [B, T, H, W, 3] -> float32 [B, proj_dim] l2-normalized.
        Dispatched through the shared DevicePipeline (bucket micro-batches,
        overlapped transfer/compute/readback)."""
        if self._pipeline is None:
            raise RuntimeError("call setup() first")
        emb = self._pipeline.run(self._params, self._resize(clips_frames))
        return emb.astype(np.float32)
