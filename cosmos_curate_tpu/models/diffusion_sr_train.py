"""Synthetic-data training for the diffusion SR denoiser.

Same training story as sr_train.py (the reference ships SeedVR2's
pretrained checkpoint; this image has no egress, so a functional
checkpoint comes from training on synthesized degradations), extended to
VIDEO windows: each sample is a ``window``-frame sequence of one crisp
procedural texture under sub-pixel translation (synthetic motion), so the
temporal attention actually learns cross-frame detail agreement.

Objective: v-prediction MSE on the HR-residual diffusion (see
models/diffusion_sr.py). One jitted train step, vmapped over a batch of
windows; synthesis on host numpy.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.models.diffusion_sr import (
    DIFF_SR_BASE,
    DenoiserUNet,
    DiffusionSRConfig,
    cosine_alpha_sigma,
)
from cosmos_curate_tpu.models.sr_train import _texture
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def synthesize_windows(
    rng: np.random.Generator, batch: int, window: int, hr: int, scale: int
) -> tuple[np.ndarray, np.ndarray]:
    """(cond [B, T, hr, hr, 3], residual [B, T, hr, hr, 3]) float32:
    cond is the bilinear-upsampled LR, residual = HR - cond (the
    diffusion target). Frames are sub-pixel translations of one texture."""
    import cv2

    lr_size = hr // scale
    pad = 8
    conds = np.empty((batch, window, hr, hr, 3), np.float32)
    residuals = np.empty_like(conds)
    for b in range(batch):
        canvas = _texture(rng, hr + pad, hr + pad)
        dx, dy = rng.uniform(0, 2, 2)  # per-window drift (pixels/frame)
        for t in range(window):
            ox, oy = t * dx, t * dy
            m = np.float32([[1, 0, -ox], [0, 1, -oy]])
            hr_f = cv2.warpAffine(canvas, m, (hr, hr), flags=cv2.INTER_LINEAR)
            lr_f = cv2.resize(hr_f, (lr_size, lr_size), interpolation=cv2.INTER_LINEAR)
            cond = cv2.resize(lr_f, (hr, hr), interpolation=cv2.INTER_LINEAR)
            conds[b, t] = cond
            residuals[b, t] = hr_f - cond
    return conds, residuals


def train(
    cfg: DiffusionSRConfig = DIFF_SR_BASE,
    *,
    steps: int = 800,
    batch: int = 8,
    hr_size: int = 32,
    lr: float = 1e-3,
    seed: int = 0,
    log_every: int = 100,
):
    import jax
    import jax.numpy as jnp
    import optax

    model = DenoiserUNet(cfg)
    rng = np.random.default_rng(seed)
    conds0, _ = synthesize_windows(rng, 1, cfg.window, hr_size, cfg.scale)
    params = model.init(
        jax.random.PRNGKey(seed),
        jnp.zeros_like(jnp.asarray(conds0[0])),
        jnp.asarray(conds0[0]),
        jnp.float32(0.5),
    )
    opt = optax.adam(lr)
    opt_state = opt.init(params)

    def loss_fn(p, key, conds, residuals):
        b = conds.shape[0]
        k_t, k_eps = jax.random.split(key)
        ts = jax.random.uniform(k_t, (b,), minval=1e-3, maxval=1.0)
        eps = jax.random.normal(k_eps, residuals.shape)

        def one(cond, x0, e, t):
            a, s = cosine_alpha_sigma(t)
            z = a * x0 + s * e
            v_target = a * e - s * x0
            v = model.apply(p, z, cond, t)
            return jnp.mean((v - v_target) ** 2)

        return jnp.mean(jax.vmap(one)(conds, residuals, eps, ts))

    @jax.jit
    def step(params, opt_state, key, conds, residuals):
        loss, grads = jax.value_and_grad(loss_fn)(params, key, conds, residuals)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    key = jax.random.PRNGKey(seed + 1)
    loss = None
    for i in range(steps):
        conds, residuals = synthesize_windows(rng, batch, cfg.window, hr_size, cfg.scale)
        key, sub = jax.random.split(key)
        params, opt_state, loss = step(
            params, opt_state, sub, jnp.asarray(conds), jnp.asarray(residuals)
        )
        if log_every and (i + 1) % log_every == 0:
            logger.info(
                "diffusion sr train step %d/%d loss %.5f", i + 1, steps, float(loss)
            )
    return params, float(loss) if loss is not None else float("nan")


def train_and_stage(
    cfg: DiffusionSRConfig = DIFF_SR_BASE,
    *,
    model_id: str = "diffusion-sr-tpu",
    out_dir: str | None = None,
    **train_kw,
):
    from cosmos_curate_tpu.models import registry

    params, loss = train(cfg, **train_kw)
    ckpt = registry.save_params(model_id, params, root=out_dir)
    logger.info("staged %s (final loss %.5f) at %s", model_id, loss, ckpt)
    return ckpt, loss


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description="Train the diffusion SR denoiser")
    ap.add_argument("--steps", type=int, default=800)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--hr-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out-dir", default=None, help="e.g. <repo>/weights to commit")
    a = ap.parse_args()
    train_and_stage(
        steps=a.steps, batch=a.batch, hr_size=a.hr_size, lr=a.lr, seed=a.seed,
        out_dir=a.out_dir,
    )
