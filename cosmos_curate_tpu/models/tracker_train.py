"""Synthetic-data training for the siamese tracker embedding.

Training pairs are (template patch, search window) crops from procedurally
generated scenes: a textured target object moves over cluttered backgrounds
with brightness/scale jitter and look-alike distractors; the label is the
target's true offset inside the search window. Loss is cross-entropy over
the correlation response map against a one-hot peak (SiamFC-style logistic
variant, public technique). No egress needed — same pattern as
models/transnet_train.py. Checkpoint ships under
``weights/tracker-siamese-tpu/``.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.models.tracker_learned import STRIDE, EmbedNet, SiameseConfig, _prep
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _texture(rng: np.random.Generator, h: int, w: int) -> np.ndarray:
    import cv2

    base = rng.integers(0, 256, 3).astype(np.float32)
    tex = np.clip(
        base + rng.normal(0, rng.uniform(5, 40), (h, w, 3)), 0, 255
    ).astype(np.uint8)
    if rng.random() < 0.5:
        tex = cv2.GaussianBlur(tex, (3, 3), 0)
    return tex


def _paste_object(
    img: np.ndarray, rng: np.random.Generator, cx: int, cy: int, size: int
) -> None:
    """Textured ellipse/rect target centered at (cx, cy)."""
    import cv2

    h, w = img.shape[:2]
    obj = _texture(rng, size, size)
    mask = np.zeros((size, size), np.uint8)
    if rng.random() < 0.5:
        cv2.ellipse(mask, (size // 2, size // 2), (size // 2 - 1, size // 3), 0, 0, 360, 255, -1)
    else:
        cv2.rectangle(mask, (1, 1), (size - 2, size - 2), 255, -1)
    x0, y0 = cx - size // 2, cy - size // 2
    x1, y1 = x0 + size, y0 + size
    sx0, sy0 = max(0, -x0), max(0, -y0)
    x0, y0 = max(0, x0), max(0, y0)
    x1, y1 = min(w, x1), min(h, y1)
    if x1 <= x0 or y1 <= y0:
        return
    region = img[y0:y1, x0:x1]
    m = mask[sy0 : sy0 + (y1 - y0), sx0 : sx0 + (x1 - x0), None] > 0
    region[:] = np.where(m, obj[sy0 : sy0 + (y1 - y0), sx0 : sx0 + (x1 - x0)], region)


def synthesize_pair_batch(
    rng: np.random.Generator, batch: int, cfg: SiameseConfig
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """-> (templates [B,ts,ts,3], searches [B,ss,ss,3], target_yx [B,2] peak
    coordinates in the response map)."""
    ts, ss = cfg.template_size, cfg.search_size
    resp_edge = (ss - ts) // STRIDE + 1
    templates = np.empty((batch, ts, ts, 3), np.uint8)
    searches = np.empty((batch, ss, ss, 3), np.uint8)
    targets = np.empty((batch, 2), np.int32)
    margin = ts // 2
    for b in range(batch):
        scene = _texture(rng, ss * 2, ss * 2)
        # clutter + distractor of similar size
        for _ in range(rng.integers(0, 4)):
            _paste_object(
                scene, rng,
                int(rng.integers(0, ss * 2)), int(rng.integers(0, ss * 2)),
                int(rng.integers(8, 24)),
            )
        obj_size = int(rng.integers(10, ts - 4))
        # place target somewhere the search window can see
        tcx = ss + int(rng.integers(-(ss // 2 - margin), ss // 2 - margin + 1))
        tcy = ss + int(rng.integers(-(ss // 2 - margin), ss // 2 - margin + 1))
        _paste_object(scene, rng, tcx, tcy, obj_size)
        searches[b] = scene[ss - ss // 2 : ss + ss // 2, ss - ss // 2 : ss + ss // 2]

        # template: crop around the true center with brightness jitter —
        # the appearance-variation the tracker must be invariant to
        patch = scene[tcy - ts // 2 : tcy + ts // 2, tcx - ts // 2 : tcx + ts // 2]
        jitter = rng.uniform(0.8, 1.2)
        templates[b] = np.clip(patch.astype(np.float32) * jitter, 0, 255).astype(np.uint8)

        # response-map coordinates of the target inside the search window
        off_x = tcx - (ss - ss // 2)  # target center in search-window pixels
        off_y = tcy - (ss - ss // 2)
        rx = int(np.clip(round((off_x - ts // 2) / STRIDE), 0, resp_edge - 1))
        ry = int(np.clip(round((off_y - ts // 2) / STRIDE), 0, resp_edge - 1))
        targets[b] = (ry, rx)
    return templates, searches, targets


def train(
    cfg: SiameseConfig = SiameseConfig(),
    *,
    steps: int = 800,
    batch: int = 16,
    lr: float = 1e-3,
    seed: int = 0,
    log_every: int = 100,
):
    import jax
    import jax.numpy as jnp
    import optax

    net = EmbedNet(cfg.features)
    rng = np.random.default_rng(seed)
    params = net.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, cfg.template_size, cfg.template_size, 3))
    )
    opt = optax.adamw(lr)
    opt_state = opt.init(params)
    resp_edge = (cfg.search_size - cfg.template_size) // STRIDE + 1

    @jax.jit
    def step(params, opt_state, templates, searches, targets):
        def loss_fn(p):
            tfeat = net.apply(p, _prep(templates))  # [B, ht, wt, F]
            sfeat = net.apply(p, _prep(searches))  # [B, hs, ws, F]

            def one(tf, sf):
                return jax.lax.conv_general_dilated(
                    sf.transpose(2, 0, 1)[None],
                    tf.transpose(2, 0, 1)[None].transpose(1, 0, 2, 3),
                    window_strides=(1, 1),
                    padding="VALID",
                    feature_group_count=tf.shape[-1],
                ).sum(axis=1)[0]

            resp = jax.vmap(one)(tfeat, sfeat)  # [B, re, re]
            logits = resp.reshape(resp.shape[0], -1) / (
                tfeat.shape[1] * tfeat.shape[2] * tfeat.shape[3]
            )
            labels = targets[:, 0] * resp_edge + targets[:, 1]
            return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    loss = None
    for i in range(steps):
        t, s, y = synthesize_pair_batch(rng, batch, cfg)
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(t), jnp.asarray(s), jnp.asarray(y)
        )
        if log_every and (i + 1) % log_every == 0:
            logger.info("tracker train step %d/%d loss %.4f", i + 1, steps, float(loss))
    return params, float(loss) if loss is not None else float("nan")


def train_and_stage(
    cfg: SiameseConfig = SiameseConfig(),
    *,
    model_id: str = "tracker-siamese-tpu",
    out_dir: str | None = None,
    **train_kw,
):
    from cosmos_curate_tpu.models import registry

    params, loss = train(cfg, **train_kw)
    ckpt = registry.save_params(model_id, params, root=out_dir)
    logger.info("staged %s (final loss %.4f) at %s", model_id, loss, ckpt)
    return ckpt, loss


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description="Train the siamese tracker embedding")
    ap.add_argument("--steps", type=int, default=800)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--out-dir", default=None)
    a = ap.parse_args()
    train_and_stage(steps=a.steps, batch=a.batch, out_dir=a.out_dir)
