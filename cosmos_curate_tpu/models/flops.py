"""Analytic FLOPs accounting for MFU reporting.

Equivalent capability of the reference's speed-of-light perf method
(docs/curator/design/SPEED_OF_LIGHT.md:22-81 — tokens/s and pipeline
efficiency vs hardware peak), translated to TPU: every model family gets an
analytic forward-FLOPs formula, and ``mfu(flops, seconds)`` divides the
achieved rate by the chip's bf16 peak. The formulas count matmul FLOPs only
(2·M·N·K per GEMM) — elementwise/normalization work is bandwidth-, not
FLOP-bound on TPU and is excluded, matching standard MFU conventions.
"""

from __future__ import annotations

import os


def transformer_layer_flops(tokens: int, width: int, *, mlp_ratio: int = 4) -> float:
    """One pre-LN transformer block forward: QKVO projections + attention
    score/value matmuls + 2-layer MLP."""
    proj = 8.0 * tokens * width * width  # 4 projections, 2·T·W·W each
    attn = 4.0 * tokens * tokens * width  # QK^T and attn·V
    mlp = 2.0 * 2.0 * tokens * width * (mlp_ratio * width)
    return proj + attn + mlp


def vit_forward_flops(cfg) -> float:
    """One image through models/vit.ViT (patch conv + blocks + projection)."""
    n = cfg.num_patches + 1  # + cls token
    patch = 2.0 * cfg.num_patches * (cfg.patch_size * cfg.patch_size * 3) * cfg.width
    blocks = cfg.layers * transformer_layer_flops(n, cfg.width)
    proj = 2.0 * cfg.width * cfg.projection_dim
    return patch + blocks + proj


def video_embed_forward_flops(cfg) -> float:
    """One clip through models/embedder.VideoEmbedModel."""
    frames = cfg.num_frames * vit_forward_flops(cfg.vit)
    t = cfg.num_frames + 1  # + query token
    d = cfg.vit.projection_dim
    temporal = cfg.temporal_layers * transformer_layer_flops(t, d)
    out = 2.0 * d * cfg.output_dim
    return frames + temporal + out


def vlm_decode_flops_per_token(cfg) -> float:
    """One decode step for one sequence through models/vlm.VLM's LM stack
    (GQA + SwiGLU + tied head). Decode attention reads the whole KV cache:
    score/value matmuls scale with max_seq (upper estimate)."""
    d = cfg.dim
    q_inner = cfg.n_heads * cfg.head_dim
    kv_inner = cfg.n_kv_heads * cfg.head_dim
    proj = 2.0 * d * q_inner + 2.0 * 2.0 * d * kv_inner + 2.0 * q_inner * d
    attn = 2.0 * 2.0 * cfg.max_seq * q_inner
    ff = int(d * cfg.hidden_mult)
    mlp = 3.0 * 2.0 * d * ff  # gate + up + down
    head = 2.0 * d * cfg.vocab
    return cfg.n_layers * (proj + attn + mlp) + head


# bf16 peak FLOPs/s per chip by TPU generation (public spec sheets).
_TPU_PEAK = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}
DEFAULT_PEAK = _TPU_PEAK["v5e"]


def chip_peak_flops() -> float:
    """Best-effort peak for the attached chip; BENCH_PEAK_FLOPS overrides."""
    env = os.environ.get("BENCH_PEAK_FLOPS")
    if env:
        return float(env)
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
        for name, peak in _TPU_PEAK.items():
            if name in kind:
                return peak
    except Exception:
        pass
    return DEFAULT_PEAK


def mfu(total_flops: float, seconds: float, *, peak: float | None = None) -> float:
    """Model FLOPs utilization: achieved FLOPs/s over chip peak."""
    if seconds <= 0:
        return 0.0
    return (total_flops / seconds) / (peak or chip_peak_flops())
