"""Synthetic-data training for the OCR detector + recognizer.

The reference ships PaddleOCR's pretrained det/rec checkpoints
(cosmos_curate/models/paddle_ocr.py:317); this image has no egress, so the
models in models/ocr.py train on text rendered with cv2's Hershey fonts over
procedural backgrounds — the same no-egress pattern as
models/transnet_train.py. Trained checkpoints are committed under
``weights/ocr-{detector,recognizer}-tpu/`` via the registry; staging real
converted checkpoints in $CURATE_MODEL_WEIGHTS_DIR still wins.

TPU-first: one jitted train step per model; host-side data synthesis.
"""

from __future__ import annotations

import numpy as np

from cosmos_curate_tpu.models.ocr import (
    BLANK_ID,
    CHARSET,
    DetectorConfig,
    RecognizerConfig,
    TextDetector,
    TextRecognizer,
    encode_text,
)
from cosmos_curate_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_FONTS = (0, 1, 2, 3, 4, 6, 7)  # cv2 FONT_HERSHEY_* family


def _background(rng: np.random.Generator, h: int, w: int) -> np.ndarray:
    import cv2

    kind = rng.integers(0, 4)
    if kind == 0:  # solid
        img = np.full((h, w, 3), rng.integers(0, 256, 3), np.uint8)
    elif kind == 1:  # linear gradient
        a = rng.integers(0, 256, 3).astype(np.float32)
        b = rng.integers(0, 256, 3).astype(np.float32)
        t = np.linspace(0, 1, w)[None, :, None]
        img = (a + (b - a) * t).astype(np.uint8)
        img = np.broadcast_to(img, (h, w, 3)).copy()
    elif kind == 2:  # random rectangles (scene-ish clutter)
        img = np.full((h, w, 3), rng.integers(0, 256, 3), np.uint8)
        for _ in range(rng.integers(2, 8)):
            x0, y0 = rng.integers(0, w), rng.integers(0, h)
            x1, y1 = rng.integers(0, w), rng.integers(0, h)
            cv2.rectangle(
                img,
                (min(x0, x1), min(y0, y1)),
                (max(x0, x1), max(y0, y1)),
                tuple(int(v) for v in rng.integers(0, 256, 3)),
                -1,
            )
    else:  # noise texture
        img = rng.integers(0, 256, (h, w, 3), np.uint8)
        import cv2 as _cv2

        img = _cv2.GaussianBlur(img, (5, 5), 0)
    return img


def _rand_text(rng: np.random.Generator, max_len: int = 10) -> str:
    n = int(rng.integers(1, max_len + 1))
    chars = CHARSET[1:]  # no leading/trailing spaces (cleaner CTC targets)
    s = "".join(chars[rng.integers(0, len(chars))] for _ in range(n))
    # interior spaces in ~half the samples: real overlays are multi-word
    # ("BREAKING NEWS"), and a recognizer that never saw the space class
    # cannot emit it (observed: 'HELLO 42' read as 'HELLO42')
    if n >= 4 and rng.random() < 0.5:
        k = int(rng.integers(1, n - 1))
        s = s[:k] + " " + s[k + 1 :]
    return s


def golden_eval_frames() -> tuple[np.ndarray, np.ndarray]:
    """(clean, texty) frames — the SINGLE definition the weights-gated
    detector golden (tests/models/test_ocr.py) and the CPU trainer's
    publish gate (scripts/train_ocr_cpu.py) both evaluate against, so the
    gate cannot drift from the test."""
    import cv2

    clean = np.full((8, 240, 320, 3), 90, np.uint8)
    for f in clean:  # non-text structure: rectangles
        cv2.rectangle(f, (40, 60), (200, 180), (200, 180, 40), -1)
    texty = clean.copy()
    for f in texty:
        cv2.putText(f, "BREAKING NEWS UPDATE", (10, 40),
                    cv2.FONT_HERSHEY_SIMPLEX, 0.8, (255, 255, 255), 2, cv2.LINE_AA)
        cv2.putText(f, "subscribe now!", (60, 220),
                    cv2.FONT_HERSHEY_DUPLEX, 0.7, (0, 255, 255), 2, cv2.LINE_AA)
    return clean, texty


def golden_rec_sample(text: str = "HELLO 42") -> np.ndarray:
    """Rendered recognizer sample shared by the golden test and the
    trainer's publish gate."""
    import cv2

    img = np.full((32, 160, 3), 255, np.uint8)
    cv2.putText(img, text, (6, 24), cv2.FONT_HERSHEY_SIMPLEX, 0.8, (0, 0, 0), 2)
    return img


def synthesize_detector_batch(
    rng: np.random.Generator, batch: int, cfg: DetectorConfig
) -> tuple[np.ndarray, np.ndarray]:
    """-> (frames uint8 [B,H,W,3], target float32 [B,H/4,W/4])."""
    import cv2

    h, w = cfg.height, cfg.width
    frames = np.empty((batch, h, w, 3), np.uint8)
    targets = np.zeros((batch, h // 4, w // 4), np.float32)
    for b in range(batch):
        img = _background(rng, h, w)
        if rng.random() < 0.75:  # text-bearing sample
            for _ in range(int(rng.integers(1, 4))):
                text = _rand_text(rng)
                font = int(_FONTS[rng.integers(0, len(_FONTS))])
                scale = float(rng.uniform(0.4, 1.0))
                thick = int(rng.integers(1, 3))
                (tw, th), _ = cv2.getTextSize(text, font, scale, thick)
                if tw >= w - 4 or th >= h - 4:
                    continue
                x = int(rng.integers(2, max(3, w - tw - 2)))
                y = int(rng.integers(th + 2, max(th + 3, h - 4)))
                color = tuple(int(v) for v in rng.integers(0, 256, 3))
                cv2.putText(img, text, (x, y), font, scale, color, thick, cv2.LINE_AA)
                # shrunken box target at 1/4 resolution
                sx0, sy0 = (x + tw // 10) // 4, (y - th + th // 10) // 4
                sx1, sy1 = (x + tw - tw // 10) // 4, (y - th // 10) // 4
                targets[b, max(0, sy0) : sy1 + 1, max(0, sx0) : sx1 + 1] = 1.0
        frames[b] = img
    return frames, targets


def synthesize_recognizer_batch(
    rng: np.random.Generator, batch: int, cfg: RecognizerConfig, max_len: int = 10
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """-> (crops uint8 [B,32,W,3], labels int32 [B,max_len], label_pad [B,max_len])."""
    import cv2

    h, w = cfg.height, cfg.max_width
    crops = np.empty((batch, h, w, 3), np.uint8)
    labels = np.zeros((batch, max_len), np.int32)
    pads = np.ones((batch, max_len), np.float32)
    for b in range(batch):
        img = _background(rng, h, w)
        text = _rand_text(rng, max_len)
        font = int(_FONTS[rng.integers(0, len(_FONTS))])
        thick = int(rng.integers(1, 3))
        # fit the text into the crop width
        scale = 1.0
        (tw, th), _ = cv2.getTextSize(text, font, scale, thick)
        scale = min(0.9 * w / max(tw, 1), 0.7 * h / max(th, 1))
        (tw, th), _ = cv2.getTextSize(text, font, scale, thick)
        x = max(1, (w - tw) // 2 + int(rng.integers(-4, 5)))
        y = min(h - 2, (h + th) // 2 + int(rng.integers(-2, 3)))
        # ensure contrast against the local background
        patch = img[max(0, y - th) : y + 2, x : x + tw + 1]
        mean = patch.mean(axis=(0, 1)) if patch.size else np.array([128.0] * 3)
        color = tuple(int(255 - v) if abs(v - 128) > 40 else (255 if v < 128 else 0) for v in mean)
        cv2.putText(img, text, (x, y), font, scale, color, thick, cv2.LINE_AA)
        crops[b] = img
        ids = encode_text(text)
        labels[b, : len(ids)] = ids
        pads[b, : len(ids)] = 0.0
    return crops, labels, pads


def train_detector(
    cfg: DetectorConfig = DetectorConfig(),
    *,
    steps: int = 500,
    batch: int = 8,
    lr: float = 1e-3,
    pos_weight: float = 3.0,
    seed: int = 0,
    log_every: int = 100,
):
    import jax
    import jax.numpy as jnp
    import optax

    model = TextDetector(cfg)
    rng = np.random.default_rng(seed)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, cfg.height, cfg.width, 3), jnp.uint8)
    )
    opt = optax.adamw(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, frames, targets):
        def loss_fn(p):
            logits = model.apply(p, frames)
            per = optax.sigmoid_binary_cross_entropy(logits, targets)
            weight = 1.0 + (pos_weight - 1.0) * targets
            return (per * weight).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    loss = None
    for i in range(steps):
        frames, targets = synthesize_detector_batch(rng, batch, cfg)
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(frames), jnp.asarray(targets)
        )
        if log_every and (i + 1) % log_every == 0:
            logger.info("ocr-det step %d/%d loss %.4f", i + 1, steps, float(loss))
    return params, float(loss) if loss is not None else float("nan")


def train_recognizer(
    cfg: RecognizerConfig = RecognizerConfig(),
    *,
    steps: int = 1200,
    batch: int = 16,
    lr: float = 2e-3,
    seed: int = 0,
    log_every: int = 100,
):
    import jax
    import jax.numpy as jnp
    import optax

    model = TextRecognizer(cfg)
    rng = np.random.default_rng(seed)
    params = model.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, cfg.height, cfg.max_width, 3), jnp.uint8)
    )
    opt = optax.adamw(lr)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state, crops, labels, label_pads):
        def loss_fn(p):
            logits = model.apply(p, crops)  # [B, T, K]
            logit_pads = jnp.zeros(logits.shape[:2], jnp.float32)
            return optax.ctc_loss(
                logits, logit_pads, labels, label_pads, blank_id=BLANK_ID
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    loss = None
    for i in range(steps):
        crops, labels, pads = synthesize_recognizer_batch(rng, batch, cfg)
        params, opt_state, loss = step(
            params, opt_state, jnp.asarray(crops), jnp.asarray(labels), jnp.asarray(pads)
        )
        if log_every and (i + 1) % log_every == 0:
            logger.info("ocr-rec step %d/%d loss %.4f", i + 1, steps, float(loss))
    return params, float(loss) if loss is not None else float("nan")


def train_and_stage(*, out_dir: str | None = None, det_kw=None, rec_kw=None):
    from cosmos_curate_tpu.models import registry

    results = {}
    for model_id, trainer, kw in (
        ("ocr-detector-tpu", train_detector, det_kw or {}),
        ("ocr-recognizer-tpu", train_recognizer, rec_kw or {}),
    ):
        params, loss = trainer(**kw)
        ckpt = registry.save_params(model_id, params, root=out_dir)
        logger.info("staged %s (final loss %.4f) at %s", model_id, loss, ckpt)
        results[model_id] = (ckpt, loss)
    return results


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description="Train OCR det/rec on synthetic text")
    ap.add_argument("--det-steps", type=int, default=500)
    ap.add_argument("--rec-steps", type=int, default=1200)
    ap.add_argument("--out-dir", default=None, help="e.g. <repo>/weights to commit")
    a = ap.parse_args()
    train_and_stage(
        out_dir=a.out_dir,
        det_kw={"steps": a.det_steps},
        rec_kw={"steps": a.rec_steps},
    )
